// Batched Euler-split edge coloring — the host half of Benes route
// construction (lux_tpu/ops/route.py).  Colors B independent deg-regular
// bipartite multigraphs with deg colors each (every color class a
// perfect matching), by recursive Euler partitions that halve the
// regularity.  This is the construction-time bottleneck of routed
// permutations at benchmark scale (pure-Python walk: ~45 s at 2^20
// elements; this: seconds at 2^24) — the per-iteration device replay is
// unaffected.
//
// Error contract (matches lux_io.cc): return 0 on success, negative
// errno-style codes otherwise; never abort.
//
// Design note: same recursion as route.py::_color_regular (a stack of
// (edge-range, deg, color-base) over an in-place stably-partitioned id
// array), with the per-split Euler walk of _split_regular.  Outputs are
// valid colorings but NOT guaranteed bit-identical to the Python walk —
// ops/route.py's oracle contract is replay equality (x[perm]), which
// any valid coloring satisfies.

#include <atomic>
#include <cstdint>
#include <climits>
#include <thread>
#include <vector>

namespace {

constexpr int kErrBadArg = -22;   // EINVAL
constexpr int kErrRange = -34;    // ERANGE: node id out of [0, nside)

struct Scratch {
  // int32 throughout (n < 2^31 by contract): the Euler walk is random-
  // access latency-bound, so narrow types halve the hot working set.
  // ``ids`` lives here for the serial walk; the threaded walk passes a
  // per-batch ids buffer explicitly (frames of one batch share it,
  // touching disjoint [lo, hi) ranges).
  std::vector<int32_t> ids, ids_tmp;      // edge ids, stable-partition tmp
  std::vector<int32_t> us, vs;            // sub-graph endpoints
  std::vector<int32_t> l_off, r_off;      // CSR offsets per side
  std::vector<int32_t> l_edges, r_edges;  // CSR edge lists
  std::vector<int32_t> l_ptr, r_ptr;      // walk skip pointers
  std::vector<uint8_t> used, half;
};

// Split the deg-regular multigraph on edges ids[lo, hi) into two
// (deg/2)-regular halves via one Euler partition; stable-partition the
// id range so the first half precedes the second.  Returns the split
// point.  ``ids`` is the (caller-owned) id permutation the range lives
// in; only [lo, hi) is read or written, so disjoint ranges are safe to
// split concurrently.
int64_t euler_split(const int64_t* u, const int64_t* v, int32_t* ids,
                    Scratch& s, int64_t lo, int64_t hi, int64_t nside) {
  const int64_t m = hi - lo;
  s.us.resize(m);
  s.vs.resize(m);
  for (int64_t k = 0; k < m; ++k) {
    s.us[k] = static_cast<int32_t>(u[ids[lo + k]]);
    s.vs[k] = static_cast<int32_t>(v[ids[lo + k]]);
  }
  // counting-sort CSR incidence per side
  s.l_off.assign(nside + 1, 0);
  s.r_off.assign(nside + 1, 0);
  for (int64_t k = 0; k < m; ++k) {
    ++s.l_off[s.us[k] + 1];
    ++s.r_off[s.vs[k] + 1];
  }
  for (int64_t i = 0; i < nside; ++i) {
    s.l_off[i + 1] += s.l_off[i];
    s.r_off[i + 1] += s.r_off[i];
  }
  s.l_edges.resize(m);
  s.r_edges.resize(m);
  s.l_ptr.assign(s.l_off.begin(), s.l_off.end() - 1);
  s.r_ptr.assign(s.r_off.begin(), s.r_off.end() - 1);
  for (int64_t k = 0; k < m; ++k) {
    s.l_edges[s.l_ptr[s.us[k]]++] = k;
    s.r_edges[s.r_ptr[s.vs[k]]++] = k;
  }
  s.l_ptr.assign(s.l_off.begin(), s.l_off.end() - 1);
  s.r_ptr.assign(s.r_off.begin(), s.r_off.end() - 1);
  s.used.assign(m, 0);
  s.half.assign(m, 0);

  auto next_l = [&](int32_t node) -> int32_t {
    int32_t p = s.l_ptr[node];
    const int32_t stop = s.l_off[node + 1];
    while (p < stop && s.used[s.l_edges[p]]) ++p;
    s.l_ptr[node] = p;
    return p < stop ? s.l_edges[p] : -1;
  };
  auto next_r = [&](int32_t node) -> int32_t {
    int32_t p = s.r_ptr[node];
    const int32_t stop = s.r_off[node + 1];
    while (p < stop && s.used[s.r_edges[p]]) ++p;
    s.r_ptr[node] = p;
    return p < stop ? s.r_edges[p] : -1;
  };

  // walk Euler circuits, assigning alternate halves along each circuit
  for (int64_t e0 = 0; e0 < m; ++e0) {
    if (s.used[e0]) continue;
    int32_t e = static_cast<int32_t>(e0);
    uint8_t take = 1;
    for (;;) {
      s.used[e] = 1;
      s.half[e] = take;
      take ^= 1;
      int32_t nxt = next_r(s.vs[e]);
      if (nxt < 0) break;
      e = nxt;
      s.used[e] = 1;
      s.half[e] = take;
      take ^= 1;
      nxt = next_l(s.us[e]);
      if (nxt < 0) break;
      e = nxt;
    }
  }

  // stable partition ids[lo, hi): half==1 first (Python keeps the
  // mask-True subset first)
  s.ids_tmp.resize(m);
  int64_t w = 0;
  for (int64_t k = 0; k < m; ++k)
    if (s.half[k]) s.ids_tmp[w++] = ids[lo + k];
  const int64_t split = w;
  for (int64_t k = 0; k < m; ++k)
    if (!s.half[k]) s.ids_tmp[w++] = ids[lo + k];
  for (int64_t k = 0; k < m; ++k) ids[lo + k] = s.ids_tmp[k];
  return lo + split;
}

int color_one(const int64_t* u, const int64_t* v, int64_t n, int32_t deg,
              int64_t nside, int32_t* colors, Scratch& s) {
  for (int64_t k = 0; k < n; ++k)
    if (u[k] < 0 || u[k] >= nside || v[k] < 0 || v[k] >= nside)
      return kErrRange;
  s.ids.resize(n);
  for (int64_t k = 0; k < n; ++k) s.ids[k] = static_cast<int32_t>(k);
  // explicit recursion stack of (lo, hi, deg, base)
  struct Frame { int64_t lo, hi; int32_t deg, base; };
  std::vector<Frame> stack;
  stack.push_back({0, n, deg, 0});
  while (!stack.empty()) {
    Frame f = stack.back();
    stack.pop_back();
    if (f.deg == 1) {
      for (int64_t k = f.lo; k < f.hi; ++k) colors[s.ids[k]] = f.base;
      continue;
    }
    const int64_t mid = euler_split(u, v, s.ids.data(), s, f.lo, f.hi,
                                    nside);
    stack.push_back({f.lo, mid, f.deg / 2, f.base});
    stack.push_back({mid, f.hi, f.deg / 2,
                     static_cast<int32_t>(f.base + f.deg / 2)});
  }
  return 0;
}

int color_batched_impl(const int64_t* u, const int64_t* v, int64_t batches,
                       int64_t n, int32_t deg, int64_t nside,
                       int32_t* colors, int32_t n_threads) {
  // nside * deg == n is the regularity contract; rejecting it here also
  // bounds the O(nside) scratch allocations (a huge nside would throw
  // bad_alloc across the extern-C boundary and abort the process)
  if (batches < 0 || n < 0 || n > INT32_MAX || deg <= 0 ||
      (deg & (deg - 1)) != 0 || nside <= 0 || nside * deg != n)
    return kErrBadArg;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 256) n_threads = 256;  // sanity clamp for a bad caller
  if (n_threads <= 1) {
    Scratch s;
    for (int64_t b = 0; b < batches; ++b) {
      const int rc = color_one(u + b * n, v + b * n, n, deg, nside,
                               colors + b * n, s);
      if (rc != 0) return rc;
    }
    return 0;
  }
  // Threaded walk: LEVEL-SYNCHRONOUS frame parallelism.  A "frame" is
  // one (batch, [lo, hi), deg, base) node of the Euler recursion tree;
  // frames of one level touch DISJOINT id/color ranges (siblings are
  // the two halves of their parent's range, batches are disjoint by
  // construction), so any schedule writes the same bytes as the serial
  // stack walk — the split of a range depends only on the range's ids,
  // which the parent fixed before its children exist.  Parallelizing
  // frames (not just batches) matters because the planners' top
  // recursion level is ONE batch (B=1) of the full n: batch-only
  // threading would leave the single biggest coloring serial.
  std::atomic<int> err(0);
  struct Frame { int64_t batch, lo, hi; int32_t deg, base; };
  // per-batch id permutations, shared across threads within a level
  std::vector<std::vector<int32_t>> ids(batches);
  std::vector<Frame> frames;
  frames.reserve(batches);
  for (int64_t b = 0; b < batches; ++b)
    frames.push_back({b, 0, n, deg, 0});

  // per-worker Scratch persists ACROSS levels: the level-0 frame sizes
  // it at O(n) and later levels reuse the capacity instead of paying
  // hundreds of MB of fresh page faults per level
  std::vector<Scratch> scratch(n_threads);
  auto level_parallel = [&](auto&& body, int64_t count) {
    std::atomic<int64_t> next(0);
    auto work = [&](int32_t t) {
      Scratch& s = scratch[t];
      for (;;) {
        const int64_t i = next.fetch_add(1, std::memory_order_relaxed);
        if (i >= count || err.load(std::memory_order_relaxed) != 0) break;
        body(i, s);
      }
    };
    std::vector<std::thread> pool;
    pool.reserve(n_threads - 1);
    for (int32_t t = 1; t < n_threads; ++t) pool.emplace_back(work, t);
    work(0);
    for (auto& th : pool) th.join();
  };

  // level -1: validate + init per-batch ids (O(n) scans, parallel)
  level_parallel([&](int64_t b, Scratch&) {
    const int64_t* ub = u + b * n;
    const int64_t* vb = v + b * n;
    for (int64_t k = 0; k < n; ++k)
      if (ub[k] < 0 || ub[k] >= nside || vb[k] < 0 || vb[k] >= nside) {
        int expected = 0;
        err.compare_exchange_strong(expected, kErrRange);
        return;
      }
    ids[b].resize(n);
    for (int64_t k = 0; k < n; ++k)
      ids[b][k] = static_cast<int32_t>(k);
  }, batches);
  if (err.load() != 0) return err.load();

  std::vector<Frame> children;
  while (!frames.empty()) {
    children.assign(2 * frames.size(), Frame{});
    level_parallel([&](int64_t i, Scratch& s) {
      const Frame& f = frames[i];
      int32_t* bids = ids[f.batch].data();
      int32_t* bcol = colors + f.batch * n;
      if (f.deg == 1) {
        for (int64_t k = f.lo; k < f.hi; ++k) bcol[bids[k]] = f.base;
        children[2 * i] = {f.batch, 0, 0, 0, 0};      // leaf: no children
        children[2 * i + 1] = {f.batch, 0, 0, 0, 0};
        return;
      }
      const int64_t mid = euler_split(u + f.batch * n, v + f.batch * n,
                                      bids, s, f.lo, f.hi, nside);
      children[2 * i] = {f.batch, f.lo, mid, f.deg / 2, f.base};
      children[2 * i + 1] = {f.batch, mid, f.hi, f.deg / 2,
                             static_cast<int32_t>(f.base + f.deg / 2)};
    }, static_cast<int64_t>(frames.size()));
    if (err.load() != 0) return err.load();
    frames.clear();
    for (const Frame& c : children)
      if (c.deg > 0) frames.push_back(c);
  }
  return 0;
}

}  // namespace

extern "C" int lux_route_color_batched(const int64_t* u, const int64_t* v,
                                       int64_t batches, int64_t n,
                                       int32_t deg, int64_t nside,
                                       int32_t* colors) {
  return color_batched_impl(u, v, batches, n, deg, nside, colors, 1);
}

// Threaded entry: identical output bytes for any n_threads (per-B
// sub-problems are independent; see color_batched_impl).
extern "C" int lux_route_color_batched_mt(const int64_t* u, const int64_t* v,
                                          int64_t batches, int64_t n,
                                          int32_t deg, int64_t nside,
                                          int32_t* colors,
                                          int32_t n_threads) {
  return color_batched_impl(u, v, batches, n, deg, nside, colors, n_threads);
}
