"""ctypes bindings to the native I/O library (lux_io.cc).

Auto-builds with `make` on first use if a toolchain is present; every entry
point degrades gracefully to the pure-NumPy path when the library is
unavailable (no compiler, no make), so the framework never hard-depends on
the native layer.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_DIR, "build", "liblux_io.so")
CONVERTER_PATH = os.path.join(_DIR, "build", "lux-convert")

_lib: Optional[ctypes.CDLL] = None
_tried = False
#: guards the one-shot build/bind: the planner fan-out (ops/expand
#: _map_parts) calls get_lib from several worker threads at once, and an
#: unlocked check-then-act here could run the 120 s `make` twice or bind
#: a half-written .so (luxcheck LUX-C001)
_LIB_LOCK = threading.Lock()


def _try_build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR, "all"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def get_lib(build: bool = True) -> Optional[ctypes.CDLL]:
    """The loaded native library, or None if unavailable."""
    if _lib is not None:  # lock-free fast path: a bound lib never changes
        return _lib
    with _LIB_LOCK:
        return _get_lib_locked(build)


def _get_lib_locked(build: bool) -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        # one failed attempt (missing toolchain / failed make) is final for
        # the process — don't re-pay the compile timeout per call
        return None
    # luxcheck: disable=LUX-C001 -- caller get_lib holds _LIB_LOCK
    _tried = True
    if not os.path.exists(_LIB_PATH):
        if not build or not _try_build():
            return None
    elif build:
        # refresh a stale prebuilt .so (make is a no-op when current);
        # failure is fine if the existing lib still has every symbol
        _try_build()
    try:
        lib = _bind(ctypes.CDLL(_LIB_PATH))
    except (OSError, AttributeError):
        # unloadable or stale .so missing a symbol (and make couldn't
        # refresh it): degrade to the NumPy paths, never crash
        return None
    # luxcheck: disable=LUX-C001 -- caller get_lib holds _LIB_LOCK
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.lux_read_header.argtypes = [ctypes.c_char_p, u32p, u64p]
    lib.lux_read_header.restype = ctypes.c_int
    lib.lux_read_rows.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint64, u64p]
    lib.lux_read_rows.restype = ctypes.c_int
    lib.lux_read_cols.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                  ctypes.c_uint64, ctypes.c_uint64, u32p]
    lib.lux_read_cols.restype = ctypes.c_int
    lib.lux_read_weights.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                     ctypes.c_uint64, ctypes.c_uint64,
                                     ctypes.c_uint64, i32p]
    lib.lux_read_weights.restype = ctypes.c_int
    lib.lux_write_from_edges.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                         ctypes.c_uint64, u32p, u32p, i32p]
    lib.lux_write_from_edges.restype = ctypes.c_int
    lib.lux_count_degrees.argtypes = [u32p, ctypes.c_uint64, ctypes.c_uint32,
                                      u32p]
    lib.lux_count_degrees.restype = ctypes.c_int
    lib.lux_bucket_split.argtypes = [u32p, ctypes.c_uint64, u32p,
                                     ctypes.c_uint32, u64p, u64p]
    lib.lux_bucket_split.restype = ctypes.c_int
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.lux_push_part_build.argtypes = [
        i32p, i64p, i32p, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
        u32p, u32p, i32p, i32p, i32p, f32p, u64p,
    ]
    lib.lux_push_part_build.restype = ctypes.c_int
    lib.lux_fill_src_pos.argtypes = [i32p, ctypes.c_uint64, u32p,
                                     ctypes.c_uint32, ctypes.c_uint32, i32p]
    lib.lux_fill_src_pos.restype = ctypes.c_int
    lib.lux_blockcsr_fill.argtypes = [
        i64p, ctypes.c_uint32, i32p, f32p, ctypes.c_uint64,
        ctypes.c_uint32, ctypes.c_uint32, i64p, i32p, i32p, f32p,
    ]
    lib.lux_blockcsr_fill.restype = ctypes.c_int
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.lux_bucket_fill.argtypes = [
        u32p, i64p, i32p, ctypes.c_uint64, ctypes.c_uint32,
        u32p, ctypes.c_uint32, ctypes.c_uint64, i64p, ctypes.c_uint64,
        i32p, i32p, u8p, f32p,
    ]
    lib.lux_bucket_fill.restype = ctypes.c_int
    lib.lux_route_color_batched.argtypes = [
        i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int64, i32p,
    ]
    lib.lux_route_color_batched.restype = ctypes.c_int
    try:
        # threaded colorer (newer .so); a stale prebuilt lib without the
        # symbol keeps every OTHER entry point alive — route_color then
        # degrades to the single-thread call instead of failing the bind
        lib.lux_route_color_batched_mt.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64, i32p, ctypes.c_int32,
        ]
        lib.lux_route_color_batched_mt.restype = ctypes.c_int
    except AttributeError:
        pass
    return lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def read_header(path: str):
    lib = get_lib()
    if lib is None:
        return None
    nv = ctypes.c_uint32()
    ne = ctypes.c_uint64()
    rc = lib.lux_read_header(path.encode(), ctypes.byref(nv), ctypes.byref(ne))
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return int(nv.value), int(ne.value)


def read_range(path: str, nv: int, ne: int, row_lo: int, row_hi: int,
               col_lo: int, col_hi: int, weighted: bool):
    """Partial-range native read (the pull_load_task_impl equivalent).
    Returns (row_end u64[row_hi-row_lo], cols u32, weights i32|None)."""
    lib = get_lib()
    if lib is None:
        return None
    rows = np.empty(row_hi - row_lo, np.uint64)
    cols = np.empty(col_hi - col_lo, np.uint32)
    rc = lib.lux_read_rows(path.encode(), row_lo, row_hi,
                           _ptr(rows, ctypes.c_uint64))
    if rc == 0:
        rc = lib.lux_read_cols(path.encode(), nv, col_lo, col_hi,
                               _ptr(cols, ctypes.c_uint32))
    w = None
    if rc == 0 and weighted:
        w = np.empty(col_hi - col_lo, np.int32)
        rc = lib.lux_read_weights(path.encode(), nv, ne, col_lo, col_hi,
                                  _ptr(w, ctypes.c_int32))
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return rows, cols, w


def write_from_edges(path: str, nv: int, src: np.ndarray, dst: np.ndarray,
                     weights: Optional[np.ndarray] = None) -> bool:
    """Native counting-sort converter; returns False if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return False
    src = np.ascontiguousarray(src, np.uint32)
    dst = np.ascontiguousarray(dst, np.uint32)
    wp = None
    if weights is not None:
        weights = np.ascontiguousarray(weights, np.int32)
        wp = _ptr(weights, ctypes.c_int32)
    rc = lib.lux_write_from_edges(
        path.encode(), nv, len(src), _ptr(src, ctypes.c_uint32),
        _ptr(dst, ctypes.c_uint32), wp,
    )
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return True


def bucket_split(srcs: np.ndarray, cuts: np.ndarray):
    """Stable owner-bucketing of an edge slice (counting sort, native).
    Returns (order int64, counts int64) or None if the lib is unavailable.
    Semantics match np.argsort(searchsorted(cuts, srcs, 'right') - 1,
    kind='stable')."""
    lib = get_lib()
    if lib is None:
        return None
    srcs = np.ascontiguousarray(srcs, np.uint32)
    cuts = np.ascontiguousarray(cuts, np.uint32)
    num_parts = len(cuts) - 1
    order = np.empty(len(srcs), np.uint64)
    counts = np.zeros(num_parts, np.uint64)
    rc = lib.lux_bucket_split(
        _ptr(srcs, ctypes.c_uint32), len(srcs), _ptr(cuts, ctypes.c_uint32),
        num_parts, _ptr(order, ctypes.c_uint64), _ptr(counts, ctypes.c_uint64),
    )
    if rc != 0:
        raise ValueError("source id beyond the last cut")
    return order.astype(np.int64), counts.astype(np.int64)


def push_part_build(srcs: np.ndarray, row_ptr_slice: np.ndarray,
                    weights: Optional[np.ndarray], nv: int,
                    counts: np.ndarray, dst_row: np.ndarray,
                    w_row: Optional[np.ndarray]):
    """Native per-part push-CSR group-by-source (graph/push_shards.py hot
    path).  Writes the CSR-ordered local dst (and weights) into the
    caller's padded rows in place; returns (uniq int32[n_uniq],
    rp int32[n_uniq+1]) or None if the lib is unavailable.  `counts` is
    an nv-sized uint32 scratch that must arrive zeroed and is returned
    zeroed, so one allocation serves every part."""
    lib = get_lib()
    if lib is None:
        return None
    srcs = np.ascontiguousarray(srcs, np.int32)
    row_ptr_slice = np.ascontiguousarray(row_ptr_slice, np.int64)
    assert dst_row.flags.c_contiguous and dst_row.dtype == np.int32
    wp = None
    if weights is not None:
        assert w_row is not None and w_row.flags.c_contiguous
        weights = np.ascontiguousarray(weights, np.int32)
        wp = _ptr(weights, ctypes.c_int32)
    n_e = len(srcs)
    cap_u = max(1, min(n_e, nv))
    touched = np.empty(cap_u, np.uint32)
    uniq = np.empty(cap_u, np.int32)
    rp = np.empty(cap_u + 1, np.int32)
    n_uniq = ctypes.c_uint64()
    rc = lib.lux_push_part_build(
        _ptr(srcs, ctypes.c_int32), _ptr(row_ptr_slice, ctypes.c_int64), wp,
        n_e, len(row_ptr_slice) - 1, nv,
        _ptr(counts, ctypes.c_uint32), _ptr(touched, ctypes.c_uint32),
        _ptr(uniq, ctypes.c_int32), _ptr(rp, ctypes.c_int32),
        _ptr(dst_row, ctypes.c_int32),
        _ptr(w_row, ctypes.c_float) if w_row is not None else None,
        ctypes.byref(n_uniq),
    )
    if rc != 0:
        raise ValueError("inconsistent part slice (src out of range or "
                         "row_ptr/n_e mismatch)")
    nt = int(n_uniq.value)
    return uniq[:nt], rp[: nt + 1]


def fill_src_pos(srcs: np.ndarray, cuts: np.ndarray, nv_pad: int,
                 out_row: np.ndarray):
    """Native gathered-state source-position fill (graph/shards.fill_part
    hot path); writes in place into the caller's row slice.  Returns True,
    or None if the lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    srcs = np.ascontiguousarray(srcs, np.int32)
    cuts = np.ascontiguousarray(cuts, np.uint32)
    assert out_row.flags.c_contiguous and out_row.dtype == np.int32
    rc = lib.lux_fill_src_pos(
        _ptr(srcs, ctypes.c_int32), len(srcs), _ptr(cuts, ctypes.c_uint32),
        len(cuts) - 1, nv_pad, _ptr(out_row, ctypes.c_int32),
    )
    if rc != 0:
        raise ValueError("source id beyond the last cut")
    return True


def blockcsr_fill(row_ptr: np.ndarray, src_pos: np.ndarray,
                  weights: Optional[np.ndarray], v_blk: int, t_chunk: int,
                  chunk_start: np.ndarray, e_src: np.ndarray,
                  e_dst: np.ndarray, e_w: Optional[np.ndarray]):
    """Native block-CSR chunk fill (ops/pallas_spmv.build_blockcsr hot
    path); writes the (C, T) chunk arrays in place.  Returns True, or
    None if the lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    row_ptr = np.ascontiguousarray(row_ptr, np.int64)
    src_pos = np.ascontiguousarray(src_pos, np.int32)
    chunk_start = np.ascontiguousarray(chunk_start, np.int64)
    wp = None
    if weights is not None:
        assert e_w is not None and e_w.dtype == np.float32
        assert e_w.flags.c_contiguous
        weights = np.ascontiguousarray(weights, np.float32)
        wp = _ptr(weights, ctypes.c_float)
    assert e_src.flags.c_contiguous and e_src.dtype == np.int32
    assert e_dst.flags.c_contiguous and e_dst.dtype == np.int32
    rc = lib.lux_blockcsr_fill(
        _ptr(row_ptr, ctypes.c_int64), len(row_ptr) - 1,
        _ptr(src_pos, ctypes.c_int32), wp, len(src_pos), v_blk, t_chunk,
        _ptr(chunk_start, ctypes.c_int64), _ptr(e_src, ctypes.c_int32),
        _ptr(e_dst, ctypes.c_int32),
        _ptr(e_w, ctypes.c_float) if e_w is not None else None,
    )
    if rc != 0:
        raise ValueError("inconsistent row_ptr for block-CSR fill")
    return True


def count_degrees(col_idx: np.ndarray, nv: int):
    """Native out-degree histogram; None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    col = np.ascontiguousarray(col_idx, np.uint32)
    deg = np.zeros(nv, np.uint32)
    rc = lib.lux_count_degrees(_ptr(col, ctypes.c_uint32), len(col), nv,
                               _ptr(deg, ctypes.c_uint32))
    if rc != 0:
        raise ValueError("source id out of range")
    return deg.astype(np.int32)


def bucket_fill(srcs, row_ptr_slice, weights, cuts, B: int,
                row_map, row_stride: int,
                src_flat, dst_flat, hf_flat, w_flat):
    """One-pass owner-bucket fill for the ring/scatter layouts
    (lux_bucket_fill): writes src_local/dst_local/head_flag/weights for
    every materialized bucket of one part slice.  ``*_flat`` are
    C-contiguous flat int32/int32/uint8-view/float32 target views whose
    origin is the part's (or column's) base slot; ``row_map[q]`` is the
    target row for owner q (-1 = skip).  Returns True, or None if the
    lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    srcs = np.asarray(srcs)
    if srcs.size and srcs.dtype.kind in "iu" and (
        int(srcs.max()) >= 2**32 or int(srcs.min()) < 0
    ):
        # ascontiguousarray(.., uint32) would silently wrap a wider or
        # negative id into a VALID bucket; the C error contract is
        # strict everywhere else, so reject here too
        raise ValueError("source id out of uint32 range")
    srcs = np.ascontiguousarray(srcs, np.uint32)
    rp = np.ascontiguousarray(row_ptr_slice, np.int64)
    cuts = np.ascontiguousarray(cuts, np.uint32)
    row_map = np.ascontiguousarray(row_map, np.int64)
    wp = None
    if weights is not None:
        assert w_flat is not None and w_flat.dtype == np.float32
        weights = np.ascontiguousarray(weights, np.int32)
        wp = _ptr(weights, ctypes.c_int32)
    for a, dt in ((src_flat, np.int32), (dst_flat, np.int32),
                  (hf_flat, np.uint8)):
        assert a.dtype == dt and a.flags.c_contiguous, (a.dtype, dt)
    rc = lib.lux_bucket_fill(
        _ptr(srcs, ctypes.c_uint32), _ptr(rp, ctypes.c_int64), wp,
        len(srcs), len(rp) - 1, _ptr(cuts, ctypes.c_uint32),
        len(cuts) - 1, B, _ptr(row_map, ctypes.c_int64), row_stride,
        _ptr(src_flat, ctypes.c_int32), _ptr(dst_flat, ctypes.c_int32),
        _ptr(hf_flat, ctypes.c_uint8),
        _ptr(w_flat, ctypes.c_float) if w_flat is not None else None,
    )
    if rc != 0:
        raise ValueError(f"bucket fill failed (rc={rc}): bad cuts/row_ptr "
                         "or bucket overflow")
    return True


_TLS = threading.local()


def set_thread_share(divisor: int) -> None:
    """Declare that the CURRENT thread is one of ``divisor`` concurrent
    planning workers (thread-local; ops/expand's fan-out sets it).  The
    colorer then takes cores/divisor threads instead of all cores, so
    nested fan-outs (part pool x route overlap x native colorer) divide
    the machine instead of multiplying to O(cores^2) threads."""
    _TLS.divisor = max(1, int(divisor))


def get_thread_share() -> int:
    return getattr(_TLS, "divisor", 1)


def route_threads() -> int:
    """Host-thread count for the batched route colorer: LUX_ROUTE_THREADS
    if set (>=1; garbage or non-positive values raise a clear error at
    the boundary instead of silently running single-threaded through a
    chip window — utils.config.env_int), else every core — divided by
    the current thread's declared planning-worker share
    (set_thread_share).  The per-B Euler walks are independent
    sub-problems, so thread count never changes output bytes — only
    wall-clock (docs/PERF.md plan-build amortization)."""
    from lux_tpu.utils.config import env_int

    base = env_int("LUX_ROUTE_THREADS", minimum=1)
    if base is None:
        base = os.cpu_count() or 1
    return max(1, base // get_thread_share())


def route_color(u: np.ndarray, v: np.ndarray, deg: int, nside: int,
                n_threads: int | None = None):
    """Batched Euler-split edge coloring (Benes route construction).

    u, v: (B, n) int64 endpoint arrays of B independent deg-regular
    bipartite multigraphs (ids in [0, nside)).  Returns (B, n) int32
    colors — each color class a perfect matching — or None when the
    native library is unavailable (caller falls back to the Python
    walk in ops/route.py; colorings may differ, replays agree).

    n_threads (default ``route_threads()``) fans the B independent
    sub-graphs over a native worker pool; the output is bitwise
    identical for every thread count (disjoint slices, per-thread
    scratch).  The ctypes call releases the GIL, so the Python planning
    layer's own executor fan-out (ops/expand._stack_parts) stacks with
    this without oversubscription drama — the atomic work queue just
    drains faster.
    """
    lib = get_lib()
    if lib is None:
        return None
    u = np.ascontiguousarray(u, np.int64)
    v = np.ascontiguousarray(v, np.int64)
    assert u.shape == v.shape and u.ndim == 2, (u.shape, v.shape)
    b, n = u.shape
    colors = np.empty((b, n), np.int32)
    if n_threads is None:
        n_threads = route_threads()
    if n_threads > 1 and hasattr(lib, "lux_route_color_batched_mt"):
        rc = lib.lux_route_color_batched_mt(
            _ptr(u, ctypes.c_int64), _ptr(v, ctypes.c_int64), b, n,
            deg, nside, _ptr(colors, ctypes.c_int32), n_threads)
    else:
        rc = lib.lux_route_color_batched(
            _ptr(u, ctypes.c_int64), _ptr(v, ctypes.c_int64), b, n,
            deg, nside, _ptr(colors, ctypes.c_int32))
    if rc != 0:
        raise ValueError(f"route color failed (rc={rc}): ids out of range "
                         "or deg not a power of two")
    return colors
