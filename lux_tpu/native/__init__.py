"""ctypes bindings to the native I/O library (lux_io.cc).

Auto-builds with `make` on first use if a toolchain is present; every entry
point degrades gracefully to the pure-NumPy path when the library is
unavailable (no compiler, no make), so the framework never hard-depends on
the native layer.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_DIR, "build", "liblux_io.so")
CONVERTER_PATH = os.path.join(_DIR, "build", "lux-convert")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _try_build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR, "all"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def get_lib(build: bool = True) -> Optional[ctypes.CDLL]:
    """The loaded native library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        # one failed attempt (missing toolchain / failed make) is final for
        # the process — don't re-pay the compile timeout per call
        return None
    _tried = True
    if not os.path.exists(_LIB_PATH) and (not build or not _try_build()):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.lux_read_header.argtypes = [ctypes.c_char_p, u32p, u64p]
    lib.lux_read_header.restype = ctypes.c_int
    lib.lux_read_rows.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint64, u64p]
    lib.lux_read_rows.restype = ctypes.c_int
    lib.lux_read_cols.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                  ctypes.c_uint64, ctypes.c_uint64, u32p]
    lib.lux_read_cols.restype = ctypes.c_int
    lib.lux_read_weights.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                     ctypes.c_uint64, ctypes.c_uint64,
                                     ctypes.c_uint64, i32p]
    lib.lux_read_weights.restype = ctypes.c_int
    lib.lux_write_from_edges.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                         ctypes.c_uint64, u32p, u32p, i32p]
    lib.lux_write_from_edges.restype = ctypes.c_int
    lib.lux_count_degrees.argtypes = [u32p, ctypes.c_uint64, ctypes.c_uint32,
                                      u32p]
    lib.lux_count_degrees.restype = ctypes.c_int
    lib.lux_bucket_split.argtypes = [u32p, ctypes.c_uint64, u32p,
                                     ctypes.c_uint32, u64p, u64p]
    lib.lux_bucket_split.restype = ctypes.c_int
    _lib = lib
    return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def read_header(path: str):
    lib = get_lib()
    if lib is None:
        return None
    nv = ctypes.c_uint32()
    ne = ctypes.c_uint64()
    rc = lib.lux_read_header(path.encode(), ctypes.byref(nv), ctypes.byref(ne))
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return int(nv.value), int(ne.value)


def read_range(path: str, nv: int, ne: int, row_lo: int, row_hi: int,
               col_lo: int, col_hi: int, weighted: bool):
    """Partial-range native read (the pull_load_task_impl equivalent).
    Returns (row_end u64[row_hi-row_lo], cols u32, weights i32|None)."""
    lib = get_lib()
    if lib is None:
        return None
    rows = np.empty(row_hi - row_lo, np.uint64)
    cols = np.empty(col_hi - col_lo, np.uint32)
    rc = lib.lux_read_rows(path.encode(), row_lo, row_hi,
                           _ptr(rows, ctypes.c_uint64))
    if rc == 0:
        rc = lib.lux_read_cols(path.encode(), nv, col_lo, col_hi,
                               _ptr(cols, ctypes.c_uint32))
    w = None
    if rc == 0 and weighted:
        w = np.empty(col_hi - col_lo, np.int32)
        rc = lib.lux_read_weights(path.encode(), nv, ne, col_lo, col_hi,
                                  _ptr(w, ctypes.c_int32))
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return rows, cols, w


def write_from_edges(path: str, nv: int, src: np.ndarray, dst: np.ndarray,
                     weights: Optional[np.ndarray] = None) -> bool:
    """Native counting-sort converter; returns False if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return False
    src = np.ascontiguousarray(src, np.uint32)
    dst = np.ascontiguousarray(dst, np.uint32)
    wp = None
    if weights is not None:
        weights = np.ascontiguousarray(weights, np.int32)
        wp = _ptr(weights, ctypes.c_int32)
    rc = lib.lux_write_from_edges(
        path.encode(), nv, len(src), _ptr(src, ctypes.c_uint32),
        _ptr(dst, ctypes.c_uint32), wp,
    )
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return True


def bucket_split(srcs: np.ndarray, cuts: np.ndarray):
    """Stable owner-bucketing of an edge slice (counting sort, native).
    Returns (order int64, counts int64) or None if the lib is unavailable.
    Semantics match np.argsort(searchsorted(cuts, srcs, 'right') - 1,
    kind='stable')."""
    lib = get_lib()
    if lib is None:
        return None
    srcs = np.ascontiguousarray(srcs, np.uint32)
    cuts = np.ascontiguousarray(cuts, np.uint32)
    num_parts = len(cuts) - 1
    order = np.empty(len(srcs), np.uint64)
    counts = np.zeros(num_parts, np.uint64)
    rc = lib.lux_bucket_split(
        _ptr(srcs, ctypes.c_uint32), len(srcs), _ptr(cuts, ctypes.c_uint32),
        num_parts, _ptr(order, ctypes.c_uint64), _ptr(counts, ctypes.c_uint64),
    )
    if rc != 0:
        raise ValueError("source id beyond the last cut")
    return order.astype(np.int64), counts.astype(np.int64)


def count_degrees(col_idx: np.ndarray, nv: int):
    """Native out-degree histogram; None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    col = np.ascontiguousarray(col_idx, np.uint32)
    deg = np.zeros(nv, np.uint32)
    rc = lib.lux_count_degrees(_ptr(col, ctypes.c_uint32), len(col), nv,
                               _ptr(deg, ctypes.c_uint32))
    if rc != 0:
        raise ValueError("source id out of range")
    return deg.astype(np.int32)
