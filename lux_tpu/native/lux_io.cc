// lux_io — native graph I/O for lux_tpu.
//
// Role parity: the reference's offline converter (tools/converter.cc) and
// the per-partition loader task (pull_load_task_impl,
// core/pull_model.inl:253-320) are native C++; this library provides the
// same capabilities for the TPU framework, exposed to Python via ctypes.
//
// Design differences from the reference (not a translation):
//   * counting sort by destination (two O(E) passes) instead of
//     comparison sort — linear time, stable, no temporary edge structs;
//   * partial-range reads use pread64 with explicit offsets so concurrent
//     per-host loaders never share file positions;
//   * all functions return 0 on success / negative errno-style codes, no
//     aborts — error handling belongs to the Python layer.
//
// .lux layout (reference README.md:56-75):
//   u32 nv | u64 ne | u64 row_end[nv] | u32 col_src[ne] | i32 weight[ne]?

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr int64_t kHeaderBytes = 12;

// owner = last q with cuts[q] <= s, or num_parts when s is beyond the
// final cut (callers treat that as -EINVAL)
uint32_t owner_of(uint32_t s, const uint32_t* cuts, uint32_t num_parts) {
  uint32_t lo = 0, hi = num_parts;
  while (lo < hi) {
    uint32_t mid = (lo + hi) / 2;
    if (cuts[mid + 1] <= s) lo = mid + 1; else hi = mid;
  }
  return lo;
}

int64_t file_size(int fd) {
  struct stat st;
  if (fstat(fd, &st) != 0) return -errno;
  return st.st_size;
}

int read_exact(int fd, void* buf, int64_t nbytes, int64_t offset) {
  char* p = static_cast<char*>(buf);
  while (nbytes > 0) {
    ssize_t got = pread(fd, p, static_cast<size_t>(nbytes), offset);
    if (got < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (got == 0) return -EIO;  // truncated file
    p += got;
    offset += got;
    nbytes -= got;
  }
  return 0;
}

int write_exact(int fd, const void* buf, int64_t nbytes) {
  const char* p = static_cast<const char*>(buf);
  while (nbytes > 0) {
    ssize_t put = write(fd, p, static_cast<size_t>(nbytes));
    if (put < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    p += put;
    nbytes -= put;
  }
  return 0;
}

}  // namespace

extern "C" {

// Read the 12-byte header. Returns 0, fills nv/ne.
int lux_read_header(const char* path, uint32_t* nv, uint64_t* ne) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  unsigned char hdr[kHeaderBytes];
  int rc = read_exact(fd, hdr, kHeaderBytes, 0);
  close(fd);
  if (rc != 0) return rc;
  memcpy(nv, hdr, 4);
  memcpy(ne, hdr + 4, 8);
  return 0;
}

// Partial row-offset read: rows [row_lo, row_hi) of the u64 offset array
// (the equivalent of pull_load_task_impl's fseeko+fread of the part's
// row slice). out must hold (row_hi - row_lo) u64s.
int lux_read_rows(const char* path, uint64_t row_lo, uint64_t row_hi,
                  uint64_t* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  int rc = read_exact(fd, out, 8 * (int64_t)(row_hi - row_lo),
                      kHeaderBytes + 8 * (int64_t)row_lo);
  close(fd);
  return rc;
}

// Partial column (edge source) read: edges [col_lo, col_hi).
int lux_read_cols(const char* path, uint32_t nv, uint64_t col_lo,
                  uint64_t col_hi, uint32_t* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  int rc = read_exact(fd, out, 4 * (int64_t)(col_hi - col_lo),
                      kHeaderBytes + 8 * (int64_t)nv + 4 * (int64_t)col_lo);
  close(fd);
  return rc;
}

// Partial weight read; returns -ENODATA if the file has no weight block.
int lux_read_weights(const char* path, uint32_t nv, uint64_t ne,
                     uint64_t col_lo, uint64_t col_hi, int32_t* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  int64_t need = kHeaderBytes + 8 * (int64_t)nv + 4 * (int64_t)ne * 2;
  int64_t sz = file_size(fd);
  if (sz < need) {
    close(fd);
    return -ENODATA;
  }
  int rc = read_exact(fd, out, 4 * (int64_t)(col_hi - col_lo),
                      kHeaderBytes + 8 * (int64_t)nv + 4 * (int64_t)ne
                          + 4 * (int64_t)col_lo);
  close(fd);
  return rc;
}

// Convert an in-memory edge list to CSC and write a .lux file.
// Counting sort by dst: O(E) time, stable (preserves input edge order
// within a destination). weights may be null.
int lux_write_from_edges(const char* path, uint32_t nv, uint64_t ne,
                         const uint32_t* src, const uint32_t* dst,
                         const int32_t* weights) {
  std::vector<uint64_t> row_end(nv, 0);
  for (uint64_t e = 0; e < ne; e++) {
    if (dst[e] >= nv || src[e] >= nv) return -EINVAL;
    row_end[dst[e]]++;
  }
  // exclusive prefix -> insertion cursors; then convert to end offsets
  std::vector<uint64_t> cursor(nv, 0);
  uint64_t run = 0;
  for (uint32_t v = 0; v < nv; v++) {
    cursor[v] = run;
    run += row_end[v];
    row_end[v] = run;
  }
  std::vector<uint32_t> col(ne);
  std::vector<int32_t> wout(weights ? ne : 0);
  for (uint64_t e = 0; e < ne; e++) {
    uint64_t slot = cursor[dst[e]]++;
    col[slot] = src[e];
    if (weights) wout[slot] = weights[e];
  }
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  int rc = 0;
  unsigned char hdr[kHeaderBytes];
  memcpy(hdr, &nv, 4);
  memcpy(hdr + 4, &ne, 8);
  if ((rc = write_exact(fd, hdr, kHeaderBytes)) == 0)
    if ((rc = write_exact(fd, row_end.data(), 8 * (int64_t)nv)) == 0)
      if ((rc = write_exact(fd, col.data(), 4 * (int64_t)ne)) == 0)
        if (weights)
          rc = write_exact(fd, wout.data(), 4 * (int64_t)ne);
  close(fd);
  return rc;
}

// Parse a whitespace text edge list ("src dst [weight]" per line) into
// preallocated arrays; returns the number of edges parsed or a negative
// error. Pass weights == null for unweighted files.
//
// Line-by-line (fgets + sscanf), NOT a stream-wide fscanf: an unweighted
// parse of a 3-column file must ignore the trailing column instead of
// desynchronizing (reading the weight as the next line's src) — this keeps
// the native path consistent with the NumPy fallback, which reads columns
// 0/1 per line. Blank lines and '#' comments are skipped like np.loadtxt.
int64_t lux_parse_edge_text(const char* path, uint64_t cap, uint32_t* src,
                            uint32_t* dst, int32_t* weights) {
  FILE* f = fopen(path, "r");
  if (!f) return -errno;
  uint64_t n = 0;
  char line[512];
  while (fgets(line, sizeof line, f)) {
    // a line longer than the buffer cannot be a valid edge line
    size_t len = strlen(line);
    if (len + 1 == sizeof line && line[len - 1] != '\n') {
      fclose(f);
      return -EINVAL;
    }
    const char* p = line;
    while (*p == ' ' || *p == '\t') p++;
    if (*p == '\0' || *p == '\n' || *p == '\r' || *p == '#') continue;
    if (n >= cap) break;
    unsigned long s, d;
    long w = 0;
    int got = sscanf(p, "%lu %lu %ld", &s, &d, &w);
    if (got < (weights ? 3 : 2)) {
      fclose(f);
      return -EINVAL;
    }
    src[n] = (uint32_t)s;
    dst[n] = (uint32_t)d;
    if (weights) weights[n] = (int32_t)w;
    n++;
  }
  int rc = ferror(f) ? -EIO : 0;
  fclose(f);
  return rc != 0 ? rc : (int64_t)n;
}

// Stable split of one part's edge slice by source-owner part — the host
// hot path of the ring / reduce_scatter / 2-D bucket builders (the role
// the reference's native Graph::Graph partition build plays,
// core/pull_model.inl:105-189, but keyed by source owner).  Counting sort
// with a binary search per edge: O(m log P + m), no comparison sort.
//   order[m]:  stable permutation grouping edge indices by owner
//   counts[P]: edges per owner
int lux_bucket_split(const uint32_t* srcs, uint64_t m, const uint32_t* cuts,
                     uint32_t num_parts, uint64_t* order, uint64_t* counts) {
  memset(counts, 0, 8 * (size_t)num_parts);
  std::vector<uint32_t> owner(m);
  for (uint64_t j = 0; j < m; j++) {
    const uint32_t lo = owner_of(srcs[j], cuts, num_parts);
    if (lo >= num_parts) return -EINVAL;  // src beyond cuts[num_parts]
    owner[j] = lo;
    counts[lo]++;
  }
  std::vector<uint64_t> cursor(num_parts);
  uint64_t run = 0;
  for (uint32_t p = 0; p < num_parts; p++) {
    cursor[p] = run;
    run += counts[p];
  }
  for (uint64_t j = 0; j < m; j++) order[cursor[owner[j]]++] = j;
  return 0;
}

// Per-part push-CSR build: group one part's edge slice by SOURCE vertex
// (stable), emitting the part's sorted unique sources, per-source edge
// offsets, and the CSR-ordered local-destination / weight arrays — the
// host hot path of graph/push_shards.py (the role the reference's
// unique-vertex init kernels play, components_gpu.cu:550-607, built on
// host here because the structure is static per partitioning).
//
// Counting sort keyed by source: two O(E) passes + an O(U log U) sort of
// the touched-source list, replacing the NumPy per-part stable argsort
// (O(E log E) with temporary index arrays).  The counts array is an
// nv-sized caller-owned scratch that must arrive zeroed; it leaves
// zeroed (only touched entries are reset), so one allocation serves all
// parts without O(P * nv) clearing.
//
//   srcs[n_e]:      slice col_idx[row_ptr[vlo] : row_ptr[vhi]]
//   row_ptr[n_v+1]: absolute offsets row_ptr[vlo..vhi]
//   weights[n_e]:   optional (null = unweighted); int32 in, float out
//   counts[nv]:     zeroed scratch (see above)
//   touched[cap_u]: scratch, cap_u >= min(n_e, nv)
//   uniq[cap_u], rp[cap_u+1], dst_out[n_e], w_out[n_e]: outputs;
//     dst_out/w_out are written as rows of the padded (P, e_pad) arrays
//   *n_uniq: number of distinct sources written to uniq/rp
int lux_push_part_build(const int32_t* srcs, const int64_t* row_ptr,
                        const int32_t* weights, uint64_t n_e, uint32_t n_v,
                        uint32_t nv, uint32_t* counts, uint32_t* touched,
                        int32_t* uniq, int32_t* rp, int32_t* dst_out,
                        float* w_out, uint64_t* n_uniq) {
  uint64_t nt = 0;
  for (uint64_t e = 0; e < n_e; e++) {
    const uint32_t s = (uint32_t)srcs[e];
    if (s >= nv) return -EINVAL;
    if (counts[s]++ == 0) touched[nt++] = s;
  }
  std::sort(touched, touched + nt);
  // prefix the sorted counts into rp; repurpose counts[] as insertion
  // cursors for the scatter pass
  uint32_t off = 0;
  rp[0] = 0;
  for (uint64_t i = 0; i < nt; i++) {
    const uint32_t s = touched[i];
    uniq[i] = (int32_t)s;
    const uint32_t c = counts[s];
    counts[s] = off;
    off += c;
    rp[i + 1] = (int32_t)off;
  }
  // scatter edges to their CSR slots, walking row_ptr so each edge's
  // part-local destination comes from its position in the slice (the
  // slice is dst-grouped CSC order, so this pass is also stable)
  const int64_t base = row_ptr[0];
  uint64_t e = 0;
  for (uint32_t v = 0; v < n_v; v++) {
    const uint64_t hi = (uint64_t)(row_ptr[v + 1] - base);
    if (hi > n_e) return -EINVAL;
    for (; e < hi; e++) {
      const uint32_t pos = counts[(uint32_t)srcs[e]]++;
      dst_out[pos] = (int32_t)v;
      if (weights) w_out[pos] = (float)weights[e];
    }
  }
  if (e != n_e) return -EINVAL;  // row_ptr slice inconsistent with n_e
  for (uint64_t i = 0; i < nt; i++) counts[touched[i]] = 0;
  *n_uniq = nt;
  return 0;
}

// Gathered-state source positions for one part's edge slice: for source
// s owned by part q (cuts[q] <= s < cuts[q+1]),
//   src_pos = q * nv_pad + (s - cuts[q])
// — the pull layout's padded all-gather addressing (graph/shards.py
// fill_part).  One O(m log P) pass in int32, replacing NumPy's
// searchsorted + int64 owner/offset temporaries (3 full-size
// intermediates on the host build hot path).
int lux_fill_src_pos(const int32_t* srcs, uint64_t m, const uint32_t* cuts,
                     uint32_t num_parts, uint32_t nv_pad, int32_t* out) {
  for (uint64_t j = 0; j < m; j++) {
    const uint32_t s = (uint32_t)srcs[j];
    const uint32_t lo = owner_of(s, cuts, num_parts);
    if (lo >= num_parts) return -EINVAL;
    out[j] = (int32_t)(lo * nv_pad + (s - cuts[lo]));
  }
  return 0;
}

// Block-CSR chunk fill for the Pallas kernel layout
// (ops/pallas_spmv.build_blockcsr): every edge lands at
//   flat = (chunk_start[block(dst)] + within/t_chunk) * t_chunk
//          + within % t_chunk
// where within = e - row_ptr[block_base].  One sequential O(ne) pass
// walking row_ptr (dst is implied by CSC position — never
// materialized), replacing the NumPy build's four O(ne) int64
// temporaries + three flat scatters.  Within a block the layout is
// slice-ordered, so the pass is forward-only and cache-friendly.
//   row_ptr[nv+1], src_pos[ne], w[ne] (nullable, pre-cast f32)
//   chunk_start[num_vblocks]: first chunk id of each vertex block
//   e_src[C*T] (pre-zeroed), e_dst[C*T] (pre-filled v_blk), e_w[C*T]
int lux_blockcsr_fill(const int64_t* row_ptr, uint32_t nv,
                      const int32_t* src_pos, const float* w, uint64_t ne,
                      uint32_t v_blk, uint32_t t_chunk,
                      const int64_t* chunk_start, int32_t* e_src,
                      int32_t* e_dst, float* e_w) {
  for (uint32_t v = 0; v < nv; v++) {
    const uint32_t b = v / v_blk;
    const int64_t block_lo = row_ptr[(uint64_t)b * v_blk];  // <= v < nv
    const int32_t dst_rel = (int32_t)(v - b * v_blk);
    const uint64_t lo = (uint64_t)row_ptr[v], hi = (uint64_t)row_ptr[v + 1];
    if (hi > ne || lo > hi) return -EINVAL;
    for (uint64_t e = lo; e < hi; e++) {
      const uint64_t within = e - (uint64_t)block_lo;
      const uint64_t flat =
          ((uint64_t)chunk_start[b] + within / t_chunk) * t_chunk
          + within % t_chunk;
      e_src[flat] = src_pos[e];
      e_dst[flat] = dst_rel;
      if (w) e_w[flat] = w[e];
    }
  }
  return 0;
}

// Out-degree histogram over an edge-source array (the native equivalent of
// pull_scan_task_impl's degree count, core/pull_model.inl:322-345).
int lux_count_degrees(const uint32_t* col, uint64_t ne, uint32_t nv,
                      uint32_t* degrees) {
  memset(degrees, 0, 4 * (int64_t)nv);
  for (uint64_t e = 0; e < ne; e++) {
    if (col[e] >= nv) return -EINVAL;
    degrees[col[e]]++;
  }
  return 0;
}

// One part-slice pass filling ALL owner buckets of the ring /
// reduce_scatter layouts (src_local, dst_local, head_flag, weights) —
// replaces the per-bucket Python fancy-indexing loop (O(P) array
// round-trips per part; 50-139 s at rmat24/P=16) with a single O(slice)
// scatter.  Works for both layouts via row_map/row_stride:
//   ring:    row_map[q] = q,                row_stride = B   (one (P,B) block)
//   scatter: row_map[q] = host row or -1,   row_stride = P*B (column p of the
//            (R,P,B) stack; caller offsets the base pointers by p*B)
// Edges arrive CSC-ordered (by destination); the stable per-owner cursor
// preserves that order inside each bucket, so head flags are computed on
// the fly against the bucket's previous destination.  The first padding
// slot of every materialized bucket is head-flagged (the
// segment_reduce_by_ends end-marker contract, parallel/ring.py
// mark_bucket_heads).  Outputs must arrive pre-padded (dst_local = V,
// src_local/weights = 0) — only real slots and the one pad flag are
// written.
int lux_bucket_fill(const uint32_t* srcs, const int64_t* row_ptr,
                    const int32_t* weights_in, uint64_t n_e, uint32_t n_v,
                    const uint32_t* cuts, uint32_t num_parts, uint64_t B,
                    const int64_t* row_map, uint64_t row_stride,
                    int32_t* src_local, int32_t* dst_local,
                    uint8_t* head_flag, float* w_out) {
  std::vector<uint64_t> cursor(num_parts, 0);
  std::vector<int32_t> prev(num_parts, -1);
  const int64_t base = row_ptr[0];
  uint64_t e = 0;
  for (uint32_t v = 0; v < n_v; v++) {
    const int64_t hi64 = row_ptr[v + 1] - base;
    if (hi64 < (int64_t)e || (uint64_t)hi64 > n_e) return -EINVAL;
    for (const uint64_t hi = (uint64_t)hi64; e < hi; e++) {
      const uint32_t s = srcs[e];
      const uint32_t q = owner_of(s, cuts, num_parts);
      if (q >= num_parts) return -EINVAL;
      const int64_t row = row_map[q];
      if (row < 0) continue;  // bucket not materialized on this host
      const uint64_t c = cursor[q]++;
      if (c >= B) return -EOVERFLOW;
      const size_t at = (size_t)row * row_stride + c;
      src_local[at] = (int32_t)(s - cuts[q]);
      dst_local[at] = (int32_t)v;
      head_flag[at] = (c == 0) || (prev[q] != (int32_t)v);
      prev[q] = (int32_t)v;
      if (weights_in) w_out[at] = (float)weights_in[e];
    }
  }
  if (e != n_e) return -EINVAL;
  for (uint32_t q = 0; q < num_parts; q++) {
    const int64_t row = row_map[q];
    if (row >= 0 && cursor[q] < B)
      head_flag[(size_t)row * row_stride + cursor[q]] = 1;
  }
  return 0;
}

}  // extern "C"
