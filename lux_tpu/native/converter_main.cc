// lux-convert — text edge list -> .lux CSC binary.
//
// CLI parity with the reference converter (tools/converter.cc): flags
// -nv, -ne, -input, -output, plus -weighted (the reference has no weighted
// converter path; weighted .lux files come pre-built).  Implementation is
// the counting-sort pipeline in lux_io.cc, not a comparison sort.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
int lux_write_from_edges(const char*, uint32_t, uint64_t, const uint32_t*,
                         const uint32_t*, const int32_t*);
int64_t lux_parse_edge_text(const char*, uint64_t, uint32_t*, uint32_t*,
                            int32_t*);
}

int main(int argc, char** argv) {
  uint32_t nv = 0;
  uint64_t ne = 0;
  const char* input = nullptr;
  const char* output = nullptr;
  bool weighted = false;
  for (int i = 1; i < argc; i++) {
    if (!strcmp(argv[i], "-nv") && i + 1 < argc) nv = strtoul(argv[++i], 0, 10);
    else if (!strcmp(argv[i], "-ne") && i + 1 < argc)
      ne = strtoull(argv[++i], 0, 10);
    else if (!strcmp(argv[i], "-input") && i + 1 < argc) input = argv[++i];
    else if (!strcmp(argv[i], "-output") && i + 1 < argc) output = argv[++i];
    else if (!strcmp(argv[i], "-weighted")) weighted = true;
    else {
      fprintf(stderr, "unknown flag: %s\n", argv[i]);
      return 2;
    }
  }
  if (!nv || !ne || !input || !output) {
    fprintf(stderr,
            "usage: lux-convert -nv N -ne M -input edges.txt -output g.lux "
            "[-weighted]\n");
    return 2;
  }
  std::vector<uint32_t> src(ne), dst(ne);
  std::vector<int32_t> w(weighted ? ne : 0);
  int64_t got = lux_parse_edge_text(input, ne, src.data(), dst.data(),
                                    weighted ? w.data() : nullptr);
  if (got < 0) {
    fprintf(stderr, "parse failed: %s\n", strerror((int)-got));
    return 1;
  }
  if ((uint64_t)got != ne) {
    fprintf(stderr, "expected %llu edges, parsed %lld\n",
            (unsigned long long)ne, (long long)got);
    return 1;
  }
  int rc = lux_write_from_edges(output, nv, ne, src.data(), dst.data(),
                                weighted ? w.data() : nullptr);
  if (rc != 0) {
    fprintf(stderr, "write failed: %s\n", strerror(-rc));
    return 1;
  }
  printf("wrote %s: nv=%u ne=%llu%s\n", output, nv, (unsigned long long)ne,
         weighted ? " (weighted)" : "");
  return 0;
}
