// Sanitizer check driver for the native layer (no Python in the loop).
//
// Built three ways by the Makefile — `make tsan` / `make asan` /
// `make ubsan` — and run by tests/test_native.py (slow tier) and
// tools/ci_check.sh.  A sanitizer report makes the process exit
// non-zero (TSan's default exitcode, ASan's abort, UBSan with
// -fno-sanitize-recover), so "rc == 0" IS "zero reports"; the driver
// additionally asserts BITWISE equality between the serial and
// multithreaded colorers, so the run re-proves PR 2's determinism
// contract while TSan watches every byte of it.
//
// Why a standalone binary instead of LD_PRELOADing libtsan under
// pytest: sanitizer runtimes must be loaded before any instrumented
// code, which for a ctypes-loaded .so means preloading into the Python
// interpreter — fragile across libc/sanitizer versions and noisy with
// CPython's own allocations.  A self-contained driver gives a clean
// zero-report baseline.
//
// Modes: `route` (the multithreaded Euler colorer), `io` (the .lux
// write/read/bucket paths), `all` (default).

#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <unistd.h>

extern "C" {
int lux_read_header(const char* path, uint32_t* nv, uint64_t* ne);
int lux_read_rows(const char* path, uint64_t row_lo, uint64_t row_hi,
                  uint64_t* out);
int lux_read_cols(const char* path, uint32_t nv, uint64_t col_lo,
                  uint64_t col_hi, uint32_t* out);
int lux_read_weights(const char* path, uint32_t nv, uint64_t ne,
                     uint64_t col_lo, uint64_t col_hi, int32_t* out);
int lux_write_from_edges(const char* path, uint32_t nv, uint64_t ne,
                         const uint32_t* src, const uint32_t* dst,
                         const int32_t* weights);
int lux_count_degrees(const uint32_t* col, uint64_t ne, uint32_t nv,
                      uint32_t* deg);
int lux_bucket_split(const uint32_t* srcs, uint64_t m,
                     const uint32_t* cuts, uint32_t num_parts,
                     uint64_t* order, uint64_t* counts);
int lux_route_color_batched(const int64_t* u, const int64_t* v,
                            int64_t batches, int64_t n, int32_t deg,
                            int64_t nside, int32_t* colors);
int lux_route_color_batched_mt(const int64_t* u, const int64_t* v,
                               int64_t batches, int64_t n, int32_t deg,
                               int64_t nside, int32_t* colors,
                               int32_t n_threads);
}

namespace {

int failures = 0;

#define CHECK(cond, ...)                                          \
  do {                                                            \
    if (!(cond)) {                                                \
      std::fprintf(stderr, "CHECK failed (%s:%d): ", __FILE__,    \
                   __LINE__);                                     \
      std::fprintf(stderr, __VA_ARGS__);                          \
      std::fprintf(stderr, "\n");                                 \
      ++failures;                                                 \
    }                                                             \
  } while (0)

// Deterministic LCG (no libc rand: reproducible across libcs, and the
// serial-vs-threaded comparison needs identical inputs every build).
struct Lcg {
  uint64_t s;
  explicit Lcg(uint64_t seed) : s(seed) {}
  uint64_t next() {
    s = s * 6364136223846793005ULL + 1442695040888963407ULL;
    return s >> 17;
  }
};

// One deg-regular bipartite multigraph: u = each left id deg times,
// v = a Fisher-Yates shuffle of the same multiset.
void make_regular(int64_t nside, int32_t deg, uint64_t seed,
                  std::vector<int64_t>& u, std::vector<int64_t>& v) {
  const int64_t n = nside * deg;
  u.resize(n);
  v.resize(n);
  for (int64_t i = 0; i < nside; ++i)
    for (int32_t d = 0; d < deg; ++d) u[i * deg + d] = v[i * deg + d] = i;
  Lcg rng(seed);
  for (int64_t i = n - 1; i > 0; --i) {
    const int64_t j = static_cast<int64_t>(rng.next() % (i + 1));
    std::swap(v[i], v[j]);
  }
}

// Every color class of a valid deg-coloring is a perfect matching:
// each side id appears exactly once per color.
void check_matching(const int64_t* u, const int64_t* v,
                    const int32_t* colors, int64_t n, int32_t deg,
                    int64_t nside) {
  std::vector<int32_t> seen_u(deg * nside, 0), seen_v(deg * nside, 0);
  for (int64_t k = 0; k < n; ++k) {
    const int32_t c = colors[k];
    CHECK(c >= 0 && c < deg, "color %d out of range", c);
    if (c < 0 || c >= deg) return;
    CHECK(++seen_u[c * nside + u[k]] == 1,
          "left id %" PRId64 " repeated in color %d", u[k], c);
    CHECK(++seen_v[c * nside + v[k]] == 1,
          "right id %" PRId64 " repeated in color %d", v[k], c);
  }
}

void run_route_case(int64_t batches, int64_t nside, int32_t deg,
                    uint64_t seed) {
  const int64_t n = nside * deg;
  std::vector<int64_t> u(batches * n), v(batches * n);
  for (int64_t b = 0; b < batches; ++b) {
    std::vector<int64_t> ub, vb;
    make_regular(nside, deg, seed + 77 * b, ub, vb);
    std::memcpy(u.data() + b * n, ub.data(), n * sizeof(int64_t));
    std::memcpy(v.data() + b * n, vb.data(), n * sizeof(int64_t));
  }
  std::vector<int32_t> serial(batches * n), threaded(batches * n);
  CHECK(lux_route_color_batched(u.data(), v.data(), batches, n, deg,
                                nside, serial.data()) == 0,
        "serial colorer failed");
  for (int32_t nt : {2, 3, 8}) {
    std::fill(threaded.begin(), threaded.end(), -1);
    CHECK(lux_route_color_batched_mt(u.data(), v.data(), batches, n, deg,
                                     nside, threaded.data(), nt) == 0,
          "threaded colorer failed (nt=%d)", nt);
    CHECK(std::memcmp(serial.data(), threaded.data(),
                      serial.size() * sizeof(int32_t)) == 0,
          "BITWISE MISMATCH serial vs %d threads (B=%" PRId64
          " nside=%" PRId64 " deg=%d)",
          nt, batches, nside, deg);
  }
  for (int64_t b = 0; b < batches; ++b)
    check_matching(u.data() + b * n, v.data() + b * n,
                   serial.data() + b * n, n, deg, nside);
  std::printf("route ok: B=%" PRId64 " nside=%" PRId64 " deg=%d x{2,3,8} "
              "threads bitwise == serial\n", batches, nside, deg);
}

void run_route() {
  // many small batches: batch-level parallelism + work-queue contention
  run_route_case(/*batches=*/6, /*nside=*/2048, /*deg=*/8, 1234);
  // ONE big batch: level-synchronous FRAME parallelism (the planner's
  // real shape — the top recursion level is a single coloring)
  run_route_case(/*batches=*/1, /*nside=*/8192, /*deg=*/16, 99);
}

void run_io() {
  const uint32_t nv = 300;
  const uint64_t ne = 4000;
  std::vector<uint32_t> src(ne), dst(ne);
  std::vector<int32_t> w(ne);
  Lcg rng(42);
  for (uint64_t e = 0; e < ne; ++e) {
    src[e] = static_cast<uint32_t>(rng.next() % nv);
    dst[e] = static_cast<uint32_t>(rng.next() % nv);
    w[e] = static_cast<int32_t>(rng.next() % 100) + 1;
  }
  std::string path = "/tmp/lux_sanitize_check_" +
                     std::to_string(static_cast<long>(getpid())) + ".lux";
  CHECK(lux_write_from_edges(path.c_str(), nv, ne, src.data(), dst.data(),
                             w.data()) == 0, "write_from_edges failed");
  uint32_t nv2 = 0;
  uint64_t ne2 = 0;
  CHECK(lux_read_header(path.c_str(), &nv2, &ne2) == 0, "read_header");
  CHECK(nv2 == nv && ne2 == ne, "header mismatch %u %" PRIu64, nv2, ne2);
  std::vector<uint64_t> rows(nv);
  CHECK(lux_read_rows(path.c_str(), 0, nv, rows.data()) == 0, "read_rows");
  CHECK(rows[nv - 1] == ne, "last row_end %" PRIu64, rows[nv - 1]);
  // partial reads at awkward offsets (the pread64 paths)
  std::vector<uint32_t> cols(ne);
  CHECK(lux_read_cols(path.c_str(), nv, 7, ne - 3, cols.data()) == 0,
        "read_cols partial");
  std::vector<int32_t> wback(ne);
  CHECK(lux_read_weights(path.c_str(), nv, ne, 7, ne - 3,
                         wback.data()) == 0, "read_weights partial");
  CHECK(lux_read_cols(path.c_str(), nv, 0, ne, cols.data()) == 0,
        "read_cols full");
  std::vector<uint32_t> deg(nv, 0);
  CHECK(lux_count_degrees(cols.data(), ne, nv, deg.data()) == 0,
        "count_degrees");
  uint64_t total = 0;
  for (uint32_t i = 0; i < nv; ++i) total += deg[i];
  CHECK(total == ne, "degree sum %" PRIu64, total);
  const uint32_t cuts[] = {0, 100, 100, 256, nv};
  std::vector<uint64_t> order(ne), counts(4, 0);
  CHECK(lux_bucket_split(src.data(), ne, cuts, 4, order.data(),
                         counts.data()) == 0, "bucket_split");
  total = 0;
  for (int q = 0; q < 4; ++q) total += counts[q];
  CHECK(total == ne, "bucket counts sum %" PRIu64, total);
  std::remove(path.c_str());
  std::printf("io ok: nv=%u ne=%" PRIu64 " roundtrip + partial reads + "
              "buckets\n", nv, ne);
}

}  // namespace

int main(int argc, char** argv) {
  const std::string mode = argc > 1 ? argv[1] : "all";
  if (mode == "route" || mode == "all") run_route();
  if (mode == "io" || mode == "all") run_io();
  if (failures) {
    std::fprintf(stderr, "sanitize_check: %d failure(s)\n", failures);
    return 1;
  }
  std::printf("sanitize_check: all clean (%s)\n", mode.c_str());
  return 0;
}
