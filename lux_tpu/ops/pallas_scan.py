"""mxscan: MXU-resident blocked segmented inclusive scan (ISSUE 11).

The float segmented sums of both engines bottom out in
``lax.associative_scan`` on the VPU (ops/segment.py "scan") — a
log-depth elementwise ladder whose every level re-materializes the full
edge array.  arXiv:2505.15112's blocked systolic scan is the scan-side
twin of mxreduce's one-hot reduction (arXiv:1811.09736, ISSUE 7): tile
the array into 128-lane rows, compute each row's inclusive prefix as a
triangular matmul on the MXU, and propagate one carried offset through
the sequential grid.  Segment restarts fold into the SAME contraction
by masking the triangular operand with the head flags, so
``segment_*_csc`` consume the scanned array through the unchanged
ends-gather — no separate correction pass.

Mechanics, per (tile_rows, 128) VMEM tile of the flattened edge array
(one Pallas kernel, one HBM read of the values + one write of the
scanned array — the floor the VPU ladder's "2 sweeps" accounting only
aspires to):

  * the packed head/pad byte tile is split into head flags ``h`` and the
    padding mask, and the tile-wide inclusive head COUNT is one
    ``(B, 128) x (128, 128)`` triangular matmul (counts <= 128: exact in
    f32);
  * per 128-lane row, the segmented-scan operand
    ``M[i, j] = (j <= i) & (c[j] == c[i])`` is the triangular operand
    masked by the head flags (equal inclusive head counts == same
    segment; ``c`` is monotone, so the mask is two broadcast compares);
  * float sums contract ``row @ M^T`` on the MXU — bf16 operands only
    where exact (M is 0/1; bf16 values are already bf16; f32 values stay
    f32), f32 accumulation ALWAYS, one rounding to the value dtype at
    the tile write;
  * min/max and INTEGER sums never touch the MXU: the same masked
    layout reduces on the VPU, dtype-preserving and BITWISE equal to
    the ladder scan (order-insensitive combiners);
  * the inter-tile offset (the running value of the segment left open at
    a tile boundary) lives in a (1, 1) VMEM scratch carried across the
    sequential grid — reset at ``program_id(0) == 0``, so the kernel
    vmaps over parts unchanged (the batch grid dim is prepended and the
    tile axis keeps its program_id).

Precision caveat (shared with mxreduce's contraction): the float-sum
matmul multiplies EVERY in-row value by its 0/1 mask entry, so a
non-finite value poisons its whole row (0 * Inf = NaN).  Padding slots
are neutralized in-kernel via the packed pad bit; real values must be
finite — true of every shipped sum program (pagerank ranks, CF errors).
min/max keep Inf semantics exactly (masked select, no multiply).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from lux_tpu.ops.pallas_shuffle import (LANE, _compiler_params,
                                        _mx_neutral)

#: packed head/pad byte: bit 0 = segment head, bit 1 = padding slot
HEAD_BIT = 1
PAD_BIT = 2


def _mxscan_defaults(tile_rows=None) -> int:
    """Scan-tile rows (LUX_MXSCAN_TILE_ROWS, default 8): rows per kernel
    tile — the kernel unrolls one masked contraction per row, exactly
    like mxreduce's LUX_MX_TILE_ROWS.  Read at TRACE time and baked into
    the compiled program, never at replay."""
    from lux_tpu.utils.config import env_int

    if tile_rows is None:
        tile_rows = env_int("LUX_MXSCAN_TILE_ROWS", 8, minimum=1,
                            maximum=256)
    if tile_rows & (tile_rows - 1):
        raise ValueError(
            f"LUX_MXSCAN_TILE_ROWS must be a power of two (tile and pad "
            f"geometry divide each other), got {tile_rows}")
    return tile_rows


def _scan_kernel(op: str, tb: int, x_ref, hv_ref, o_ref, carry_ref):
    """One (tb, 128) tile: masked triangular intra-row scan + the carried
    inter-tile offset.  ``carry_ref`` is (1, 1) VMEM scratch holding the
    scanned value at the end of the previous row/tile."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    x = x_ref[:]
    float_sum = op == "sum" and jnp.issubdtype(x.dtype, jnp.floating)
    neutral = _mx_neutral(op, x.dtype)

    @pl.when(i == 0)
    def _():
        carry_ref[:, :] = jnp.full_like(
            carry_ref, _mx_neutral(op, carry_ref.dtype))

    hv = hv_ref[:].astype(jnp.int32)
    h = (hv & HEAD_BIT).astype(jnp.float32)
    pad = (hv & PAD_BIT) != 0
    # neutralize padding BEFORE any contraction: junk pad values may be
    # Inf/NaN and 0 * NaN = NaN would poison the row's matmul (the same
    # rule as mxreduce's sentinel masking)
    xm = jnp.where(pad, neutral, x)
    # inclusive head count per row: C[r, i] = sum_{j<=i} h[r, j] — ONE
    # (tb, 128) x (128, 128) triangular matmul, exact in f32 (<= 128)
    io0 = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 0)
    io1 = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
    tri_incl = (io0 <= io1).astype(jnp.float32)  # tri[j, i] = j <= i
    c = jax.lax.dot_general(
        h, tri_incl, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (tb, 128): C[r, i] = sum_j h[r, j] * (j <= i)
    ct = jnp.transpose(c)          # (128, tb): c[j] addressable per row
    if not float_sum:
        xt = jnp.transpose(xm)     # (128, tb): values as columns
    cd = jnp.bfloat16 if x.dtype == jnp.bfloat16 else jnp.float32
    carry = carry_ref[:, :]
    for r in range(tb):
        c_row = c[r:r + 1, :]          # (1, 128): c[i] along lanes
        c_col = ct[:, r:r + 1]         # (128, 1): c[j] along sublanes
        no_head = c_row == 0.0         # carry applies before row's 1st head
        if float_sum:
            # M[i, j] = (j <= i) & (c[j] >= c[i]): the triangular operand
            # masked by the head flags (c monotone: >= on j <= i <=> ==)
            m = (io1 <= io0) & (c_row >= c_col)
            y = jax.lax.dot_general(
                xm[r:r + 1, :].astype(cd), m.astype(cd),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (1, 128): y[0, i] = sum_j x[j] * M[i, j], f32 accumulate
            y = y + jnp.where(no_head, carry, jnp.float32(0.0))
        else:
            # masked VPU layout: Mt[j, i] = (j <= i) & (c[j] >= c[i])
            mt = (io0 <= io1) & (c_col >= c_row)
            masked = jnp.where(mt, xt[:, r:r + 1], neutral)
            red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[op]
            y = red(masked, axis=0, keepdims=True)  # (1, 128)
            if op == "sum":
                y = y + jnp.where(no_head, carry,
                                  jnp.zeros((), x.dtype))
            elif op == "min":
                y = jnp.where(no_head, jnp.minimum(y, carry), y)
            else:
                y = jnp.where(no_head, jnp.maximum(y, carry), y)
        carry = y[:, LANE - 1:LANE]
        o_ref[r:r + 1, :] = y.astype(o_ref.dtype)
    carry_ref[:, :] = carry


@functools.partial(jax.jit,
                   static_argnames=("op", "tile_rows", "interpret"))
def mxscan_segmented(vals, head_flag, invalid, op: str = "sum",
                     tile_rows: int | None = None,
                     interpret: bool | None = None):
    """Segmented inclusive scan of ``vals`` (E,) with restarts at
    ``head_flag`` slots — the drop-in for ``ops.segment._segmented_scan``
    on 1-D values.  ``invalid`` (E,) bool marks padding slots whose
    values must be neutralized in-kernel (csc callers: slot index >=
    row_ptr[-1]; bucketed callers: dst_local == num_segments); invalid
    slots' OUTPUTS are unspecified, exactly like the ladder scan's
    padding outputs, and are never read by the ends gathers.

    Returns the scanned array in ``vals.dtype`` (float sums accumulate
    in f32 and round once per tile row on the way out).
    """
    if op not in ("sum", "min", "max"):
        raise ValueError(f"mxscan op must be sum|min|max, got {op!r}")
    if vals.ndim != 1:
        raise ValueError(
            "mxscan_segmented is a 1-D kernel; (E, K)-valued reductions "
            "keep the VPU scan (ops/segment dispatches the fallback)")
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tb = _mxscan_defaults(tile_rows)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    n = vals.shape[0]
    if n == 0:
        return vals
    hv = (head_flag.astype(jnp.uint8) * jnp.uint8(HEAD_BIT)  # luxcheck: disable=LUX-P003 -- flag BYTE (values 0-3), a mask operand never used as a gather index
          | invalid.astype(jnp.uint8) * jnp.uint8(PAD_BIT))  # luxcheck: disable=LUX-P003 -- same packed flag byte, second bit
    unit = tb * LANE
    padn = (-n) % unit
    if padn:
        vals = jnp.pad(vals, (0, padn))
        hv = jnp.pad(hv, (0, padn), constant_values=PAD_BIT)
    rows = vals.shape[0] // LANE
    x2 = vals.reshape(rows, LANE)
    hv2 = hv.reshape(rows, LANE)
    float_sum = op == "sum" and jnp.issubdtype(vals.dtype, jnp.floating)
    carry_dtype = jnp.float32 if float_sum else vals.dtype
    spec = pl.BlockSpec((tb, LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_scan_kernel, op, tb),
        grid=(rows // tb,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x2.shape, vals.dtype),
        scratch_shapes=[pltpu.VMEM((1, 1), carry_dtype)],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(x2, hv2)
    out = out.reshape(-1)
    return out[:n] if padn else out


def mxscan_residency_bytes(tile_rows: int, val_bytes: int = 4) -> int:
    """VMEM residency of one mxscan kernel instance — the LUX-J4 ledger
    entry (analysis/ir/vmem.check_vmem_mxscan).  Streamed operands
    double-buffer through the Pallas pipeline: the value tile (in + out)
    and the packed head/pad byte tile; on top live the tile-wide head
    count and its transpose (f32), the transposed value tile (VPU
    path bound), the per-row (128, 128) masked triangular operand plus
    its compare/select twin, and the (1, 1) carry."""
    tile = 2 * tile_rows * LANE * (2 * val_bytes + 1)
    counts = 2 * tile_rows * LANE * 4          # C + C^T, f32
    xt = tile_rows * LANE * val_bytes          # transposed values
    masks = 2 * LANE * LANE * 4                # iota/compare + masked op
    return tile + counts + xt + masks + 8
