"""Pallas TPU kernel: fused segmented reduction over CSC edge blocks.

The hot loop of every pull iteration is gather(state) -> reduce-by-dst —
the role of the reference's pr_kernel block-scan edge sweep
(pagerank_gpu.cu:49-102).  XLA's options are a scatter-add (serializes on
TPU) or a log-depth segmented scan (multiple passes over the edge array).
This kernel does it in ONE pass using the MXU:

  * edges are re-laid out on the host into the static "block-CSR" form:
    each VERTEX block's edge span is padded to a multiple of the chunk
    size T, so every grid step i processes edge chunk i and accumulates
    into exactly one output vertex block (``chunk_block[i]``, a prefetched
    scalar that routes the output BlockSpec);
  * inside a chunk, reduction-by-destination is a one-hot contraction:
    onehot[v, t] = (dst_rel[t] == v), contrib = onehot @ vals — an
    (V_BLK, T) x (T, 1) matmul on the systolic array instead of atomics;
  * the grid is sequential ("arbitrary"), so chunks of the same vertex
    block accumulate in VMEM; ``chunk_first`` zero-initializes each block.

The gather itself (vals = state[src_pos]) stays in XLA where the HLO
gather is already efficient — Mosaic has no vector gather primitive.

min/max variants use a masked VPU reduce over the same one-hot mask
(no matmul identity for min), same layout.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.graph.csc import HostGraph
from lux_tpu.ops.pallas_shuffle import _compiler_params

V_BLK = 512  # output vertex block (lanes: multiple of 128)
T_CHUNK = 512  # edges per grid step


def _round_up(x, m):
    return -(-x // m) * m


@dataclasses.dataclass
class BlockCSR:
    """Host-precomputed static block-CSR layout for one part.

    Arrays:
      e_src_pos: (C, T) int32   gather positions (padding -> 0)
      e_dst_rel: (C, T) int32   dst - block_base, in [0, V_BLK); padding
                                holds V_BLK (matches no one-hot row)
      e_weight:  (C, T) float32 | None — only for weighted graphs
      chunk_block: (C,) int32   output vertex-block of each chunk
      chunk_first: (C,) int32   1 on the first chunk of each block
    """

    nv: int
    num_vblocks: int
    num_chunks: int
    e_src_pos: np.ndarray
    e_dst_rel: np.ndarray
    e_weight: Optional[np.ndarray]
    chunk_block: np.ndarray
    chunk_first: np.ndarray
    v_blk: int = V_BLK
    t_chunk: int = T_CHUNK


def build_blockcsr(
    g: HostGraph,
    src_pos: Optional[np.ndarray] = None,
    v_blk: Optional[int] = None,
    t_chunk: Optional[int] = None,
) -> BlockCSR:
    """Re-lay out a CSC graph into chunk-aligned vertex blocks.

    ``src_pos`` defaults to the raw source ids (single-part layout); pass
    shard positions for the distributed gathered-state layout.
    ``v_blk``/``t_chunk`` default to the MEASURED tile winner when the
    chip sweep has recorded one (.lux_winners.json "tpu:pallas_tiles",
    engine.methods.pallas_tiles), else the compiled-in V_BLK/T_CHUNK —
    an unattended chip window updates every later build's tiles without
    a code edit, like the method-winner overlay.
    """
    if v_blk is None or t_chunk is None:
        from lux_tpu.engine.methods import pallas_tiles

        meas = pallas_tiles()
        if v_blk is None:
            v_blk = meas[0] if meas else V_BLK
        if t_chunk is None:
            t_chunk = meas[1] if meas else T_CHUNK
    if src_pos is None:
        src_pos = g.col_idx.astype(np.int32)
    num_vblocks = _round_up(g.nv, v_blk) // v_blk
    ne = int(g.row_ptr[-1])

    block_lo = np.asarray(
        g.row_ptr[np.minimum(np.arange(num_vblocks) * v_blk, g.nv)],
        np.int64,
    )
    block_hi = np.asarray(
        g.row_ptr[np.minimum((np.arange(num_vblocks) + 1) * v_blk, g.nv)],
        np.int64,
    )
    chunks_per_block = np.maximum(1, -(-(block_hi - block_lo) // t_chunk))
    num_chunks = int(chunks_per_block.sum())
    chunk_start = np.zeros(num_vblocks + 1, np.int64)
    np.cumsum(chunks_per_block, out=chunk_start[1:])

    e_src_pos = np.zeros((num_chunks, t_chunk), np.int32)
    e_dst_rel = np.full((num_chunks, t_chunk), v_blk, np.int32)
    e_weight = None
    if g.weights is not None:
        e_weight = np.zeros((num_chunks, t_chunk), np.float32)

    from lux_tpu import native

    if native.blockcsr_fill(
        g.row_ptr, src_pos[:ne],
        g.weights[:ne] if g.weights is not None else None,
        v_blk, t_chunk, chunk_start[:-1],
        e_src_pos.reshape(-1), e_dst_rel.reshape(-1),
        e_weight.reshape(-1) if e_weight is not None else None,
    ) is None:
        # NumPy fallback (and the oracle): fully vectorized — a per-chunk
        # Python loop is O(ne/T) iterations, hours at RMAT27 scale; every
        # edge's chunk and slot are computed array-wise, then placed with
        # one flat scatter per array
        dst = g.dst_of_edges()
        # per-edge block (edges are CSC-ordered, blocks are contiguous)
        e_block = np.repeat(
            np.arange(num_vblocks, dtype=np.int64), block_hi - block_lo
        )
        within = np.arange(ne, dtype=np.int64) - block_lo[e_block]
        e_chunk = chunk_start[e_block] + within // t_chunk
        e_slot = within % t_chunk
        flat = e_chunk * t_chunk + e_slot
        e_src_pos.reshape(-1)[flat] = src_pos[:ne]
        e_dst_rel.reshape(-1)[flat] = (
            dst[:ne].astype(np.int64) - e_block * v_blk
        ).astype(np.int32)
        if e_weight is not None:
            e_weight.reshape(-1)[flat] = g.weights[:ne]
    chunk_block = np.repeat(
        np.arange(num_vblocks, dtype=np.int32), chunks_per_block
    )
    chunk_first = np.zeros(num_chunks, np.int32)
    chunk_first[chunk_start[:-1]] = 1
    return BlockCSR(
        nv=g.nv,
        num_vblocks=num_vblocks,
        num_chunks=num_chunks,
        e_src_pos=e_src_pos,
        e_dst_rel=e_dst_rel,
        e_weight=e_weight,
        chunk_block=chunk_block,
        chunk_first=chunk_first,
        v_blk=v_blk,
        t_chunk=t_chunk,
    )


def reduce_neutral(op: str, dtype) -> jnp.ndarray:
    """min/max identity for ``dtype`` (ints: the iinfo bound — the push
    apps' labels/distances are int32, where +-inf does not exist and a
    float detour would lose exactness past 2^24)."""
    d = jnp.dtype(dtype)
    if jnp.issubdtype(d, jnp.integer):
        info = jnp.iinfo(d)
        return jnp.asarray(info.max if op == "min" else info.min, d)
    return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, d)


def _spmv_kernel(op: str, v_blk: int, compute_dtype,
                 chunk_block_ref, chunk_first_ref, vals_ref, dst_ref,
                 out_ref):
    """Out block is a COLUMN (v_blk, 1): the MXU contraction result
    (V_BLK, 1) and the lane-reduced min/max (keepdims) are both
    sublane-major, so accumulation never needs a sublane<->lane relayout
    (the transposes Mosaic would otherwise insert per grid step).

    sum rides the MXU (one-hot contraction); min/max are masked VPU lane
    reductions over the same one-hot mask, dtype-preserving (int32 labels
    stay int32 — no float roundtrip)."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    @pl.when(chunk_first_ref[i] == 1)
    def _():
        if op == "sum":
            out_ref[:] = jnp.zeros_like(out_ref)
        else:
            out_ref[:] = jnp.full_like(
                out_ref, reduce_neutral(op, out_ref.dtype)
            )

    dst = dst_ref[0]  # (1, T)
    vals = vals_ref[0]  # (1, T)
    t = dst.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (v_blk, t), 0)
    onehot = iota == dst  # (V_BLK, T); padding dst==v_blk matches nothing
    if op == "sum":
        # compute_dtype=bfloat16 doubles the MXU rate; the one-hot matrix
        # is exact in bf16 and accumulation stays f32 (preferred type) —
        # only the per-edge values quantize, matching a bf16 state anyway
        contrib = jax.lax.dot_general(
            onehot.astype(compute_dtype),
            vals.astype(compute_dtype),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (V_BLK, 1)
        out_ref[:] = out_ref[:] + contrib
    else:
        neutral = reduce_neutral(op, vals.dtype)
        masked = jnp.where(onehot, jnp.broadcast_to(vals, onehot.shape), neutral)
        if op == "min":
            out_ref[:] = jnp.minimum(
                out_ref[:], jnp.min(masked, axis=1, keepdims=True)
            )
        else:
            out_ref[:] = jnp.maximum(
                out_ref[:], jnp.max(masked, axis=1, keepdims=True)
            )


@functools.partial(
    jax.jit,
    static_argnames=("op", "v_blk", "num_vblocks", "interpret", "compute_dtype"),
)
def spmv_blockcsr(
    edge_vals: jnp.ndarray,  # (C, T) float32 — gathered+weighted per edge
    e_dst_rel: jnp.ndarray,  # (C, T) int32
    chunk_block: jnp.ndarray,  # (C,) int32
    chunk_first: jnp.ndarray,  # (C,) int32
    op: str = "sum",
    v_blk: int = V_BLK,
    num_vblocks: int | None = None,
    interpret: bool = False,
    compute_dtype: str = "float32",
):
    """Segmented reduction -> (num_vblocks * v_blk,) via the Pallas kernel.
    sum accumulates/returns float32; min/max preserve the input dtype
    (int32 labels stay exact)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not num_vblocks:
        raise ValueError("num_vblocks is required (use BlockCSR.num_vblocks)")
    out_dtype = jnp.float32 if op == "sum" else edge_vals.dtype
    num_chunks, t = edge_vals.shape
    # Mosaic block rule: a block's last two dims must be sublane/lane
    # aligned (8/128) OR equal the array's.  A (1, t) block over (C, t)
    # fails the sublane leg, so chunk arrays carry a unit sublane dim —
    # (C, 1, t) with (1, 1, t) blocks — which is layout-identical (the
    # trailing dim is unchanged; the reshape is free).
    edge_vals3 = edge_vals.reshape(num_chunks, 1, t)
    e_dst_rel3 = e_dst_rel.reshape(num_chunks, 1, t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_chunks,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, cb, cf: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, cb, cf: (i, 0, 0)),
        ],
        # column block: row-block cb[i] of the (num_vblocks*v_blk, 1) output
        out_specs=pl.BlockSpec((v_blk, 1), lambda i, cb, cf: (cb[i], 0)),
    )
    out = pl.pallas_call(
        functools.partial(_spmv_kernel, op, v_blk, jnp.dtype(compute_dtype)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_vblocks * v_blk, 1), out_dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(chunk_block, chunk_first, edge_vals3, e_dst_rel3)
    return out.reshape(num_vblocks * v_blk)


def _spmv2d_kernel(v_blk: int,
                   chunk_block_ref, chunk_first_ref, vals_ref, dst_ref,
                   out_ref):
    """2-D value variant: per-chunk (T, K) values, (V_BLK, K) output block.
    The contraction onehot(V_BLK, T) @ vals(T, K) is a true MXU matmul —
    this is the CF accumulation (err * srcVec summed by destination,
    colfilter_gpu.cu:88-89) in one pass."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)

    @pl.when(chunk_first_ref[i] == 1)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    dst = dst_ref[0]  # (1, T)
    vals = vals_ref[0]  # (T, K)
    t = dst.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (out_ref.shape[1], t), 0)
    onehot = (iota == dst).astype(jnp.float32)
    contrib = jax.lax.dot_general(
        onehot, vals.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (V_BLK, K)
    out_ref[0] = out_ref[0] + contrib


@functools.partial(jax.jit, static_argnames=("v_blk", "num_vblocks", "interpret"))
def spmv_blockcsr_2d(
    edge_vals: jnp.ndarray,  # (C, T, K) float32
    e_dst_rel: jnp.ndarray,  # (C, T) int32
    chunk_block: jnp.ndarray,
    chunk_first: jnp.ndarray,
    v_blk: int = V_BLK,
    num_vblocks: int | None = None,
    interpret: bool = False,
):
    """Segmented SUM of (C, T, K) values -> (num_vblocks * v_blk, K)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not num_vblocks:
        raise ValueError("num_vblocks is required (use BlockCSR.num_vblocks)")
    num_chunks, t, k = edge_vals.shape
    # unit sublane dim on the dst chunks (same Mosaic block rule as the 1-D
    # variant; the (1, t, k) values block already satisfies it since t is
    # sublane-aligned and k equals the array's lane dim)
    e_dst_rel3 = e_dst_rel.reshape(num_chunks, 1, t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_chunks,),
        in_specs=[
            pl.BlockSpec((1, t, k), lambda i, cb, cf: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, cb, cf: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, v_blk, k), lambda i, cb, cf: (cb[i], 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_spmv2d_kernel, v_blk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_vblocks, v_blk, k), jnp.float32),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(chunk_block, chunk_first, edge_vals, e_dst_rel3)
    return out.reshape(num_vblocks * v_blk, k)


def pagerank_step_pallas(bc: BlockCSR, state, degree, nv, alpha=0.15,
                         interpret: bool = False):
    """One PageRank iteration using the kernel (single part).

    state: (nv_pad,) pre-divided ranks where nv_pad >= nv (gather source);
    degree: (num_vblocks*v_blk,) int32.  Returns same-shaped new state.
    """
    from lux_tpu.models.pagerank import apply_rank_update

    vals = state[jnp.asarray(bc.e_src_pos)]
    acc = spmv_blockcsr(
        vals, jnp.asarray(bc.e_dst_rel), jnp.asarray(bc.chunk_block),
        jnp.asarray(bc.chunk_first), op="sum", v_blk=bc.v_blk,
        num_vblocks=bc.num_vblocks, interpret=interpret,
    )
    pr = apply_rank_update(acc, degree, nv, alpha)
    return pr[: state.shape[0]]
