"""Per-destination segment reductions over CSC edge blocks.

TPU-native replacement for the reference's per-block CUB
``BlockScan::ExclusiveSum`` + edge-sweep + atomics pattern
(pagerank_gpu.cu:59-95, sssp_gpu.cu:94-130): CSC edges are already grouped by
destination, so each reduction is a *sorted* segmented reduction.  Three
interchangeable strategies, all deterministic (unlike the reference's
atomics):

  * ``scan``    — segmented inclusive scan via `lax.associative_scan` over
                  (value, head_flag) pairs, then gather each segment's last
                  element.  Log-depth, fully vectorized, numerically safe
                  (accumulation stays within a segment).  The default.
  * ``cumsum``  — plain cumsum + gather-diff at row boundaries (sum only).
                  Cheapest, but the global prefix magnitude costs float32
                  precision on large graphs.
  * ``mxsum``   — cumsum computed as blocked lower-triangular MATMULS
                  (the tensor-core scan construction of arXiv:1811.09736)
                  + the same gather-diff (sum only).  Rides the MXU: one
                  (B,T)x(T,T) contraction + a recursive block-offset scan
                  instead of a log-depth elementwise ladder.  Same global-
                  prefix precision caveat as ``cumsum``.
  * ``mxscan``  — the SEGMENTED scan itself as blocked triangular MXU
                  contractions (lux_tpu.ops.pallas_scan, ISSUE 11;
                  arXiv:2505.15112's blocked systolic scan): one Pallas
                  kernel, head flags masking the triangular operand so
                  restarts fold into the contraction, a carried
                  inter-tile offset through the sequential grid.  Float
                  sums accumulate in f32 (own deterministic association,
                  like mxsum vs scan — tolerance-equal to ``scan``);
                  min/max and integer sums use the same masked layout on
                  the VPU, BITWISE equal to ``scan``.  1-D values only:
                  (E, K) shapes fall back to ``scan`` (bitwise-identical
                  to asking for ``scan``).
  * ``scatter`` — `segment_sum/min/max` with sorted ids (XLA scatter).

All take static-shape padded inputs from lux_tpu.graph.shards.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def _segmented_scan(vals: jnp.ndarray, head_flag: jnp.ndarray, op: Callable):
    """Inclusive segmented scan: restarts accumulation at head_flag slots."""

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    out, _ = jax.lax.associative_scan(combine, (vals, head_flag))
    return out


def _ends_gather(scanned, row_ptr, neutral):
    """Pick each segment's final accumulated value; neutral for empty rows."""
    ends = row_ptr[1:] - 1
    nonempty = row_ptr[1:] > row_ptr[:-1]
    safe = jnp.clip(ends, 0, scanned.shape[0] - 1)
    nonempty = nonempty.reshape(nonempty.shape + (1,) * (scanned.ndim - 1))
    return jnp.where(nonempty, scanned[safe], neutral)


#: the ONE copy of the bucketed exchange drivers' method-assert text
#: (push-ring / pull-ring / scatter / feat share the invariant, so
#: they must share the words — a drifting copy would state false
#: guidance about where refined winners go)
BUCKETED_METHODS_NOTE = (
    "bucketed (row_ptr-free) exchange drivers accept method='scan' "
    "or 'scatter' only (--method / LUX_BENCH_METHOD); auto-resolved "
    "scan-family winners (LUX_SUM_MODE: mxsum/mxscan) never reach "
    "this driver — they refine through resolve_sum on the csc "
    "engines, and apps/common downgrades them before these "
    "exchanges — so pass 'scan' or 'scatter' explicitly")


def _mxscan_csc(vals, row_ptr, head_flag, op):
    """The mxscan scanned array for a csc-encoded segment reduction:
    slots at or past row_ptr[-1] are padding and neutralize in-kernel
    (lux_tpu.ops.pallas_scan precision caveat)."""
    from lux_tpu.ops import pallas_scan

    invalid = (jnp.arange(vals.shape[0], dtype=row_ptr.dtype)
               >= row_ptr[-1])
    return pallas_scan.mxscan_segmented(vals, head_flag, invalid, op=op)


MX_BLOCK = 512  # triangular-matmul tile for the mxsum cumsum


def matmul_cumsum(x: jnp.ndarray, block: int = MX_BLOCK) -> jnp.ndarray:
    """Inclusive cumsum along axis 0 as blocked triangular matmuls
    (MXU-friendly; arXiv:1811.09736 construction): per-block prefix =
    x2 @ L^T with L lower-triangular ones, block offsets by recursing on
    the block sums.  f32 accumulation throughout.  (E, K) values ride
    the same contraction with K batched along the free axis — this lifts
    the former 1-D-only restriction that silently degraded ``mxsum`` to
    a plain cumsum for CF/feat-shaped values (ISSUE 11)."""
    n = x.shape[0]
    if n == 0:
        return x
    pad = (-n) % block
    xp = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    nb = xp.shape[0] // block
    tri = jnp.tril(jnp.ones((block, block), jnp.float32))
    if x.ndim == 1:
        x2 = xp.reshape(nb, block)
        intra = jax.lax.dot_general(
            x2.astype(jnp.float32), tri,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (nb, block): intra[b, i] = sum_{j<=i} x2[b, j]
        tots = intra[:, -1]
    else:
        x2 = xp.reshape((nb, block) + x.shape[1:])
        x2 = x2.reshape(nb, block, -1)  # (nb, block, K)
        intra = jax.lax.dot_general(
            x2.astype(jnp.float32), tri,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (nb, K, block): intra[b, k, i] = sum_{j<=i} x2[b, j, k]
        intra = jnp.swapaxes(intra, 1, 2)  # (nb, block, K)
        tots = intra[:, -1, :]
    if nb > block:
        incl = matmul_cumsum(tots, block)
    else:
        incl = jnp.cumsum(tots, axis=0)
    offs = incl - tots  # exclusive block offsets
    out = intra + offs[:, None]
    return out.reshape((-1,) + x.shape[1:])[:n].astype(x.dtype)


def segment_sum_csc(
    vals: jnp.ndarray,
    row_ptr: jnp.ndarray,
    head_flag: jnp.ndarray,
    dst_local: jnp.ndarray | None = None,
    method: str = "scan",
) -> jnp.ndarray:
    """Sum ``vals`` (edge-aligned, (E,) or (E, K)) per destination -> (V, ...)."""
    if method == "mxsum" and jnp.issubdtype(vals.dtype, jnp.integer):
        # matmul_cumsum accumulates in float32 UNCONDITIONALLY — exact
        # for the float sums the strategy was built for, but integer
        # sums (ISSUE 13's uint32 bitset unions / int32 alive counts)
        # must stay exact past 2^24: downgrade to the bitwise scan,
        # same family-downgrade contract as segment_reduce_by_ends's
        # (a banked tpu:sum=mxsum winner stays safe on every program)
        method = "scan"
    if method == "mxscan" and vals.ndim > 1:
        method = "scan"  # the blocked kernel is 1-D (module docstring)
    if method == "mxscan":
        scanned = _mxscan_csc(vals, row_ptr, head_flag, "sum")
        return _ends_gather(scanned, row_ptr, jnp.zeros((), vals.dtype))
    if method == "scan":
        flag = head_flag
        if vals.ndim > 1:
            flag = head_flag[:, None]
        scanned = _segmented_scan(vals, jnp.broadcast_to(flag, vals.shape), jnp.add)
        return _ends_gather(scanned, row_ptr, jnp.zeros((), vals.dtype))
    if method in ("cumsum", "mxsum"):
        if method == "mxsum":
            c = matmul_cumsum(vals)
        else:
            c = jnp.cumsum(vals, axis=0)
        zero = jnp.zeros((1,) + vals.shape[1:], vals.dtype)
        c = jnp.concatenate([zero, c], axis=0)
        return c[row_ptr[1:]] - c[row_ptr[:-1]]
    if method == "scatter":
        assert dst_local is not None
        return jax.ops.segment_sum(
            _scatter_dtype(vals), dst_local, num_segments=row_ptr.shape[0] - 1,
            indices_are_sorted=True,
        ).astype(vals.dtype)
    raise ValueError(
        f"segment_sum_csc: unknown method {method!r}; accepted: 'scan', "
        "'mxscan', 'cumsum', 'mxsum', 'scatter' (--method / "
        "LUX_BENCH_METHOD; the scan-family refinement is LUX_SUM_MODE, "
        "engine/methods.sum_mode)")


def _scatter_dtype(vals: jnp.ndarray) -> jnp.ndarray:
    """TPU XLA scatter has no native sub-f32 float update path — a bf16
    scatter-add lowers to a serialized emulation (measured ~1e4x slower than
    the f32 scatter on a v5-class chip).  Widen low-precision floats to f32
    for the scatter and round once on the way out; accumulation in f32 is
    also strictly better numerically."""
    if vals.dtype in (jnp.bfloat16, jnp.float16):
        return vals.astype(jnp.float32)
    return vals


def _segment_minmax(vals, row_ptr, head_flag, dst_local, op, neutral, method):
    if method == "mxscan" and vals.ndim > 1:
        method = "scan"  # the blocked kernel is 1-D (module docstring)
    if method == "mxscan":
        scanned = _mxscan_csc(vals, row_ptr, head_flag,
                              "min" if op is jnp.minimum else "max")
        return _ends_gather(scanned, row_ptr, neutral)
    if method == "scan":
        flag = head_flag
        if vals.ndim > 1:
            flag = head_flag.reshape(head_flag.shape + (1,) * (vals.ndim - 1))
        scanned = _segmented_scan(vals, jnp.broadcast_to(flag, vals.shape), op)
        return _ends_gather(scanned, row_ptr, neutral)
    if method == "scatter":
        assert dst_local is not None
        seg = jax.ops.segment_min if op is jnp.minimum else jax.ops.segment_max
        return seg(
            _scatter_dtype(vals), dst_local, num_segments=row_ptr.shape[0] - 1,
            indices_are_sorted=True,
        ).astype(vals.dtype)
    raise ValueError(
        f"segment min/max: unknown method {method!r}; accepted: 'scan', "
        "'mxscan' (bitwise — min/max stay on the masked VPU path), "
        "'scatter' (cumsum/mxsum are sum-only prefix-diff strategies); "
        "set via --method / LUX_BENCH_METHOD or LUX_SUM_MODE")


def segment_reduce_by_ends(
    vals: jnp.ndarray,
    head_flag: jnp.ndarray,
    dst_local: jnp.ndarray,
    num_segments: int,
    reduce: str = "sum",
    method: str = "scan",
) -> jnp.ndarray:
    """Per-destination reduction WITHOUT a (V+1) row_ptr: segment ends are
    the positions where ``dst_local`` changes, and each end's scanned value
    is scattered into the (num_segments, ...) output.

    This is the compressed encoding for the O(P^2)-bucket exchange layouts
    (ring/reduce_scatter): a dense per-bucket row_ptr would cost
    O(P^2 * V) host+device memory (~35 GB at the RMAT27/P=64 target,
    SURVEY.md §7.3) while head_flag/dst_local are already edge-aligned —
    so per-bucket cost stays O(bucket edges).  Padding slots must carry
    ``dst_local == num_segments`` (dropped by the scatter).  Empty
    destinations get the reduce's neutral element, matching the
    *_csc reducers.

    Accepted methods: ``scan``, ``scatter``, and ``mxscan`` (ISSUE 11 —
    the blocked MXU scan replaces the VPU ladder for 1-D values, using
    the dst_local sentinel as its padding mask).  ``cumsum``/``mxsum``
    DOWNGRADE to ``scan``: the prefix-diff strategies need a row_ptr the
    bucketed encoding deliberately doesn't have — so a blanket
    scan-family winner (engine/methods.sum_mode) stays safe on every
    layout, with the bucketed paths running exactly the shipped VPU
    scan.  ``mxscan`` on (E, K) values downgrades the same way (1-D
    kernel).
    """
    if reduce == "sum":
        op, neutral = jnp.add, jnp.zeros((), vals.dtype)
    elif reduce == "min":
        op = jnp.minimum
        neutral = jnp.asarray(
            jnp.iinfo(vals.dtype).max
            if jnp.issubdtype(vals.dtype, jnp.integer)
            else jnp.inf,
            vals.dtype,
        )
    elif reduce == "max":
        op = jnp.maximum
        neutral = jnp.asarray(
            jnp.iinfo(vals.dtype).min
            if jnp.issubdtype(vals.dtype, jnp.integer)
            else -jnp.inf,
            vals.dtype,
        )
    else:
        raise ValueError(reduce)

    if method == "scatter":
        seg = {
            "sum": jax.ops.segment_sum,
            "min": jax.ops.segment_min,
            "max": jax.ops.segment_max,
        }[reduce]
        # ids are sorted within a bucket (CSC order); padding ids ==
        # num_segments fall outside and are dropped
        return seg(
            _scatter_dtype(vals), dst_local, num_segments=num_segments,
            indices_are_sorted=True,
        ).astype(vals.dtype)
    if method in ("cumsum", "mxsum") or (method == "mxscan"
                                         and vals.ndim > 1):
        method = "scan"  # see docstring: prefix-diff needs a row_ptr
    if method == "mxscan":
        from lux_tpu.ops import pallas_scan

        scanned = pallas_scan.mxscan_segmented(
            vals, head_flag, dst_local >= num_segments, op=reduce)
    elif method == "scan":
        flag = head_flag.reshape(head_flag.shape + (1,) * (vals.ndim - 1))
        scanned = _segmented_scan(vals, jnp.broadcast_to(flag, vals.shape),
                                  op)
    else:
        raise ValueError(
            f"segment_reduce_by_ends: unknown method {method!r}; "
            "bucketed (row_ptr-free) reductions accept 'scan', 'scatter' "
            "and 'mxscan' ('cumsum'/'mxsum' downgrade to 'scan' — "
            "prefix-diff needs a row_ptr); set via --method / "
            "LUX_BENCH_METHOD or LUX_SUM_MODE (engine/methods.sum_mode)")
    # an edge is its segment's end iff the next slot starts a new segment
    # (head_flag is True at position 0 of every segment, including the
    # first padding slot after the real edges)
    is_end = jnp.concatenate(
        [head_flag[1:], jnp.ones((1,), head_flag.dtype)]
    )
    # non-end slots are redirected to num_segments and dropped, so only one
    # value per segment lands in the output (sum stays exact).  The end
    # scatter is widened like every other scatter path: a bf16 .at[].op
    # hits the same serialized TPU emulation (_scatter_dtype).
    idx = jnp.where(is_end, dst_local, num_segments)
    scanned_w = _scatter_dtype(scanned)
    out = jnp.full((num_segments,) + vals.shape[1:], neutral, scanned_w.dtype)
    if reduce == "sum":
        return out.at[idx].add(scanned_w, mode="drop").astype(vals.dtype)
    if reduce == "min":
        return out.at[idx].min(scanned_w, mode="drop").astype(vals.dtype)
    return out.at[idx].max(scanned_w, mode="drop").astype(vals.dtype)


def reducers():
    """Public reduce-name -> segment-function table (shared by the pull
    engine and the ring driver; keep in one place)."""
    return {
        "sum": segment_sum_csc,
        "min": segment_min_csc,
        "max": segment_max_csc,
    }


def segment_min_csc(vals, row_ptr, head_flag, dst_local=None, method="scan"):
    """Min of ``vals`` per destination; empty rows get the dtype max."""
    neutral = jnp.asarray(jnp.iinfo(vals.dtype).max if jnp.issubdtype(vals.dtype, jnp.integer) else jnp.inf, vals.dtype)
    return _segment_minmax(vals, row_ptr, head_flag, dst_local, jnp.minimum, neutral, method)


def segment_max_csc(vals, row_ptr, head_flag, dst_local=None, method="scan"):
    """Max of ``vals`` per destination; empty rows get the dtype min."""
    neutral = jnp.asarray(jnp.iinfo(vals.dtype).min if jnp.issubdtype(vals.dtype, jnp.integer) else -jnp.inf, vals.dtype)
    return _segment_minmax(vals, row_ptr, head_flag, dst_local, jnp.maximum, neutral, method)
