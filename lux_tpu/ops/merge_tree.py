"""Static asynchronous reduction trees for the cross-part frontier merge.

The push engine's bulk-synchronous merge concatenates every part's
frontier queue (a reshape on one device, an `all_gather` over ICI on the
dist engines) and scatters the whole concatenation into each part's
state slice — one barrier per superstep.  Tascade (arXiv:2311.15810)
argues the aggregation should instead climb a STATIC reduction tree:
per-part partial frontiers combine pairwise, atomic-free, with the
combine order fixed at compile time so every participant provably
executes the identical schedule.  This module is that schedule:

* :func:`plan_tree` — the pairwise combine levels for any arity
  (non-powers of two get byes), a pure host-side plan;
* :func:`tree_combine` — the device-side evaluation of that plan over a
  stacked ``(B, ...)`` block of partial accumulators;
* :func:`neutral` — the combiner identity each partial starts from;
* :func:`staged_concat_gather` — the dist engines' queue exchange as
  ceil(log2 D) staged `ppermute` rounds (a Bruck concatenation) instead
  of one bulk `all_gather`.

Exactness contract (pinned by tests/test_merge_tree.py):

* min / max / integer sum are associative AND commutative in machine
  arithmetic, so ``tree_combine`` is bitwise-identical to any other
  combine order — including the bulk left-fold — at every arity;
* float sum reassociates, so a float-sum tree is NOT bitwise the bulk
  fold.  The push engine therefore ships tree mode only for its min/max
  programs; float-sum trees stay behind the oracle-gated
  ``tpu:merge_mode`` A/B race (bench.py `merge_micro_tree_vs_bulk`)
  with the VPU bulk fold the default until measured on chip.

Deadlock-freedom (LUX-J3): :func:`staged_concat_gather`'s ppermute
rounds are straight-line code with static Python-int rotation offsets —
no branch, no data-dependent trip count — so every mesh participant
executes the same collective sequence unconditionally.  The collective
checker (analysis/ir/collectives.py) proves the enclosing loop/branch
predicates are psum-agreed exactly as it does for the bulk all_gather.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@lru_cache(maxsize=None)
def plan_tree(arity: int) -> Tuple[Tuple[Tuple[int, int], ...], ...]:
    """The static pairwise combine schedule for ``arity`` partials.

    Returns a tuple of levels; each level is a tuple of ``(dst, src)``
    index pairs meaning "combine partial ``src`` into partial ``dst``".
    Indices not named at a level carry through unchanged (byes — how a
    non-power-of-two arity stays balanced).  Level count is
    ceil(log2(arity)); an arity of 0 or 1 has no levels.
    """
    if arity < 0:
        raise ValueError(f"arity must be >= 0, got {arity}")
    levels = []
    live = list(range(arity))
    while len(live) > 1:
        pairs = []
        nxt = []
        i = 0
        while i + 1 < len(live):
            pairs.append((live[i], live[i + 1]))
            nxt.append(live[i])
            i += 2
        if i < len(live):
            nxt.append(live[i])  # bye: odd survivor rides up untouched
        levels.append(tuple(pairs))
        live = nxt
    return tuple(levels)


def tree_depth(arity: int) -> int:
    return len(plan_tree(arity))


def tree_combine(partials, op):
    """Combine a stacked ``(B, ...)`` block of partial accumulators up
    the :func:`plan_tree` schedule; returns the ``(...)`` root.

    ``op`` is the elementwise combiner (``jnp.minimum`` / ``jnp.maximum``
    / ``jnp.add``).  The adjacent-pair levels are evaluated as two
    strided slices per level — the whole level combines in ONE
    vectorized ``op`` call, so the device cost is ceil(log2 B) passes
    over the accumulator, not B.
    """
    b = partials.shape[0]
    if b == 0:
        raise ValueError("tree_combine needs at least one partial")
    while b > 1:
        even = (b // 2) * 2
        lo = partials[0:even:2]
        hi = partials[1:even:2]
        nxt = op(lo, hi)
        if b % 2:
            nxt = jnp.concatenate([nxt, partials[even:]], axis=0)
        partials = nxt
        b = partials.shape[0]
    return partials[0]


def neutral(reduce: str, dtype):
    """The combiner identity a partial accumulator starts from —
    combining it with any value returns that value bitwise (min/max on
    IEEE floats: ``min(x, +inf) == x`` for every non-NaN x, including
    signed zeros; integers: the dtype extremes)."""
    dt = jnp.dtype(dtype)
    if reduce == "sum":
        return jnp.zeros((), dt)
    if reduce not in ("min", "max"):
        raise ValueError(f"unknown reduce {reduce!r}")
    if jnp.issubdtype(dt, jnp.integer):
        info = jnp.iinfo(dt)
        return jnp.asarray(info.max if reduce == "min" else info.min, dt)
    return jnp.asarray(np.inf if reduce == "min" else -np.inf, dt)


def bruck_schedule(num_dev: int) -> Tuple[int, ...]:
    """The static rotation offsets of :func:`staged_concat_gather`:
    doubling strides ``(1, 2, 4, ...)`` below ``num_dev`` — the
    mesh-collective schedule LUX-J3 audits, ceil(log2 D) rounds."""
    if num_dev < 1:
        raise ValueError(f"num_dev must be >= 1, got {num_dev}")
    offs = []
    s = 1
    while s < num_dev:
        offs.append(s)
        s *= 2
    return tuple(offs)


def staged_concat_gather(block, axis_name: str, num_dev: int):
    """Concatenate every device's ``(k, ...)`` block along the mesh axis
    via staged ppermute rounds — the reduction-tree replacement for
    ``all_gather(..., tiled=True)`` in the push engine's queue exchange.

    Bruck construction: at round ``s`` (static doubling offsets from
    :func:`bruck_schedule`) each device appends the buffer received from
    device ``(d + s) % D`` to its own, then truncates to ``D`` device
    blocks.  After ceil(log2 D) rounds device ``d`` holds the blocks of
    devices ``d, d+1, ..., d+D-1`` (mod D) — the full concatenation in a
    per-device ROTATED order.  The push engine's downstream consumers
    are all order-independent (the walk totals are sums; the destination
    scatter is a min/max), so the rotation never reaches the carry and
    results stay bitwise identical to the bulk gather.

    The rounds are unconditional straight-line collectives with static
    integer offsets: every participant runs the identical sequence, the
    LUX-J3 deadlock-freedom argument (module docstring).
    """
    k = block.shape[0]
    buf = block
    blocks = 1
    for s in bruck_schedule(num_dev):
        recv = jax.lax.ppermute(
            buf, axis_name,
            [(j, (j - s) % num_dev) for j in range(num_dev)],
        )
        buf = jnp.concatenate([buf, recv], axis=0)
        blocks = min(2 * blocks, num_dev)
        # consecutive-mod-D truncation: the first D device blocks of
        # [d..d+2s) are exactly [d..d+D) — duplicates fall off the end
        buf = buf[: blocks * k]
    return buf
