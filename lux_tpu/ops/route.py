"""Host-side Benes/Clos routing: any fixed permutation as per-digit gathers.

Why this exists (measured on the round-5 v5e window, tools/
tpu_gather_probe.py): XLA's flat 1-D gather — the pull engine's per-edge
state read, the role of the reference's coalesced load_kernel
(pagerank_gpu.cu:34-47) — runs at ~7 ns/element on TPU (scalar-unit
issue-bound), while Mosaic's ``tpu.dynamic_gather`` moves elements at
~0.08 ns/element.  But the hardware primitive is narrow: a gather can
only move data along the LANE axis (width 128) or within ONE vreg of
sublanes (width <= 8).  An arbitrary N-element permutation therefore has
to be routed through those widths.

This module does the classic answer: factor N into "digits" from
{128, 8, 4, 2}, view the flat array as a mixed-radix hypercube, and
decompose the permutation Clos-style:

    route(pi over (D, M)) = [gather along D] o [per-d route over M] o
                            [gather along D]

which yields 2k-1 passes for k digits (a Benes network of radix-128/8
stages).  Each pass gathers along exactly ONE digit, batched over all
others — exactly the shape ``tpu.dynamic_gather`` supports — and the
middle recursion is batched, so all leaves of one level share a single
physical pass.  Pass index arrays are precomputed HERE, once per graph;
the device replays them every iteration (ops/pallas_shuffle.apply_route).

The stage-1/3 index construction is an edge coloring of the D-regular
bipartite multigraph between source and destination middle-coordinates:
repeated Euler splits halve the regularity until single matchings remain
(possible because digits are powers of two).  Pure NumPy+Python here —
O(N log D) pointer walking; ``native/lux_route.cc`` accelerates the same
contract for benchmark-scale graphs (built lazily, identical output).
"""
from __future__ import annotations

import dataclasses

import numpy as np

#: digits a pass may gather along: 128 rides the lane shuffle, <=8 stays
#: within one sublane vreg ("multiple source vregs along gather
#: dimension" is the Mosaic error past 8).
LANE = 128
MAX_SUBLANE = 8


def factor_digits(n: int) -> list[int]:
    """Factor ``n`` (a power of two, >= 2) into gatherable digits,
    most-significant first: as many 128s as possible, then one 8/4/2
    remainder digit (kept in the MIDDLE recursion where it costs one
    pass, not two)."""
    if n & (n - 1) or n < 2:
        raise ValueError(f"n must be a power of two >= 2, got {n}")
    digits = []
    while n >= LANE:
        digits.append(LANE)
        n //= LANE
    while n > 1:
        d = min(n, MAX_SUBLANE)
        digits.append(d)
        n //= d
    # middle digit is cheapest (appears once in the Benes pass list):
    # put the small remainder digit innermost
    return digits


def benes_axes(k: int) -> tuple[int, ...]:
    """The gathered-axis sequence of the 2k-1 Benes passes (the "V"
    order build_route emits: dims[min(j, 2k-2-j)] for pass j)."""
    return tuple(min(j, 2 * k - 2 - j) for j in range(2 * k - 1))


def plan_fusion_groups(dims, max_block_elems: int = 1 << 17,
                       max_group: int = 3) -> tuple[int, ...]:
    """Pack the Benes pass sequence into consecutive FUSION GROUPS for
    the pass-fused device replay (ops/pallas_shuffle.plan_route_pf).

    A group of passes can chain inside one Pallas kernel with
    VMEM-resident intermediates exactly when every inter-pass relayout
    stays local to the block spanned by the group's gathered digits:
    the block size is the product of the group's DISTINCT digit dims
    (axes repeat across the Benes "V" turn — e.g. passes gathering
    axes (2, 3, 2) span only dims[2]*dims[3]).  ``max_block_elems``
    is the VMEM budget expressed in elements (the kernel holds the
    data tile, its per-pass index tiles, and the double-buffered
    copies of both); ``max_group`` bounds the number of index operands
    resident per kernel.  Returns the group LENGTHS, summing to 2k-1.

    Greedy left-to-right packing: each pass joins the current group
    while the distinct-digit block stays within budget.  Purely a
    function of (dims, knobs) — every part of a multi-part plan gets
    the identical grouping, which the stacked-plan replay relies on.
    """
    if max_block_elems < LANE:
        raise ValueError(f"max_block_elems must be >= {LANE}, "
                         f"got {max_block_elems}")
    if max_group < 1:
        raise ValueError(f"max_group must be >= 1, got {max_group}")
    axes = benes_axes(len(dims))
    return _pack_axes(dims, axes, max_block_elems, max_group)


def _pack_axes(dims, axes, max_block_elems: int,
               max_group: int) -> tuple[int, ...]:
    """Greedy left-to-right packing of ``axes`` (a slice of the Benes
    pass sequence) into fusion groups under the distinct-digit block
    budget — the shared engine behind plan_fusion_groups and the
    mxreduce grouping (plan_mx_fusion_groups)."""
    groups: list[int] = []
    cur: list[int] = []  # distinct axes of the current group, in order
    cur_len = 0
    for a in axes:
        nxt = cur if a in cur else cur + [a]
        blk = 1
        for x in nxt:
            blk *= dims[x]
        if cur and (blk > max_block_elems or cur_len >= max_group):
            groups.append(cur_len)
            cur, cur_len = [a], 1
        else:
            cur, cur_len = list(nxt), cur_len + 1
    if cur_len:
        groups.append(cur_len)
    assert sum(groups) == len(axes), (groups, axes)
    return tuple(groups)


def plan_mx_fusion_groups(dims, max_block_elems: int = 1 << 17,
                          max_group: int = 3,
                          mx_max_block: int = 1024
                          ) -> tuple[tuple[int, ...], int]:
    """Fusion grouping for an MXREDUCE route (ops/pallas_shuffle
    ``plan_route_pf_mx``): the FINAL group is the longest pass suffix
    whose distinct-digit block fits ``mx_max_block`` — that group's
    kernel chains the suffix gathers AND the segmented one-hot
    reduction on the same VMEM tile, so its block size also bounds the
    reduce tile (the rank-block alignment padding in ops/expand's mx
    layout is a multiple of the tile span; a big suffix block would
    inflate the group space).  The prefix packs greedily exactly like
    plan_fusion_groups.

    Returns ``(group_sizes, suffix_len)`` with
    ``group_sizes[-1] == suffix_len``; the final Benes pass gathers
    digit 0 (dim <= 128), so a valid suffix always exists."""
    if mx_max_block < LANE:
        raise ValueError(f"mx_max_block must be >= {LANE}, "
                         f"got {mx_max_block}")
    if max_group < 1:
        raise ValueError(f"max_group must be >= 1, got {max_group}")
    axes = benes_axes(len(dims))
    suffix = 0
    for ln in range(1, min(max_group, len(axes)) + 1):
        distinct = set(axes[-ln:])
        blk = 1
        for a in distinct:
            blk *= dims[a]
        if blk > mx_max_block:
            break
        suffix = ln
    assert suffix >= 1, (dims, mx_max_block)
    prefix = (_pack_axes(dims, axes[:-suffix], max_block_elems,
                         max_group) if len(axes) > suffix else ())
    return prefix + (suffix,), suffix


@dataclasses.dataclass
class Pass:
    """One device pass: gather along ``digit`` (size ``dim``) with the
    digit at position ``axis`` of the mixed-radix ``shape``; ``idx`` is
    the full-size int32 gather index array, laid out in ``shape`` order,
    values in [0, dim)."""

    shape: tuple[int, ...]
    axis: int
    idx: np.ndarray  # shape == self.shape, int32


@dataclasses.dataclass
class Route:
    """A routed permutation: applying ``passes`` in order to ``x``
    (flattened mixed-radix layout) yields ``x[perm]``."""

    n: int
    dims: tuple[int, ...]
    passes: list[Pass]


def _split_regular(u: np.ndarray, v: np.ndarray, deg: int, nl: int, nr: int):
    """Split a deg-regular bipartite multigraph (edges u[i]->v[i], u in
    [0,nl), v in [0,nr)) into two (deg/2)-regular halves via an Euler
    partition.  Returns a bool mask (True = first half).  Pure Python
    pointer walk — the reference implementation and small-N path."""
    m = len(u)
    # incidence CSR per side (stable argsort = vectorized bucket fill)
    l_off = np.zeros(nl + 1, np.int64)
    np.add.at(l_off[1:], u, 1)
    np.cumsum(l_off, out=l_off)
    l_edges = np.argsort(u, kind="stable")
    r_off = np.zeros(nr + 1, np.int64)
    np.add.at(r_off[1:], v, 1)
    np.cumsum(r_off, out=r_off)
    r_edges = np.argsort(v, kind="stable")

    used = np.zeros(m, bool)
    half = np.zeros(m, bool)
    l_ptr = l_off[:-1].copy()
    r_ptr = r_off[:-1].copy()

    def _next_l(node):
        p = l_ptr[node]
        stop = l_off[node + 1]
        while p < stop and used[l_edges[p]]:
            p += 1
        l_ptr[node] = p
        return l_edges[p] if p < stop else -1

    def _next_r(node):
        p = r_ptr[node]
        stop = r_off[node + 1]
        while p < stop and used[r_edges[p]]:
            p += 1
        r_ptr[node] = p
        return r_edges[p] if p < stop else -1

    for e0 in range(m):
        if used[e0]:
            continue
        # walk the Euler circuit containing e0, alternating halves:
        # L->R edges get the parity flag, the return R->L edge the other
        e = e0
        take = True
        while True:
            used[e] = True
            half[e] = take
            take = not take
            # continue from the right endpoint: leave via an unused edge
            nxt = _next_r(v[e])
            if nxt < 0:
                # circuit closed on the right side; all circuits in an
                # even-regular multigraph close where they started
                break
            e = nxt
            used[e] = True
            half[e] = take
            take = not take
            nxt = _next_l(u[e])
            if nxt < 0:
                break
            e = nxt
    return half


def _color_regular(u: np.ndarray, v: np.ndarray, deg: int, nl: int,
                   nr: int) -> np.ndarray:
    """Color a deg-regular bipartite multigraph with ``deg`` colors
    (deg a power of two) by recursive Euler splits; returns int32
    colors per edge, each color class a perfect matching."""
    colors = np.zeros(len(u), np.int32)
    stack = [(np.arange(len(u), dtype=np.int64), deg, 0)]
    while stack:
        sel, d, base = stack.pop()
        if d == 1:
            colors[sel] = base
            continue
        mask = _split_regular(u[sel], v[sel], d, nl, nr)
        stack.append((sel[mask], d // 2, base))
        stack.append((sel[~mask], d // 2, base + d // 2))
    return colors


def _color_regular_batched(u: np.ndarray, v: np.ndarray, deg: int,
                           nside: int,
                           n_threads: int | None = None) -> np.ndarray:
    """Color B independent deg-regular bipartite multigraphs
    (u, v: (B, n)) with deg colors each.  Native single-call path
    (native/lux_route.cc) when available — threaded over the
    independent per-B sub-graphs (bitwise-identical for any thread
    count); Python Euler walk per batch otherwise.  Colorings may
    differ between the two — both are valid (every color class a
    perfect matching), and route correctness is pinned on replay
    equality, not on specific colors."""
    from lux_tpu import native, obs

    with obs.span("plan.color", batches=int(u.shape[0]),
                  n=int(u.shape[1]), deg=int(deg)) as sp:
        out = native.route_color(u, v, deg, nside, n_threads=n_threads)
        if out is not None:
            sp.set(native=True)
            return out
        sp.set(native=False)
        return np.stack([
            _color_regular(u[b], v[b], deg, nside, nside)
            for b in range(u.shape[0])
        ])


def _route_rec(perms: np.ndarray, dims: list[int]) -> list[np.ndarray]:
    """Recursive Clos decomposition, batched.  ``perms`` is (B, n): B
    independent permutations, each mapping TARGET flat index -> SOURCE
    flat index over mixed-radix ``dims`` (row-major).  Returns the pass
    index arrays (B, n) outermost-first; pass j gathers along digit
    dims[min(j, 2k-2-j)] (the Benes "V" order — see build_route, which
    reshapes per pass).  Batching keeps the coloring at ONE native call
    per recursion level instead of exploding into per-subproblem Python
    calls."""
    b, n = perms.shape
    d = dims[0]
    if len(dims) == 1:
        # single digit: the permutation IS a gather along it
        return [perms.astype(np.int32)]
    m = n // d  # size of the middle (remaining digits) space
    tgt = np.arange(n, dtype=np.int64)
    src = perms.astype(np.int64)
    # coordinates: flat = digit * m + mid  (digit is OUTERMOST, row-major)
    m2 = tgt % m  # (n,) shared across batches
    d1, m1 = src // m, src % m  # (B, n)
    # color the D-regular multigraph m1 -> m2 with D colors
    colors = _color_regular_batched(
        m1, np.broadcast_to(m2, (b, n)), d, m).astype(np.int64)
    # stage 1: within each middle-coordinate m1 (a "column"), move along
    # the digit axis: element (d1, m1) -> (c, m1).  idx1[c, m1] = d1.
    idx1 = np.empty((b, n), np.int32)
    np.put_along_axis(idx1, colors * m + m1, d1.astype(np.int32), axis=1)
    # stage 2 (recurse): within each digit value c, an arbitrary
    # permutation of the middle space: target (c, m2) pulls from (c, m1)
    mid_perm = np.empty((b, n), np.int64)
    np.put_along_axis(mid_perm, colors * m + m2, m1, axis=1)
    sub = _route_rec(mid_perm.reshape(b * d, m), dims[1:])
    mids = [s.reshape(b, n) for s in sub]
    # stage 3: within each m2 column, digit c -> d2: idx3[d2, m2] = c,
    # and since target coordinates enumerate (d2, m2) in flat order this
    # is the colors array itself
    idx3 = colors.astype(np.int32)
    return [idx1] + mids + [idx3]


def build_route(perm: np.ndarray, dims: list[int] | None = None) -> Route:
    """Decompose ``perm`` (out[i] = x[perm[i]], a bijection on a
    power-of-two N) into 2k-1 digit-gather passes.

    Every pass array is returned reshaped to the full mixed-radix
    ``shape`` with ``axis`` marking the gathered digit, so the device
    side can transpose that axis into lane/sublane position and feed
    ``tpu.dynamic_gather`` directly.
    """
    n = len(perm)
    if dims is None:
        dims = factor_digits(n)
    assert int(np.prod(dims)) == n, (dims, n)
    flat_passes = [
        p.reshape(-1)
        for p in _route_rec(np.asarray(perm, np.int64)[None], list(dims))
    ]
    k = len(dims)
    assert len(flat_passes) == 2 * k - 1
    shape = tuple(dims)
    passes = []
    for j, idx in enumerate(flat_passes):
        axis = min(j, 2 * k - 2 - j)
        passes.append(Pass(shape=shape, axis=axis,
                           idx=idx.reshape(shape)))
    return Route(n=n, dims=shape, passes=passes)


def apply_route_np(route: Route, x: np.ndarray) -> np.ndarray:
    """NumPy oracle: replay the passes with take_along_axis."""
    y = np.asarray(x).reshape(route.dims)
    for p in route.passes:
        y = np.take_along_axis(y, p.idx, axis=p.axis)
    return y.reshape(-1)
