"""Routed expand: the pull engine's per-edge state read as lane shuffles.

The pull hot loop's LOAD phase is ``state[src_pos]`` — an E-sized random
gather from the (P*V,) replicated state (the reference's coalesced
load_kernel, pagerank_gpu.cu:34-47).  On TPU, XLA lowers that to a
scalar-issue-bound flat gather measured at ~7 ns/element on the round-5
v5e window, while Mosaic lane shuffles move data at ~0.02 ns/element/pass
(tools/tpu_gather_probe.py, .lux_winners.json ``tpu:gather_probe``).

This module re-expresses the gather as pure data MOVEMENT so every step
is a routable shuffle:

    state[src_pos]  =  perm2 ∘ fill_forward ∘ perm1 (state)

1. ``perm1`` — a Benes-routed PERMUTATION (ops/route.py) that places each
   distinct source's state value at the HEAD slot of its run in CSR edge
   order (edges sorted by source, so each source's edges are contiguous).
2. ``fill_forward`` — broadcast each head value across its run.  With
   STATIC run boundaries this is hierarchical and lane-local: one lane
   gather fills within each 128-lane row (cells whose head is in an
   earlier row all share ONE value — the run crossing the row start), and
   the per-row carry is the same fill-forward problem 128x smaller.
   Total cost ~1.01 lane passes over N.
3. ``perm2`` — a second routed permutation from CSR slot order to the
   engine's CSC slot order, where the existing segmented reducers
   (ops/segment.py) consume the values unchanged.

Every step moves bits without arithmetic, so the result is BITWISE equal
to the direct gather — the engine's A/B flag can never change numerics.

Cost model: perm1 and perm2 are 2k-1 passes each (k = len(dims), 4 at
N=2^24 → 7 passes), fill_forward ~1 — ~15 HBM-bandwidth passes replacing
E scalar-issued gather slots.  At rmat20/ef16 that is ~5 ms vs ~117 ms.
The PASS-FUSED form (``to_pf`` / the ``pf=True`` planners) chains 2-3
passes per Pallas kernel with VMEM-resident intermediates
(ops/pallas_shuffle.StaticRoutePF), cutting those ~15 sweeps to ~7 —
bitwise-identical replay, knobs/accounting in docs/PERF.md
("Pass-fused routed hot loop").
"""
from __future__ import annotations

import concurrent.futures as _cf
import dataclasses
import hashlib
import json
import os
import stat
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu import obs
from lux_tpu.ops import route as route_mod
from lux_tpu.ops import pallas_shuffle as shuf

LANE = 128

#: bump when plan_expand / freeze_plan output layout changes — salts the
#: disk-cache key so stale cache files can never replay an incompatible
#: plan (4: pickle -> npz+json storage; keys carry array shape/dtype;
#: 5: one cache entry PER PART/BUCKET keyed on that part's own index
#: arrays — a repartition recut rebuilds only the buckets whose arrays
#: changed).  The round-6 PASS-FUSED families did NOT bump this: the
#: unfused on-disk bytes are unchanged (re-paying the benchmark-scale
#: Euler colorings would cost ~15 min/part for nothing), and the pf
#: entries live under their own tags with their own PF_FORMAT + knob
#: salt (_pf_salt) — the codec merely GAINED static types, which only
#: pf entries reference.
PLAN_FORMAT = 5

#: bump when the pass-fused plan layout (StaticRoutePF/StaticGroup/
#: StaticStep or the pf array arrangement) changes — salts ONLY the
#: "*-pf" cache families.
PF_FORMAT = 1

#: bump when the mxreduce plan layout (StaticMXGroup, the rank-major
#: aligned group space, or the mx array arrangement) changes — salts
#: ONLY the "fused-mx-*" cache family.
MX_FORMAT = 1

#: bump when the FUSED plan array arrangement changes — salts the
#: "fused-*" cache families only (expand/ring/cf entries are untouched,
#: the same surgical-salt precedent as PF_FORMAT/MX_FORMAT).
#: 1: plans gained the runtime ``gslot`` array (CSC edge -> group slot,
#: sentinel n2 on padding) that lets mutation overlays tombstone edges
#: in GROUP SPACE at apply time (apply_fused ``del_val=``) — the fused
#: families now serve live mutation without the expand downgrade.
FUSED_FORMAT = 1


# ---------------------------------------------------------------------------
# plan-build accounting + the host-side planning executor
# ---------------------------------------------------------------------------

_PLAN_STATS_LOCK = threading.Lock()
_PLAN_STATS = {"cold_s": 0.0, "warm_s": 0.0, "built": 0, "loaded": 0}


def _stats_add(kind: str, seconds: float, count: int = 1) -> None:
    with _PLAN_STATS_LOCK:
        _PLAN_STATS[f"{kind}_s"] += seconds
        _PLAN_STATS["built" if kind == "cold" else "loaded"] += count


def plan_stats_snapshot() -> dict:
    """Cumulative plan-construction accounting for this process:
    ``cold_s`` seconds spent BUILDING plans (cache misses), ``warm_s``
    seconds spent LOADING them from the disk cache, and the entry
    counts.  Threaded builds sum per-entry wall time, so cold_s is
    CPU-ish work, not wall clock — bench.py reports both next to every
    GTEPS row (``plan_build_seconds``) so amortization claims stay
    honest (VERDICT r5 #6)."""
    with _PLAN_STATS_LOCK:
        return dict(_PLAN_STATS)


def reset_plan_stats() -> None:
    with _PLAN_STATS_LOCK:
        for k in _PLAN_STATS:
            _PLAN_STATS[k] = 0.0 if k.endswith("_s") else 0


def _plan_threads() -> int:
    """Python-side plan fan-out width: LUX_PLAN_THREADS if set (>=1;
    garbage or non-positive values raise a clear error naming the knob
    at the boundary — the old silent fallback hid a typo'd value as a
    mysteriously serial plan build), else one per core.  The per-part
    planners are pure NumPy + the native colorer (which releases the
    GIL), so threads scale until the cores do."""
    from lux_tpu.utils.config import env_int

    n = env_int("LUX_PLAN_THREADS", minimum=1)
    return n if n is not None else (os.cpu_count() or 1)


def _parallel_map(count: int, fn, workers: int):
    """Daemon-thread parallel map with an atomic work counter, results
    in index order.  DAEMON threads on purpose: concurrent.futures
    executors register an atexit join, so a bench worker that abandons
    an in-flight plan build (budget spent) would hang at interpreter
    exit until the build finished — daemon workers just die with the
    process instead.  Synchronous callers still join normally."""
    import itertools

    from lux_tpu import native

    results = [None] * count
    errors = []
    counter = itertools.count()  # next() is atomic under the GIL
    # compound the parent's share: a worker of THIS pool spawned from a
    # worker of an outer pool is one of parent*workers machine-wide, so
    # the native colorer under it divides cores accordingly instead of
    # multiplying thread counts (O(cores^2) on many-core hosts)
    parent_share = native.get_thread_share()

    def work():
        native.set_thread_share(parent_share * workers)
        while not errors:
            i = next(counter)
            if i >= count:
                return
            try:
                results[i] = fn(i)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
                return

    threads = [threading.Thread(target=work, daemon=True,
                                name=f"lux-plan-w{t}")
               for t in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


def _map_parts(num_parts: int, fn):
    """Run fn(i) for i in range(num_parts), fanned over the planning
    pool, results in index order.  Each plan_one is a pure function of
    its part's arrays, so the schedule can never change the bytes —
    only the wall clock.  Ephemeral workers per call keep nested
    planners (an async parent + per-part children) deadlock-free."""
    if num_parts <= 1 or _plan_threads() <= 1:
        return [fn(i) for i in range(num_parts)]
    return _parallel_map(num_parts, fn, min(_plan_threads(), num_parts))


class PlanFuture:
    """Handle to a routed plan being built off the caller's thread.
    ``ready()`` polls; ``result()`` blocks and returns the ordinary
    (static, arrays) pair.  Engines/drivers use this to pipeline plan
    construction with graph load and the first direct-gather iterations
    (engine/pull.run_pull_fixed_overlapped)."""

    def __init__(self, fut: _cf.Future):
        self._fut = fut

    def ready(self) -> bool:
        return self._fut.done()

    def result(self, timeout: float | None = None):
        return self._fut.result(timeout)


def plan_async(build) -> PlanFuture:
    """Run any plan builder (e.g. ``lambda:
    plan_expand_shards_cached(shards)``) on a background DAEMON thread
    and return a PlanFuture.  Daemon so an abandoned build (e.g. the
    bench worker skipping the routed line with the budget spent) never
    blocks process exit; the builder's own per-part fan-out still runs
    at full width underneath."""
    fut: _cf.Future = _cf.Future()

    def run():
        try:
            fut.set_result(build())
        except BaseException as e:  # noqa: BLE001 — delivered via result()
            fut.set_exception(e)

    threading.Thread(target=run, name="lux-plan-async", daemon=True).start()
    return PlanFuture(fut)


def _idx8_enabled() -> bool:
    """uint8 pass indices (default ON): every routed pass's index values
    are digit-local (< 128), so int32 storage wastes 4x HBM read traffic
    per pass.  LUX_ROUTE_IDX8=0 falls back to int32 — the escape hatch
    if a chip's Mosaic lowering rejects the u8 gather operand."""
    return os.environ.get("LUX_ROUTE_IDX8", "1") != "0"


def _narrow_idx(a: np.ndarray) -> np.ndarray:
    """Narrow ONE gather-index array to uint8.  Digit-local values are
    < 128 by construction (lane digit 128, sublane digits <= 8, ff
    in-row columns < 128) — assert rather than silently fall back, so a
    structural change that breaks the invariant fails loudly instead of
    quietly losing the 4x traffic win."""
    if not np.issubdtype(a.dtype, np.integer):
        return a  # ff levels interleave bool ext masks with index arrays
    if a.size:
        # strictly < LANE: that is the invariant the u8 lane/sublane
        # gathers require (lane fixup, ff in-row columns, sublane digits
        # are all digit-local).  [128, 256) would fit a uint8 but gather
        # out of bounds under promise_in_bounds — fail here instead.
        assert a.min() >= 0 and a.max() < LANE, (a.dtype, a.min(), a.max())
    return a.astype(np.uint8)


def _narrow_mx(a: np.ndarray) -> np.ndarray:
    """Narrow an mxreduce RANK tile to uint8.  Unlike gather indices
    these are COMPARISON operands (onehot = iota == rank), so the bound
    is the u8 range itself: values are in [0, v_blk] with v_blk <= 248
    (ops/pallas_shuffle._mx_defaults) and v_blk the padding sentinel —
    never gathered through, safe anywhere <= 255."""
    if a.size:
        assert a.min() >= 0 and a.max() <= 255, (a.dtype, a.min(), a.max())
    return a.astype(np.uint8)  # luxcheck: disable=LUX-P003 -- rank tiles are compared (iota == rank), never gathered through; the full u8 range is the bound and it IS asserted one line up


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# ---------------------------------------------------------------------------
# fill-forward planning
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FFLevelStatic:
    """Static half of one fill-forward level: the array is viewed
    (rows, 128); ``base`` levels have no carry recursion."""

    rows: int
    base: bool


@dataclasses.dataclass(frozen=True)
class FFStatic:
    levels: tuple[FFLevelStatic, ...]
    n: int


def plan_ff(h: np.ndarray):
    """Plan fill-forward for static head map ``h`` (h[e] = index of the
    first slot of e's run; h[e] <= e, h monotone, h[h[e]] == h[e],
    h[0] == 0).  len(h) must be a power of two >= 128.

    Returns (FFStatic, tuple of per-level index/mask arrays): for each
    non-base level ``(inrow_idx int32 (R,128), ext_mask bool (R,128))``,
    for the base level ``(inrow_idx (1,128),)``.
    """
    n = len(h)
    assert n >= LANE and n & (n - 1) == 0, n
    assert h[0] == 0, "slot 0 must be a head"
    statics: list[FFLevelStatic] = []
    arrays: list[np.ndarray] = []
    h = np.asarray(h, np.int64)
    while True:
        rows = len(h) // LANE
        hr, hc = (h // LANE).reshape(rows, LANE), (h % LANE).reshape(rows, LANE)
        own = np.arange(rows, dtype=np.int64)[:, None]
        same = hr == own
        inrow_idx = np.where(same, hc, 0).astype(np.int32)
        if rows == 1:
            statics.append(FFLevelStatic(rows=1, base=True))
            arrays.append(inrow_idx)
            return FFStatic(levels=tuple(statics), n=n), tuple(arrays)
        ext_mask = ~same
        statics.append(FFLevelStatic(rows=rows, base=False))
        arrays.append(inrow_idx)
        arrays.append(ext_mask)
        # row-level recursion: heads -> head-containing rows; pad the
        # row array up to a 128-multiple power of two with self-heads
        heads = np.flatnonzero(h == np.arange(len(h), dtype=np.int64))
        head_rows = np.unique(heads // LANE)
        sub_n = max(_next_pow2(rows), LANE)
        h2 = np.arange(sub_n, dtype=np.int64)
        pos = np.searchsorted(head_rows, np.arange(rows), side="right") - 1
        h2[:rows] = head_rows[pos]
        h = h2


def apply_ff(x, static: FFStatic, arrays, interpret: bool = False,
             rb: int = 1024):
    """Device fill-forward replay: x (n,) -> x[h] (bitwise)."""
    return _ff_rec(x, static.levels, list(arrays), interpret, rb)


def _ff_rec(x, levels, arrays, interpret, rb):
    lv = levels[0]
    y = x.reshape(lv.rows, LANE)
    inrow_idx = arrays.pop(0)
    tmp = shuf.lane_gather(y, inrow_idx, rb=min(rb, lv.rows),
                           interpret=interpret)
    if lv.base:
        return tmp.reshape(-1)
    ext_mask = arrays.pop(0)
    w = tmp[:, LANE - 1]
    sub_n = max(_next_pow2(lv.rows), LANE)
    wp = jnp.pad(w, (0, sub_n - lv.rows))
    f = _ff_rec(wp, levels[1:], arrays, interpret, rb)[: lv.rows]
    rc = jnp.roll(f, 1)  # rc[r] = f[r-1]; row 0 is never external
    out = jnp.where(ext_mask, rc[:, None], tmp)
    return out.reshape(-1)


def apply_ff_np(x, h):
    """NumPy oracle."""
    return np.asarray(x)[np.asarray(h, np.int64)]


# ---------------------------------------------------------------------------
# the full expand plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ExpandStatic:
    """Hashable descriptor of a routed expand (safe as a jit static).
    ``r1``/``r2`` hold either the unfused StaticRoute or, after
    ``to_pf``, the pass-fused StaticRoutePF — replay dispatches on the
    type, everything downstream is agnostic."""

    n: int
    e_pad: int
    state_size: int
    r1: object  # shuf.StaticRoute | shuf.StaticRoutePF
    ff: FFStatic
    r2: object


def _build_routes(*perms):
    """Build several INDEPENDENT Benes routes, concurrently when the
    planning pool allows: a plan's r1/r2 (and fused's vr) share no
    state, and the Euler coloring under build_route releases the GIL in
    the native layer — so even a single-part (P=1) plan build uses the
    host's cores.  Pure functions: the schedule can't change bytes."""
    if _plan_threads() <= 1 or len(perms) <= 1:
        return tuple(route_mod.build_route(p) for p in perms)
    return tuple(_parallel_map(
        len(perms), lambda i: route_mod.build_route(perms[i]),
        min(len(perms), _plan_threads())))


def _plan_expand_half(src_pos: np.ndarray, m: int, state_size: int):
    """Shared expand-half construction (state -> filled CSR-run slots):
    perm1 + fill-forward plan.  Returns
    (n, csr, perm1, ff_static, ff_arrays) — used by both plan_expand
    and plan_fused so the two can never diverge; the callers build the
    perm1 route TOGETHER with their other route perms (_build_routes)
    so independent colorings overlap."""
    e_pad = len(src_pos)
    n = max(_next_pow2(e_pad), _next_pow2(state_size), LANE)
    sp = np.asarray(src_pos[:m], np.int64)
    csr = np.argsort(sp, kind="stable")  # csr slot j holds CSC edge csr[j]
    sp_sorted = sp[csr]
    head = np.empty(m, bool)
    if m:
        head[0] = True
        head[1:] = sp_sorted[1:] != sp_sorted[:-1]
    head_slots = np.flatnonzero(head)
    uniq = sp_sorted[head_slots] if m else np.empty(0, np.int64)

    # perm1: out[head_slot j] = x[uniq j]; all other slots filled with
    # the unused source indices in ascending order (any bijection works)
    perm1 = np.empty(n, np.int64)
    perm1[head_slots] = uniq
    used_src = np.zeros(n, bool)
    used_src[uniq] = True
    used_tgt = np.zeros(n, bool)
    used_tgt[head_slots] = True
    perm1[~used_tgt] = np.flatnonzero(~used_src)

    # fill-forward: h[e] = head slot of e's run (CSR space); padding
    # slots are their own heads
    h = np.arange(n, dtype=np.int64)
    if m:
        h[:m] = head_slots[np.cumsum(head) - 1]
    ff_static, ff_arrays = plan_ff(h)
    return n, csr, perm1, ff_static, ff_arrays


def plan_expand(src_pos: np.ndarray, m: int, state_size: int):
    """Plan the routed expand for ONE part.

    src_pos: (e_pad,) int32 CSC-edge-order gather indices (real edges in
    slots [0, m), padding after — graph/shards.fill_part layout).
    state_size: size of the gathered state the engine reads (P*V).

    Returns (ExpandStatic, tuple of np arrays) — the arrays are the
    pytree half (r1 passes, ff levels, r2 passes, concatenated in that
    order; ExpandStatic knows the split points implicitly via its
    sub-plans).
    """
    e_pad = len(src_pos)
    n, csr, perm1, ff_static, ff_arrays = _plan_expand_half(
        src_pos, m, state_size)

    # perm2: CSR slot j carries CSC edge csr[j] -> out[csr[j]] = y[j]
    perm2 = np.empty(n, np.int64)
    perm2[csr] = np.arange(m, dtype=np.int64)
    perm2[m:] = np.arange(m, n, dtype=np.int64)
    r1, r2 = _build_routes(perm1, perm2)

    r1s, r1a = shuf.freeze_plan(shuf.plan_route(r1))
    r2s, r2a = shuf.freeze_plan(shuf.plan_route(r2))
    static = ExpandStatic(n=n, e_pad=e_pad, state_size=state_size,
                          r1=r1s, ff=ff_static, r2=r2s)
    arrays = tuple(r1a) + tuple(ff_arrays) + tuple(r2a)
    if _idx8_enabled():
        # every array here is a gather index (or a bool ff mask)
        arrays = tuple(_narrow_idx(a) for a in arrays)
    return static, arrays


def _ff_array_count(ff: FFStatic) -> int:
    return sum(1 if lv.base else 2 for lv in ff.levels)


def _num_expand_arrays(static) -> int:
    """Total plan-array count of an expand-shaped static (r1 + ff + r2)
    — the ONE place the layout arithmetic lives (split_arrays, the
    fused splitter, and the CF src/dst split all derive from it).
    Routes may be unfused (StaticRoute, one array per pass) or
    pass-fused (StaticRoutePF, one per in-group gather step) — the
    count helper in pallas_shuffle covers both."""
    return (shuf.route_num_arrays(static.r1) + _ff_array_count(static.ff)
            + shuf.route_num_arrays(static.r2))


def split_arrays(static: ExpandStatic, arrays):
    """Recover the (r1, ff, r2) array groups from the flat tuple."""
    n1 = shuf.route_num_arrays(static.r1)
    nff = _ff_array_count(static.ff)
    r1a = arrays[:n1]
    ffa = arrays[n1:n1 + nff]
    r2a = arrays[n1 + nff:]
    assert len(r2a) == shuf.route_num_arrays(static.r2)
    return r1a, ffa, r2a


def apply_expand(full_state, static: ExpandStatic, arrays,
                 interpret: bool = False):
    """Device replay: full_state (state_size,) -> full_state[src_pos]
    (e_pad,), bitwise equal to the direct gather."""
    if full_state.ndim != 1:
        raise ValueError(
            "routed expand supports scalar (1-D) vertex state only; "
            f"got shape {full_state.shape} — vector-state programs "
            "(e.g. colfilter's (V, k)) must use the direct gather")
    r1a, ffa, r2a = split_arrays(static, arrays)
    x = jnp.pad(full_state, (0, static.n - static.state_size))
    y = shuf.apply_route_frozen(x, static.r1, r1a, interpret=interpret)
    y = apply_ff(y, static.ff, ffa, interpret=interpret)
    z = shuf.apply_route_frozen(y, static.r2, r2a, interpret=interpret)
    return z[: static.e_pad]


def apply_expand_np(src_pos, full_state):
    """NumPy oracle of the whole expand (the direct gather)."""
    return np.asarray(full_state)[np.asarray(src_pos, np.int64)]


# ---------------------------------------------------------------------------
# pass fusion (routed-pf): upgrade routed plans to the fused-kernel replay
# ---------------------------------------------------------------------------


def _pf_salt() -> str:
    """Cache-key salt for pass-fused plan entries: the pf layout version
    plus the fusion knobs — those are baked into the frozen static
    (grouping + tile geometry), so two processes with different knobs
    (or across a pf-layout change) must never share an entry."""
    blk, grp, mb = shuf._pf_defaults()
    return f":pfv{PF_FORMAT}:{blk}:{grp}:{mb}"


def _pf_key_one(base_key_one):
    """Wrap a per-part cache key with the pass-fusion salt."""
    salt = _pf_salt().encode()

    def key_one(h, i):
        base_key_one(h, i)
        h.update(salt)

    return key_one


def _pf_route(static_route, route_arrays, knobs=(None, None, None)):
    """One frozen route + arrays -> pass-fused form, re-narrowed."""
    s, a = shuf.pf_from_frozen(static_route, tuple(route_arrays),
                               max_block=knobs[0], max_group=knobs[1],
                               vmem_mb=knobs[2])
    if _idx8_enabled():
        a = tuple(_narrow_idx(x) for x in a)
    return s, a


def _to_pf_one(static, arrays, knobs=(None, None, None)):
    """ONE part's plan -> pass-fused (the single derivation shared by
    to_pf, the cached pf planners, and the CF recursion)."""
    arrays = tuple(np.asarray(a) for a in arrays)
    if isinstance(static, ExpandStatic):
        r1a, ffa, r2a = split_arrays(static, arrays)
        r1s, r1n = _pf_route(static.r1, r1a, knobs)
        r2s, r2n = _pf_route(static.r2, r2a, knobs)
        return (dataclasses.replace(static, r1=r1s, r2=r2s),
                tuple(r1n) + tuple(ffa) + tuple(r2n))
    if isinstance(static, FusedStatic):
        if getattr(static, "mx", None) is not None:
            raise TypeError(
                "to_pf: mxreduce plans are already pass-fused (and their "
                "r2 grouping is mx-constrained); build them with "
                "plan_fused(..., mx=True)")
        r1a, ffa, r2a, gmask, gweights, gslot, vra, _mxa = \
            split_fused_arrays(static, arrays, static.weighted)
        r1s, r1n = _pf_route(static.r1, r1a, knobs)
        r2s, r2n = _pf_route(static.r2, r2a, knobs)
        vrs, vrn = _pf_route(static.vr, vra, knobs)
        warr = (gweights,) if static.weighted else ()
        return (dataclasses.replace(static, r1=r1s, r2=r2s, vr=vrs),
                tuple(r1n) + tuple(ffa) + tuple(r2n) + (gmask,) + warr
                + (gslot,) + tuple(vrn))
    if isinstance(static, CFRouteStatic):
        n_src = _num_expand_arrays(static.src)
        s_src, a_src = _to_pf_one(static.src, arrays[:n_src], knobs)
        s_dst, a_dst = _to_pf_one(static.dst, arrays[n_src:], knobs)
        return CFRouteStatic(src=s_src, dst=s_dst), tuple(a_src) + tuple(a_dst)
    raise TypeError(f"to_pf: unsupported plan static {type(static)}")


def to_pf(plan, max_block=None, max_group=None, vmem_mb=None):
    """Upgrade a routed plan to the PASS-FUSED replay (``routed-pf``):
    every Benes route inside the plan (expand r1/r2, fused r1/r2/vr, CF
    src/dst) is regrouped so 2-3 consecutive permutation passes run in
    ONE Pallas kernel with VMEM-resident intermediates
    (ops/pallas_shuffle.pf_from_frozen) — ~40%+ fewer HBM sweeps per
    iteration, bitwise-identical replay (the same per-pass permutations
    move the same bits; the fill-forward levels and the fused group
    reduce are untouched, so even the fused sum association is
    unchanged).

    Pure NumPy rearrangement of the frozen plan — no Euler recoloring —
    so a cached unfused plan upgrades in seconds.  Accepts both a
    single-part plan (2-D arrays) and a stacked shards plan ((P, ...)
    arrays); parts share one static, asserted like every shards planner.
    """
    static, arrays = plan
    arrays = tuple(np.asarray(a) for a in arrays)
    knobs = (max_block, max_group, vmem_mb)
    if arrays and arrays[0].ndim == 3:
        num_parts = arrays[0].shape[0]
        return _stack_from(_map_parts(
            num_parts,
            lambda i: _to_pf_one(static, tuple(a[i] for a in arrays),
                                 knobs)))
    return _to_pf_one(static, arrays, knobs)


# ---------------------------------------------------------------------------
# fused expand + reduce (v2): the WHOLE hot loop as routed movement
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FusedStatic:
    """Hashable descriptor of a fused routed pull iteration: expand
    (r1 + ff as in ExpandStatic) -> permute into a per-destination
    pow2-padded GROUP layout (r2) -> masked elementwise edge_value ->
    per-group reshape-reduce -> small V-space route into accumulator
    order.  Replaces gather + segmented reduce with ~16 HBM-bandwidth
    passes; float sums use the group-layout association (a deterministic
    method-specific order, like mxsum's matmul association)."""

    n: int              # expand space (state/CSR slots)
    n2: int             # group space (>= padded group layout size)
    state_size: int
    v_pad: int          # accumulator slots (local part state size)
    nv_route: int       # pow2 routing space for the accumulator
    reduce: str         # "sum" | "min" | "max"
    weighted: bool      # plan carries pre-routed f32 weights
    #: (offset, count, 2**k) per width class.  ``offset`` is a GROUP-
    #: SPACE element offset for the plain layout, a RANK offset for the
    #: mxreduce layout (whose element offsets carry per-rank-block
    #: alignment padding and live in the plan's seg-boundary tiles).
    groups: tuple[tuple[int, int, int], ...]
    r1: object  # shuf.StaticRoute | shuf.StaticRoutePF (see ExpandStatic)
    ff: FFStatic
    r2: object
    vr: object
    #: mxreduce: the final r2 group fused WITH the segmented reduction
    #: (ops/pallas_shuffle.StaticMXGroup).  When set, ``r2`` holds only
    #: the prefix groups (identity final — the reduction consumes the
    #: final physical layout via plan-time rank tiles) and the plan's
    #: arrays carry (mx step idx tiles, dst_rel, tile_block, tile_first)
    #: in place of the group mask.  None = the plain masked group-reduce.
    mx: object = None
    #: base CSC edge slots (length of the runtime ``gslot`` tombstone
    #: route, FUSED_FORMAT 1) — the overlay's del_val addresses these.
    e_pad: int = 0


def _neutral_like(reduce: str, dtype):
    """Empty-slot identity, matching ops/segment.py's empty-row
    convention (dtype max/min for integer min/max)."""
    if reduce == "sum":
        return jnp.asarray(0, dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if reduce == "min" else info.min, dtype)
    return jnp.asarray(jnp.inf if reduce == "min" else -jnp.inf, dtype)


def plan_fused(src_pos: np.ndarray, dst_local: np.ndarray, m: int,
               state_size: int, v_pad: int, reduce: str = "sum",
               weights: np.ndarray | None = None,
               template: dict[int, int] | None = None,
               mx: bool = False):
    """Plan the fused routed pull for ONE part.

    src_pos / dst_local: (e_pad,) CSC-order arrays (fill_part layout:
    real edges in [0, m), dst_local sorted ascending).  v_pad: the
    part's padded vertex count (accumulator size).  weights: optional
    per-edge float32 (routed into group layout HERE, at plan time).

    Returns (FusedStatic, arrays): arrays = r1 passes + ff levels + r2
    passes + (group_mask float/bool, group_weights or (), vr passes).

    ``mx=True`` plans the MXREDUCE form instead: the group layout goes
    rank-major with tile-span-aligned rank blocks, r2's target
    permutation is pre-composed with the pass-fused final physical
    layout (shuf.mx_physical_order), and the final pass group carries
    the segmented reduction in-kernel (shuf.StaticMXGroup) driven by
    plan-time SEGMENT-BOUNDARY TILES — dst_rel (u8 rank map, sentinel
    = v_blk), tile_block/tile_first (scalar-prefetch output routing).
    Arrays become r1 + ff + r2-prefix + mx-steps + (dst_rel,
    tile_block, tile_first) + (weights?) + vr; no group mask (the
    sentinel subsumes it).  r1/vr freeze pass-fused directly."""
    n, csr, perm1, ff_static, ff_arrays = _plan_expand_half(
        src_pos, m, state_size)

    # --- group layout: per-destination pow2-padded blocks ---
    dl = np.asarray(dst_local[:m], np.int64)
    dsts, counts = np.unique(dl, return_counts=True)  # ascending = CSC order
    ks = _width_classes(counts)
    order = np.argsort(ks, kind="stable")  # group by k, stable by dst
    if template is None:
        template = {int(k): int((ks == k).sum()) for k in np.unique(ks)}
    assert set(int(k) for k in np.unique(ks)) <= set(template), (
        "template is missing width classes present in the data")
    groups: list[tuple[int, int, int]] = []
    seg_base = np.empty(len(dsts), np.int64)  # group-layout start per dst
    seg_stride = np.empty(len(dsts), np.int64)  # per-rank step within seg
    total_rank = np.empty(len(dsts), np.int64)  # dst -> totals-array slot
    off = 0
    rank_off = 0
    rank_widths: list[np.ndarray] = []  # mx: per-RANK pad width (+dummies)
    for k in sorted(template):
        sel = order[ks[order] == k]
        width = 1 << int(k)
        cnt = template[k]  # >= len(sel); extra rows are dummies that
        # stay masked to the reduce neutral (multi-part plans share one
        # template so every part's FusedStatic — and so the vmapped /
        # sharded engines — stay uniform)
        assert len(sel) <= cnt, (k, len(sel), cnt)
        total_rank[sel] = rank_off + np.arange(len(sel), dtype=np.int64)
        if mx:
            # mx layout is derived from per-rank widths below; ``groups``
            # records RANK offsets (element offsets carry alignment pads)
            groups.append((rank_off, cnt, width))
            rank_widths.append(np.full(cnt, width, np.int64))
        else:
            groups.append((off, cnt, width))
            if width < LANE:
                # COLUMN-major (width, count) block: narrow-minor-dim row
                # layouts like (count, 2) pad every row to a 128-lane vreg
                # on TPU (measured ~7 ms of the fused loop); transposed,
                # the reduction runs along <= 16 sublane rows with count
                # on the lane axis
                seg_base[sel] = off + np.arange(len(sel), dtype=np.int64)
                seg_stride[sel] = cnt
            else:
                seg_base[sel] = (off
                                 + np.arange(len(sel), dtype=np.int64)
                                 * width)
                seg_stride[sel] = 1
            off += cnt * width
        rank_off += cnt
    total_slots = rank_off  # template slots incl. dummies

    mx_geom = None
    if mx:
        # --- mxreduce layout: rank-major segments, every v_blk-rank
        # block's span starting on a reduce-tile boundary, so each
        # kernel tile accumulates into exactly ONE output block ---
        mx_max_block, tile_rows, v_blk = shuf._mx_defaults()
        widths = (np.concatenate(rank_widths) if rank_widths
                  else np.zeros(0, np.int64))
        num_blocks = max(-(-total_slots // v_blk), 1)
        ts = tile_rows * LANE
        cumw = np.zeros(total_slots + 1, np.int64)
        np.cumsum(widths, out=cumw[1:])
        bounds = np.minimum(np.arange(num_blocks + 1, dtype=np.int64)
                            * v_blk, total_slots)
        block_sizes = cumw[bounds[1:]] - cumw[bounds[:-1]]
        aligned = -(-block_sizes // ts) * ts
        aligned_start = np.zeros(num_blocks, np.int64)
        np.cumsum(aligned[:-1], out=aligned_start[1:])
        span = int(aligned_start[-1] + block_sizes[-1]) if total_slots else 0
        n2 = max(_next_pow2(max(span, 1)), n, LANE)
        if total_slots:
            blk = np.arange(total_slots, dtype=np.int64) // v_blk
            seg_base_rank = (aligned_start[blk]
                             + (cumw[:-1] - cumw[blk * v_blk]))
        else:
            seg_base_rank = np.zeros(0, np.int64)
        mx_geom = (mx_max_block, tile_rows, v_blk, num_blocks,
                   aligned_start, seg_base_rank)
    else:
        n2 = max(_next_pow2(off), n, LANE)

    # perm2: CSR slot j (edge csr[j], dst dl[csr[j]]) -> its slot in the
    # group layout (seg base + rank within segment)
    seg_of_edge = np.searchsorted(dsts, dl)         # (m,) CSC order
    seg_starts = np.zeros(len(dsts) + 1, np.int64)
    np.cumsum(counts, out=seg_starts[1:])
    rank_csc = np.arange(m, dtype=np.int64) - seg_starts[seg_of_edge]
    if mx:
        edge_rank = total_rank[seg_of_edge]
        gslot_csc = mx_geom[5][edge_rank] + rank_csc  # rank-major, stride 1
    else:
        gslot_csc = (seg_base[seg_of_edge]
                     + rank_csc * seg_stride[seg_of_edge])  # (m,) group slot
    # out[group slot of edge e] = y_csr[csr slot of e]
    csr_slot_of_edge = np.empty(m, np.int64)
    csr_slot_of_edge[csr] = np.arange(m, dtype=np.int64)
    perm2 = np.empty(n2, np.int64)
    used_tgt2 = np.zeros(n2, bool)
    used_src2 = np.zeros(n2, bool)
    perm2[gslot_csc] = csr_slot_of_edge
    used_tgt2[gslot_csc] = True
    used_src2[csr_slot_of_edge] = True
    perm2[~used_tgt2] = np.flatnonzero(~used_src2)

    if mx:
        # pre-compose with the final physical layout: routing perm2r and
        # SKIPPING the restore transpose lands the desired layout
        # directly under the in-kernel reduction's rank tiles
        mx_max_block, tile_rows, v_blk, num_blocks, aligned_start, _ = \
            mx_geom
        pf_blk, pf_grp, _ = shuf._pf_defaults()
        dims2 = route_mod.factor_digits(n2)
        group_sizes, _sfx = route_mod.plan_mx_fusion_groups(
            dims2, pf_blk, pf_grp, mx_max_block)
        sigma = shuf.mx_physical_order(n2, dims2, group_sizes)
        perm2r = np.empty(n2, np.int64)
        perm2r[sigma] = perm2
        # segment-boundary tiles: rank map (sentinel v_blk on padding,
        # dummy-rank, and junk slots) + per-tile output-block routing
        rank_rel = np.full(n2, v_blk, np.int64)
        if m:
            rank_rel[gslot_csc] = edge_rank % v_blk
        R = n2 // LANE
        tb = max(1, min(tile_rows, R))
        num_tiles = R // tb
        tstarts = np.arange(num_tiles, dtype=np.int64) * (tb * LANE)
        tile_block = np.clip(
            np.searchsorted(aligned_start, tstarts, side="right") - 1,
            0, num_blocks - 1).astype(np.int32)
        tile_first = np.zeros(num_tiles, np.int32)
        tile_first[0] = 1
        tile_first[1:][tile_block[1:] != tile_block[:-1]] = 1
        if weights is not None:
            gweights = np.zeros(n2, np.float32)
            gweights[gslot_csc] = np.asarray(weights[:m], np.float32)
    elif weights is not None:
        # static group-space pre-routed weights (plain layout)
        gweights = np.zeros(n2, np.float32)
        gweights[gslot_csc] = np.asarray(weights[:m], np.float32)
    if not mx:
        gmask = np.zeros(n2, bool)
        gmask[gslot_csc] = True
    # runtime tombstone route: CSC edge rank -> group slot, sentinel n2
    # on the padding rows (scatter mode="drop" ignores it).  Lets a
    # mutation overlay mask deleted edges in GROUP SPACE at apply time
    # (apply_fused ``del_val=``) without touching the frozen routes —
    # the fused families serve live mutation without the expand
    # downgrade.  CSC order matches OverlayArrays.del_val.
    gslot_full = np.full(len(src_pos), n2, np.int32)
    gslot_full[:m] = gslot_csc

    # accumulator route: totals (group order: one per dst, concat by k)
    # -> dst_local slots of a (nv_route,) vector; uncovered slots pull
    # from the zero tail
    nv_route = max(_next_pow2(max(v_pad, total_slots)), LANE)
    permv = np.empty(nv_route, np.int64)
    used_tgtv = np.zeros(nv_route, bool)
    used_srcv = np.zeros(nv_route, bool)
    permv[dsts] = total_rank
    used_tgtv[dsts] = True
    used_srcv[total_rank] = True
    # every other accumulator slot reads an unused source slot; source
    # slots >= num_seg are filled with the reduce neutral on device
    permv[~used_tgtv] = np.flatnonzero(~used_srcv)

    if mx:
        r1, r2, vr = _build_routes(perm1, perm2r, permv)
        r1s, r1a = shuf.plan_route_pf(r1)
        vrs, vra = shuf.plan_route_pf(vr)
        r2s, r2a, mxs, mxa = shuf.plan_route_pf_mx(
            r2, v_blk=v_blk, num_blocks=num_blocks, op=reduce,
            group_sizes=group_sizes, tile_rows=tb)
        static = FusedStatic(
            n=n, n2=n2, state_size=state_size, v_pad=v_pad,
            nv_route=nv_route, reduce=reduce,
            weighted=weights is not None, groups=tuple(groups),
            r1=r1s, ff=ff_static, r2=r2s, vr=vrs, mx=mxs,
            e_pad=len(src_pos),
        )
        idx_groups = (tuple(r1a) + tuple(ff_arrays) + tuple(r2a)
                      + tuple(mxa))
        dst_rel = np.ascontiguousarray(rank_rel.reshape(R, LANE))
        if _idx8_enabled():
            idx_groups = tuple(_narrow_idx(a) for a in idx_groups)
            dst_rel = _narrow_mx(dst_rel)
            vra = tuple(_narrow_idx(a) for a in vra)
        else:
            dst_rel = dst_rel.astype(np.int32)
        warr = ((np.ascontiguousarray(gweights.reshape(R, LANE)),)
                if weights is not None else ())
        arrays = (idx_groups + (dst_rel, tile_block, tile_first) + warr
                  + (gslot_full,) + tuple(vra))
        return static, arrays

    r1, r2, vr = _build_routes(perm1, perm2, permv)
    r1s, r1a = shuf.freeze_plan(shuf.plan_route(r1))
    r2s, r2a = shuf.freeze_plan(shuf.plan_route(r2))
    vrs, vra = shuf.freeze_plan(shuf.plan_route(vr))
    static = FusedStatic(
        n=n, n2=n2, state_size=state_size, v_pad=v_pad,
        nv_route=nv_route, reduce=reduce, weighted=weights is not None,
        groups=tuple(groups), r1=r1s, ff=ff_static, r2=r2s, vr=vrs,
        e_pad=len(src_pos),
    )
    idx_groups = tuple(r1a) + tuple(ff_arrays) + tuple(r2a)
    if _idx8_enabled():
        idx_groups = tuple(_narrow_idx(a) for a in idx_groups)
        vra = tuple(_narrow_idx(a) for a in vra)
    warr = (gweights,) if weights is not None else ()
    arrays = idx_groups + (gmask,) + warr + (gslot_full,) + tuple(vra)
    return static, arrays


def split_fused_arrays(static: FusedStatic, arrays, weighted: bool):
    """Recover the array groups of a fused plan's flat tuple.  Returns
    (r1a, ffa, r2a, gmask, gweights, gslot, vra, mxa): ``mxa`` is () for
    plain plans; for mxreduce plans it is (step tiles..., dst_rel,
    tile_block, tile_first) and ``gmask`` is None (the rank tiles'
    sentinel subsumes the mask).  ``gslot`` is the (e_pad,) CSC-edge ->
    group-slot tombstone route (FUSED_FORMAT 1)."""
    n1 = shuf.route_num_arrays(static.r1)
    nff = _ff_array_count(static.ff)
    n2p = shuf.route_num_arrays(static.r2)
    r1a = arrays[:n1]
    ffa = arrays[n1:n1 + nff]
    r2a = arrays[n1 + nff:n1 + nff + n2p]
    rest = arrays[n1 + nff + n2p:]
    mxg = getattr(static, "mx", None)
    if mxg is not None:
        nmx = len(mxg.steps) + 3  # steps + dst_rel + tile_block/first
        mxa = rest[:nmx]
        rest = rest[nmx:]
        gmask = None
        gweights = rest[0] if weighted else None
        gslot = rest[int(weighted)]
        vra = rest[1 + int(weighted):]
    else:
        mxa = ()
        gmask = rest[0]
        gweights = rest[1] if weighted else None
        gslot = rest[1 + int(weighted)]
        vra = rest[2 + int(weighted):]
    assert len(vra) == shuf.route_num_arrays(static.vr)
    return r1a, ffa, r2a, gmask, gweights, gslot, vra, mxa


def apply_fused(full_state, static: FusedStatic, arrays, edge_value=None,
                weighted: bool | None = None, interpret: bool = False,
                del_val=None):
    """Device replay of the fused routed pull for one part: full_state
    (state_size,) -> accumulator (v_pad,).

    edge_value(src_vals, weights) is applied elementwise in GROUP layout
    (dst-state-dependent programs are unsupported here — use the expand
    path).  Sum association follows the group layout — a deterministic,
    method-specific order, like mxsum's.  An MXREDUCE plan
    (``static.mx``) runs the final pass group and the segmented
    reduction in ONE Pallas kernel (shuf.mxreduce_pass_gather):
    edge_value applies on the VMEM tile, float sums contract on the MXU
    (f32 accumulate — its own deterministic association; min/max and
    integer ops reduce on the VPU, dtype-preserving bitwise), and the
    group-space array is read once, never written back.

    ``del_val``: optional (e_pad,) bool CSC-order tombstones (overlay
    deletions).  Scattered through the plan's ``gslot`` route into a
    GROUP-SPACE mask: the plain layout folds it into the group mask, the
    mx layout redirects the tombstoned ranks to the kernel's sentinel
    (v_blk) — deleted edges reduce as the neutral, bitwise equal to the
    expand path's pre-reduce masking, with zero plan rebuild and zero
    retrace across delta occupancies (same shapes, same static)."""
    if full_state.ndim != 1:
        raise ValueError("fused routed pull supports 1-D state only")
    if weighted is None:
        weighted = static.weighted
    r1a, ffa, r2a, gmask, gweights, gslot, vra, mxa = split_fused_arrays(
        static, arrays, weighted)
    g_del = None
    if del_val is not None:
        g_del = (jnp.zeros((static.n2,), jnp.bool_)
                 .at[gslot].set(del_val, mode="drop"))
    x = jnp.pad(full_state, (0, static.n - static.state_size))
    y = shuf.apply_route_frozen(x, static.r1, r1a, interpret=interpret)
    y = apply_ff(y, static.ff, ffa, interpret=interpret)
    y = jnp.pad(y, (0, static.n2 - static.n))
    y = shuf.apply_route_frozen(y, static.r2, r2a, interpret=interpret)
    total_slots = sum(cnt for _, cnt, _ in static.groups)
    mxg = getattr(static, "mx", None)
    if mxg is not None:
        # r2 above ran only the PREFIX groups (identity final); the mx
        # kernel chains the suffix gathers with the reduction
        y = y.reshape(mxg.view)
        if mxg.perm_axes:
            y = y.transpose(mxg.perm_axes)
        y = y.reshape(mxg.kshape)
        n_steps = len(mxg.steps)
        step_a = tuple(mxa[:n_steps])
        dst_rel, tile_block, tile_first = mxa[n_steps:]
        if g_del is not None:
            dst_rel = jnp.where(g_del.reshape(dst_rel.shape),
                                jnp.asarray(mxg.v_blk, dst_rel.dtype),
                                dst_rel)
        edge_fn = None
        if edge_value is not None:
            edge_fn = (edge_value if weighted
                       else (lambda v, w: edge_value(v, None)))
        totals_col = shuf.mxreduce_pass_gather(
            y, step_a, dst_rel, tile_block, tile_first, group=mxg,
            edge_fn=edge_fn,
            weights=gweights if weighted else None,
            interpret=interpret)
        t = totals_col[:total_slots]
        neutral = _neutral_like(static.reduce, t.dtype)
    else:
        if edge_value is not None:
            y = edge_value(y, gweights) if weighted else edge_value(y, None)
        neutral = _neutral_like(static.reduce, y.dtype)
        keep = gmask if g_del is None else gmask & ~g_del
        y = jnp.where(keep, y, neutral)
        red = {"sum": jnp.sum, "min": jnp.min,
               "max": jnp.max}[static.reduce]
        totals = []
        for off, count, width in static.groups:
            blk = jax.lax.dynamic_slice(y, (off,), (count * width,))
            if width < LANE:  # column-major (width, count) block
                totals.append(red(blk.reshape(width, count), axis=0))
            else:
                totals.append(red(blk.reshape(count, width), axis=1))
        t = jnp.concatenate(totals) if totals else jnp.zeros(0, y.dtype)
    t = jnp.concatenate([
        t, jnp.full((static.nv_route - t.shape[0],), neutral, t.dtype)])
    acc = shuf.apply_route_frozen(t, static.vr, vra, interpret=interpret)
    return acc[: static.v_pad]


def _width_classes(counts: np.ndarray) -> np.ndarray:
    """Per-segment width class k (pad width = 2**k) from segment sizes.
    The ONE derivation shared by template construction and plan_fused —
    divergence would route through uninitialized layout slots."""
    return np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64)


def _group_template(arrays) -> dict[int, int]:
    """Shared per-width-class group counts: the MAX over parts of each
    class's segment count.  Every part planned against this template
    yields an identical FusedStatic (dummy rows mask to the reduce
    neutral), so the vmapped and sharded engines stay uniform."""
    template: dict[int, int] = {}
    for i in range(arrays.src_pos.shape[0]):
        dl = arrays.dst_local[i][arrays.edge_mask[i]]
        _, counts = np.unique(dl, return_counts=True)
        ks = _width_classes(counts)
        for k in np.unique(ks):
            template[int(k)] = max(template.get(int(k), 0),
                                   int((ks == k).sum()))
    return template


@dataclasses.dataclass(frozen=True)
class CFRouteStatic:
    """Routed load for WIDE (V, K) dst-dependent programs (colfilter):
    the src gather routes per feature column via ``src``, and the
    dst-state read — ``local_state[dst_local]``, ALSO a sorted-runs
    gather — routes via ``dst`` (an expand plan over the part's local
    state).  Hashable jit static."""

    src: ExpandStatic
    dst: ExpandStatic


def _cf_plan_one(shards, i: int):
    """ONE part's CF route plan — the single derivation shared by the
    cached and uncached planners."""
    arrays = shards.arrays
    v_pad = arrays.row_ptr.shape[1] - 1
    m = int(np.count_nonzero(arrays.edge_mask[i]))
    s_src, a_src = plan_expand(np.asarray(arrays.src_pos[i]), m,
                               shards.spec.gathered_size)
    s_dst, a_dst = plan_expand(np.asarray(arrays.dst_local[i]), m,
                               v_pad)
    return CFRouteStatic(src=s_src, dst=s_dst), tuple(a_src) + tuple(a_dst)


def plan_cf_route_shards(shards, pf: bool = False):
    """(CFRouteStatic, stacked arrays) for the wide dst-dependent pull:
    arrays = src-plan arrays + dst-plan arrays (split by the statics'
    pass counts).  ``pf=True``: pass-fused (both sub-plans)."""
    plan = _stack_parts(shards.arrays.src_pos.shape[0],
                        lambda i: _cf_plan_one(shards, i))
    return to_pf(plan) if pf else plan


def _cf_key_one(shards):
    arrays = shards.arrays
    v_pad = arrays.row_ptr.shape[1] - 1

    def key_one(h, i):
        for f in (arrays.src_pos[i], arrays.dst_local[i],
                  arrays.edge_mask[i]):
            _hash_array(h, f)
        h.update(f"{shards.spec.gathered_size}:{v_pad}".encode())

    return key_one


def plan_cf_route_shards_cached(shards, cache_dir: str | None = None,
                                pf: bool = False):
    """plan_cf_route_shards with the shared per-part disk cache."""
    num = shards.arrays.src_pos.shape[0]
    key_one = _cf_key_one(shards)
    if not pf:
        return _cached_stack("cf", num, key_one,
                             lambda i: _cf_plan_one(shards, i), cache_dir)
    base_one = _cached_part_fn("cf", num, key_one,
                               lambda i: _cf_plan_one(shards, i), cache_dir)
    return _cached_stack("cf-pf", num, _pf_key_one(key_one),
                         lambda i: _to_pf_one(*base_one(i)), cache_dir,
                         validate=_pf_form)


def has_cached_cf_plan(shards, cache_dir: str | None = None,
                       pf: bool = False):
    """Per-part paths when the CF plan family is fully cached, else
    None (tools/plan_prewarm.py --check-only)."""
    key_one = _cf_key_one(shards)
    if pf:
        return _warm_paths("cf-pf", shards.arrays.src_pos.shape[0],
                           _pf_key_one(key_one), cache_dir)
    return _warm_paths("cf", shards.arrays.src_pos.shape[0],
                       key_one, cache_dir)


def apply_cf_route(full_state, local_state, static: CFRouteStatic, arrays,
                   interpret: bool = False):
    """(src_state (E, K), dst_state (E, K)) via routed expands per
    feature column — bitwise equal to the direct gathers."""
    n_src = _num_expand_arrays(static.src)
    a_src, a_dst = arrays[:n_src], arrays[n_src:]

    def col_src(col):
        return apply_expand(col, static.src, a_src, interpret=interpret)

    def col_dst(col):
        return apply_expand(col, static.dst, a_dst, interpret=interpret)

    src = jax.vmap(col_src, in_axes=1, out_axes=1)(full_state)
    dst = jax.vmap(col_dst, in_axes=1, out_axes=1)(local_state)
    return src, dst


def plan_ring_route_shards(rshards):
    """(ExpandStatic, (P, P_src, ...) stacked arrays) for the RING
    exchange: one expand plan per (resident part, streamed source part)
    bucket — src_local gathers a (V,)-sized streamed block with bucket-
    local indices, real edges prefix-packed (pads hold the V sentinel in
    dst_local).  Uniform e_bucket_pad/V make every (i, q) static
    identical, so the ring fold dynamic-indexes the plan slice by the
    traced round part id.

    SCALE NOTE: the per-device plan footprint is O(P * n_b * passes)
    with n_b >= nv_pad — the plans do NOT shrink with the streamed block
    the way ring state does, so at the ring module's RMAT27/P=64 target
    the routed mode's index arrays dominate; it is a single-pod /
    moderate-P accelerator, not the capacity mode (preflight charges
    it via routed_bucket_plan_bytes_analytic)."""
    return _plan_bucket_routes(rshards.rarrays.src_local,
                               rshards.rarrays.dst_local,
                               rshards.pull.spec.nv_pad)


def _bucket_plan_one(src_local, dst_local, v_pad: int, state_size: int,
                     flat: int):
    """ONE bucket's expand plan over the shared (R, B) bucket layout
    (block-local src indices, real edges prefix-packed, dst pads hold
    the V sentinel) — the single derivation for the cached AND uncached
    ring / reduce_scatter / edge2d planners."""
    num_src = src_local.shape[1]
    i, q = divmod(flat, num_src)
    m = int(np.count_nonzero(dst_local[i, q] < v_pad))
    return plan_expand(np.asarray(src_local[i, q]), m, state_size)


def _plan_bucket_routes(src_local, dst_local, v_pad: int,
                        state_size: int | None = None):
    """Shared (R, P, B) bucket planner for the ring / reduce_scatter /
    edge2d exchanges; ``state_size`` defaults to the per-block v_pad
    (edge2d gathers the (P*V,) parts-gathered state instead)."""
    if state_size is None:
        state_size = v_pad
    num_r, num_src = src_local.shape[:2]
    static, flat_stacked = _stack_parts(
        num_r * num_src,
        lambda flat: _bucket_plan_one(src_local, dst_local, v_pad,
                                      state_size, flat))
    stacked = tuple(a.reshape((num_r, num_src) + a.shape[1:])
                    for a in flat_stacked)
    return static, stacked


def plan_scatter_route_shards(sshards):
    """Bucket plans for the reduce_scatter exchange: bucket (i, p)
    gathers MY resident source block i for destination part p — the
    indexing transpose of the ring's, same machinery (and the same
    SCALE NOTE as plan_ring_route_shards)."""
    return _plan_bucket_routes(sshards.sarrays.src_local,
                               sshards.sarrays.dst_local,
                               sshards.pull.spec.nv_pad)


def plan_edge2d_route_shards(eshards):
    """Per-(part, edge-shard) chunk plans for the 2-D mesh: each chunk's
    E2-width src_pos gathers the (P*V,) parts-gathered state (pads hold
    the V sentinel in dst_local).  Uniform chunk pad + gathered size ->
    one shared static; same SCALE NOTE as the bucket planners."""
    a2 = eshards.arrays2d
    v_pad = a2.vtx_mask.shape[1]
    return _plan_bucket_routes(a2.src_pos, a2.dst_local, v_pad,
                               a2.src_pos.shape[0] * v_pad)


def plan_edge2d_route_shards_cached(eshards, cache_dir: str | None = None):
    """plan_edge2d_route_shards with the shared per-bucket disk cache."""
    a2 = eshards.arrays2d
    v_pad = a2.vtx_mask.shape[1]
    return _bucket_route_cached(
        "e2d", a2.src_pos, a2.dst_local, v_pad,
        a2.src_pos.shape[0] * v_pad, cache_dir)


def _hash_array(h, a) -> None:
    """Fold ONE array into a cache key: shape + dtype + bytes.  Byte-
    identical arrays with different layouts (e.g. a (2, n) int32 vs a
    (n, 2) int32, or an int32 vs a float32 view) must never collide —
    replaying a plan built for a different layout would gather garbage."""
    a = np.ascontiguousarray(a)
    h.update(f"{a.shape}:{a.dtype.str}:".encode())
    h.update(a.tobytes())


def _entry_path(cache_dir: str, tag: str, key_one, i: int) -> str:
    """Disk path of ONE part/bucket's plan entry: sha1 over the
    (tag, PLAN_FORMAT, idx8) salt plus whatever key_one(h, i) folds in
    (that part's OWN index arrays + scalar layout salts).  The (tag,
    PLAN_FORMAT) pair IS the cache salt — renaming a tag invalidates
    that plan family exactly like a format bump, so change either only
    deliberately (and re-warm the benchmark-scale caches after)."""
    h = hashlib.sha1()
    h.update(f"{tag}{PLAN_FORMAT}:idx8={_idx8_enabled()}:".encode())
    key_one(h, i)
    return os.path.join(cache_dir, f"{tag}_{h.hexdigest()[:16]}.npz")


def _cached_part_fn(tag: str, num_parts: int, key_one, build_one,
                    cache_dir: str | None = None, paths=None,
                    validate=None):
    """Per-part disk-cached plan getter: returns ``one(i) -> (static,
    arrays)``.  Shared by _cached_stack (which fans it out over the
    planning pool) and the pass-fused planners (whose build path feeds a
    cached UNFUSED entry through the numpy pf transform).  ``validate``
    (static -> bool) guards a family against entries of the WRONG PLAN
    FORM — e.g. a caller handing unfused-family paths to a pf planner
    would otherwise silently replay unfused kernels under the pf label;
    a failing entry is treated like corruption: rebuilt and
    overwritten, so the family self-corrects."""
    cache_dir = cache_dir or _default_cache_dir()
    trusted = _cache_dir_trusted(cache_dir)
    if paths is None and trusted:
        paths = [_entry_path(cache_dir, tag, key_one, i)
                 for i in range(num_parts)]

    def one(i):
        path = paths[i] if trusted else None
        if path is not None and os.path.exists(path):
            # span-timed: _stats_add consumes the SPAN's duration, so
            # plan_build_seconds and the event log's plan.* waterfall
            # are views over one clock (no drift between the bench row
            # and the flight recorder)
            sp = obs.span("plan.load", tag=tag, part=i)
            try:
                with sp:
                    static, arrays = _load_plan(path)
                    if validate is not None and not validate(static):
                        raise ValueError(
                            "entry is not of this plan family's form")
                _stats_add("warm", sp.dur)
                return static, arrays
            except (OSError, ValueError, KeyError) as e:
                # corrupt/foreign entry: rebuild (and overwrite) rather
                # than fail every driver that shares the cache
                print(f"# plan cache ignored ({path}): {e}", flush=True)
        sp = obs.span("plan.build", tag=tag, part=i)
        with sp:
            static, arrays = build_one(i)
        _stats_add("cold", sp.dur)
        if path is not None:
            try:
                _save_plan(path, (static, arrays))
            except (OSError, TypeError, ValueError) as e:
                # the plan is already in hand; a failed store (disk
                # full, future static field outside the codec
                # vocabulary) must cost cache warmth, never the run
                print(f"# plan cache not written ({path}): {e}", flush=True)
        return static, tuple(arrays)

    return one


def _cached_stack(tag: str, num_parts: int, key_one, build_one,
                  cache_dir: str | None = None, paths=None,
                  validate=None):
    """Incrementally-cached plan family: one npz entry PER PART/BUCKET,
    keyed on that part's own index arrays, so a repartition/recut
    (engine/repartition.py) reloads every untouched bucket and rebuilds
    only the changed ones.  Misses build in parallel on the planning
    pool; an untrusted cache dir (see _cache_dir_trusted) degrades to
    always-build — correctness never depends on the cache, only
    plan-construction time does."""
    one = _cached_part_fn(tag, num_parts, key_one, build_one, cache_dir,
                          paths, validate=validate)
    return _stack_from(_map_parts(num_parts, one))


def _pf_form(static) -> bool:
    """True iff a plan static is in the plain PASS-FUSED form (family
    guard for the "*-pf" cache tags; mxreduce entries have their own
    family and are rejected here)."""
    if isinstance(static, CFRouteStatic):
        return _pf_form(static.src) and _pf_form(static.dst)
    if getattr(static, "mx", None) is not None:
        return False
    return isinstance(static.r1, shuf.StaticRoutePF)


def _mx_form(static) -> bool:
    """Family guard for the "fused-mx-*" cache tags: the entry must be
    an MXREDUCE plan (pass-fused routes + the in-kernel reduce group)."""
    return (isinstance(static, FusedStatic)
            and getattr(static, "mx", None) is not None
            and isinstance(static.r1, shuf.StaticRoutePF))


def _mx_salt() -> str:
    """Cache-key salt for mxreduce entries: the pf salt (grouping knobs
    are baked into the prefix groups) plus the mx geometry knobs —
    tile rows, suffix block bound, and v_blk all shape the frozen
    layout, so processes with different knobs must never share one."""
    blk, rows, vb = shuf._mx_defaults()
    return _pf_salt() + f":mx{MX_FORMAT}:{blk}:{rows}:{vb}"


def _mx_key_one(base_key_one):
    """Wrap a per-part cache key with the mxreduce salt."""
    salt = _mx_salt().encode()

    def key_one(h, i):
        base_key_one(h, i)
        h.update(salt)

    return key_one


def resolve_fused_mx(mx: bool | None) -> bool:
    """``mx=None`` on the fused planners follows the chip-measured
    reduce-mode winner (engine/methods.reduce_mode: overlay entry
    ``tpu:reduce_mode``, banked by the micro race / bench micro row) —
    an unattended window's measurement flips the fused families to the
    MXU reduction without a code edit.  Explicit True/False always
    wins (the bench A/B lines and the fused-mx app flag are explicit)."""
    if mx is not None:
        return mx
    from lux_tpu.engine import methods

    return methods.reduce_mode() == "mxreduce"


def _bucket_route_cached(tag: str, src_local, dst_local, v_pad: int,
                         state_size: int, cache_dir: str | None = None):
    """Per-bucket incremental cache over the shared (R, B) bucket
    planner layout (ring / reduce_scatter / edge2d): bucket (i, q) keys
    on ITS slice of src_local/dst_local only."""
    num_r, num_src = src_local.shape[:2]

    def key_one(h, flat):
        i, q = divmod(flat, num_src)
        _hash_array(h, src_local[i, q])
        _hash_array(h, dst_local[i, q])
        h.update(f"{v_pad}:{state_size}".encode())

    static, flat_stacked = _cached_stack(
        tag, num_r * num_src, key_one,
        lambda flat: _bucket_plan_one(src_local, dst_local, v_pad,
                                      state_size, flat),
        cache_dir)
    stacked = tuple(a.reshape((num_r, num_src) + a.shape[1:])
                    for a in flat_stacked)
    return static, stacked


def plan_ring_route_shards_cached(rshards, cache_dir: str | None = None):
    """plan_ring_route_shards with the shared per-bucket disk cache."""
    v_pad = rshards.pull.spec.nv_pad
    return _bucket_route_cached(
        "ring", rshards.rarrays.src_local, rshards.rarrays.dst_local,
        v_pad, v_pad, cache_dir)


def plan_scatter_route_shards_cached(sshards, cache_dir: str | None = None):
    """plan_scatter_route_shards with the shared per-bucket disk cache."""
    v_pad = sshards.pull.spec.nv_pad
    return _bucket_route_cached(
        "rscat", sshards.sarrays.src_local, sshards.sarrays.dst_local,
        v_pad, v_pad, cache_dir)


def _fused_plan_one(shards, template, reduce: str, i: int,
                    mx: bool = False):
    """ONE part's fused plan against a SHARED template — the single
    derivation for the cached and uncached fused planners."""
    arrays = shards.arrays
    v_pad = arrays.row_ptr.shape[1] - 1
    m = int(np.count_nonzero(arrays.edge_mask[i]))
    return plan_fused(
        np.asarray(arrays.src_pos[i]), np.asarray(arrays.dst_local[i]),
        m, shards.spec.gathered_size, v_pad, reduce,
        weights=np.asarray(arrays.weights[i]), template=template, mx=mx)


def plan_fused_shards(shards, reduce: str = "sum", pf: bool = False,
                      mx: bool | None = False):
    """plan_fused for a PullShards bundle.  Parts share one group
    TEMPLATE (max segment count per width class across parts), so all
    parts produce the same FusedStatic and the vmapped engine batches
    them; the price is a few dummy group rows per part, masked to the
    reduce neutral.  ``pf=True`` returns the pass-fused form;
    ``mx=True`` (or mx=None with a banked "mxreduce" tpu:reduce_mode
    winner — resolve_fused_mx) the MXREDUCE form, which is inherently
    pass-fused."""
    if resolve_fused_mx(mx):
        template = _group_template(shards.arrays)
        return _stack_parts(
            shards.arrays.src_pos.shape[0],
            lambda i: _fused_plan_one(shards, template, reduce, i,
                                      mx=True))
    template = _group_template(shards.arrays)
    plan = _stack_parts(shards.arrays.src_pos.shape[0],
                        lambda i: _fused_plan_one(shards, template, reduce, i))
    return to_pf(plan) if pf else plan


def _default_cache_dir() -> str:
    """Per-user plan cache dir (vetted by _cache_dir_trusted before any
    read or write: 0o700, owned by this uid, no symlink)."""
    uid = os.getuid() if hasattr(os, "getuid") else "na"
    return os.path.join(tempfile.gettempdir(), f"lux_expand_plans_{uid}")


#: the dataclass vocabulary a cached plan static may contain — the JSON
#: decoder instantiates ONLY these (nothing in the cache file can name
#: arbitrary code, unlike the pickle format this replaced).  Built
#: EAGERLY at import: the cached planners read it from _map_parts worker
#: threads, and the old unlocked lazy init was a check-then-act race
#: (luxcheck LUX-C001 — benign under the GIL today, a landmine under
#: free threading)
_STATIC_TYPES = {
    cls.__name__: cls
    for cls in (ExpandStatic, FusedStatic, CFRouteStatic, FFStatic,
                FFLevelStatic, shuf.StaticRoute, shuf.StaticPass,
                shuf.StaticRoutePF, shuf.StaticGroup, shuf.StaticStep,
                shuf.StaticMXGroup)
}


def _static_types() -> dict:
    return _STATIC_TYPES


def _static_to_obj(x):
    """Plan static -> JSON-able tree (dataclasses tagged by name)."""
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        return {
            "__type__": type(x).__name__,
            "fields": {
                f.name: _static_to_obj(getattr(x, f.name))
                for f in dataclasses.fields(x)
            },
        }
    if isinstance(x, tuple):
        return {"__tuple__": [_static_to_obj(v) for v in x]}
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.bool_,)):
        return bool(x)
    if isinstance(x, (int, float, str, bool)) or x is None:
        return x
    raise TypeError(f"unserializable plan-static field: {type(x)}")


def _static_from_obj(o):
    if isinstance(o, dict) and "__type__" in o:
        cls = _static_types()[o["__type__"]]
        return cls(**{k: _static_from_obj(v) for k, v in o["fields"].items()})
    if isinstance(o, dict) and "__tuple__" in o:
        return tuple(_static_from_obj(v) for v in o["__tuple__"])
    return o


def _cache_dir_trusted(cache_dir: str) -> bool:
    """Create (0o700) and vet the plan-cache dir.  The parent is the
    world-writable temp dir, so another local user can pre-create the
    path: refuse any dir that is a symlink, not owned by this uid, or
    group/world-writable — both for loading AND for storing (a plan
    written into an attacker's dir hands them replace rights)."""
    try:
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        st = os.lstat(cache_dir)
    except OSError:
        return False
    if stat.S_ISLNK(st.st_mode) or not stat.S_ISDIR(st.st_mode):
        return False
    if hasattr(os, "getuid") and st.st_uid != os.getuid():
        return False
    if st.st_mode & 0o022:  # group/world-writable
        return False
    return True


def _save_plan(path: str, plan) -> None:
    """(static, arrays) -> one npz: arrays under index keys + the static
    as a JSON byte blob.  No pickle anywhere — loading this file cannot
    execute code."""
    static, arrays = plan
    blob = np.frombuffer(
        json.dumps(_static_to_obj(static)).encode(), np.uint8
    )
    payload = {f"a{i}": np.asarray(a) for i, a in enumerate(arrays)}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, __static__=blob, **payload)
    os.replace(tmp, path)


def _load_plan(path: str):
    with np.load(path, allow_pickle=False) as z:
        static = _static_from_obj(
            json.loads(bytes(z["__static__"]).decode())
        )
        arrays = tuple(z[f"a{i}"] for i in range(len(z.files) - 1))
    return static, arrays


def _stack_from(per_part):
    """Assert the statics agree (the vmapped/sharded engines rely on one
    shared static) and stack the arrays with a leading part axis."""
    statics = [st for st, _ in per_part]
    assert all(st == statics[0] for st in statics[1:]), (
        "parts must share one plan static")
    num_parts = len(per_part)
    stacked = tuple(
        np.stack([per_part[i][1][j] for i in range(num_parts)])
        for j in range(len(per_part[0][1]))
    )
    return statics[0], stacked


def _stack_parts(num_parts: int, plan_one):
    """Per-part plan fan-out shared by every *_shards planner: plan each
    part on the planning thread pool (_map_parts — each plan_one is a
    pure function of its part's arrays, so parallelism is bitwise-free),
    then assert/stack via _stack_from."""
    def one(i):
        st, a = plan_one(i)
        return st, tuple(a)

    return _stack_from(_map_parts(num_parts, one))


def _fused_key_one(shards, template):
    arrays = shards.arrays
    tmpl_salt = json.dumps(sorted(template.items())).encode()

    def key_one(h, i):
        for f in (arrays.src_pos[i], arrays.dst_local[i],
                  arrays.weights[i], arrays.edge_mask[i]):
            _hash_array(h, f)
        v_pad = arrays.row_ptr.shape[1] - 1
        h.update(f"{shards.spec.gathered_size}:{v_pad}".encode())
        h.update(f":fusedv{FUSED_FORMAT}".encode())
        h.update(tmpl_salt)

    return key_one


def plan_fused_shards_cached(shards, reduce: str = "sum",
                             cache_dir: str | None = None,
                             pf: bool = False,
                             mx: bool | None = False):
    """plan_fused_shards with the shared per-part disk cache (the reduce
    op joins the tag so min/max/sum plans never collide).  Each part's
    key folds the SHARED group template: a recut that changes any
    part's width-class census invalidates exactly the parts it must
    (every part's FusedStatic depends on the template).  ``pf=True``:
    the pass-fused family (see plan_expand_shards_cached); ``mx``
    (True, or None following the banked tpu:reduce_mode winner): the
    mxreduce family — its own "fused-mx-<reduce>" tag, keys folding
    the mx geometry knobs, entries guarded by the _mx_form validator
    so a foreign entry rebuilds instead of silently replaying the
    wrong reduce layout."""
    template = _group_template(shards.arrays)
    num = shards.arrays.src_pos.shape[0]
    key_one = _fused_key_one(shards, template)
    if resolve_fused_mx(mx):
        return _cached_stack(
            f"fused-mx-{reduce}", num, _mx_key_one(key_one),
            lambda i: _fused_plan_one(shards, template, reduce, i,
                                      mx=True),
            cache_dir, validate=_mx_form)
    if not pf:
        return _cached_stack(
            f"fused-{reduce}", num, key_one,
            lambda i: _fused_plan_one(shards, template, reduce, i),
            cache_dir)
    base_one = _cached_part_fn(
        f"fused-{reduce}", num, key_one,
        lambda i: _fused_plan_one(shards, template, reduce, i), cache_dir)
    return _cached_stack(
        f"fused-pf-{reduce}", num, _pf_key_one(key_one),
        lambda i: _to_pf_one(*base_one(i)), cache_dir,
        validate=_pf_form)


def has_cached_fused_plan(shards, reduce: str = "sum",
                          cache_dir: str | None = None, pf: bool = False,
                          mx: bool | None = False):
    """Per-part paths when the fused plan family is fully cached, else
    None (tools/plan_prewarm.py --check-only)."""
    template = _group_template(shards.arrays)
    key_one = _fused_key_one(shards, template)
    if resolve_fused_mx(mx):
        return _warm_paths(f"fused-mx-{reduce}",
                           shards.arrays.src_pos.shape[0],
                           _mx_key_one(key_one), cache_dir)
    if pf:
        return _warm_paths(f"fused-pf-{reduce}",
                           shards.arrays.src_pos.shape[0],
                           _pf_key_one(key_one), cache_dir)
    return _warm_paths(f"fused-{reduce}", shards.arrays.src_pos.shape[0],
                       key_one, cache_dir)


def _expand_key_one(shards):
    arrays = shards.arrays

    def key_one(h, i):
        _hash_array(h, arrays.src_pos[i])
        _hash_array(h, arrays.edge_mask[i])
        h.update(str(shards.spec.gathered_size).encode())

    return key_one


def _expand_plan_one(shards, i: int):
    arrays = shards.arrays
    m = int(np.count_nonzero(arrays.edge_mask[i]))
    return plan_expand(np.asarray(arrays.src_pos[i]), m,
                       shards.spec.gathered_size)


def _warm_paths(tag: str, num_parts: int, key_one,
                cache_dir: str | None):
    """Per-part cache paths when the whole family would be a pure disk
    load (EVERY entry present), else None."""
    cache_dir = cache_dir or _default_cache_dir()
    if not _cache_dir_trusted(cache_dir):
        return None
    paths = tuple(_entry_path(cache_dir, tag, key_one, i)
                  for i in range(num_parts))
    return paths if all(os.path.exists(p) for p in paths) else None


def has_cached_expand_plan(shards, cache_dir: str | None = None,
                           pf: bool = False):
    """The tuple of per-part cache paths when plan_expand_shards_cached
    would be a pure disk load (EVERY part's entry present), else None —
    lets callers (bench default race) include the routed line only when
    it will not burn plan-construction time inside a TPU budget, and
    reuse the paths without re-hashing the arrays."""
    key_one = _expand_key_one(shards)
    if pf:
        return _warm_paths("expand-pf", shards.arrays.src_pos.shape[0],
                           _pf_key_one(key_one), cache_dir)
    return _warm_paths("expand", shards.arrays.src_pos.shape[0],
                       key_one, cache_dir)


def plan_expand_shards_cached(shards, cache_dir: str | None = None,
                              cache_path=None, pf: bool = False):
    """plan_expand_shards with the per-part disk cache keyed on each
    part's exact gather layout (src_pos + edge_mask bytes + gathered
    size).  Route construction is ~90 s per part at 2^24 single-thread
    even with the native colorer (latency-bound Euler walk) — threaded
    it scales with cores, but benchmark A/B reruns must still not re-pay
    it; the per-iteration device replay never touches this path.
    ``cache_path``: a has_cached_expand_plan result to skip re-hashing.

    ``pf=True``: the pass-fused plan family ("expand-pf" entries, keys
    fold the fusion knobs).  A pf miss loads (or builds AND caches) the
    unfused entry and upgrades it with the numpy transform — the Euler
    coloring is never re-paid for the pf variant.  ``cache_path`` must
    then come from ``has_cached_expand_plan(..., pf=True)``: entries of
    the wrong plan form are rejected by a family guard and rebuilt, so
    a mixed-up path can cost time but never silently replay unfused
    kernels under the pf label."""
    num = shards.arrays.src_pos.shape[0]
    key_one = _expand_key_one(shards)
    if not pf:
        return _cached_stack(
            "expand", num, key_one,
            lambda i: _expand_plan_one(shards, i), cache_dir,
            paths=list(cache_path) if cache_path else None)
    base_one = _cached_part_fn("expand", num, key_one,
                               lambda i: _expand_plan_one(shards, i),
                               cache_dir)
    return _cached_stack(
        "expand-pf", num, _pf_key_one(key_one),
        lambda i: _to_pf_one(*base_one(i)), cache_dir,
        paths=list(cache_path) if cache_path else None,
        validate=_pf_form)


def plan_expand_shards(shards, pf: bool = False):
    """Plan the routed expand for every part of a PullShards bundle.

    Returns ``(ExpandStatic, tuple of (P, ...) stacked arrays)`` — the
    form the engine's vmapped iteration consumes
    (lux_tpu/engine/pull.py ``route=``).  All parts share one static
    (same e_pad / gathered size → same dims), asserted here.
    ``pf=True`` returns the pass-fused form (see to_pf).
    """
    plan = _stack_parts(shards.arrays.src_pos.shape[0],
                        lambda i: _expand_plan_one(shards, i))
    return to_pf(plan) if pf else plan
