"""Device replay of a routed permutation (ops/route.py) at shuffle speed.

Each Benes pass gathers along one digit.  Mosaic's ``tpu.dynamic_gather``
covers exactly two shapes (measured on the round-5 v5e window,
tools/tpu_gather_probe.py: 0.08 ns/element vs 7 ns for XLA's flat
gather, the pull engine's former per-edge state read — reference role:
pagerank_gpu.cu:34-47 load_kernel):

  * LANE pass: gather along a 128 digit, batched over rows — operand
    block (rb, 128), index values in [0, 128);
  * SUBLANE pass: gather along a digit d <= 8 (one vreg of sublanes),
    batched over lanes — operand block (d, lb), index values in [0, d).

The digit being gathered must sit in the right position of the physical
layout, so the host-side planner (``plan_route``) threads ONE transpose
per pass: it tracks the running digit order, transposes the DATA
directly from the previous pass's layout into this pass's, and
pre-arranges every index array into its kernel layout at build time
(indices are digit-local values — relayouts move their positions, never
their values).  All transposes are XLA copies at HBM bandwidth; the
gathers never leave VMEM.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.ops.route import Route

LANE = 128


def _compiler_params(pltpu, **kw):
    """pltpu.CompilerParams across jax versions (TPUCompilerParams before
    the 0.5-era rename) — one shim shared by every kernel in the repo."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _lane_kernel(x_ref, i_ref, o_ref):
    # idx may arrive uint8 (digit-local values < 128 — 4x less HBM
    # traffic per pass); the widening cast happens in VMEM, free next to
    # the gather
    o_ref[:] = jnp.take_along_axis(
        x_ref[:], i_ref[:].astype(jnp.int32), axis=1,
        mode="promise_in_bounds"
    )


def _sublane_kernel(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(
        x_ref[:], i_ref[:].astype(jnp.int32), axis=0,
        mode="promise_in_bounds"
    )


@functools.partial(jax.jit, static_argnames=("rb", "interpret"))
def lane_gather(x, idx, rb: int = 1024, interpret: bool = False):
    """(R, 128) per-row lane shuffle: out[r, c] = x[r, idx[r, c]]."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = x.shape[0]
    if r == 1:
        # Mosaic rejects a (1, 128) gather operand ("Shape mismatch in
        # input, indices and output", measured on v5e); a single row is
        # 128 elements — plain XLA is exact and negligible
        return jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1)
    rb = min(rb, r)
    assert r % rb == 0, (r, rb)
    spec = pl.BlockSpec((rb, LANE), lambda i: (i, 0))
    return pl.pallas_call(
        _lane_kernel,
        grid=(r // rb,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(x, idx)


@functools.partial(jax.jit, static_argnames=("lb", "interpret"))
def sublane_gather(x, idx, lb: int = 16384, interpret: bool = False):
    """(d, L) per-lane sublane shuffle (d <= 8, one vreg):
    out[s, l] = x[idx[s, l], l]."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    d, length = x.shape
    assert d <= 8, d
    lb = min(lb, length)
    assert length % lb == 0, (length, lb)
    spec = pl.BlockSpec((d, lb), lambda i: (0, i))
    return pl.pallas_call(
        _sublane_kernel,
        grid=(length // lb,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(x, idx)


@dataclasses.dataclass
class DevicePass:
    """One planned pass: transpose the flat data from the previous
    layout via ``perm_axes`` (on the mixed-radix ``view`` of the
    PREVIOUS layout), then run ``kind`` with the pre-arranged ``idx``."""

    kind: str  # "lane" | "sublane"
    view: tuple[int, ...]  # reshape of the incoming flat array
    perm_axes: tuple[int, ...]  # np.transpose axes, () if identity
    kshape: tuple[int, ...]  # 2-D kernel operand shape
    idx: np.ndarray  # int32, kshape


@dataclasses.dataclass
class RoutePlan:
    n: int
    dims: tuple[int, ...]
    passes: list[DevicePass]
    final_view: tuple[int, ...]
    final_perm: tuple[int, ...]  # restore row-major digit order at the end


def plan_route(route: Route) -> RoutePlan:
    """Compile a host Route into transposed-once-per-pass device form."""
    dims = route.dims
    k = len(dims)
    order = list(range(k))  # current digit order, outer->inner
    passes: list[DevicePass] = []
    for p in route.passes:
        g = p.axis
        d = dims[g]
        if d == LANE or (route.n >= LANE and d <= LANE and LANE % d == 0):
            # a small digit (d < 128, d | 128) ALSO rides the lane
            # kernel: with the digit innermost, each 128-lane row holds
            # 128/d whole digit-blocks, and the gather stays block-local
            # via the static fixup lane = (lane//d)*d + idx.  This
            # avoids the sublane kernel's narrow-minor-dim layouts
            # ((2, n/2) measured ~10x slower than lane passes on v5e).
            # Digits that do NOT divide 128 (caller-supplied dims —
            # build_route accepts any factorization) would make the
            # fixup gather across block boundaries under
            # promise_in_bounds: they fall through to the sublane
            # kernel, whose own d <= 8 assert fails loudly instead.
            assert d <= LANE and LANE % d == 0, d
            new_order = [a for a in order if a != g] + [g]
            kshape = (route.n // LANE, LANE)
            kind = "lane"
        else:
            new_order = [g] + [a for a in order if a != g]
            kshape = (d, route.n // d)
            kind = "sublane"
        view = tuple(dims[a] for a in order)
        perm_axes = tuple(order.index(a) for a in new_order)
        if perm_axes == tuple(range(k)):
            perm_axes = ()
        # index array: canonical row-major -> this pass's layout
        idx = np.ascontiguousarray(
            np.transpose(p.idx, new_order).reshape(kshape), np.int32
        )
        if kind == "lane" and d < LANE:
            idx = ((np.arange(LANE, dtype=np.int32)[None, :] // d) * d
                   + idx)
        passes.append(DevicePass(kind=kind, view=view,
                                 perm_axes=perm_axes, kshape=kshape,
                                 idx=idx))
        order = new_order
    final_view = tuple(dims[a] for a in order)
    final_perm = tuple(order.index(a) for a in range(k))
    if final_perm == tuple(range(k)):
        final_perm = ()
    return RoutePlan(n=route.n, dims=dims, passes=passes,
                     final_view=final_view, final_perm=final_perm)


def device_indices(plan: RoutePlan):
    """The per-pass index arrays as device arrays (put once per graph)."""
    return tuple(jnp.asarray(p.idx) for p in plan.passes)


@dataclasses.dataclass(frozen=True)
class StaticPass:
    """Hashable half of a DevicePass (everything but the index data)."""

    kind: str
    view: tuple[int, ...]
    perm_axes: tuple[int, ...]
    kshape: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class StaticRoute:
    """Hashable route descriptor — safe as a jit static argument, so the
    big per-pass index arrays travel as TRACED pytree leaves (engine
    integration: lux_tpu/ops/expand.py) instead of baked constants."""

    n: int
    dims: tuple[int, ...]
    passes: tuple[StaticPass, ...]
    final_view: tuple[int, ...]
    final_perm: tuple[int, ...]


def freeze_plan(plan: RoutePlan):
    """Split a RoutePlan into (StaticRoute, tuple-of-index-arrays)."""
    static = StaticRoute(
        n=plan.n,
        dims=tuple(plan.dims),
        passes=tuple(
            StaticPass(kind=p.kind, view=tuple(p.view),
                       perm_axes=tuple(p.perm_axes),
                       kshape=tuple(p.kshape))
            for p in plan.passes
        ),
        final_view=tuple(plan.final_view),
        final_perm=tuple(plan.final_perm),
    )
    return static, tuple(p.idx for p in plan.passes)


def apply_route_frozen(x, static: StaticRoute, idx_dev, rb: int = 1024,
                       lb: int = 16384, interpret: bool = False):
    """apply_route on a frozen (StaticRoute, idx arrays) pair.  Traced-
    data/static-metadata split makes this directly jittable and
    vmappable (idx arrays stacked with a leading part axis)."""
    y = x
    for p, idx in zip(static.passes, idx_dev):
        y = y.reshape(p.view)
        if p.perm_axes:
            y = y.transpose(p.perm_axes)
        y = y.reshape(p.kshape)
        if p.kind == "lane":
            y = lane_gather(y, idx, rb=rb, interpret=interpret)
        else:
            y = sublane_gather(y, idx, lb=lb, interpret=interpret)
        y = y.reshape(-1)
    y = y.reshape(static.final_view)
    if static.final_perm:
        y = y.transpose(static.final_perm)
    return y.reshape(-1)


def apply_route(x, plan: RoutePlan, idx_dev=None, rb: int = 1024,
                lb: int = 16384, interpret: bool = False):
    """Replay the permutation on device: x flat (n,) -> x[perm].

    Jit-safe (static plan, traced data); pass ``idx_dev`` from
    ``device_indices`` to avoid re-uploading indices per call.
    """
    if idx_dev is None:
        idx_dev = device_indices(plan)
    static, _ = freeze_plan(plan)
    return apply_route_frozen(x, static, idx_dev, rb=rb, lb=lb,
                              interpret=interpret)
