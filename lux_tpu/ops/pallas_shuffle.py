"""Device replay of a routed permutation (ops/route.py) at shuffle speed.

Each Benes pass gathers along one digit.  Mosaic's ``tpu.dynamic_gather``
covers exactly two shapes (measured on the round-5 v5e window,
tools/tpu_gather_probe.py: 0.08 ns/element vs 7 ns for XLA's flat
gather, the pull engine's former per-edge state read — reference role:
pagerank_gpu.cu:34-47 load_kernel):

  * LANE pass: gather along a 128 digit, batched over rows — operand
    block (rb, 128), index values in [0, 128);
  * SUBLANE pass: gather along a digit d <= 8 (one vreg of sublanes),
    batched over lanes — operand block (d, lb), index values in [0, d).

The digit being gathered must sit in the right position of the physical
layout, so the host-side planner (``plan_route``) threads ONE transpose
per pass: it tracks the running digit order, transposes the DATA
directly from the previous pass's layout into this pass's, and
pre-arranges every index array into its kernel layout at build time
(indices are digit-local values — relayouts move their positions, never
their values).  All transposes are XLA copies at HBM bandwidth; the
gathers never leave VMEM.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.ops.route import Route

LANE = 128


def _compiler_params(pltpu, **kw):
    """pltpu.CompilerParams across jax versions (TPUCompilerParams before
    the 0.5-era rename) — one shim shared by every kernel in the repo."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _lane_kernel(x_ref, i_ref, o_ref):
    # idx may arrive uint8 (digit-local values < 128 — 4x less HBM
    # traffic per pass); the widening cast happens in VMEM, free next to
    # the gather
    o_ref[:] = jnp.take_along_axis(
        x_ref[:], i_ref[:].astype(jnp.int32), axis=1,
        mode="promise_in_bounds"
    )


def _sublane_kernel(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(
        x_ref[:], i_ref[:].astype(jnp.int32), axis=0,
        mode="promise_in_bounds"
    )


@functools.partial(jax.jit, static_argnames=("rb", "interpret"))
def lane_gather(x, idx, rb: int = 1024, interpret: bool = False):
    """(R, 128) per-row lane shuffle: out[r, c] = x[r, idx[r, c]]."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = x.shape[0]
    keep = 0
    if r < 8 and 8 % r == 0:
        # Mosaic rejects sub-(8, 128) gather operands ("Shape mismatch
        # in input, indices and output", measured on v5e for the ff base
        # level's (1, 128)).  This was the routed pipeline's ONLY
        # out-of-band plain-XLA pass; instead, tile the rows up to one
        # full f32 vreg tile and slice back — duplicated rows gather
        # identical values, so the kept slice is bitwise the same and
        # every routed pass now goes through Mosaic.
        keep = r
        x = jnp.tile(x, (8 // r, 1))
        idx = jnp.tile(idx, (8 // r, 1))
        r = 8
    elif r < 8:
        # non-dividing sub-tile row counts never occur in routed plans
        # (all row counts are powers of two); keep the exact XLA path
        # rather than gather garbage through a partial tile
        return jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1)
    rb = min(rb, r)
    assert r % rb == 0, (r, rb)
    spec = pl.BlockSpec((rb, LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        _lane_kernel,
        grid=(r // rb,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(x, idx)
    return out[:keep] if keep else out


@functools.partial(jax.jit, static_argnames=("lb", "interpret"))
def sublane_gather(x, idx, lb: int = 16384, interpret: bool = False):
    """(d, L) per-lane sublane shuffle (d <= 8, one vreg):
    out[s, l] = x[idx[s, l], l]."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    d, length = x.shape
    assert d <= 8, d
    lb = min(lb, length)
    assert length % lb == 0, (length, lb)
    spec = pl.BlockSpec((d, lb), lambda i: (0, i))
    return pl.pallas_call(
        _sublane_kernel,
        grid=(length // lb,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(x, idx)


@dataclasses.dataclass
class DevicePass:
    """One planned pass: transpose the flat data from the previous
    layout via ``perm_axes`` (on the mixed-radix ``view`` of the
    PREVIOUS layout), then run ``kind`` with the pre-arranged ``idx``."""

    kind: str  # "lane" | "sublane"
    view: tuple[int, ...]  # reshape of the incoming flat array
    perm_axes: tuple[int, ...]  # np.transpose axes, () if identity
    kshape: tuple[int, ...]  # 2-D kernel operand shape
    idx: np.ndarray  # int32, kshape


@dataclasses.dataclass
class RoutePlan:
    n: int
    dims: tuple[int, ...]
    passes: list[DevicePass]
    final_view: tuple[int, ...]
    final_perm: tuple[int, ...]  # restore row-major digit order at the end


def plan_route(route: Route) -> RoutePlan:
    """Compile a host Route into transposed-once-per-pass device form."""
    dims = route.dims
    k = len(dims)
    order = list(range(k))  # current digit order, outer->inner
    passes: list[DevicePass] = []
    for p in route.passes:
        g = p.axis
        d = dims[g]
        if d == LANE or (route.n >= LANE and d <= LANE and LANE % d == 0):
            # a small digit (d < 128, d | 128) ALSO rides the lane
            # kernel: with the digit innermost, each 128-lane row holds
            # 128/d whole digit-blocks, and the gather stays block-local
            # via the static fixup lane = (lane//d)*d + idx.  This
            # avoids the sublane kernel's narrow-minor-dim layouts
            # ((2, n/2) measured ~10x slower than lane passes on v5e).
            # Digits that do NOT divide 128 (caller-supplied dims —
            # build_route accepts any factorization) would make the
            # fixup gather across block boundaries under
            # promise_in_bounds: they fall through to the sublane
            # kernel, whose own d <= 8 assert fails loudly instead.
            assert d <= LANE and LANE % d == 0, d
            new_order = [a for a in order if a != g] + [g]
            kshape = (route.n // LANE, LANE)
            kind = "lane"
        else:
            new_order = [g] + [a for a in order if a != g]
            kshape = (d, route.n // d)
            kind = "sublane"
        view = tuple(dims[a] for a in order)
        perm_axes = tuple(order.index(a) for a in new_order)
        if perm_axes == tuple(range(k)):
            perm_axes = ()
        # index array: canonical row-major -> this pass's layout
        idx = np.ascontiguousarray(
            np.transpose(p.idx, new_order).reshape(kshape), np.int32
        )
        if kind == "lane" and d < LANE:
            idx = ((np.arange(LANE, dtype=np.int32)[None, :] // d) * d
                   + idx)
        passes.append(DevicePass(kind=kind, view=view,
                                 perm_axes=perm_axes, kshape=kshape,
                                 idx=idx))
        order = new_order
    final_view = tuple(dims[a] for a in order)
    final_perm = tuple(order.index(a) for a in range(k))
    if final_perm == tuple(range(k)):
        final_perm = ()
    return RoutePlan(n=route.n, dims=dims, passes=passes,
                     final_view=final_view, final_perm=final_perm)


def device_indices(plan: RoutePlan):
    """The per-pass index arrays as device arrays (put once per graph)."""
    return tuple(jnp.asarray(p.idx) for p in plan.passes)


@dataclasses.dataclass(frozen=True)
class StaticPass:
    """Hashable half of a DevicePass (everything but the index data)."""

    kind: str
    view: tuple[int, ...]
    perm_axes: tuple[int, ...]
    kshape: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class StaticRoute:
    """Hashable route descriptor — safe as a jit static argument, so the
    big per-pass index arrays travel as TRACED pytree leaves (engine
    integration: lux_tpu/ops/expand.py) instead of baked constants."""

    n: int
    dims: tuple[int, ...]
    passes: tuple[StaticPass, ...]
    final_view: tuple[int, ...]
    final_perm: tuple[int, ...]


def freeze_plan(plan: RoutePlan):
    """Split a RoutePlan into (StaticRoute, tuple-of-index-arrays)."""
    static = StaticRoute(
        n=plan.n,
        dims=tuple(plan.dims),
        passes=tuple(
            StaticPass(kind=p.kind, view=tuple(p.view),
                       perm_axes=tuple(p.perm_axes),
                       kshape=tuple(p.kshape))
            for p in plan.passes
        ),
        final_view=tuple(plan.final_view),
        final_perm=tuple(plan.final_perm),
    )
    return static, tuple(p.idx for p in plan.passes)


def apply_route_frozen(x, static, idx_dev, rb: int = 1024,
                       lb: int = 16384, interpret: bool = False):
    """apply_route on a frozen (StaticRoute, idx arrays) pair.  Traced-
    data/static-metadata split makes this directly jittable and
    vmappable (idx arrays stacked with a leading part axis).  A
    pass-fused static (StaticRoutePF, below) replays through the fused
    kernel family instead — same contract, ~40% fewer HBM sweeps."""
    if isinstance(static, StaticRoutePF):
        return apply_route_frozen_pf(x, static, idx_dev,
                                     interpret=interpret)
    y = x
    for p, idx in zip(static.passes, idx_dev):
        y = y.reshape(p.view)
        if p.perm_axes:
            y = y.transpose(p.perm_axes)
        y = y.reshape(p.kshape)
        if p.kind == "lane":
            y = lane_gather(y, idx, rb=rb, interpret=interpret)
        else:
            y = sublane_gather(y, idx, lb=lb, interpret=interpret)
        y = y.reshape(-1)
    y = y.reshape(static.final_view)
    if static.final_perm:
        y = y.transpose(static.final_perm)
    return y.reshape(-1)


def apply_route(x, plan: RoutePlan, idx_dev=None, rb: int = 1024,
                lb: int = 16384, interpret: bool = False):
    """Replay the permutation on device: x flat (n,) -> x[perm].

    Jit-safe (static plan, traced data); pass ``idx_dev`` from
    ``device_indices`` to avoid re-uploading indices per call.
    """
    if idx_dev is None:
        idx_dev = device_indices(plan)
    static, _ = freeze_plan(plan)
    return apply_route_frozen(x, static, idx_dev, rb=rb, lb=lb,
                              interpret=interpret)


# ---------------------------------------------------------------------------
# pass-fused replay: 2-3 Benes passes per kernel, intermediates in VMEM
# ---------------------------------------------------------------------------
#
# The unfused replay above costs one HBM round trip (read + write of the
# full n-element state) per pass, plus an XLA transpose between most
# passes — ~15 trips per routed expand (docs/PERF.md).  But every pass
# permutes within a <= 128-wide digit, so the data a group of 2-3
# consecutive passes touches stays within blocks of prod(group digit
# dims) elements: a VMEM tile covering whole blocks can chain the passes
# ON CHIP — one HBM read, one HBM write per GROUP.
#
# Mechanics, per group (host-planned in _pf_plan):
#   * the group's digits are kept INNERMOST in every in-group layout, so
#     each inter-pass relayout permutes only within the group block;
#   * a relayout that moves elements only WITHIN 128-lane rows is
#     absorbed into the next pass's gather indices at plan time (two
#     in-row permutations compose into one), costing nothing;
#   * a relayout that crosses rows (e.g. the (128, 128) digit swap)
#     becomes a static reshape/transpose/reshape on the VMEM tile;
#   * index arrays carry FULL in-row lanes (digit fixup + any absorbed
#     relayout composed in), so every gather is one `take_along_axis`
#     row gather — values stay < 128, u8-narrowable as before.
#
# Grid steps stream (tile, idx tiles) HBM->VMEM through the standard
# Pallas TPU pipeline, which double-buffers BlockSpec'd operands: tile
# k+1's copies are in flight while tile k computes, so the fused kernels
# run at bandwidth, not at DMA latency.  Pass grouping comes from
# ops/route.plan_fusion_groups under a VMEM budget; the grouping and the
# tile geometry are serialized in StaticRoutePF, so a frozen plan replays
# identically regardless of the knobs' values at replay time.


@dataclasses.dataclass(frozen=True)
class StaticStep:
    """One in-kernel gather step of a fused pass group: an optional
    cross-row in-tile relayout (static reshape/transpose/reshape on the
    VMEM tile) followed by a 128-lane row gather whose index tile holds
    full in-row lanes."""

    relayout: tuple | None  # ((view...), (perm...)) over the tile, or None


@dataclasses.dataclass(frozen=True)
class StaticGroup:
    """Static half of one fused pass group (hashable, jit-safe)."""

    view: tuple[int, ...]       # reshape of the incoming flat array
    perm_axes: tuple[int, ...]  # entry transpose (XLA), () if identity
    kshape: tuple[int, ...]     # 2-D kernel operand shape (R, 128)
    block_rows: int             # grid tile rows (multiple of the block's)
    steps: tuple[StaticStep, ...]


@dataclasses.dataclass(frozen=True)
class StaticRoutePF:
    """Hashable pass-fused route descriptor — drop-in for StaticRoute
    wherever a frozen route is replayed (apply_route_frozen dispatches
    on the type); index arrays travel as traced pytree leaves exactly
    like the unfused plan's."""

    n: int
    dims: tuple[int, ...]
    groups: tuple[StaticGroup, ...]
    final_view: tuple[int, ...]
    final_perm: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class StaticMXGroup:
    """Static half of an MXREDUCE final group (hashable, jit-safe): the
    route's last 1-3 Benes passes chained in ONE Pallas kernel with the
    segmented reduction — the kernel gathers like a fused pass group,
    then contracts each tile against its plan-time rank map
    (onehot(v_blk, T) @ vals on the MXU for float sums; masked VPU
    reduce for min/max and integer sums, the no-matmul-identity layout)
    and accumulates straight into the (num_blocks * v_blk, 1) totals
    column.  The full group-space array is READ once and never written
    back: the separate segment/scatter sweep of the plain fused replay
    is gone (roofline.routed_hbm_passes charges this kernel 0.5 sweeps).

    Precision contract (docs/PERF.md "MXU reduction"): the one-hot
    operand is exact in bf16; values enter the contraction in their own
    dtype (f32 stays f32 — no quantization — and bf16 state is already
    bf16, so operands are "bf16 where exact"); accumulation is ALWAYS
    f32 (preferred_element_type), and float-sum totals are returned as
    f32.  min/max and integer ops never touch the MXU and preserve
    their dtype bitwise."""

    view: tuple[int, ...]       # reshape of the incoming flat array
    perm_axes: tuple[int, ...]  # entry transpose (XLA), () if identity
    kshape: tuple[int, ...]     # 2-D kernel operand shape (R, 128)
    block_rows: int             # reduce-tile rows (covers whole blocks)
    steps: tuple[StaticStep, ...]
    v_blk: int                  # totals ranks per output block
    num_blocks: int             # output blocks (>= 1)
    op: str                     # "sum" | "min" | "max"


def route_num_arrays(static) -> int:
    """Index-array count of a frozen route (unfused: one per pass;
    pass-fused: one per in-group gather step) — the ONE place array
    layout arithmetic for both forms lives."""
    if isinstance(static, StaticRoutePF):
        return sum(len(g.steps) for g in static.groups)
    return len(static.passes)


def route_num_hbm_passes(static) -> int:
    """Full-array HBM read+write sweeps of a frozen route's replay:
    kernels launched (unfused: per pass; fused: per group).  Entry
    transposes between groups/passes are additional XLA copies in both
    forms and are excluded here, as in utils/roofline's model."""
    if isinstance(static, StaticRoutePF):
        return len(static.groups)
    return len(static.passes)


def _pf_defaults(max_block=None, max_group=None, vmem_mb=None):
    """Pass-fusion knobs with env defaults: LUX_PF_MAX_BLOCK (elements a
    group's digit block may span), LUX_PF_MAX_GROUP (passes per kernel),
    LUX_PF_VMEM_MB (tile budget for the double-buffered operands).  The
    knobs shape the PLAN; they are baked into the frozen static (and the
    plan-cache key, ops/expand), never read at replay time."""
    from lux_tpu.utils.config import env_int

    if max_block is None:
        max_block = env_int("LUX_PF_MAX_BLOCK", 1 << 17, minimum=LANE)
    if max_group is None:
        max_group = env_int("LUX_PF_MAX_GROUP", 3, minimum=1)
    if vmem_mb is None:
        vmem_mb = env_int("LUX_PF_VMEM_MB", 8, minimum=1)
    return max_block, max_group, vmem_mb


def _pf_block_rows(R: int, rpb: int, n_steps: int, vmem_bytes: int) -> int:
    """Tile rows for one fused kernel: the largest power of two whose
    double-buffered operand set (f32 data in+out, int32-width index tile
    per step — conservative vs the u8 narrowing) fits the budget,
    clamped to the whole array.  A tile can never shrink below ONE block
    unit (rpb rows) — if that already blows the budget the knobs are
    inconsistent (LUX_PF_MAX_BLOCK too big for LUX_PF_VMEM_MB), and the
    right failure is HERE at plan time, not a Mosaic VMEM blow-up on
    chip where the interpret-mode suite can never catch it."""
    per_elem = 2 * (8 + 4 * n_steps)
    rows = max(vmem_bytes // (LANE * per_elem), 1)
    if rpb > rows:
        raise ValueError(
            f"pass-fusion block of {rpb * LANE} elements needs "
            f"~{rpb * LANE * per_elem} B of VMEM, over the "
            f"{vmem_bytes} B budget — lower LUX_PF_MAX_BLOCK or raise "
            "LUX_PF_VMEM_MB")
    tb = 1
    while tb * 2 <= rows:
        tb *= 2
    return max(rpb, min(tb, R))


def _block_relayout(dims, gorder, new_gorder):
    """Positional source map of an in-tile digit relayout: for each
    position p in the NEW block layout, src[p] is the position of that
    element in the OLD layout.  Returns (src (B,), row_local) — the
    relayout is identical for every block, so one B-element map covers
    the whole array."""
    shape = tuple(dims[a] for a in gorder)
    b = 1
    for s in shape:
        b *= s
    ids = np.arange(b, dtype=np.int64).reshape(shape)
    perm = tuple(gorder.index(a) for a in new_gorder)
    src = np.ascontiguousarray(np.transpose(ids, perm)).ravel()
    if b <= LANE:
        return src, True  # sub-row blocks can never cross rows
    row_local = bool((src // LANE == np.arange(b, dtype=np.int64)
                      // LANE).all())
    return src, row_local


def _compose_rowlocal(row_idx: np.ndarray, src: np.ndarray,
                      b: int) -> np.ndarray:
    """Fold a row-local relayout into the next pass's in-row gather:
    combined[r, c] = old-layout lane of the element the gather wants at
    (r, c).  ``src`` is the block map from _block_relayout; ``b`` the
    block size."""
    t = row_idx
    if b >= LANE:
        rpb = b // LANE
        rows = (np.arange(t.shape[0], dtype=np.int64)[:, None] % rpb) * LANE
        return src[rows + t] % LANE
    return (t // b) * b + src[t % b]


def _pf_plan(n: int, dims, canon, group_sizes, vmem_bytes: int,
             mx=None):
    """Lower canonical Benes pass indices into the pass-fused frozen
    form.  ``canon``: per-pass full-size index arrays in canonical
    mixed-radix shape (Route.passes[j].idx), values in [0, dims[axis]).
    Returns (StaticRoutePF, tuple of (R, 128) int32 index arrays, one
    per gather step).

    ``mx`` (a dict with keys v_blk/num_blocks/op/tile_rows) turns the
    LAST group into an MXREDUCE group: its passes chain in the same
    kernel as the segmented one-hot reduction (mxreduce_pass_gather),
    the final canonical-order restore transpose is SKIPPED (the
    reduction consumes the final PHYSICAL layout directly — callers
    pre-compose their target permutation with ``mx_physical_order`` so
    that layout IS the desired one), and the return grows to
    (StaticRoutePF[prefix groups, identity final], prefix arrays,
    StaticMXGroup, mx step arrays)."""
    from lux_tpu.ops import route as route_mod

    k = len(dims)
    for d in dims:
        if d > LANE or LANE % d:
            raise ValueError(
                "pass fusion requires lane-eligible digits (d <= 128, "
                f"d | 128); got dims={tuple(dims)}")
    if n < LANE:
        raise ValueError(f"pass fusion requires n >= {LANE}, got {n}")
    axes = route_mod.benes_axes(k)
    assert len(canon) == len(axes), (len(canon), len(axes))
    assert sum(group_sizes) == len(axes), (group_sizes, axes)
    R = n // LANE
    order = list(range(k))
    groups: list[StaticGroup] = []
    arrays: list[np.ndarray] = []
    mx_group = None
    mx_arrays: list[np.ndarray] = []
    j = 0
    for gi, glen in enumerate(group_sizes):
        is_mx = mx is not None and gi == len(group_sizes) - 1
        gaxes = list(axes[j:j + glen])
        gcanon = canon[j:j + glen]
        sset: list[int] = []
        for a in gaxes:
            if a not in sset:
                sset.append(a)
        B = 1
        for a in sset:
            B *= dims[a]
        rpb = max(B // LANE, 1)
        if is_mx:
            # the reduce tile: small (the rank-block alignment padding
            # of the mx layout is a multiple of its span), covering
            # whole suffix blocks so the chained gathers stay tile-local
            tb = max(rpb, min(int(mx["tile_rows"]), R))
            assert tb % rpb == 0 and R % tb == 0, (tb, rpb, R)
        else:
            tb = _pf_block_rows(R, rpb, glen, vmem_bytes)
        rest = [a for a in order if a not in sset]
        # entry layout: rest axes (current relative order) outermost,
        # group axes innermost with the first gathered axis in lane
        # position — all in-group movement is then block-local
        gorder = [a for a in order if a in sset and a != gaxes[0]]
        gorder.append(gaxes[0])
        new_order = rest + gorder
        view = tuple(dims[a] for a in order)
        perm_axes = tuple(order.index(a) for a in new_order)
        if perm_axes == tuple(range(k)):
            perm_axes = ()
        steps: list[StaticStep] = []
        g_arrays: list[np.ndarray] = []
        for step_i, (g, idx_canon) in enumerate(zip(gaxes, gcanon)):
            d = dims[g]
            relayout = None
            src = None
            if step_i and gorder[-1] != g:
                new_gorder = [a for a in gorder if a != g] + [g]
                src, row_local = _block_relayout(dims, gorder, new_gorder)
                if not row_local:
                    ub = tb * LANE // B
                    rview = (ub,) + tuple(dims[a] for a in gorder)
                    rperm = (0,) + tuple(gorder.index(a) + 1
                                         for a in new_gorder)
                    relayout = (rview, rperm)
                    src = None
                gorder = new_gorder
            full_order = rest + gorder
            idx_full = np.ascontiguousarray(
                np.transpose(np.asarray(idx_canon, np.int64), full_order)
            ).reshape(R, LANE)
            base = (np.arange(LANE, dtype=np.int64)[None, :] // d) * d
            row_idx = base + idx_full
            if src is not None:
                row_idx = _compose_rowlocal(row_idx, src, B)
            assert row_idx.min() >= 0 and row_idx.max() < LANE, (
                row_idx.min(), row_idx.max())
            steps.append(StaticStep(relayout=relayout))
            g_arrays.append(np.ascontiguousarray(row_idx, np.int32))
        if is_mx:
            mx_group = StaticMXGroup(
                view=view, perm_axes=perm_axes, kshape=(R, LANE),
                block_rows=tb, steps=tuple(steps),
                v_blk=int(mx["v_blk"]), num_blocks=int(mx["num_blocks"]),
                op=str(mx["op"]))
            mx_arrays = g_arrays
        else:
            groups.append(StaticGroup(view=view, perm_axes=perm_axes,
                                      kshape=(R, LANE), block_rows=tb,
                                      steps=tuple(steps)))
            arrays.extend(g_arrays)
        order = rest + gorder
        j += glen
    if mx is not None:
        # the reduction consumes the final physical layout in place —
        # no restore transpose; the layout the caller's rank map was
        # built against must be exactly the one the threading produced
        assert order == _pf_final_order(dims, group_sizes), (
            order, group_sizes)
        return (StaticRoutePF(n=n, dims=tuple(dims),
                              groups=tuple(groups),
                              final_view=(n,), final_perm=()),
                tuple(arrays), mx_group, tuple(mx_arrays))
    final_view = tuple(dims[a] for a in order)
    final_perm = tuple(order.index(a) for a in range(k))
    if final_perm == tuple(range(k)):
        final_perm = ()
    return (StaticRoutePF(n=n, dims=tuple(dims), groups=tuple(groups),
                          final_view=final_view, final_perm=final_perm),
            tuple(arrays))


def plan_route_pf(route: Route, group_sizes=None, max_block=None,
                  max_group=None, vmem_mb=None):
    """Compile a host Route into the pass-fused frozen form directly.
    ``group_sizes`` overrides the planner (tests force specific group
    widths through it)."""
    from lux_tpu.ops import route as route_mod

    max_block, max_group, vmem_mb = _pf_defaults(max_block, max_group,
                                                 vmem_mb)
    if group_sizes is None:
        group_sizes = route_mod.plan_fusion_groups(route.dims, max_block,
                                                   max_group)
    canon = [np.asarray(p.idx) for p in route.passes]
    return _pf_plan(route.n, route.dims, canon, group_sizes, vmem_mb << 20)


def _frozen_canonical(static: StaticRoute, arrays):
    """Reconstruct the canonical per-pass index arrays from a frozen
    unfused plan by inverting plan_route's per-pass arrangement (the
    layout threading is deterministic, so the inversion is exact).  The
    passes must be a full Benes sequence of lane passes — the only form
    the expand planners produce for n >= 128."""
    from lux_tpu.ops import route as route_mod

    dims = static.dims
    k = len(dims)
    if len(static.passes) != 2 * k - 1:
        raise ValueError(
            f"pass fusion expects a full Benes pass list (2k-1), got "
            f"{len(static.passes)} passes for {k} digits")
    axes = route_mod.benes_axes(k)
    order = list(range(k))
    canon = []
    for p, arr, g in zip(static.passes, arrays, axes):
        if p.kind != "lane":
            raise ValueError("pass fusion covers lane-kernel routes only")
        d = dims[g]
        new_order = [a for a in order if a != g] + [g]
        idx = np.asarray(arr, np.int64).reshape(p.kshape)
        if d < LANE:
            idx = idx - (np.arange(LANE, dtype=np.int64)[None, :] // d) * d
        shaped = idx.reshape(tuple(dims[a] for a in new_order))
        inv = tuple(np.argsort(np.asarray(new_order)))
        canon.append(np.ascontiguousarray(
            np.transpose(shaped, inv)).astype(np.int32))
        order = new_order
    return canon


def pf_from_frozen(static: StaticRoute, arrays, group_sizes=None,
                   max_block=None, max_group=None, vmem_mb=None):
    """Transform a frozen UNFUSED route plan into the pass-fused form —
    pure NumPy rearrangement, no Euler recoloring, so a cached unfused
    plan upgrades in seconds instead of minutes.  Replay is bitwise
    identical to the unfused replay of the same plan (the fused kernels
    move the same bits through the same per-pass permutations)."""
    from lux_tpu.ops import route as route_mod

    max_block, max_group, vmem_mb = _pf_defaults(max_block, max_group,
                                                 vmem_mb)
    if group_sizes is None:
        group_sizes = route_mod.plan_fusion_groups(static.dims, max_block,
                                                   max_group)
    canon = _frozen_canonical(static, arrays)
    return _pf_plan(static.n, static.dims, canon, group_sizes,
                    vmem_mb << 20)


# ---------------------------------------------------------------------------
# mxreduce: the segmented reduction fused into the final pass group
# ---------------------------------------------------------------------------
#
# The plain fused replay (apply_fused) ends with: last r2 kernel writes
# the full group-space array back to HBM, then a separate masked
# reshape-reduce sweep READS it all again.  mxreduce deletes both: the
# final group's kernel keeps each tile in VMEM after its chained
# gathers, applies the program's edge_value, and reduces the tile by
# destination RANK via the one-hot contraction of arXiv:1811.09736
# (the pattern already proven on this repo's spmv kernels), streaming
# only the tiny totals column out.  The host-side planner (ops/expand)
# lays the group space out so that (a) ranks are monotone along the
# final PHYSICAL layout (the route's target permutation is pre-composed
# with mx_physical_order, so no restore transpose is ever needed) and
# (b) every reduce tile maps into exactly ONE v_blk-rank output block
# (rank-block starts are tile-span aligned) — which lets the output
# BlockSpec be scalar-prefetch routed and accumulated in VMEM exactly
# like ops/pallas_spmv's block-CSR kernel.


def _mx_defaults(mx_max_block=None, tile_rows=None, v_blk=None):
    """mxreduce knobs with env defaults: LUX_MX_MAX_BLOCK (largest
    suffix-group digit block the reduce kernel may chain — also bounds
    the rank-block alignment padding), LUX_MX_TILE_ROWS (reduce-tile
    rows; the kernel unrolls one contraction per row), LUX_MX_VBLK
    (totals ranks per output block; multiple of 8, <= 248 so the u8
    rank tiles keep a distinct sentinel).  Like the pf knobs they shape
    the PLAN (and salt the cache key) and are never read at replay."""
    from lux_tpu.utils.config import env_int

    if mx_max_block is None:
        mx_max_block = env_int("LUX_MX_MAX_BLOCK", 1024, minimum=LANE)
    if tile_rows is None:
        tile_rows = env_int("LUX_MX_TILE_ROWS", 8, minimum=1)
    if v_blk is None:
        v_blk = env_int("LUX_MX_VBLK", 128, minimum=8, maximum=248)
    if v_blk % 8:
        raise ValueError(f"LUX_MX_VBLK must be a multiple of 8 (the "
                         f"output column's sublane alignment), got {v_blk}")
    for name, v in (("LUX_MX_MAX_BLOCK", mx_max_block),
                    ("LUX_MX_TILE_ROWS", tile_rows)):
        if v & (v - 1):
            raise ValueError(f"{name} must be a power of two (tile and "
                             f"block geometry divide each other), got {v}")
    if mx_max_block > tile_rows * LANE:
        raise ValueError(
            f"LUX_MX_MAX_BLOCK ({mx_max_block}) exceeds the reduce tile "
            f"(LUX_MX_TILE_ROWS*128 = {tile_rows * LANE}): the suffix "
            "group's blocks must fit one tile")
    return mx_max_block, tile_rows, v_blk


def _pf_final_order(dims, group_sizes) -> list[int]:
    """The digit-axis order of the array's FINAL physical layout after
    all fused groups, BEFORE the restore transpose — a dry run of
    _pf_plan's order threading (asserted against the real plan there,
    so the two can never drift).  Needed ahead of route construction:
    the mxreduce planner pre-composes its target permutation with this
    layout (mx_physical_order)."""
    from lux_tpu.ops import route as route_mod

    k = len(dims)
    axes = route_mod.benes_axes(k)
    assert sum(group_sizes) == len(axes), (group_sizes, axes)
    order = list(range(k))
    j = 0
    for glen in group_sizes:
        gaxes = list(axes[j:j + glen])
        sset: list[int] = []
        for a in gaxes:
            if a not in sset:
                sset.append(a)
        rest = [a for a in order if a not in sset]
        gorder = [a for a in order if a in sset and a != gaxes[0]]
        gorder.append(gaxes[0])
        for step_i, g in enumerate(gaxes):
            if step_i and gorder[-1] != g:
                gorder = [a for a in gorder if a != g] + [g]
        order = rest + gorder
        j += glen
    return order


def mx_physical_order(n: int, dims, group_sizes) -> np.ndarray:
    """sigma: the canonical flat slot living at each FINAL physical
    position of a pass-fused replay that skips the restore transpose.
    A caller that wants physical position p to end up holding
    ``x[desired[p]]`` routes the permutation ``routed`` where
    ``routed[sigma] = desired`` — the Benes machinery then lands the
    desired layout directly and the mxreduce kernel consumes it with
    plan-time rank tiles, no transpose."""
    order = _pf_final_order(dims, group_sizes)
    ids = np.arange(n, dtype=np.int64).reshape(tuple(dims))
    return np.ascontiguousarray(np.transpose(ids, order)).reshape(-1)


def plan_route_pf_mx(route: Route, v_blk: int, num_blocks: int, op: str,
                     group_sizes, tile_rows: int, max_block=None,
                     max_group=None, vmem_mb=None):
    """Compile a host Route into the MXREDUCE pass-fused form: the
    prefix groups replay as ordinary fused kernels (identity final —
    no restore), the suffix group becomes the StaticMXGroup consumed by
    ``mxreduce_pass_gather``.  ``group_sizes`` MUST come from
    route.plan_mx_fusion_groups for the same dims, and the route's
    target permutation must have been pre-composed with
    ``mx_physical_order(n, dims, group_sizes)``.

    Returns (StaticRoutePF, prefix arrays, StaticMXGroup, mx step
    arrays)."""
    max_block, max_group, vmem_mb = _pf_defaults(max_block, max_group,
                                                 vmem_mb)
    canon = [np.asarray(p.idx) for p in route.passes]
    return _pf_plan(route.n, route.dims, canon, group_sizes,
                    vmem_mb << 20,
                    mx={"v_blk": v_blk, "num_blocks": num_blocks,
                        "op": op, "tile_rows": tile_rows})


def _mx_neutral(op: str, dtype):
    if op == "sum":
        return jnp.zeros((), dtype)
    return reduce_neutral_mx(op, dtype)


def reduce_neutral_mx(op: str, dtype):
    """min/max identity (same convention as ops/pallas_spmv
    reduce_neutral; duplicated at this layer to keep pallas_shuffle
    importable without the spmv module's graph deps)."""
    d = jnp.dtype(dtype)
    if jnp.issubdtype(d, jnp.integer):
        info = jnp.iinfo(d)
        return jnp.asarray(info.max if op == "min" else info.min, d)
    return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, d)


def _mx_kernel(group: StaticMXGroup, edge_fn, weighted: bool,
               out_dtype, tile_block_ref, tile_first_ref, x_ref, *refs):
    """MXREDUCE kernel body: chained gathers on the VMEM tile (exactly
    _pf_kernel's steps), then edge_value + sentinel masking + the
    per-row one-hot reduction accumulated into the scalar-prefetch
    routed output block.  One HBM read of the tile, zero full writes."""
    import jax.experimental.pallas as pl

    tb, v_blk, op = group.block_rows, group.v_blk, group.op
    n_steps = len(group.steps)
    o_ref = refs[-1]
    i = pl.program_id(0)
    y = x_ref[:]
    for st, iref in zip(group.steps, refs[:n_steps]):
        if st.relayout is not None:
            rview, rperm = st.relayout
            y = y.reshape(rview).transpose(rperm).reshape(tb, LANE)
        y = jnp.take_along_axis(
            y, iref[:].astype(jnp.int32), axis=1, mode="promise_in_bounds"
        )
    dst = refs[n_steps][:].astype(jnp.int32)  # (tb, 128) rank-rel map
    w = refs[n_steps + 1][:] if weighted else None
    vals = edge_fn(y, w) if edge_fn is not None else y
    valid = dst < v_blk  # sentinel (v_blk) marks padding/junk slots
    neutral = _mx_neutral(op, vals.dtype)
    # mask BEFORE the contraction: routed junk values may be Inf/NaN
    # sentinels (e.g. int32 maxes cast by an edge_fn) and 0 * NaN = NaN
    # would poison the matmul accumulator
    vals = jnp.where(valid, vals, neutral)

    @pl.when(tile_first_ref[i] == 1)
    def _():
        o_ref[:] = jnp.full_like(o_ref, _mx_neutral(op, o_ref.dtype))

    float_sum = op == "sum" and jnp.issubdtype(vals.dtype, jnp.floating)
    if float_sum:
        # MXU path: bf16 operands where exact (the one-hot is exact in
        # bf16; bf16 values are already bf16), f32 accumulate always
        cd = (jnp.bfloat16 if vals.dtype == jnp.bfloat16
              else jnp.float32)
        acc = jnp.zeros((v_blk, 1), jnp.float32)
    else:
        acc = jnp.full((v_blk, 1), _mx_neutral(op, vals.dtype),
                       vals.dtype)
    for r in range(tb):
        dr = dst[r:r + 1, :]    # (1, 128)
        vr = vals[r:r + 1, :]   # (1, 128)
        iota = jax.lax.broadcasted_iota(jnp.int32, (v_blk, LANE), 0)
        onehot = iota == dr     # (v_blk, 128); sentinel matches no row
        if float_sum:
            acc = acc + jax.lax.dot_general(
                onehot.astype(cd), vr.astype(cd),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            masked = jnp.where(onehot, jnp.broadcast_to(vr, onehot.shape),
                               _mx_neutral(op, vals.dtype))
            red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[op]
            part = red(masked, axis=1, keepdims=True)
            if op == "sum":
                acc = acc + part
            elif op == "min":
                acc = jnp.minimum(acc, part)
            else:
                acc = jnp.maximum(acc, part)
    if op == "sum":
        o_ref[:] = o_ref[:] + acc.astype(out_dtype)
    elif op == "min":
        o_ref[:] = jnp.minimum(o_ref[:], acc.astype(out_dtype))
    else:
        o_ref[:] = jnp.maximum(o_ref[:], acc.astype(out_dtype))


@functools.partial(jax.jit,
                   static_argnames=("group", "edge_fn", "interpret"))
def mxreduce_pass_gather(x, idx, dst_rel, tile_block, tile_first,
                         group: StaticMXGroup, edge_fn=None,
                         weights=None, interpret: bool = False):
    """Run the MXREDUCE final group: x (R, 128) in the group's entry
    layout -> totals (num_blocks * v_blk, 1).

    ``idx``: tuple of per-step gather index tiles ((R, 128), values
    < 128, u8 or wider).  ``dst_rel``: (R, 128) plan-time rank map of
    the FINAL layout (values < v_blk; v_blk = padding sentinel;
    u8-narrowable).  ``tile_block``/``tile_first``: (R / block_rows,)
    int32 scalar-prefetch routing of each tile's output block.
    ``edge_fn(vals, weights)`` is the program's elementwise edge_value,
    applied on the VMEM tile; ``weights`` an optional (R, 128) f32
    plan-time array in the same layout."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = x.shape[0]
    tb = group.block_rows
    assert r % tb == 0, (r, tb)
    assert group.kshape == (r, LANE), (group.kshape, x.shape)
    weighted = weights is not None
    if edge_fn is None:
        val_dtype = x.dtype
    else:
        val_dtype = jax.eval_shape(
            edge_fn, jax.ShapeDtypeStruct((tb, LANE), x.dtype),
            jax.ShapeDtypeStruct((tb, LANE), jnp.float32)
            if weighted else None).dtype
    out_dtype = (jnp.float32
                 if group.op == "sum" and jnp.issubdtype(val_dtype,
                                                         jnp.floating)
                 else val_dtype)
    spec = pl.BlockSpec((tb, LANE), lambda i, cb, cf: (i, 0))
    n_in = 1 + len(idx) + 1 + int(weighted)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(r // tb,),
        in_specs=[spec] * n_in,
        out_specs=pl.BlockSpec((group.v_blk, 1),
                               lambda i, cb, cf: (cb[i], 0)),
    )
    operands = (x,) + tuple(idx) + (dst_rel,)
    if weighted:
        operands = operands + (weights,)
    out = pl.pallas_call(
        functools.partial(_mx_kernel, group, edge_fn, weighted,
                          jnp.dtype(out_dtype)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (group.num_blocks * group.v_blk, 1), out_dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(tile_block, tile_first, *operands)
    return out.reshape(-1)


def _pf_kernel(steps, tb, x_ref, *refs):
    """Fused pass-group kernel body: chain (relayout?, row gather) steps
    on the VMEM-resident tile; one HBM read (x tile), one HBM write (out
    tile), index tiles streamed per step."""
    o_ref = refs[-1]
    y = x_ref[:]
    for st, iref in zip(steps, refs[:-1]):
        if st.relayout is not None:
            rview, rperm = st.relayout
            y = y.reshape(rview).transpose(rperm).reshape(tb, LANE)
        y = jnp.take_along_axis(
            y, iref[:].astype(jnp.int32), axis=1, mode="promise_in_bounds"
        )
    o_ref[:] = y


@functools.partial(jax.jit, static_argnames=("group", "interpret"))
def fused_pass_gather(x, idx, group: StaticGroup, interpret: bool = False):
    """Run ONE fused pass group: x (R, 128) -> out (R, 128), with the
    group's 2-3 permutation passes chained in VMEM.  ``idx`` is the
    tuple of per-step index arrays (same (R, 128) geometry, values
    < 128, u8 or wider)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = x.shape[0]
    tb = group.block_rows
    assert r % tb == 0, (r, tb)
    spec = pl.BlockSpec((tb, LANE), lambda i: (i, 0))
    return pl.pallas_call(
        functools.partial(_pf_kernel, group.steps, tb),
        grid=(r // tb,),
        in_specs=[spec] * (1 + len(idx)),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("arbitrary",)
        ),
        interpret=interpret,
    )(x, *idx)


def apply_route_frozen_pf(x, static: StaticRoutePF, idx_dev,
                          interpret: bool = False):
    """apply_route_frozen for the pass-fused form: one kernel per GROUP,
    entry transposes between groups only."""
    y = x
    i = 0
    for g in static.groups:
        y = y.reshape(g.view)
        if g.perm_axes:
            y = y.transpose(g.perm_axes)
        y = y.reshape(g.kshape)
        n_steps = len(g.steps)
        y = fused_pass_gather(y, tuple(idx_dev[i:i + n_steps]), group=g,
                              interpret=interpret)
        i += n_steps
        y = y.reshape(-1)
    y = y.reshape(static.final_view)
    if static.final_perm:
        y = y.transpose(static.final_perm)
    return y.reshape(-1)
