"""--serve CLI driver shared by the sssp and pagerank apps.

Runs the full serving path on one process: build the pull layout, warm
the configured Q buckets, push the requested query burst through the
micro-batching scheduler, and print the structured metrics summary as a
single JSON line (the same shape tools/serve_bench.py and the bench.py
``sssp_qps_*`` row emit).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from lux_tpu.serve.benchmarks import pick_sources
from lux_tpu.serve.metrics import ServeMetrics
from lux_tpu.serve.scheduler import MicroBatchScheduler, RejectedError
from lux_tpu.serve.warm import WarmEngineCache
from lux_tpu.utils.config import RunConfig


def _validate(cfg: RunConfig) -> None:
    bad = []
    if cfg.distributed:
        bad.append("--distributed")
    if cfg.exchange != "allgather":
        bad.append(f"--exchange {cfg.exchange}")
    if cfg.method == "pallas":
        bad.append("--method pallas")
    if getattr(cfg, "route_gather", ""):
        bad.append("--route-gather")
    if cfg.compact_gather or cfg.sort_segments:
        bad.append("--compact-gather/--sort-segments")
    if cfg.ckpt_every or cfg.ckpt_dir:
        bad.append("checkpointing")
    if getattr(cfg, "repartition_every", 0):
        bad.append("--repartition-every")
    if cfg.verbose:
        bad.append("--verbose")
    if getattr(cfg, "stream_hbm_gib", 0.0):
        bad.append("--stream-hbm-gib")
    if getattr(cfg, "weighted", False) or getattr(cfg, "delta", 0):
        bad.append("--weighted/--delta")
    if bad:
        raise SystemExit(
            "--serve is the single-process batched query service "
            "(allgather pull layout, unweighted programs); it does not "
            "combine with: " + ", ".join(bad))


def parse_buckets(spec: str) -> tuple:
    try:
        qs = tuple(sorted({int(x) for x in spec.split(",") if x.strip()}))
    except ValueError:
        raise SystemExit(f"--serve-buckets: bad bucket list {spec!r}")
    if not qs or qs[0] < 1:
        raise SystemExit(f"--serve-buckets: buckets must be >= 1: {spec!r}")
    return qs


def parse_sources(cfg: RunConfig, g) -> np.ndarray:
    if cfg.serve_sources:
        try:
            src = np.asarray(
                [int(x) for x in cfg.serve_sources.split(",") if x.strip()],
                np.int32)
        except ValueError:
            raise SystemExit(
                f"--serve-sources: bad vertex list {cfg.serve_sources!r}")
        if src.size == 0 or src.min() < 0 or src.max() >= g.nv:
            raise SystemExit(
                f"--serve-sources: vertices must be in [0, {g.nv})")
        return src
    if cfg.serve_queries < 1:
        raise SystemExit("--serve-queries must be >= 1")
    return pick_sources(g, cfg.serve_queries, seed=cfg.seed)


def _check_answers(app: str, g, cfg: RunConfig, sources, answers) -> int:
    """-check: validate every answer against the app's host oracle
    contract (triangle inequality for sssp, one-iteration residual for
    ppr is covered by tests — here the first few seeds get the exact
    oracle).  Returns the violation count."""
    bad = 0
    if app == "sssp":
        from lux_tpu.models import sssp as sssp_model

        for i in range(len(sources)):
            bad += sssp_model.check_distances(g, answers[i])
            # bind the answer to ITS request: the triangle inequality
            # holds for any source's distance field (even all-INF), so a
            # row mismapped across requests would otherwise pass
            if answers[i][int(sources[i])] != 0:
                bad += 1
    else:
        from lux_tpu.models.pagerank import ppr_reference

        for i in range(min(len(sources), 4)):
            want = ppr_reference(g, int(sources[i]), cfg.num_iters)
            scale = max(float(np.abs(want).mean()), 1e-30)
            tol = 1e-3 * np.maximum(np.abs(want), scale)
            bad += int(np.sum(np.abs(answers[i] - want) > tol))
    return bad


def run_serve_cli(cfg: RunConfig, g, app: str) -> int:
    """The --serve entry: serve cfg.serve_queries (or --serve-sources)
    through warm engines; prints per-run JSON metrics.  Returns the
    process exit code."""
    from lux_tpu import obs
    from lux_tpu.apps import common
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.utils.timing import Timer

    _validate(cfg)
    buckets = parse_buckets(cfg.serve_buckets)
    sources = parse_sources(cfg, g)
    with obs.span("serve.layout", parts=cfg.num_parts):
        shards = build_pull_shards(g, cfg.num_parts)
    metrics = ServeMetrics()
    cache = WarmEngineCache(
        shards, apps=(app,), q_buckets=buckets, method=cfg.method,
        num_iters=cfg.num_iters, max_iters=cfg.max_iters, metrics=metrics,
    )
    warm_s = cache.prewarm()
    print(f"warmed {len(buckets)} {app} bucket(s) {buckets} in "
          f"{warm_s:.1f} s")
    sched = MicroBatchScheduler(
        cache, app=app, max_wait_ms=cfg.serve_wait_ms,
        max_queue=cfg.serve_max_queue,
        default_timeout_ms=cfg.serve_timeout_ms, metrics=metrics,
    )
    timer = Timer()
    futs = []
    with obs.span("serve.burst", app=app, queries=len(sources)):
        for s in sources:
            while True:
                try:
                    futs.append(sched.submit(int(s)))
                    break
                except RejectedError:
                    # burst larger than the admission bound: pump the
                    # scheduler until the queue drains a batch, then retry —
                    # the backpressure loop a real client would run
                    if not sched.step():
                        time.sleep(max(cfg.serve_wait_ms / 4e3, 1e-4))
        sched.drain()
    answers = []
    timeouts = 0
    for f in futs:
        try:
            answers.append(f.result(timeout=0))
        except Exception:  # noqa: BLE001 — timeout/engine error rows
            answers.append(None)
            timeouts += 1
    elapsed = timer.stop()
    cache_stats = cache.stats()
    summary = metrics.summary(elapsed_s=elapsed, cache_stats=cache_stats)
    # end-of-run snapshot: the event log's serve section is complete even
    # when the periodic cadence never fired (short bursts)
    metrics.emit_snapshot(summary=summary)
    print(json.dumps({"metric": f"{app}_serve", "run_id": obs.run_id(),
                      **summary}), flush=True)
    prom_path = os.environ.get("LUX_SERVE_PROM")
    if prom_path:
        # one-shot scrape artifact: the same Prometheus text a fleet
        # collector would pull (node_exporter textfile-collector style).
        # A bad path must not fail a run that already answered its
        # queries — observability is never load-bearing
        try:
            with open(prom_path, "w", encoding="utf-8") as f:
                # exemplars off: the textfile collector parses classic
                # 0.0.4 text format, where exemplar syntax is illegal
                f.write(metrics.dump(elapsed_s=elapsed,
                                     cache_stats=cache_stats,
                                     exemplars=False))
            print(f"# prometheus metrics -> {prom_path}", flush=True)
        except OSError as e:
            print(f"# prometheus metrics NOT written ({prom_path}): {e}",
                  file=sys.stderr, flush=True)
    if cfg.check:
        ok_rows = [(s, a) for s, a in zip(sources, answers) if a is not None]
        violations = _check_answers(
            app, g, cfg, [s for s, _ in ok_rows],
            [a for _, a in ok_rows]) + timeouts
        ok = common.print_check(f"{app} serve", violations)
        return 0 if ok else 1
    return 0
