"""lux_tpu.serve — batched multi-source query serving.

Every engine below this package runs ONE analytics job per invocation;
serving turns the same frontier machinery into a request/response path:

  * ``serve.batched``   — multi-source engines: one compiled step answers
    Q sssp/bfs sources or Q personalized-PageRank seeds per iteration
    (trailing query axis over shared graph shards).
  * ``serve.warm``      — compiled-engine cache keyed on
    (app, method, layout, Q bucket), pre-traced at service start.
  * ``serve.scheduler`` — dynamic micro-batching admission queue:
    coalesce, pad, deadline, backpressure, cold-shape degradation.
  * ``serve.metrics``   — per-query latency percentiles, batch occupancy,
    queue depth, warm-vs-cold hit ratio (the structured-stats path of
    utils/timing + utils/roofline).
  * ``serve.benchmarks``— the measurement core shared by
    tools/serve_bench.py and the bench.py ``sssp_qps_*`` row.
  * ``serve.fleet``     — the multi-replica layer: controller/worker
    split, consistent-hash routing, cross-replica backpressure, live
    republish, and the ``sssp_fleet_qps_*`` saturation bench.

The unit of work here is a REQUEST, not a graph.
"""
from lux_tpu.serve.batched import (  # noqa: F401
    BatchedEngine,
    BatchedResult,
    MultiSourcePPR,
    MultiSourceSSSP,
)
from lux_tpu.serve.scheduler import (  # noqa: F401
    MicroBatchScheduler,
    RejectedError,
    ServeTimeoutError,
)
from lux_tpu.serve.warm import EngineKey, WarmEngineCache  # noqa: F401
