"""lux_tpu.serve — batched multi-source query serving.

Every engine below this package runs ONE analytics job per invocation;
serving turns the same frontier machinery into a request/response path:

  * ``serve.batched``   — multi-source engines: one compiled step answers
    Q sssp/bfs sources or Q personalized-PageRank seeds per iteration
    (trailing query axis over shared graph shards).
  * ``serve.warm``      — compiled-engine cache keyed on
    (app, method, layout, Q bucket), pre-traced at service start.
  * ``serve.scheduler`` — dynamic micro-batching admission queue:
    coalesce, pad, deadline, backpressure, cold-shape degradation.
  * ``serve.metrics``   — per-query latency percentiles, batch occupancy,
    queue depth, warm-vs-cold hit ratio (the structured-stats path of
    utils/timing + utils/roofline).
  * ``serve.benchmarks``— the measurement core shared by
    tools/serve_bench.py and the bench.py ``sssp_qps_*`` row.
  * ``serve.fleet``     — the multi-replica layer: controller/worker
    split, consistent-hash routing, cross-replica backpressure, live
    republish, and the ``sssp_fleet_qps_*`` saturation bench.

The unit of work here is a REQUEST, not a graph.

Exports resolve LAZILY (PEP 562): ``serve.batched``/``serve.warm``
import jax at module scope, but the fleet's jax-free leaves (``fleet.
wire``, ``fleet.pubproto``, ``live.journal``, ``autopilot.election``)
must stay importable under the bare-package stub (tools/_jaxfree.py) so
the protocol tier (``lux_tpu.analysis.proto``, tools/luxproto.py) can
model-check the REAL constants/classes on a cold host in milliseconds.
``import lux_tpu.serve.fleet.wire`` therefore never touches jax;
``from lux_tpu.serve import BatchedEngine`` still works and pays the
jax import only when asked.
"""
_EXPORTS = {
    "BatchedEngine": "lux_tpu.serve.batched",
    "BatchedResult": "lux_tpu.serve.batched",
    "MultiSourcePPR": "lux_tpu.serve.batched",
    "MultiSourceSSSP": "lux_tpu.serve.batched",
    "MicroBatchScheduler": "lux_tpu.serve.scheduler",
    "RejectedError": "lux_tpu.serve.scheduler",
    "ServeTimeoutError": "lux_tpu.serve.scheduler",
    "EngineKey": "lux_tpu.serve.warm",
    "WarmEngineCache": "lux_tpu.serve.warm",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
