"""Dynamic micro-batching scheduler: the admission path between raw
requests and the warm batched engines.

Pure-Python policy, explicitly pumpable for tests (``step(now=...)`` with
an injected clock) and runnable as a background thread for a live
service.  The policy:

  * **Coalesce** — pending requests accumulate until either the largest
    warm Q bucket fills or the oldest request has waited ``max_wait_ms``;
    then the batch dispatches into the smallest warm bucket that covers
    the pending count (padded with a repeat of the first query — padding
    answers are computed and discarded).
  * **Deadlines** — a request may carry ``timeout_ms``; requests whose
    deadline passes while queued resolve with ServeTimeoutError at the
    next pump, never hang.  ``ServeFuture.result(timeout=...)`` takes an
    independent wall guard — pass one when the scheduler thread's health
    is not your problem to trust (the default, like any future, blocks).
  * **Backpressure** — the queue is bounded; a submit beyond
    ``max_queue`` raises RejectedError carrying ``retry_after_ms``
    (estimated from the recent batch service time and the current
    depth), the reject-with-retry-after contract.
  * **Cold degradation** — when no warm engine exists for the app (an
    unwarmed shape arrived, e.g. service started without prewarm), the
    scheduler degrades to Q=1: it cold-traces the cheapest engine shape
    once and serves requests singly rather than paying a large-bucket
    compile on the request path.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional

from lux_tpu.serve.metrics import ServeMetrics
from lux_tpu.serve.warm import WarmEngineCache


class ServeTimeoutError(TimeoutError):
    """The request's deadline expired before an answer was produced."""


class RejectedError(RuntimeError):
    """Bounded-queue backpressure: retry after ``retry_after_ms``."""

    def __init__(self, retry_after_ms: float):
        super().__init__(
            f"queue full; retry after {retry_after_ms:.0f} ms")
        self.retry_after_ms = retry_after_ms


@dataclasses.dataclass
class _Request:
    query: int
    enqueue_t: float
    deadline_t: Optional[float]
    event: threading.Event
    result: object = None
    error: Optional[BaseException] = None
    traversed: int = 0
    rounds: int = 0
    #: mutation generation the answering batch served (None = the cache
    #: is not live; static-snapshot serving carries no tag)
    generation: Optional[int] = None
    #: distributed trace id of the request (obs/dtrace.py), carried so
    #: the dispatch span and the latency histogram's exemplars can link
    #: batches back to fleet timelines; None = untraced
    trace: Optional[str] = None


class ServeFuture:
    """Handle to one submitted query."""

    def __init__(self, req: _Request):
        self._req = req

    def done(self) -> bool:
        return self._req.event.is_set()

    def result(self, timeout: Optional[float] = None):
        """The (nv,) answer vector; raises ServeTimeoutError on deadline
        expiry, or after ``timeout`` wall seconds without a resolution.
        ``timeout=None`` blocks indefinitely — pass a bound whenever the
        pump is a thread you don't control."""
        if not self._req.event.wait(timeout):
            raise ServeTimeoutError("no result within wait timeout")
        if self._req.error is not None:
            raise self._req.error
        return self._req.result

    @property
    def traversed_edges(self) -> int:
        return self._req.traversed

    @property
    def rounds(self) -> int:
        return self._req.rounds

    @property
    def generation(self) -> Optional[int]:
        """Mutation generation the answer reflects (a LOWER bound: the
        overlay installed at dispatch, never newer than the state the
        batch actually saw); None when serving a static snapshot."""
        return self._req.generation


class MicroBatchScheduler:
    #: seconds between periodic ``serve.metrics`` flight-recorder
    #: snapshots (0 disables); measured by the scheduler's own clock so
    #: fake-clock tests can drive it deterministically
    snapshot_every_s: float = 30.0

    def __init__(self, cache: WarmEngineCache, app: str = "sssp",
                 max_wait_ms: float = 5.0, max_queue: int = 256,
                 default_timeout_ms: float = 0.0, clock=time.monotonic,
                 metrics: Optional[ServeMetrics] = None):
        self.cache = cache
        self.app = app
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.default_timeout_ms = float(default_timeout_ms)
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self._clock = clock
        self._queue: List[_Request] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._last_service_s = 0.0
        self._last_snapshot_t: Optional[float] = None

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def _retry_after_ms(self, depth: int) -> float:
        """Backpressure hint: how long until the queue has likely drained
        one max bucket — recent batch service time scaled by the backlog
        in buckets, floored at one coalescing window."""
        per_batch = max(self._last_service_s * 1e3, self.max_wait_ms)
        buckets = max(depth // max(self._max_bucket(), 1), 1)
        return per_batch * buckets

    def submit(self, query: int, timeout_ms: Optional[float] = None,
               trace: Optional[str] = None) -> ServeFuture:
        now = self._clock()
        t = self.default_timeout_ms if timeout_ms is None else float(timeout_ms)
        deadline = now + t / 1e3 if t > 0 else None
        req = _Request(query=int(query), enqueue_t=now, deadline_t=deadline,
                       event=threading.Event(), trace=trace)
        with self._wake:
            if len(self._queue) >= self.max_queue:
                self.metrics.record_rejected()
                raise RejectedError(self._retry_after_ms(len(self._queue)))
            self._queue.append(req)
            self.metrics.sample_queue_depth(len(self._queue))
            self._wake.notify()
        return ServeFuture(req)

    # ------------------------------------------------------------------
    # batching policy
    # ------------------------------------------------------------------

    def _max_bucket(self) -> int:
        warm = self.cache.warm_buckets(self.app)
        return max(warm) if warm else 1

    def _pick_bucket(self, n: int) -> tuple:
        """(q, warm): the bucket a batch of ``n`` real queries dispatches
        into.  Smallest warm bucket covering n; the largest warm bucket
        when n overflows them all; (1, False) — the cold Q=1 degradation —
        when nothing is warm."""
        warm = self.cache.warm_buckets(self.app)
        if not warm:
            return 1, False
        for q in warm:
            if q >= n:
                return q, True
        return max(warm), True

    def _expire(self, now: float) -> int:
        """Resolve queued requests whose deadline passed; returns count."""
        expired, kept = [], []
        with self._lock:
            for r in self._queue:
                (expired if r.deadline_t is not None and now >= r.deadline_t
                 else kept).append(r)
            self._queue = kept
        for r in expired:
            r.error = ServeTimeoutError(
                f"deadline expired after {(now - r.enqueue_t) * 1e3:.1f} ms "
                "in queue")
            self.metrics.record_timeout()
            r.event.set()
        return len(expired)

    def _ready(self, now: float) -> bool:
        with self._lock:
            if not self._queue:
                return False
            if len(self._queue) >= self._max_bucket():
                return True
            oldest = self._queue[0].enqueue_t
            # dispatch early when waiting out the window would blow a
            # queued deadline
            tightest = min(
                (r.deadline_t for r in self._queue
                 if r.deadline_t is not None),
                default=None,
            )
            if tightest is not None and tightest <= now + self.max_wait_ms / 1e3:
                return True
            return (now - oldest) * 1e3 >= self.max_wait_ms

    def _take(self, n: int) -> List[_Request]:
        with self._lock:
            batch, self._queue = self._queue[:n], self._queue[n:]
        return batch

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def step(self, now: Optional[float] = None) -> int:
        """One pump: expire deadlines, then dispatch at most one batch.
        Returns the number of requests RESOLVED (answers + timeouts).
        Deterministic and reentrant-free — tests drive it with a fake
        clock; the background thread just calls it in a loop."""
        from lux_tpu import obs

        now = self._clock() if now is None else now
        self._maybe_snapshot(now)
        resolved = self._expire(now)
        if not self._ready(now):
            return resolved
        q, warm_bucket = self._pick_bucket(self.pending())
        batch = self._take(q)
        if not batch:
            return resolved
        queries = [r.query for r in batch]
        pad = q - len(queries)
        queries = queries + [queries[0]] * pad
        # distributed-trace linkage: the batch's dispatch span names the
        # traces it serves (bounded — a Q=64 batch lists a sample), so a
        # stitched timeline can find which batch answered a request
        traces = [r.trace for r in batch if r.trace is not None]
        t0 = self._clock()
        try:
            # the dispatch span is the serving hot path's flight-recorder
            # row: one per batch, covering engine lookup + the batched run
            with obs.span("serve.dispatch", app=self.app, q=q,
                          real=len(batch),
                          **({"traces": traces[:4],
                              "n_traced": len(traces)}
                             if traces else {})) as sp:
                # ONE read of self.cache for the whole dispatch: a
                # republish commit reassigns it concurrently, and an
                # old-cache engine run with the NEW cache's overlay
                # (different e_pad/nv_pad) would shape-error the batch
                cache = self.cache
                engine, was_warm = cache.get(self.app, q)
                # one atomic tuple read: the generation tag below is the
                # overlay this batch dispatches with (a racing newer
                # install makes the tag a lower bound — safe direction)
                overlay = cache.current_overlay()
                if overlay is None:
                    out = engine.run(queries)
                    gen = None
                else:
                    gen, oarr, deg = overlay
                    out = engine.run(queries, oarrays=oarr, degree=deg)
                sp.set(warm=was_warm, generation=gen)
        except Exception as e:  # noqa: BLE001 — a failed batch must
            # resolve its requests (a hung future is worse than any error)
            for r in batch:
                r.error = e
                r.event.set()
            return resolved + len(batch)
        service_s = self._clock() - t0
        with self._lock:
            # submitters read this (via _retry_after_ms) under the same
            # lock; an unguarded cross-thread write worked only by the
            # grace of the GIL (luxcheck triage, thread-safety family)
            self._last_service_s = service_s
        self.metrics.record_batch(q=q, real=len(batch),
                                  warm=warm_bucket and was_warm,
                                  service_s=service_s)
        done_t = self._clock()
        for i, r in enumerate(batch):
            r.result = out.query_state(i)
            r.traversed = out.traversed[i]
            r.rounds = int(out.rounds[i])
            r.generation = gen
            self.metrics.record_done(
                latency_s=done_t - r.enqueue_t,
                wait_s=t0 - r.enqueue_t,
                traversed=out.traversed[i],
                trace=r.trace,
            )
            r.event.set()
        return resolved + len(batch)

    def _maybe_snapshot(self, now: float) -> None:
        """Periodic ``serve.metrics`` point (snapshot_every_s cadence on
        the scheduler's own clock) — the long-lived service's heartbeat
        in the event log."""
        if not self.snapshot_every_s:
            return
        with self._lock:
            last = self._last_snapshot_t
            if last is not None and now - last < self.snapshot_every_s:
                return
            self._last_snapshot_t = now
        if last is not None:  # first pump only arms the timer
            self.metrics.emit_snapshot()

    def drain(self, max_steps: int = 10_000) -> int:
        """Pump until the queue is empty; returns requests resolved.
        An idle pump (queue waiting out the coalescing window) sleeps a
        quarter-window instead of spinning, so the step budget is always
        far larger than any wait a queued request can legally incur."""
        total = 0
        for _ in range(max_steps):
            if not self.pending():
                break
            did = self.step()
            total += did
            if not did and self.pending():
                time.sleep(max(self.max_wait_ms / 4e3, 1e-4))
        return total

    # ------------------------------------------------------------------
    # background service loop
    # ------------------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._running = True

        def loop():
            while self._running:
                did = self.step()
                if did:
                    continue
                with self._wake:
                    if not self._queue and self._running:
                        self._wake.wait(timeout=self.max_wait_ms / 1e3)
                if self._queue:
                    # sub-window sleep so the coalescing deadline is
                    # observed to ~1/4 of max_wait_ms
                    time.sleep(self.max_wait_ms / 4e3)

        self._thread = threading.Thread(
            target=loop, name="lux-serve-scheduler", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True):
        if drain:
            self.drain()
        self._running = False
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
