"""Warm engine cache: compiled batched engines keyed on
(app, method, part layout, Q bucket).

A cold query pays the full trace + XLA compile of the batched loop
(tens of seconds at bench scale on the CPU fallback) before any graph
work happens; a service must pay that once per SHAPE, at start.  The
cache pre-traces the common Q buckets (default 1/8/64) for each served
app, resolves ``--method auto`` through the measured-winners overlay
exactly like the one-shot drivers (engine/methods.resolve — a chip
window's recorded winner redirects the serving path too), and counts
warm hits vs cold traces so the serving metrics can report the ratio.

The layout half of the key exists because a compiled engine binds the
shard GEOMETRY (part count, padded sizes): serving a rebuilt/repartitioned
graph through a stale engine would be a shape error at best.  Engines for
a superseded layout are dropped when a new shards bundle is installed.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Optional, Tuple

from lux_tpu.engine import methods
from lux_tpu.graph.shards import PullShards
from lux_tpu.serve.batched import BatchedEngine, make_program
from lux_tpu.utils.config import env_int

#: Q buckets pre-traced at service start.  1 covers the latency floor and
#: the cold-degradation path, 64 the throughput bucket; 8 the middle.
DEFAULT_Q_BUCKETS = (1, 8, 64)

#: LRU bound on live engines (env LUX_SERVE_ENGINE_CAP).  Every republish
#: builds a fresh cache, but within one cache ad-hoc Q shapes and
#: multi-app serving can still accumulate compiled engines without bound;
#: 32 covers apps x buckets with headroom while capping resident compiled
#: programs + their state buffers.
DEFAULT_MAX_ENGINES = 32


def layout_key(shards: PullShards) -> tuple:
    """Hashable shard-geometry key: everything a compiled engine binds."""
    s = shards.spec
    return (s.num_parts, s.nv, s.ne, s.nv_pad, s.e_pad, s.weighted)


@dataclasses.dataclass(frozen=True)
class EngineKey:
    app: str
    method: str
    layout: tuple
    q: int


class WarmEngineCache:
    """Engine cache + pre-tracer.  ``get`` returns (engine, was_warm);
    a miss builds AND executes the engine inline (the cold trace the
    scheduler's degradation policy tries to keep at Q=1)."""

    def __init__(self, shards: PullShards, apps=("sssp",),
                 q_buckets=DEFAULT_Q_BUCKETS, method: str = "auto",
                 num_iters: int = 10, max_iters: int = 10_000,
                 metrics=None, max_engines: Optional[int] = None,
                 overlay_static=None):
        self.shards = shards
        #: mutate.overlay.OverlayStatic -> every engine of this cache is
        #: the LIVE twin (takes OverlayArrays per batch); the current
        #: arrays live in ``_overlay`` as one immutable (generation,
        #: oarrays, degree) tuple so dispatchers read them atomically
        self.overlay_static = overlay_static
        self._overlay = None
        self.apps = tuple(apps)
        self.q_buckets = tuple(sorted(set(int(q) for q in q_buckets)))
        if self.q_buckets and self.q_buckets[0] < 1:
            raise ValueError(f"q buckets must be >= 1: {self.q_buckets}")
        self.num_iters = num_iters
        self.max_iters = max_iters
        #: optional ServeMetrics sink (evictions feed the service's
        #: counter set so a fleet scrape sees cache churn per replica)
        self.metrics = metrics
        if max_engines is None:
            max_engines = env_int("LUX_SERVE_ENGINE_CAP",
                                  DEFAULT_MAX_ENGINES, minimum=1)
        if max_engines < 1:
            raise ValueError(f"max_engines must be >= 1: {max_engines}")
        self.max_engines = int(max_engines)
        self._layout = layout_key(shards)
        # one resolution per app (reduce differs), shared by every bucket
        self._method = {
            app: methods.resolve(method, make_program(app, shards.spec.nv).reduce)
            for app in self.apps
        }
        # insertion/recency-ordered: the LRU eviction order (get/_build
        # refresh recency; the oldest entry past max_engines is dropped)
        self._engines: "collections.OrderedDict[EngineKey, BatchedEngine]" \
            = collections.OrderedDict()
        self.evictions = 0
        # ONE device placement of the graph arrays, shared by every
        # engine of this layout (a per-engine copy would multiply the
        # O(E) arrays by the bucket count)
        self._device_arrays = None
        self._lock = threading.Lock()
        self.warm_hits = 0
        self.cold_traces = 0
        self.warm_seconds = 0.0

    def key(self, app: str, q: int) -> EngineKey:
        return EngineKey(app=app, method=self._method[app],
                         layout=self._layout, q=int(q))

    # ------------------------------------------------------------------
    # live overlay (mutation-aware serving)
    # ------------------------------------------------------------------

    def set_overlay(self, generation: int, oarrays, degree=None) -> None:
        """Install the CURRENT mutation overlay: one atomic store of an
        immutable (generation, device OverlayArrays, device degree)
        tuple.  Dispatchers that read the tuple before a newer install
        tag their answers with the OLDER generation — a lower bound on
        what the batch actually served, which is exactly the direction
        read-your-writes needs."""
        import jax
        import jax.numpy as jnp

        if self.overlay_static is None:
            raise ValueError(
                "cache was built without overlay_static; a live worker "
                "must construct its WarmEngineCache with the overlay "
                "descriptor so every engine compiles the overlay twin")
        dev_o = jax.tree.map(jnp.asarray, oarrays)
        dev_d = None if degree is None else jnp.asarray(degree)
        self._overlay = (int(generation), dev_o, dev_d)

    def current_overlay(self):
        """(generation, OverlayArrays, degree) or None (non-live cache).
        A live cache that has not had set_overlay called yet serves the
        zero-churn empty overlay at generation 0."""
        if self.overlay_static is None:
            return None
        ov = self._overlay
        if ov is None:
            from lux_tpu.mutate import overlay as _ovl

            self.set_overlay(0, _ovl.empty_overlay_arrays(
                self.shards, self.overlay_static.cap))
            ov = self._overlay
        return ov

    def _warm_oarrays(self):
        ov = self.current_overlay()
        return None if ov is None else ov[1]

    def prewarm(self, apps=None, q_buckets=None) -> float:
        """Trace + compile + run one dummy batch per (app, bucket);
        returns the wall seconds spent (service-start cost, reported by
        the bench drivers so it is never mistaken for request latency)."""
        from lux_tpu import obs

        t0 = time.perf_counter()
        for app in apps if apps is not None else self.apps:
            for q in q_buckets if q_buckets is not None else self.q_buckets:
                # one span per (app, bucket): the compile waterfall of a
                # service start is attributable per engine shape
                with obs.span("serve.pretrace", app=app, q=int(q)):
                    self._build(app, int(q)).warm(self._warm_oarrays())
        spent = time.perf_counter() - t0
        with self._lock:
            self.warm_seconds += spent
        return spent

    def warm_buckets(self, app: str) -> tuple:
        """Ascending Q buckets with a WARMED engine for ``app``."""
        with self._lock:
            return tuple(sorted(
                k.q for k, e in self._engines.items()
                if k.app == app and k.layout == self._layout and e._warmed
            ))

    def is_warm(self, app: str, q: int) -> bool:
        with self._lock:
            e = self._engines.get(self.key(app, q))
        return e is not None and e._warmed

    def _build(self, app: str, q: int) -> BatchedEngine:
        import jax
        import jax.numpy as jnp

        k = self.key(app, q)
        with self._lock:
            eng = self._engines.get(k)
            if eng is None:
                if self._device_arrays is None:
                    self._device_arrays = jax.tree.map(
                        jnp.asarray, self.shards.arrays)
                eng = BatchedEngine(
                    self.shards, app, q, method=k.method,
                    num_iters=self.num_iters, max_iters=self.max_iters,
                    device_arrays=self._device_arrays,
                    overlay_static=self.overlay_static,
                )
                self._engines[k] = eng
                self._evict_locked()
            else:
                self._engines.move_to_end(k)  # refresh LRU recency
        return eng

    def _evict_locked(self) -> None:
        """Drop least-recently-used engines past ``max_engines`` (caller
        holds the lock).  A dropped engine's compiled program may still
        be referenced by an in-flight batch via its local handle — the
        cache only forgets it, so the next request for that shape pays a
        fresh cold trace (counted, like every cold trace)."""
        while len(self._engines) > self.max_engines:
            self._engines.popitem(last=False)
            self.evictions += 1
            if self.metrics is not None:
                self.metrics.record_eviction()

    def get(self, app: str, q: int) -> Tuple[BatchedEngine, bool]:
        """(engine, was_warm).  A cold get warms the engine inline —
        callers that must not pay a large compile on the request path
        degrade to q=1 first (scheduler policy).  Counter updates stay
        under the cache lock (concurrent pumps must not lose hits);
        the warm itself runs outside it, serialized by the engine's own
        lock so a racing second pump blocks instead of double-compiling."""
        from lux_tpu import obs

        eng = self._build(app, q)
        with self._lock:
            was_warm = eng._warmed
            if was_warm:
                self.warm_hits += 1
            else:
                self.cold_traces += 1
        if was_warm:
            # hit: a point, not a span — nothing is waited on
            obs.point("serve.cache", app=app, q=int(q), warm=True)
            return eng, True
        t0 = time.perf_counter()
        # miss: the request path is paying a trace+compile — exactly the
        # event a post-mortem needs to see on the timeline
        with obs.span("serve.cold_trace", app=app, q=int(q)):
            eng.warm(self._warm_oarrays())
        with self._lock:
            self.warm_seconds += time.perf_counter() - t0
        return eng, False

    def install_shards(self, shards: PullShards) -> None:
        """Swap in a rebuilt graph layout; engines for the old geometry
        are dropped (their compiled shapes no longer match)."""
        with self._lock:
            self.shards = shards
            self._layout = layout_key(shards)
            self._device_arrays = None  # re-place on next build
            self._overlay = None        # stale occupancy, stale shapes
            self._engines = collections.OrderedDict(
                (k, e) for k, e in self._engines.items()
                if k.layout == self._layout
            )

    def stats(self) -> dict:
        with self._lock:
            warmed = sum(1 for e in self._engines.values() if e._warmed)
            total = len(self._engines)
            hits, cold = self.warm_hits, self.cold_traces
            evicted = self.evictions
        return {
            "engines": total,
            "engines_warm": warmed,
            "max_engines": self.max_engines,
            # resident engines / LRU cap — the Prometheus gauge feeding
            # capacity planning (an always-1.0 cache is thrashing its
            # LRU; see evictions)
            "occupancy": round(total / max(self.max_engines, 1), 4),
            "evictions": evicted,
            "warm_hits": hits,
            "cold_traces": cold,
            "warm_hit_ratio": round(hits / max(hits + cold, 1), 4),
            "warm_seconds": round(self.warm_seconds, 3),
        }
