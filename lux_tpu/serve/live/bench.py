"""Mixed read/write measurement for the live fleet — the core behind
bench.py's standing ``sssp_live_w{W}_rmat{scale}_cpu`` row.

The workload is the product shape: a writer admitting edge-churn
batches through the controller (each batch = half deletes of live base
edges, half inserts — edge count roughly conserved) while closed-loop
readers keep sssp queries in flight against the fleet.  Measured:

* sustained write batches/s + rows/s (admit -> journal -> replicate ->
  every replica acked, the full write path);
* read QPS under the concurrent write load;
* read STALENESS in generations — journal generation at submit minus
  the generation tag the answer carries — p50/p99 (the number that
  makes "how far behind are reads" a measured contract, not a vibe);
* fleet refresh latency: one ``refresh_fleet`` after the mixed window
  (warm standing states to the final generation, every replica).

Thread-mode by design, like the saturation bench's fast path: the live
layer is host coordination + O(delta) overlay rebuilds, and the row
must be bankable on CPU with no chip window.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from lux_tpu.serve.fleet.controller import FleetError
from lux_tpu.serve.live.controller import start_live_fleet


def churn_batch(dlog, rng, rows: int):
    """(src, dst, op) for one balanced churn batch against ``dlog``'s
    CURRENT epoch base: rows//2 deletes of LIVE base edges (the base
    minus already-tombstoned slots — compaction-epoch safe) + rows//2
    inserts of fresh random edges."""
    base = dlog.base
    ndel = rows // 2
    live = np.flatnonzero(~dlog.del_base)
    ndel = min(ndel, len(live))
    dele = rng.choice(live, ndel, replace=False) if ndel else \
        np.zeros(0, np.int64)
    nins = rows - ndel
    src = np.concatenate([np.asarray(base.col_idx, np.int64)[dele],
                          rng.integers(0, base.nv, nins)])
    dst = np.concatenate([np.asarray(base.dst_of_edges(),
                                     np.int64)[dele],
                          rng.integers(0, base.nv, nins)])
    op = np.concatenate([np.zeros(ndel, np.int8),
                         np.ones(nins, np.int8)])
    return src, dst, op


def _pct(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(p / 100 * len(sorted_vals)),
                           len(sorted_vals) - 1)]


def measure_live_mixed(scale: int = 12, ef: int = 8, workers: int = 2,
                       parts: int = 2, batch_rows: int = 64,
                       write_batches: int = 20,
                       reader_threads: int = 2,
                       cap: Optional[int] = None, seed: int = 0,
                       buckets: Sequence[int] = (1, 8),
                       rmw_frac: float = 0.25,
                       min_window_s: float = 2.0) -> dict:
    """One mixed window on a fresh thread-mode live fleet; returns the
    bench row plus the raw tallies.  ``rmw_frac`` of reads carry a
    ``min_generation`` bound at the submit-time journal generation —
    the read-your-writes path measured under load, not just tested."""
    from lux_tpu import obs
    from lux_tpu.graph import generate
    from lux_tpu.serve.benchmarks import pick_sources

    g = generate.rmat(scale, ef, seed=seed)
    # capacity sized to the window's own churn (the PR 10 bench-row
    # rule): all inserts could land in one part on a skewed draw
    need = (batch_rows * write_batches) // 2 + batch_rows
    cap = cap if cap is not None else max(1024, need)
    snap = os.path.join(tempfile.gettempdir(),
                        f"lux_live_bench_{os.getpid()}.lux")
    sources = pick_sources(g, 64, seed=seed)
    rng = np.random.default_rng(seed)
    # standing pagerank rides the replicas' gather route (luxmerge:
    # fused-pf by default — mutation overlays on the fastest plan
    # family), so the refresh leg measures the shipped serving config
    fleet = start_live_fleet(
        workers, g, parts=parts, cap=cap, buckets=buckets,
        snapshot_path=snap, graph_id=f"rmat{scale}",
        standing=(("sssp", 0), ("pagerank", None)))
    ctl = fleet.controller
    # the standing serving SLOs (obs/slo.py), scored over this window's
    # own reads + writes: the row records a verdict per objective with
    # exemplar trace ids linking into the run's stitched timelines
    from lux_tpu.obs.slo import default_fleet_slos

    ctl.set_slos(default_fleet_slos())
    stop = threading.Event()
    reads_ok = [0] * reader_threads
    read_errors = [0] * reader_threads
    staleness: List[List[int]] = [[] for _ in range(reader_threads)]
    lat_ms: List[List[float]] = [[] for _ in range(reader_threads)]
    #: last generation admit_writes RETURNED (journaled + replica-acked)
    #: — the bound a read-your-writes client actually holds.  Bounding
    #: on ctl.generation() would race the replication window: the
    #: journal advances at admit, replicas ack later, and a bounded
    #: read in between is a spurious StaleReadError.
    acked_gen = [0]

    def reader(slot: int) -> None:
        k = 0
        while not stop.is_set():
            g_sub = ctl.generation()
            bound = (acked_gen[0]
                     if (k % max(int(1 / max(rmw_frac, 1e-9)), 1) == 0)
                     else None)
            try:
                f = ctl.submit(int(sources[k % len(sources)]),
                               min_generation=bound)
                f.result(timeout=60)
            except FleetError:
                read_errors[slot] += 1
                k += 1
                continue
            reads_ok[slot] += 1
            if f.generation is not None:
                staleness[slot].append(max(g_sub - f.generation, 0))
            if f.latency_s is not None:
                lat_ms[slot].append(f.latency_s * 1e3)
            k += 1

    try:
        with obs.span("live.bench.mixed", workers=workers,
                      batches=write_batches, rows=batch_rows):
            threads = [threading.Thread(target=reader, args=(i,),
                                        name=f"lux-live-bench-read-{i}",
                                        daemon=True)
                       for i in range(reader_threads)]
            for t in threads:
                t.start()
            t0 = time.perf_counter()
            compactions = 0
            for b in range(write_batches):
                src, dst, op = churn_batch(ctl.journal.log, rng,
                                           batch_rows)
                rep = ctl.admit_writes(src, dst, op)
                acked_gen[0] = rep["generation"]
                compactions += int(rep["compacted"])
            write_s = time.perf_counter() - t0
            # writes can outpace the readers on a small graph; keep the
            # read side of the mixed window open long enough that its
            # QPS and staleness percentiles mean something
            while time.perf_counter() - t0 < min_window_s:
                time.sleep(0.02)
            stop.set()
            for t in threads:
                t.join(timeout=120)
            read_s = time.perf_counter() - t0
            with obs.span("live.bench.refresh"):
                refresh = ctl.refresh_fleet()
            gens = ctl.worker_generations()
            ctl_stats = ctl.stats()
            slo_rows = ctl.slo_status()
    finally:
        fleet.close()
        try:
            os.unlink(snap)
        except OSError:
            pass
    stale = sorted(x for s in staleness for x in s)
    lats = sorted(x for s in lat_ms for x in s)
    ok = sum(reads_ok)
    # accounted HBM sweeps of ONE standing-pagerank refresh iteration,
    # per route family (utils/roofline.py) — the luxmerge win the row
    # banks: the pre-luxmerge refresh paid the DIRECT gather's sweeps,
    # the fused-pf route the replicas now ride pays the routed total.
    # Plan construction is host-side accounting on the same layout the
    # fleet served (build_pull_shards is deterministic), outside every
    # timed region.
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.ops import expand
    from lux_tpu.utils import roofline

    sh_acc = build_pull_shards(g, parts)
    fst, _ = expand.plan_fused_shards_cached(sh_acc, "sum", pf=True,
                                             mx=False)
    est, _ = expand.plan_expand_shards_cached(sh_acc, pf=True)
    refresh_passes = {
        "direct": roofline.pull_hbm_passes("scan"),
        "expand_pf": roofline.routed_hbm_passes(est, "scan"),
        "fused_pf": roofline.routed_hbm_passes(fst, "scan"),
        "route_family": os.environ.get("LUX_LIVE_ROUTE", "fused-pf"),
    }
    row = {
        "metric": f"sssp_live_w{workers}_rmat{scale}_cpu",
        "value": round(ok / max(read_s, 1e-9), 2),
        "unit": "QPS",
        "write_batches_per_s": round(write_batches / max(write_s, 1e-9),
                                     2),
        "write_rows_per_s": round(
            write_batches * batch_rows / max(write_s, 1e-9), 1),
        "reads": ok,
        "read_errors": sum(read_errors),
        "read_p50_ms": round(_pct(lats, 50), 2),
        "read_p99_ms": round(_pct(lats, 99), 2),
        "staleness_gen_p50": _pct(stale, 50),
        "staleness_gen_p99": _pct(stale, 99),
        "fleet_refresh_s": refresh["seconds"],
        "hbm_passes": refresh_passes,
        "final_generation": max(gens.values()) if gens else 0,
        "worker_generations": gens,
        "compactions": compactions,
        "workers": workers,
        "batch_rows": batch_rows,
        "app": "sssp",
        "platform": "cpu",
        "nv": int(g.nv),
        "ne": int(g.ne),
        "controller": ctl_stats,
        "slo": slo_rows,
    }
    return row
