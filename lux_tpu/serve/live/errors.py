"""The live-serving protocol surface: exceptions + reason constants.

These are the types/strings the generation-line protocol SPEAKS — a
replica refusing an out-of-sequence delta, a controller permanently
refusing a worker at handshake.  They live in their own stdlib-only
module (not ``replica.py``, which imports the jax-backed overlay
machinery) so the protocol model tier (``lux_tpu.analysis.proto``,
tools/luxproto.py) imports the REAL types under tools/_jaxfree.py's
bare-package stub: the conformance bridge's whole point is that the
model cannot drift from the spellings the fleet actually uses.
"""
from __future__ import annotations

#: the three PERMANENT ``add_worker`` refusal reasons
#: (``WorkerRefusedError.reason``): takeover()'s retry loop treats any
#: other failure as transient; these can never heal by re-helloing.
REFUSE_STATIC = "static"            # worker serves no generation tags
REFUSE_AHEAD = "ahead_of_journal"   # split-brain guard: wrong history
REFUSE_PRE_EPOCH = "pre_epoch"      # compacted past: restart from snap

REFUSAL_REASONS = (REFUSE_STATIC, REFUSE_AHEAD, REFUSE_PRE_EPOCH)


class GenerationGap(RuntimeError):
    """A delta arrived out of sequence: the replica holds ``have``, the
    batch claims ``want``.  The controller answers with the catch-up
    stream (batches have+1..)."""

    def __init__(self, have: int, want: int):
        super().__init__(
            f"replica is at generation {have}, delta claims {want} — "
            "re-sync from the controller journal")
        self.have = int(have)
        self.want = int(want)
