"""lux_tpu.serve.live — mutation-aware serving: the write path through
the fleet (ISSUE 12, ROADMAP item 2).

PR 8's fleet swaps compacted snapshots; PR 10's delta-log lives on one
host.  This package closes the seam: the controller ADMITS edge
insert/delete batches, sequences them into an authoritative crash-safe
journal (mutate/deltalog.py's npz+``.ok`` format) with monotonic
GENERATION numbers, and replicates each committed batch to every
replica, where workers install statically-shaped overlays into the
serving engines (no retrace, no snapshot swap) and run PR 10's warm
refresh between queries.  Query answers carry generation tags;
admission takes a ``min_generation`` bound — read-your-writes.
``DeltaOverflow`` anywhere escalates to a fleet-wide compaction through
the token-guarded two-phase republish; a joining/recovering worker
catches up by snapshot + journal replay.

Pieces:

* ``journal.py``   — LiveJournal: the controller's sequencer (the ONE
  write order) + batch wire packing + the compaction epoch.
* ``replica.py``   — LiveReplica: worker-side delta log, serving
  overlays, standing-state warm refresh.
* ``controller.py``— LiveFleetController: admit/replicate/refresh/
  compact + generation-aware routing and worker catch-up.
* ``bench.py``     — thread-mode live fleet helper + the mixed
  read/write measurement behind bench.py's ``sssp_live_*`` row.
* ``errors.py``    — the protocol-surface exceptions/reason constants
  (``GenerationGap``, the ``add_worker`` refusal reasons): stdlib-only
  so the protocol model tier imports the REAL types without jax.

Exports resolve LAZILY (PEP 562, same contract as ``lux_tpu.serve``):
``journal``/``errors`` are jax-free and must stay importable under
tools/_jaxfree.py's bare-package stub.
"""
_EXPORTS = {
    "LiveFleetController": "lux_tpu.serve.live.controller",
    "promote_live_controller": "lux_tpu.serve.live.controller",
    "LiveJournal": "lux_tpu.serve.live.journal",
    "GenerationGap": "lux_tpu.serve.live.errors",
    "LiveReplica": "lux_tpu.serve.live.replica",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
