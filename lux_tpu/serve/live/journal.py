"""LiveJournal: the controller's authoritative write sequencer.

One journal defines the fleet's ONE write order.  Every admitted batch
is resolved against the controller's copy of the base graph and — when
``journal_dir`` is set — made durable through ``mutate/deltalog.py``'s
crash-safe npz+``.ok`` protocol BEFORE any replica sees it, so a
controller crash can never have acknowledged a write the journal lost.

Generations are monotonic across the whole life of the graph::

    generation = base_generation + batches applied this epoch

``base_generation`` advances at each COMPACTION (the epoch boundary):
the merged snapshot then contains every batch up to it, the DeltaLog
journal rotates (deltalog.journal_reset — crash-safe, prefix-consistent)
and a fresh epoch starts empty.  ``live_meta.json`` (fsync'd, next to
the DeltaLog's own ``meta.json``) carries the epoch base so a restarted
controller resumes the SAME generation line; the DeltaLog meta's
``base_sha`` refuses a journal replayed against the wrong snapshot.

Batches ride the fleet wire as ONE ``(rows, 4)`` int64 array
(src, dst, op, weight columns) — ``pack_batch``/``unpack_batch`` are
the two ends of that frame.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np

from lux_tpu.graph.csc import HostGraph
from lux_tpu.mutate.deltalog import DeltaLog, _fsync_write

#: live-meta layout version
LIVE_FORMAT = 1


def read_live_meta(journal_dir: str) -> Optional[dict]:
    """The epoch meta (``live_meta.json``) of a live journal dir, or
    None when absent.  Shared by the controller's LiveJournal and the
    workers' LiveReplica — one format, one generation line."""
    path = os.path.join(journal_dir, "live_meta.json")
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        meta = json.loads(f.read().decode())
    if meta.get("format") != LIVE_FORMAT:
        raise ValueError(
            f"live journal {journal_dir}: format "
            f"{meta.get('format')} != {LIVE_FORMAT}")
    return meta


def write_live_meta(journal_dir: str, base_generation: int) -> None:
    _fsync_write(os.path.join(journal_dir, "live_meta.json"), json.dumps({
        "format": LIVE_FORMAT,
        "base_generation": int(base_generation),
    }).encode())


def pack_batch(src, dst, op, weight=None) -> np.ndarray:
    """One mutation batch -> the (rows, 4) int64 wire array."""
    src = np.atleast_1d(np.asarray(src, np.int64))
    dst = np.atleast_1d(np.asarray(dst, np.int64))
    op = np.atleast_1d(np.asarray(op, np.int64))
    w = (np.zeros(len(src), np.int64) if weight is None
         else np.atleast_1d(np.asarray(weight, np.int64)))
    if not (len(src) == len(dst) == len(op) == len(w)):
        raise ValueError("batch arrays must share one length")
    return np.stack([src, dst, op, w], axis=1)


def unpack_batch(arr: np.ndarray) -> Tuple[np.ndarray, ...]:
    """(rows, 4) int64 wire array -> (src, dst, op, weight)."""
    arr = np.asarray(arr)
    if arr.ndim != 2 or arr.shape[1] != 4:
        raise ValueError(f"delta frame must be (rows, 4); got {arr.shape}")
    a = arr.astype(np.int64, copy=False)
    return a[:, 0], a[:, 1], a[:, 2].astype(np.int8), a[:, 3]


class LiveJournal:
    """The sequencer.  ``base``: the controller's HostGraph copy of the
    CURRENT epoch's snapshot (numpy only — the controller never imports
    jax).  ``journal_dir=None`` keeps it in-memory (tests, ephemeral
    fleets); a directory makes every admitted batch durable before the
    commit generation is returned."""

    def __init__(self, base: HostGraph,
                 journal_dir: Optional[str] = None):
        self.journal_dir = journal_dir
        self.base_generation = 0
        meta = None
        if journal_dir is not None:
            os.makedirs(journal_dir, mode=0o700, exist_ok=True)
            meta = read_live_meta(journal_dir)
            if meta is not None:
                self.base_generation = int(meta["base_generation"])
        # replays any committed epoch batches (and validates base_sha)
        self.log = DeltaLog(base, journal_dir=journal_dir)
        #: committed batches of THIS epoch, wire-packed, replication +
        #: catch-up order; index i commits generation base_generation+i+1
        self._batches: List[np.ndarray] = []
        #: idempotent write-id -> commit generation (ISSUE 14): a client
        #: retrying an admit whose ack was lost to a controller crash
        #: gets the ALREADY-COMMITTED generation back instead of a
        #: double apply.  Journaled write-ids ride inside the batch npz
        #: (``wid`` key, ignored by replay), so a RESTARTED controller
        #: rebuilds the current epoch's map; ids of compacted epochs
        #: survive only in this process's memory — documented window.
        self._write_ids: Dict[str, int] = {}
        if journal_dir is not None and self.log.batches_applied:
            self._reload_epoch_batches()
        if journal_dir is not None and meta is None:
            self._write_live_meta()

    # ------------------------------------------------------------------
    # generations
    # ------------------------------------------------------------------

    def generation(self) -> int:
        return self.base_generation + self.log.batches_applied

    def admit(self, src, dst, op, weight=None,
              write_id: Optional[str] = None) -> int:
        """Sequence ONE batch: resolve against the merged state, journal
        it durably (marker last), and return its COMMIT generation.
        Raises like DeltaLog.apply on an invalid batch — nothing is
        journaled, no generation is burned.

        ``write_id``: idempotence key — a replayed admit with an
        already-committed id returns that commit's generation WITHOUT
        applying anything (the retry-after-lost-ack path; callers that
        replicate must check ``generation()`` did not advance)."""
        if write_id is not None:
            got = self._write_ids.get(str(write_id))
            if got is not None:
                return got
        arr = pack_batch(src, dst, op, weight)
        s, d, o, w = unpack_batch(arr)
        extra = None
        if write_id is not None:
            extra = {"wid": np.frombuffer(
                str(write_id).encode("utf-8"), np.uint8)}
        self.log.apply(s, d, o, w, journal_extra=extra)
        self._batches.append(arr)
        gen = self.generation()
        if write_id is not None:
            self._write_ids[str(write_id)] = gen
        return gen

    def lookup_write(self, write_id: str) -> Optional[int]:
        """The commit generation of an already-admitted ``write_id``,
        or None."""
        return self._write_ids.get(str(write_id))

    # ------------------------------------------------------------------
    # replication / catch-up views
    # ------------------------------------------------------------------

    def payload(self, generation: int) -> np.ndarray:
        """The wire array of the batch that committed ``generation``."""
        idx = int(generation) - self.base_generation - 1
        if not (0 <= idx < len(self._batches)):
            raise KeyError(
                f"generation {generation} is not in this epoch "
                f"({self.base_generation}..{self.generation()}] — "
                "compacted-away batches live in the snapshot)")
        return self._batches[idx]

    def batches_since(self, generation: int):
        """(gen, wire array) for every committed batch AFTER
        ``generation`` — the catch-up stream for a joining/recovering
        worker.  ``generation`` below the epoch base raises: those
        batches were compacted into the snapshot, so the worker must
        restart from it instead."""
        g = int(generation)
        if g < self.base_generation:
            raise KeyError(
                f"generation {g} predates the current epoch base "
                f"{self.base_generation}: the missing batches were "
                "compacted into the snapshot — reload the worker from "
                "it and catch up from there")
        return [(g0 + 1, self._batches[g0 - self.base_generation])
                for g0 in range(g, self.generation())]

    # ------------------------------------------------------------------
    # compaction epoch
    # ------------------------------------------------------------------

    def compact(self, snapshot_path: Optional[str] = None) -> HostGraph:
        """Fold the epoch into a new base: write the merged snapshot
        durably (when a path is given — REQUIRED for a journaled
        sequencer, same rule as MutableGraph.compact), rotate the
        journal, advance ``base_generation`` to the current generation
        and start the next epoch empty.  Returns the merged graph (what
        the fleet republish ships)."""
        from lux_tpu.mutate.compact import snapshot_write

        if self.journal_dir is not None and snapshot_path is None:
            raise ValueError(
                "a journaled LiveJournal needs a snapshot path to "
                "compact: rotating the journal without persisting the "
                "merged base would drop durable writes")
        merged = self.log.merged_graph()
        if snapshot_path is not None:
            snapshot_write(snapshot_path, merged)
        self.base_generation = self.generation()
        self.log.journal_reset()
        self.log = DeltaLog(merged, journal_dir=self.journal_dir)
        self._batches = []
        if self.journal_dir is not None:
            self._write_live_meta()
        return merged

    # ------------------------------------------------------------------
    # epoch reload
    # ------------------------------------------------------------------

    def _write_live_meta(self) -> None:
        write_live_meta(self.journal_dir, self.base_generation)

    def _reload_epoch_batches(self) -> None:
        """Rebuild the wire-packed batch list from the committed journal
        files (the DeltaLog already replayed them into its state; this
        restores the replication/catch-up view a restarted controller
        needs)."""
        for seq in range(self.log.batches_applied):
            with np.load(self.log._batch_path(seq),
                         allow_pickle=False) as z:
                self._batches.append(
                    pack_batch(z["src"], z["dst"], z["op"], z["w"]))
                if "wid" in z.files:  # idempotent write-id rides along
                    wid = bytes(np.asarray(z["wid"],
                                           np.uint8)).decode("utf-8")
                    self._write_ids[wid] = (self.base_generation
                                            + seq + 1)

    def stats(self) -> dict:
        return {
            "generation": self.generation(),
            "base_generation": self.base_generation,
            "epoch_batches": len(self._batches),
            "write_ids": len(self._write_ids),
            **self.log.stats(),
        }
