"""LiveFleetController: the write path through the fleet.

Extends PR 8's FleetController with the mutation-aware serving
protocol (ISSUE 12 / ROADMAP item 2):

* **admit_writes** — the ONE write order: each batch is sequenced into
  the authoritative LiveJournal (durably, when journaled) and only THEN
  replicated to every live worker as a ``delta`` op; the commit
  generation returns to the caller once every reachable replica
  acknowledged, so a subsequent ``submit(min_generation=gen)`` is
  read-your-writes end to end.  The write path is single-writer
  (``_write_lock``) — generations are total-ordered by construction.
* **replication faults** — a worker that dies mid-replication is
  retired by the base controller (its reads move to ring successors);
  a sequence gap (a recovered worker that lost its uncommitted tail)
  is answered with the catch-up stream from the journal; a rejoining
  worker is synced in ``add_worker`` (snapshot + journal replay on its
  side, ``batches_since`` from ours).
* **refresh_fleet** — fans the ``refresh`` op to every replica so
  PR 10's warm refresh (SSSP/CC bitwise, PageRank exact-fixpoint) runs
  fleet-wide between queries; standing reads (``read_standing``) serve
  the refreshed states O(1) with generation tags.
* **compaction escalation** — a ``DeltaOverflow`` on any replica
  escalates here: the journal compacts into a durable snapshot
  (``snapshot_path``), ``base_generation`` advances, and the fleet
  moves onto the new epoch through the token-guarded two-phase
  republish (old overlays serve until the atomic commit; zero shed).

The controller still never imports jax — the journal is numpy, the
graph math lives in the workers.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from lux_tpu.graph.csc import HostGraph
from lux_tpu.obs import dtrace
from lux_tpu.serve.fleet.controller import (
    FleetController,
    FleetError,
    NoWorkersError,
    WorkerRefusedError,
    _Pending,
)
from lux_tpu.serve.fleet.wire import ConnectionClosed
from lux_tpu.serve.live.errors import (
    REFUSE_AHEAD,
    REFUSE_PRE_EPOCH,
    REFUSE_STATIC,
)
from lux_tpu.serve.live.journal import LiveJournal, read_live_meta


class LiveFleetController(FleetController):
    """``base``: the controller's HostGraph copy of the CURRENT epoch
    snapshot (what every worker loaded).  ``journal_dir`` makes the
    write order durable; ``snapshot_path`` names where compactions
    write merged snapshots — REQUIRED before any overflow can be
    escalated (and for journaled compaction at all)."""

    def __init__(self, base: HostGraph,
                 journal_dir: Optional[str] = None,
                 snapshot_path: Optional[str] = None,
                 delta_timeout_s: float = 60.0,
                 refresh_timeout_s: float = 600.0, **kw):
        super().__init__(**kw)
        self.journal = LiveJournal(base, journal_dir=journal_dir)
        self.snapshot_path = snapshot_path
        self.delta_timeout_s = float(delta_timeout_s)
        self.refresh_timeout_s = float(refresh_timeout_s)
        #: single-writer sequencing: admits, republishes, and the
        #: compactions they escalate to are totally ordered; reads
        #: never take this.  Reentrant because compact_fleet (holding
        #: it) republishes through the serialized override below.
        #: Acquisition order is _write_lock BEFORE the base _lock on
        #: every path (checker-enforced: LUX-L002); the fine-grained
        #: _lock is never held across a send/wait — replication blocks
        #: under _write_lock ONLY, which is the point of the lock.
        self._write_lock = threading.RLock()
        self._live_counts = {"writes": 0, "write_rows": 0,
                             "compactions": 0, "resyncs": 0,
                             "overflow_compactions": 0,
                             "write_dedups": 0}

    def _hello_info(self) -> dict:
        """The live handshake extras: our journal generation, so the
        worker-side split-brain guard can refuse a controller whose
        journal is BEHIND the worker's own (a wiped/wrong-dir
        controller must not re-sequence generations the fleet already
        acked)."""
        return {**super()._hello_info(), "live": True,
                "journal_generation": self.journal.generation()}

    # ------------------------------------------------------------------
    # membership: live handshake + catch-up
    # ------------------------------------------------------------------

    def add_worker(self, host: str, port: int,
                   timeout_s: float = 60.0, tc=None) -> str:
        """Base handshake + the live catch-up: the worker must be live
        and at-or-behind the journal; behind means it recovered/joined
        from the epoch snapshot + its local committed prefix, and the
        missing batches stream to it before it serves a stale-bounded
        read."""
        wid = super().add_worker(host, port, timeout_s=timeout_s, tc=tc)
        with self._lock:
            handle = self._workers[wid]
        info = handle.info
        # the three PERMANENT rejections raise WorkerRefusedError, not
        # plain FleetError: takeover()'s per-endpoint retry loop treats
        # FleetError as transient and would re-hello a worker that can
        # never qualify for the full deadline — these cannot heal by
        # retrying the same handshake
        if not info.get("live"):
            self.remove_worker(wid, shutdown=False)
            raise WorkerRefusedError(
                REFUSE_STATIC,
                f"worker {wid} is not live (start it with --live / a "
                "LiveReplica); a static replica would serve writes-blind "
                "answers with no generation tag")
        have = int(info.get("delta_generation", 0))
        gen = self.journal.generation()
        if have > gen:
            self.remove_worker(wid, shutdown=False)
            raise WorkerRefusedError(
                REFUSE_AHEAD,
                f"worker {wid} is at generation {have}, ahead of the "
                f"journal ({gen}) — it belongs to a different write "
                "history (wrong journal dir or wiped controller state)")
        if have < self.journal.base_generation:
            self.remove_worker(wid, shutdown=False)
            raise WorkerRefusedError(
                REFUSE_PRE_EPOCH,
                f"worker {wid} is at generation {have}, before the "
                f"current epoch base {self.journal.base_generation}: its "
                "missing batches were compacted into the snapshot — "
                "restart it from the current snapshot")
        self._raise_delta_gen(handle, have)
        if have < gen:
            with self._lock:
                self._live_counts["resyncs"] += 1
            self._sync_worker(handle, tc=tc)
        return wid

    def _raise_delta_gen(self, handle, gen: int) -> None:
        """Monotonic, LOCKED delta_gen update: the heartbeat thread
        does its max() under self._lock, so an unlocked store here
        could be overwritten by a stale heartbeat read-modify-write —
        exactly the backslide that would make a just-acked
        min_generation read spuriously StaleReadError."""
        with self._lock:
            handle.delta_gen = max(handle.delta_gen, int(gen))

    def _sync_worker(self, handle, start: Optional[int] = None,
                     tc=None) -> None:
        """Stream the batches a behind worker is missing, in order.
        ``start`` overrides the tracked delta_gen — the gen_gap path
        passes the worker's OWN reported position instead of lowering
        the shared (heartbeat-raced) field.  ``tc``: the trace driving
        this catch-up (a takeover's re-hello, an admit's gen_gap
        answer) — each streamed batch rides as a traced delta frame,
        so recovery work is attributable in the stitched timeline."""
        if start is None:
            start = handle.delta_gen
        sctx = tc.child() if tc is not None else dtrace.mint()
        with dtrace.tspan("live.sync", sctx, always=True,
                          worker=handle.wid,
                          have=start, want=self.journal.generation()):
            for gen, arr in self.journal.batches_since(start):
                rep = self._delta_rpc(handle, gen, arr,
                                      self.delta_timeout_s, tc=sctx)
                if rep.get("kind") == "overflow":
                    raise FleetError(
                        f"worker {handle.wid} overflowed at generation "
                        f"{gen} during catch-up — compact the fleet "
                        "first (compact_fleet), then rejoin it")
                if not rep.get("ok"):
                    raise FleetError(
                        f"worker {handle.wid} failed catch-up at "
                        f"generation {gen}: {rep.get('err')}")
                self._raise_delta_gen(handle, gen)

    # ------------------------------------------------------------------
    # the write path
    # ------------------------------------------------------------------

    def admit_writes(self, src, dst, op, weight=None,
                     timeout_s: Optional[float] = None,
                     write_id: Optional[str] = None) -> dict:
        """Admit ONE edge-mutation batch: sequence it into the journal
        (durable before anything else sees it), replicate to every live
        worker, return the commit generation once all reachable
        replicas acknowledged.  An overflow anywhere escalates to a
        fleet-wide compaction (``snapshot_path`` required) before
        returning.  Raises like DeltaLog.apply on an invalid batch —
        nothing journaled, nothing replicated, no generation burned.

        ``write_id`` (ISSUE 14): idempotence key for the retry
        envelope.  A client whose ack was lost (controller crash after
        journaling) retries the SAME id against the promoted
        controller and gets the already-committed generation back —
        ``deduped: True``, nothing re-applied, nothing re-replicated
        (the replicas were synced past it at re-hello)."""
        timeout_s = self.delta_timeout_s if timeout_s is None else timeout_s
        # the WRITE trace root (ISSUE 15): keyed on the idempotent
        # write_id, so a client retrying a lost ack against a PROMOTED
        # controller lands its replay — and its dedup answer — in the
        # SAME trace as the original attempt.  That identity is what
        # makes the kill-mid-write drill stitch into one timeline.
        wtc = dtrace.mint(
            key=None if write_id is None else f"w:{write_id}")
        t_admit = time.monotonic()
        try:
            return self._admit_writes_scored(src, dst, op, weight,
                                             write_id, timeout_s,
                                             wtc, t_admit)
        except Exception:
            # a failed admit (journal refusal, overflow-escalation
            # failure, replication FleetError) is write_ack-BAD — the
            # SLO must see it, like submit keeps availability honest
            # about sheds
            self._observe_write(t_admit, ok=False, tc=wtc)
            raise

    def _admit_writes_scored(self, src, dst, op, weight, write_id,
                             timeout_s, wtc, t_admit):
        from lux_tpu import obs

        with self._write_lock:
            if write_id is not None:
                got = self.journal.lookup_write(write_id)
                if got is not None:
                    with self._lock:
                        self._live_counts["write_dedups"] += 1
                    obs.point("live.admit.dedup", write_id=str(write_id),
                              generation=got,
                              **(wtc.attrs() if wtc is not None
                                 and wtc.sampled else {}))
                    dtrace.emit_span("live.admit", wtc, t_admit,
                                     time.monotonic(), ok=True,
                                     deduped=True, generation=got)
                    self._observe_write(t_admit, ok=True, tc=wtc)
                    return {"generation": got,
                            "acked": self.live_workers(),
                            "compacted": False, "deduped": True}
            rows = int(np.size(np.atleast_1d(np.asarray(src))))
            with dtrace.tspan("live.admit", wtc, always=True,
                              rows=rows) as sp:
                gen = self.journal.admit(src, dst, op, weight,
                                         write_id=write_id)
                acked, overflow = self._replicate(gen, timeout_s,
                                                  tc=wtc)
                compacted = False
                if overflow:
                    # SATELLITE (ISSUE 14): the overflow-escalated
                    # compaction used to run inside the generic
                    # republish spans only — invisible as an ESCALATION
                    # in the flight recorder, so a chaos soak's latency
                    # spike had nothing to attribute itself to.  Now:
                    # its own span + counter, nested around the fold.
                    with self._lock:
                        self._live_counts["overflow_compactions"] += 1
                    obs.point("live.overflow.escalated", generation=gen,
                              rows=rows)
                    with obs.span("live.overflow.compact",
                                  generation=gen, rows=rows):
                        self._compact_fleet_locked()
                    compacted = True
                    acked = self.live_workers()
                with self._lock:
                    self._live_counts["writes"] += 1
                    self._live_counts["write_rows"] += rows
                sp.set(generation=gen, acked=len(acked),
                       compacted=compacted, deduped=False)
        self._observe_write(t_admit, ok=True, tc=wtc)
        # standing-query subscribers (ISSUE 16): push, don't poll —
        # outside the write lock (the hub only coalesces a pending
        # generation here; its dispatcher thread does the reads)
        self._notify_subs(gen, tc=wtc)
        return {"generation": gen, "acked": acked,
                "compacted": compacted, "deduped": False}

    def _observe_write(self, t0: float, ok: bool, tc=None) -> None:
        """Score one admit against the write_latency SLO (admit ->
        every reachable replica acked)."""
        with self._lock:
            engine = self._slo
        if engine is None:
            return
        engine.observe_write(
            time.monotonic() - t0, ok=ok,
            trace_id=None if tc is None else tc.trace_id)

    def _delta_rpc(self, handle, gen: int, arr: np.ndarray,
                   timeout_s: float, tc=None) -> dict:
        """One delta frame to one worker; returns the reply dict (ok or
        kind=gen_gap/overflow/error) — NEVER raises for a worker-side
        refusal, only for transport loss (as FleetError).  Hand-rolled
        next to FleetController._send because a delta carries an array
        payload (the base _send is header-only).  ``tc``: the write/
        sync trace — each frame carries its own child, and the
        replication hop is emitted as a ``live.replicate`` span so the
        worker's ``worker.delta`` span has its controller-side
        parent."""
        ctx = tc.child() if tc is not None else None
        t0 = time.monotonic()

        def span(ok: bool, **extra) -> None:
            dtrace.emit_span("live.replicate", ctx, t0, time.monotonic(),
                             ok=ok, worker=handle.wid,
                             generation=int(gen), **extra)

        p = _Pending("rpc")
        rid = self._next_rid()
        with self._lock:
            handle.pending[rid] = p
        msg = {"op": "delta", "req_id": rid, "generation": int(gen)}
        if ctx is not None:
            msg["tc"] = ctx.to_wire()
        try:
            handle.conn.send(msg, arr=arr)
        except ConnectionClosed:
            with self._lock:
                still_mine = handle.pending.pop(rid, None) is not None
            if still_mine:
                # the reader's _retire did not harvest this pending:
                # book the death ourselves (same shape as base _send);
                # a harvested rpc already carries p.error — fall through
                self._on_conn_lost(handle)
                span(False, kind="died_mid_replication")
                raise FleetError(
                    f"worker {handle.wid} died mid-replication"
                ) from None
        if not p.event.wait(timeout_s):
            span(False, kind="ack_timeout")
            raise FleetError(
                f"worker {handle.wid} did not ack generation {gen} "
                f"within {timeout_s}s")
        if p.error is not None:
            span(False, kind="error")
            raise FleetError(str(p.error))
        span(bool(p.reply.get("ok")),
             kind=None if p.reply.get("ok") else p.reply.get("kind"))
        return p.reply

    def _replicate(self, gen: int, timeout_s: float, tc=None
                   ) -> Tuple[List[str], bool]:
        """Fan one committed batch to every live worker.  Returns
        (acked worker ids, overflow anywhere).  A worker lost mid-
        replication is simply absent from the ack list (the base
        controller retired it — its reads moved); a gen_gap worker gets
        the catch-up stream inline.  ``tc``: the admitting write's
        trace, carried on every replication frame."""
        arr = self.journal.payload(gen)
        with self._lock:
            handles = [h for h in self._workers.values() if h.alive]
        acked: List[str] = []
        overflow = False
        for h in handles:
            try:
                rep = self._delta_rpc(h, gen, arr, timeout_s, tc=tc)
            except FleetError:
                continue  # retired mid-replication; rejoin re-syncs it
            if rep.get("ok"):
                self._raise_delta_gen(h, gen)
                acked.append(h.wid)
            elif rep.get("kind") == "overflow":
                # durable on that worker, not servable: escalate
                overflow = True
            elif rep.get("kind") == "gen_gap":
                try:
                    with self._lock:
                        self._live_counts["resyncs"] += 1
                    self._sync_worker(h, start=int(rep.get("have", 0)),
                                      tc=tc)
                    acked.append(h.wid)
                except FleetError:
                    continue
        return acked, overflow

    def republish(self, path, graph_id=None,
                  prepare_timeout_s: float = 600.0,
                  commit_timeout_s: float = 30.0,
                  base_generation=None) -> dict:
        """The base two-phase republish, SERIALIZED against the write
        path: the live worker's prepare-refusal message points
        operators here, and a delta racing a worker's cache/replica
        commit swap would install an old-epoch overlay into new-base
        engines."""
        with self._write_lock:
            return super().republish(
                path, graph_id=graph_id,
                prepare_timeout_s=prepare_timeout_s,
                commit_timeout_s=commit_timeout_s,
                base_generation=base_generation)

    # ------------------------------------------------------------------
    # fleet-wide refresh + standing reads
    # ------------------------------------------------------------------

    def refresh_fleet(self, timeout_s: Optional[float] = None) -> dict:
        """Run the warm refresh on EVERY replica (parallel — each
        worker refreshes between its own queries).  Returns per-worker
        {generation, apps{...}} plus the fleet wall seconds (the bench
        row's ``fleet_refresh_s``)."""
        timeout_s = (self.refresh_timeout_s if timeout_s is None
                     else timeout_s)
        with self._lock:
            handles = [h for h in self._workers.values() if h.alive]
        if not handles:
            raise NoWorkersError("refresh with no live workers")
        t0 = time.perf_counter()
        rtc = dtrace.mint()
        with dtrace.tspan("live.refresh_fleet", rtc,
                          workers=[h.wid for h in handles]):
            from lux_tpu.serve.fleet.controller import _HandedOff

            pendings = []
            for h in handles:
                try:
                    msg = {"op": "refresh"}
                    if rtc is not None:
                        msg["tc"] = rtc.to_wire()
                    pendings.append((h, self._send(
                        h, msg, _Pending("rpc"))))
                except (ConnectionClosed, _HandedOff):
                    continue  # a dying worker's refresh is just absent
            out: Dict[str, dict] = {}
            deadline = time.monotonic() + timeout_s
            for h, p in pendings:
                if not p.event.wait(max(deadline - time.monotonic(),
                                        0.001)):
                    raise FleetError(
                        f"worker {h.wid} did not finish refresh within "
                        f"{timeout_s}s")
                if p.error is not None or not p.reply.get("ok"):
                    raise FleetError(
                        f"worker {h.wid} refresh failed: "
                        f"{p.error or p.reply.get('err')}")
                out[h.wid] = {k: v for k, v in p.reply.items()
                              if k not in ("req_id", "ok")}
        # a refresh recomputes every standing answer: subscribers get
        # the refreshed states pushed under the refresh's own trace
        self._notify_subs(self.journal.generation(), tc=rtc,
                          refreshed=True)
        return {"workers": out,
                "seconds": round(time.perf_counter() - t0, 4)}

    # -- standing-query subscriptions (serve/autopilot, ISSUE 16) ------

    def subscribe(self, app: str = "sssp", min_generation: int = 0):
        """Register a standing-query subscription: the returned
        :class:`~lux_tpu.serve.autopilot.subscribe.Subscription`
        receives every refreshed answer for ``app`` pushed on
        write-commit and fleet refresh, with the generation tag as the
        cursor — the push replacement for ``read_standing`` polling.
        The hub (and its subscribers) SURVIVES a controller death: an
        elected successor adopts it via ``SubscriptionHub.rebind``, so
        a client registers once per fleet, not once per incarnation."""
        from lux_tpu.serve.autopilot.subscribe import SubscriptionHub

        with self._lock:
            if self._sub_hub is None:
                self._sub_hub = SubscriptionHub(self)
            hub = self._sub_hub
        return hub.subscribe(app, cursor=min_generation)

    def unsubscribe(self, sub) -> None:
        with self._lock:
            hub = self._sub_hub
        if hub is not None:
            hub.unsubscribe(sub)

    def _notify_subs(self, generation: int, tc=None,
                     refreshed: bool = False) -> None:
        with self._lock:
            hub = self._sub_hub
        if hub is not None:
            hub.notify(int(generation), tc=tc, refreshed=refreshed)

    def read_standing(self, app: str = "sssp",
                      worker: Optional[str] = None,
                      timeout_s: float = 30.0) -> dict:
        """One replica's refreshed standing state for ``app``:
        {state, generation, iters, worker, tolerance}.  ``worker=None``
        picks the freshest live replica.  ``tolerance`` is the declared
        served-error bound the answering refresh quiesced under
        (0.0 = exact fixpoint) — the luxmerge twin of the stale tag."""
        with self._lock:
            handles = [h for h in self._workers.values() if h.alive
                       and (worker is None or h.wid == worker)]
        if not handles:
            raise NoWorkersError(f"no live worker matches {worker!r}")
        h = max(handles, key=lambda x: x.delta_gen)
        p = self._send(h, {"op": "read", "app": app}, _Pending("rpc"))
        if not p.event.wait(timeout_s):
            raise FleetError(f"worker {h.wid} read timed out")
        if p.error is not None or not p.reply.get("ok"):
            raise FleetError(f"worker {h.wid} read: "
                             f"{p.error or p.reply.get('err')}")
        return {"state": p.arr, "generation": int(p.reply["generation"]),
                "iters": int(p.reply["iters"]), "worker": h.wid,
                "arg": p.reply.get("arg"),
                "tolerance": float(p.reply.get("tolerance") or 0.0)}

    def read_standing_all(self, app: str = "sssp",
                          timeout_s: float = 30.0) -> Dict[str, dict]:
        """The standing state from EVERY live replica — the acceptance
        surface: after a refresh, all entries must agree bitwise
        (SSSP/CC) / to <= 1 ulp (PageRank) and carry tags >= the last
        admitted generation."""
        out = {}
        for wid in self.live_workers():
            out[wid] = self.read_standing(app, worker=wid,
                                          timeout_s=timeout_s)
        return out

    # ------------------------------------------------------------------
    # compaction escalation
    # ------------------------------------------------------------------

    def compact_fleet(self) -> dict:
        """Public entry: fold the journal epoch into a new snapshot and
        republish it fleet-wide (token-guarded two-phase; old overlays
        serve until the atomic commit)."""
        with self._write_lock:
            return self._compact_fleet_locked()

    def _compact_fleet_locked(self) -> dict:
        from lux_tpu import obs

        if self.snapshot_path is None:
            raise FleetError(
                "fleet compaction needs LiveFleetController("
                "snapshot_path=...) — an overflowed delta log cannot "
                "fold into a base nobody persists")
        gen = self.journal.generation()
        with obs.span("live.compact_fleet", generation=gen):
            self.journal.compact(self.snapshot_path)
            rep = self.republish(self.snapshot_path,
                                 graph_id=self.graph_id,
                                 base_generation=gen)
            with self._lock:
                self._live_counts["compactions"] += 1
                for h in self._workers.values():
                    if h.alive:
                        h.delta_gen = gen
        return {"generation": gen, "republish": rep}

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def generation(self) -> int:
        return self.journal.generation()

    def worker_generations(self) -> Dict[str, int]:
        with self._lock:
            return {wid: h.delta_gen
                    for wid, h in self._workers.items() if h.alive}

    def stats(self) -> dict:
        out = super().stats()
        with self._lock:
            out.update(self._live_counts)
        out["journal"] = self.journal.stats()
        out["worker_generations"] = self.worker_generations()
        return out

    def _own_prom_text(self) -> str:
        """Base families + the journal/live-path gauges the Prometheus
        surface was missing (ISSUE 15 satellite): controller journal
        depth (epoch batches held for catch-up), and per-worker
        journal-vs-replicated generation lag — labelled per worker
        like every fleet series."""
        text = super()._own_prom_text()
        js = self.journal.stats()
        gen = self.journal.generation()
        lines = []
        for name, val, help_text in (
                ("lux_live_journal_depth", js["epoch_batches"],
                 "batches journaled since the epoch base (catch-up "
                 "stream length)"),
                ("lux_live_journal_generation", gen,
                 "the controller journal's commit generation"),
                ("lux_live_base_generation", js["base_generation"],
                 "the current epoch base (advances at compaction)")):
            lines.extend([f"# HELP {name} {help_text}",
                          f"# TYPE {name} gauge", f"{name} {val}"])
        gens = self.worker_generations()
        if gens:
            name = "lux_live_worker_generation_lag"
            lines.extend([
                f"# HELP {name} journal generation minus this worker's "
                "replicated generation",
                f"# TYPE {name} gauge"])
            lines.extend(
                f'{name}{{worker="{w}"}} {max(gen - g, 0)}'
                for w, g in sorted(gens.items()))
        return text + "\n".join(lines) + "\n"


def promote_live_controller(base: HostGraph, journal_dir: str,
                            snapshot_path: Optional[str],
                            endpoints, deadline_s: float = 30.0,
                            seed: int = 0, **kw):
    """Controller FAILOVER (ISSUE 14): build a fresh (restarted or
    standby-promoted) LiveFleetController on the authoritative journal
    dir and re-enroll the surviving workers.

    Recovery is exactly the durable state: ``live_meta.json`` carries
    the epoch base generation; when an epoch boundary passed (a
    compaction), the CURRENT base is the snapshot at ``snapshot_path``,
    not the original graph, so it is (re)loaded from there; the
    DeltaLog replay then restores this epoch's committed batches — the
    whole generation line, with the base-sha check refusing a journal
    against the wrong snapshot.  ``takeover`` rebuilds the ring from
    worker re-hellos with jittered backoff, re-arms the publish-token
    state (discard), and — through the live ``add_worker`` — streams
    catch-up batches to any replica the dead controller had not
    finished replicating to.  Workers whose journals are AHEAD of ours
    refuse us (split-brain guard) and are reported, not enrolled.

    Returns ``(controller, takeover_report)``."""
    from lux_tpu import obs
    from lux_tpu.graph.format import read_lux

    meta = read_live_meta(journal_dir)
    if meta is not None and int(meta["base_generation"]) > 0:
        if snapshot_path is None or not os.path.exists(snapshot_path):
            raise FleetError(
                f"journal {journal_dir} is on epoch base "
                f"{meta['base_generation']} but no snapshot exists at "
                f"{snapshot_path!r} — the epoch base graph is the "
                "compacted snapshot, not the original")
        base = read_lux(snapshot_path)
    with obs.span("live.promote", journal=journal_dir,
                  endpoints=[f"{h}:{p}" for h, p in endpoints]):
        ctl = LiveFleetController(
            base, journal_dir=journal_dir, snapshot_path=snapshot_path,
            **kw)
        rep = ctl.takeover(endpoints, deadline_s=deadline_s, seed=seed)
    return ctl, rep


def start_live_fleet(n_workers: int, g: HostGraph, parts: int = 2,
                     cap: Optional[int] = None,
                     buckets=(1, 4), graph_id: str = "live",
                     standing=(("sssp", 0),),
                     journal_root: Optional[str] = None,
                     snapshot_path: Optional[str] = None,
                     max_queue: int = 256, wait_ms: float = 2.0,
                     hb_interval_s: float = 0.25, method: str = "auto",
                     route_family: Optional[str] = None,
                     tolerance: float = 0.0):
    """A thread-mode live fleet over one in-memory graph: ``n_workers``
    LiveReplica-backed ReplicaWorkers sharing the pull layout, behind a
    LiveFleetController.  ``journal_root`` gives the controller
    (``<root>/controller``) and each worker (``<root>/<wid>``) durable
    journals — the replication-fault tests and any real deployment want
    this; None keeps everything in-memory.  Returns a fleet/bench.Fleet
    (``close()`` tears it all down)."""
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.serve.fleet.bench import Fleet
    from lux_tpu.serve.fleet.worker import ReplicaWorker
    from lux_tpu.serve.live.replica import LiveReplica

    shards = build_pull_shards(g, parts)
    ctl = LiveFleetController(
        g, journal_dir=(None if journal_root is None
                        else os.path.join(journal_root, "controller")),
        snapshot_path=snapshot_path, hb_interval_s=hb_interval_s)
    workers: list = []
    fleet = Fleet(ctl, workers, [])
    try:
        for i in range(n_workers):
            wid = f"w{i}"
            live = LiveReplica(
                g, shards, cap=cap,
                journal_dir=(None if journal_root is None
                             else os.path.join(journal_root, wid)),
                standing=standing, method=method,
                route_family=route_family, tolerance=tolerance)
            w = ReplicaWorker(
                shards, worker_id=wid, graph_id=graph_id,
                q_buckets=tuple(buckets), max_queue=max_queue,
                max_wait_ms=wait_ms, method=method, live=live).start()
            workers.append(w)
            ctl.add_worker("127.0.0.1", w.port)
    except BaseException:
        fleet.close()
        raise
    return fleet
