"""LiveReplica: a worker's half of the mutation-aware serving contract.

Owns, per replica:

* the local DELTA LOG — every replicated batch is journaled through
  ``mutate/deltalog.py``'s npz+``.ok`` protocol BEFORE it is
  acknowledged, so a worker killed between delta receipt and the
  marker loses exactly that batch and recovers to the exact committed
  prefix (the controller re-sends the rest at rejoin: snapshot +
  journal replay + catch-up stream);
* the SERVING OVERLAYS — after each applied batch the statically-shaped
  (OverlayArrays, merged-degree) pair is rebuilt (O(delta) host work)
  and handed to the worker's WarmEngineCache, so every batched query
  answers against the merged graph with NO retrace and NO snapshot
  swap;
* the STANDING STATES — per configured (app, arg) pair a converged
  app state kept warm with PR 10's refresh machinery
  (``mutate/refresh.py``): SSSP/CC bitwise-equal to a cold rebuild of
  the merged graph, PageRank an exact f32 fixpoint.  ``refresh()`` runs
  BETWEEN queries (the worker's refresh thread) — queries keep flowing
  through the overlays meanwhile, so refresh latency never blocks
  reads.

Generations: ``generation()`` counts the journaled prefix
(``base_generation`` + batches applied); ``servable_generation()`` is
what the installed overlay actually serves — they differ only in the
overflow window (a batch journaled but too big for the overlay buffers,
the state that escalates to fleet compaction).
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from lux_tpu.graph.csc import HostGraph
from lux_tpu.mutate import overlay as ovl
from lux_tpu.mutate.deltalog import DeltaLog
from lux_tpu.mutate.graph import MutableGraph
from lux_tpu.serve.live.journal import (
    read_live_meta,
    unpack_batch,
    write_live_meta,
)

from lux_tpu.serve.live.errors import GenerationGap  # noqa: F401 — the
# protocol exception moved to the stdlib-only errors module so the model
# tier imports the real type jax-free; re-exported here for callers

#: standing apps the refresh dispatcher knows (arg = sssp start vertex;
#: pagerank / components take none)
STANDING_APPS = ("sssp", "pagerank", "components")


def parse_standing(spec: str) -> Tuple[Tuple[str, Optional[int]], ...]:
    """``"sssp:0,pagerank"`` -> (("sssp", 0), ("pagerank", None)) — the
    --standing CLI format."""
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        app, _, arg = tok.partition(":")
        if app not in STANDING_APPS:
            raise ValueError(
                f"unknown standing app {app!r}; expected one of "
                f"{STANDING_APPS} (sssp takes ':<start>')")
        out.append((app, int(arg) if arg else None))
    return tuple(out)


class LiveReplica:
    """``g``/``shards``: the CURRENT epoch base and ITS default-layout
    pull shards (the exact bundle the serving cache holds — overlays
    address base edge slots by position, so they must be built from the
    serving layout, pinned identical to the push-embedded one by
    test_live).  ``base_generation``: the epoch base this snapshot
    represents; a journaled replica recovers it from ``live_meta.json``
    (written on first open) and replays its committed prefix."""

    def __init__(self, g: HostGraph, shards, cap: Optional[int] = None,
                 journal_dir: Optional[str] = None,
                 base_generation: int = 0,
                 standing: Tuple[Tuple[str, Optional[int]], ...] = (),
                 method: str = "auto", max_iters: int = 10_000,
                 route_family: Optional[str] = None,
                 tolerance: float = 0.0):
        self.shards = shards
        self.cap = ovl.delta_cap(cap)
        self.method = method
        self.max_iters = int(max_iters)
        # Standing-refresh gather route: since luxmerge the fused
        # families tombstone deleted edges in group space, so the
        # PageRank refresh rides the FASTEST plan family instead of the
        # forced-expand downgrade.  None -> LUX_LIVE_ROUTE env, default
        # 'fused-pf' (banked tpu:reduce_mode winner); '' / 'none'
        # disables routing (the pre-luxmerge direct gather).
        if route_family is None:
            route_family = os.environ.get("LUX_LIVE_ROUTE", "fused-pf")
        self.route_family = str(route_family)
        #: frontier-tolerance band for the standing PageRank refresh —
        #: 0.0 is bitwise the exact fixpoint loop; > 0 trades a declared
        #: per-entry served-error bound (surfaced on every fleet read as
        #: the tolerance tag) for fewer warm iterations.
        self.tolerance = float(tolerance)
        self._pr_route = None  # lazily-planned pagerank gather route
        self.journal_dir = journal_dir
        self.standing_spec = tuple(
            (app, None if arg is None else int(arg))
            for app, arg in standing)
        for app, _arg in self.standing_spec:
            if app not in STANDING_APPS:
                raise ValueError(f"unknown standing app {app!r}")
        self.mg = MutableGraph(g, num_parts=shards.spec.num_parts,
                               cap=self.cap)
        self.mg._pull = shards  # one layout: serving == refresh
        self.base_generation = int(base_generation)
        if journal_dir is not None:
            os.makedirs(journal_dir, mode=0o700, exist_ok=True)
            meta = read_live_meta(journal_dir)
            if meta is not None:
                self.base_generation = int(meta["base_generation"])
            # replays the committed prefix (stops at the first missing
            # .ok marker — the kill-between-receipt-and-marker window)
            self.mg.log = DeltaLog(g, journal_dir=journal_dir)
            if meta is None:
                write_live_meta(journal_dir, self.base_generation)
        self._servable = self.generation()
        #: app -> {state (nv,), stacked (pagerank), generation, iters}
        self._standing: Dict[str, dict] = {}

    # ------------------------------------------------------------------
    # generations
    # ------------------------------------------------------------------

    def generation(self) -> int:
        """Journaled generation: base + committed batches."""
        return self.base_generation + self.mg.log.batches_applied

    def servable_generation(self) -> int:
        """What the installed overlay serves (== generation() except in
        the overflow window awaiting fleet compaction)."""
        return self._servable

    # ------------------------------------------------------------------
    # the write path
    # ------------------------------------------------------------------

    def apply_batch(self, arr: np.ndarray, generation: int):
        """Apply ONE replicated batch (wire (rows, 4) array) claiming
        commit ``generation``.  Journals durably (when journaled), then
        rebuilds the serving overlay.  Returns (oarrays, degree) for the
        cache install.  Raises GenerationGap on a sequence gap (nothing
        applied) and DeltaOverflow when the batch no longer fits the
        overlay capacity (the batch IS journaled — the write is durable,
        just not servable until the fleet compacts)."""
        want = int(generation)
        have = self.generation()
        if want != have + 1:
            raise GenerationGap(have, want)
        src, dst, op, w = unpack_batch(arr)
        self.mg.log.apply(src, dst, op, w)
        oarr, deg = self.serving_overlay()  # raises DeltaOverflow
        self._servable = want
        return oarr, deg

    def serving_overlay(self):
        """(OverlayArrays, merged (P, V) degree stack) for the CURRENT
        log — what the worker installs into its WarmEngineCache."""
        _, oarr = ovl.build_pull_overlay(self.shards, self.mg.log,
                                         self.cap)
        deg = ovl.merged_degree_stacked(self.shards, self.mg.log)
        return oarr, deg

    @property
    def overlay_static(self) -> ovl.OverlayStatic:
        return ovl.OverlayStatic(cap=self.cap,
                                 weighted=self.shards.spec.weighted)

    # ------------------------------------------------------------------
    # standing states (PR 10 warm refresh, between queries)
    # ------------------------------------------------------------------

    def refresh(self) -> dict:
        """Bring every standing state to the current servable
        generation: warm refresh from the prior converged state (cold
        overlay convergence the first time).  SSSP/CC land bitwise on
        the merged graph's unique fixpoint; PageRank on an exact f32
        fixpoint (<= 1 ulp across layouts, per the PR 10 contract)."""
        import time

        from lux_tpu import obs

        gen = self.servable_generation()
        apps = {}
        t0 = time.perf_counter()
        with obs.span("live.refresh", generation=gen,
                      apps=[a for a, _ in self.standing_spec]):
            for app, arg in self.standing_spec:
                ent = self._standing.get(app)
                ts = time.perf_counter()
                if app == "sssp":
                    ent = self._refresh_sssp(ent, arg)
                elif app == "components":
                    ent = self._refresh_components(ent)
                else:
                    ent = self._refresh_pagerank(ent)
                ent["generation"] = gen
                ent["arg"] = arg
                ent["seconds"] = round(time.perf_counter() - ts, 4)
                self._standing[app] = ent
                apps[app] = {"iters": ent["iters"],
                             "seconds": ent["seconds"]}
        return {"generation": gen, "apps": apps,
                "seconds": round(time.perf_counter() - t0, 4)}

    def standing(self, app: str) -> dict:
        """The refreshed entry for ``app`` (KeyError when it was never
        refreshed or is not configured)."""
        return self._standing[app]

    def inherit_standing(self, prior: "LiveReplica") -> None:
        """Carry converged standing states across a republish — but
        ONLY entries refreshed at exactly the new epoch base: the new
        base is the merged graph at ``base_generation``, so a state
        converged there is a valid warm prior, while one converged
        EARLIER is missing batches the new base already contains — the
        fresh-epoch refresh (empty log → no dirty set) would re-tag it
        as current without recomputing, serving stale answers.  Dropped
        entries (stale, or shape-mismatched after a recut) cold-rebuild
        on the next refresh."""
        for app, ent in prior._standing.items():
            if ent.get("generation") != self.base_generation:
                continue
            stacked = ent.get("stacked")
            if stacked is not None and stacked.shape != (
                    self.shards.arrays.vtx_mask.shape):
                continue
            if ent["state"].shape != (self.mg.base.nv,):
                continue
            self._standing[app] = dict(ent)

    def _refresh_sssp(self, ent, start):
        from lux_tpu.mutate import refresh as R

        if ent is None:
            from lux_tpu.models.sssp import SSSPProgram

            prog = SSSPProgram(nv=self.mg.base.nv, start=int(start))
            dist0 = np.full(self.mg.base.nv, prog.inf, np.int32)
            dist0[int(start)] = 0
            frontier = np.zeros(self.mg.base.nv, bool)
            frontier[int(start)] = True
            # a cold run THROUGH the overlay loop: same compiled family
            # as every later warm refresh, exact on the merged graph
            state, it = R._run_push_overlay(
                prog, self.mg, dist0, frontier, self.method,
                self.max_iters, pad_fill=prog.inf)
            dist = self.mg.push_shards.scatter_to_global(
                np.asarray(state))
            return {"state": dist, "iters": int(it)}
        dist, it = R.refresh_sssp(self.mg, ent["state"], int(start),
                                  method=self.method,
                                  max_iters=self.max_iters)
        return {"state": dist, "iters": int(it)}

    def _refresh_components(self, ent):
        from lux_tpu.mutate import refresh as R

        if ent is None:
            from lux_tpu.models.components import MaxLabelProgram

            nv = self.mg.base.nv
            labels0 = np.arange(nv, dtype=np.int32)
            frontier = np.ones(nv, bool)
            state, it = R._run_push_overlay(
                MaxLabelProgram(), self.mg, labels0, frontier,
                self.method, self.max_iters, pad_fill=-1)
            labels = self.mg.push_shards.scatter_to_global(
                np.asarray(state))
            return {"state": labels, "iters": int(it)}
        labels, it = R.refresh_components(self.mg, ent["state"],
                                          method=self.method,
                                          max_iters=self.max_iters)
        return {"state": labels, "iters": int(it)}

    def _pagerank_route(self):
        """The (cached) base-graph gather plan the standing PageRank
        refresh rides — the base gather is unchanged by churn, so one
        plan serves every refresh of the epoch.  Family comes from the
        ``route_family`` knob (env LUX_LIVE_ROUTE, default 'fused-pf');
        every family is bitwise-equal through the overlay, so this is a
        perf decision only."""
        rg = self.route_family
        if rg in ("", "none"):
            return None
        if self._pr_route is None:
            from lux_tpu.apps.common import route_base, route_is_pf, \
                route_mx
            from lux_tpu.ops import expand

            shards = self.mg.pull_shards
            pf = route_is_pf(rg)
            if route_base(rg) == "fused":
                self._pr_route = expand.plan_fused_shards_cached(
                    shards, "sum", pf=pf, mx=route_mx(rg))
            else:
                self._pr_route = expand.plan_expand_shards_cached(
                    shards, pf=pf)
        return self._pr_route

    def _refresh_pagerank(self, ent):
        from lux_tpu.mutate import refresh as R

        shards = self.mg.pull_shards
        route = self._pagerank_route()
        if ent is None:
            oarr, deg = self.serving_overlay()
            stacked, it = R.converge_pagerank(
                shards, method=self.method, route=route,
                overlay=(self.overlay_static, oarr),
                degree_override=deg, tolerance=self.tolerance)
        else:
            stacked, it = R.refresh_pagerank(self.mg, ent["stacked"],
                                             method=self.method,
                                             route=route,
                                             tolerance=self.tolerance)
        stacked = np.asarray(stacked)
        return {"state": shards.scatter_to_global(stacked),
                "stacked": stacked, "iters": int(it),
                "tolerance": self.tolerance}

    # ------------------------------------------------------------------
    # republish plumbing
    # ------------------------------------------------------------------

    def rebind_journal(self, journal_dir: Optional[str],
                       prior: Optional["LiveReplica"] = None) -> None:
        """Post-commit: take over ``journal_dir`` for the new epoch —
        rotate the PRIOR replica's journal (its batches now live in this
        replica's base snapshot) and open a fresh one.  A staged replica
        is built journal-less during prepare (the dir still holds
        old-epoch batches against the old base) and adopts the dir only
        here.  Crash order matches compact.py: the snapshot was durable
        before commit, so a kill mid-rotation leaves either the old
        committed prefix (stale but consistent) or the fresh epoch."""
        self.journal_dir = journal_dir
        if journal_dir is None:
            return
        if prior is not None and prior.journal_dir == journal_dir \
                and prior.mg.log.journal_dir is not None:
            prior.mg.log.journal_reset()
        self.mg.log = DeltaLog(self.mg.base, journal_dir=journal_dir)
        write_live_meta(journal_dir, self.base_generation)

    def stats(self) -> dict:
        occ = ovl.occupancy(self.shards, self.mg.log, self.cap)
        return {
            "generation": self.generation(),
            "servable_generation": self.servable_generation(),
            "base_generation": self.base_generation,
            "delta_occupancy": occ,
            "standing": {app: {"generation": e.get("generation"),
                               "iters": e.get("iters"),
                               "tolerance": e.get("tolerance", 0.0)}
                         for app, e in self._standing.items()},
        }
