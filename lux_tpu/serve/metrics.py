"""Serving metrics: the request-side observability surface.

Collected by the scheduler per request/batch and summarized through the
same structured-stats helpers the analytics path uses
(utils/timing.percentiles + utils/roofline.serve_summarize), so a
serving run emits bench.py-parsable JSON just like an engine run emits
GTEPS lines.

Memory is bounded for a long-lived service: histograms reservoir-sample
past their cap (utils/timing.LatencyHistogram), batch records keep a
recent window plus running aggregates, and queue depth keeps only its
running max.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time

from lux_tpu.utils.roofline import serve_summarize
from lux_tpu.utils.timing import LatencyHistogram


@dataclasses.dataclass
class BatchRecord:
    q: int  # dispatched bucket size (incl. padding)
    real: int  # real (non-padding) queries
    warm: bool  # engine came from the warm cache
    service_s: float  # engine wall time for the batch


class ServeMetrics:
    """Thread-safe counters for one service lifetime."""

    #: recent BatchRecords kept for inspection; aggregates are unbounded
    RECENT_BATCHES = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self.latency = LatencyHistogram()  # enqueue -> result, per request
        self.queue_wait = LatencyHistogram()  # enqueue -> dispatch
        self.batches = collections.deque(maxlen=self.RECENT_BATCHES)
        self._batch_count = 0
        self._batch_slots = 0
        self._batch_real = 0
        self._batch_warm = 0
        self.completed = 0
        self.timeouts = 0
        self.rejected = 0
        self.evictions = 0  # warm-cache engines dropped by the LRU bound
        self.retries = 0  # re-dispatched / envelope-retried requests served
        self.stale_reads = 0  # bounded-staleness degraded reads served
        self.traversed_edges = 0
        self._depth_max = 0
        self._depth_n = 0
        #: service birth on the monotonic clock — scrape()'s qps
        #: denominator, so a scrape is meaningful from the first
        #: request, not only after a caller hands dump() an elapsed_s
        self._t_start = time.monotonic()
        #: latency-bucket upper bound -> (trace_id, seconds): the most
        #: recent distributed-trace exemplar per histogram bucket
        #: (ISSUE 15) — a burning latency SLO links to timelines
        self._exemplars: dict = {}

    def record_batch(self, q: int, real: int, warm: bool, service_s: float):
        with self._lock:
            self.batches.append(BatchRecord(q, real, warm, service_s))
            self._batch_count += 1
            self._batch_slots += q
            self._batch_real += real
            self._batch_warm += int(warm)

    def record_done(self, latency_s: float, wait_s: float, traversed: int,
                    trace: str | None = None):
        with self._lock:
            self.completed += 1
            self.latency.record(latency_s)
            self.queue_wait.record(wait_s)
            self.traversed_edges += int(traversed)
            if trace is not None:
                for le in self.BUCKETS_S:
                    if latency_s <= le:
                        self._exemplars[le] = (str(trace), latency_s)
                        break
                else:
                    self._exemplars["+Inf"] = (str(trace), latency_s)

    def record_timeout(self):
        with self._lock:
            self.timeouts += 1

    def record_rejected(self):
        with self._lock:
            self.rejected += 1

    def record_eviction(self):
        with self._lock:
            self.evictions += 1

    def record_retry(self):
        with self._lock:
            self.retries += 1

    def record_stale_read(self):
        with self._lock:
            self.stale_reads += 1

    def counters(self) -> dict:
        """Point-in-time copy of the monotonic counters — the worker
        heartbeat payload (readers must not reach for the private lock)."""
        with self._lock:
            return {
                "completed": self.completed,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "evictions": self.evictions,
                "retries": self.retries,
                "stale_reads": self.stale_reads,
                "batches": self._batch_count,
                "traversed_edges": self.traversed_edges,
            }

    def sample_queue_depth(self, depth: int):
        with self._lock:
            self._depth_n += 1
            self._depth_max = max(self._depth_max, int(depth))

    def summary(self, elapsed_s: float | None = None,
                cache_stats: dict | None = None) -> dict:
        """JSON-ready summary; ``elapsed_s`` (service wall time) enables
        the QPS/aggregate-GTEPS fields."""
        with self._lock:
            out = {
                "completed": self.completed,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "evictions": self.evictions,
                "retries": self.retries,
                "stale_reads": self.stale_reads,
                "latency_ms": self.latency.summary_ms(),
                "queue_wait_ms": self.queue_wait.summary_ms(),
                "batches": self._batch_count,
            }
            if self._depth_n:
                out["queue_depth_max"] = self._depth_max
            if self._batch_count:
                out["batch_occupancy"] = round(
                    self._batch_real / max(self._batch_slots, 1), 4)
                out["warm_batch_ratio"] = round(
                    self._batch_warm / self._batch_count, 4)
            completed = self.completed
            traversed = self.traversed_edges
            lat = list(self.latency.samples)
        if elapsed_s is not None:
            out.update(serve_summarize(completed, elapsed_s, traversed,
                                       latencies_s=lat))
        if cache_stats:
            out["engine_cache"] = cache_stats
        return out

    # -- scrape + flight-recorder surfaces --------------------------------

    #: Prometheus histogram boundaries (seconds) for request latency and
    #: queue wait; chosen to straddle the measured serving band (warm
    #: Q=64 batch ≈ ms, cold trace ≈ s)
    BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def _histogram_lines(self, name: str, hist, help_text: str,
                         lab: str = "", exemplars: dict | None = None
                         ) -> list:
        """Prometheus text-format histogram from a LatencyHistogram.
        Past the reservoir cap the recorder holds a uniform SAMPLE of the
        stream, so bucket counts are scaled to the true request count
        (the standard reservoir estimator) while ``_count`` stays exact.
        ``lab`` is a pre-rendered label pair (``replica="w0",``) merged
        ahead of ``le`` on every bucket sample.  ``exemplars`` maps a
        bucket bound to its latest (trace_id, seconds) — appended in the
        OpenMetrics exemplar form (``# {trace_id="..."} <value>``), the
        link from a histogram bucket to a stitched fleet timeline."""
        samples = list(hist.samples)
        count = len(hist)
        lines = [f"# HELP {name} {help_text}",
                 f"# TYPE {name} histogram"]
        bare = f"{{{lab[:-1]}}}" if lab else ""  # label set sans le

        def exm(le) -> str:
            ex = (exemplars or {}).get(le)
            if ex is None:
                return ""
            return f' # {{trace_id="{ex[0]}"}} {round(ex[1], 6)}'

        scale = (count / len(samples)) if samples else 0.0
        cum = 0
        for le in self.BUCKETS_S:
            cum = sum(1 for s in samples if s <= le)
            lines.append(f'{name}_bucket{{{lab}le="{le}"}} '
                         f"{int(round(cum * scale))}{exm(le)}")
        lines.append(f'{name}_bucket{{{lab}le="+Inf"}} {count}'
                     f'{exm("+Inf")}')
        lines.append(f"{name}_sum{bare} {round(sum(samples) * scale, 6)}")
        lines.append(f"{name}_count{bare} {count}")
        return lines

    def dump(self, elapsed_s: float | None = None,
             cache_stats: dict | None = None, replica: str = "",
             exemplars: bool = True) -> str:
        """Prometheus text exposition of the full counter/gauge/histogram
        set — the scrape surface a serving fleet's collector reads
        (ROADMAP item 2).  Pure string formatting over the same state
        ``summary()`` reports; safe to call from any thread.

        ``replica`` labels every series with the worker id, so metrics
        the fleet controller aggregates across workers stay per-worker
        attributable (one scrape surface, R label values — the
        Prometheus idiom, not R metric namespaces).

        ``exemplars`` appends the per-bucket trace-id exemplars to the
        latency histogram in OpenMetrics form.  Exemplar syntax is an
        OPENMETRICS feature — a strict classic text-format (0.0.4)
        parser rejects the trailing ``# {...}``, so pass
        ``exemplars=False`` when the output feeds one (the
        textfile-collector artifact does)."""
        lab = f'replica="{replica}",' if replica else ""
        sfx = f"{{{lab[:-1]}}}" if lab else ""
        with self._lock:
            lines = []

            def counter(name, val, help_text):
                lines.extend([f"# HELP {name} {help_text}",
                              f"# TYPE {name} counter",
                              f"{name}{sfx} {val}"])

            def gauge(name, val, help_text):
                lines.extend([f"# HELP {name} {help_text}",
                              f"# TYPE {name} gauge",
                              f"{name}{sfx} {val}"])

            counter("lux_serve_requests_completed_total", self.completed,
                    "requests answered")
            counter("lux_serve_requests_timeout_total", self.timeouts,
                    "requests whose deadline expired in queue")
            counter("lux_serve_requests_shed_total", self.rejected,
                    "requests rejected by bounded-queue backpressure")
            counter("lux_serve_batches_total", self._batch_count,
                    "engine batches dispatched")
            counter("lux_serve_engine_evictions_total", self.evictions,
                    "warm-cache engines dropped by the LRU bound")
            counter("lux_serve_retries_total", self.retries,
                    "re-dispatched or envelope-retried requests served")
            counter("lux_serve_stale_reads_total", self.stale_reads,
                    "bounded-staleness degraded reads served")
            counter("lux_serve_traversed_edges_total", self.traversed_edges,
                    "edges traversed across all answered queries")
            if self._depth_n:  # same no-samples guard as summary()
                gauge("lux_serve_queue_depth_max", self._depth_max,
                      "maximum observed queue depth")
            if self._batch_count:
                gauge("lux_serve_batch_occupancy",
                      round(self._batch_real / max(self._batch_slots, 1), 4),
                      "real queries / dispatched slots")
                gauge("lux_serve_warm_batch_ratio",
                      round(self._batch_warm / self._batch_count, 4),
                      "batches served by a warm engine")
            lines.extend(self._histogram_lines(
                "lux_serve_request_latency_seconds", self.latency,
                "enqueue-to-result latency", lab=lab,
                exemplars=dict(self._exemplars) if exemplars else None))
            lines.extend(self._histogram_lines(
                "lux_serve_queue_wait_seconds", self.queue_wait,
                "enqueue-to-dispatch wait", lab=lab))
            completed = self.completed
        if elapsed_s is not None and elapsed_s > 0:
            lines.extend([
                "# HELP lux_serve_qps completed requests per second",
                "# TYPE lux_serve_qps gauge",
                f"lux_serve_qps{sfx} {round(completed / elapsed_s, 4)}"])
        if cache_stats and (cache_stats.get("warm_hits")
                            or cache_stats.get("cold_traces")):
            # warm.py's stats() already derives the ratio — expose that
            # same number rather than a second computation that could
            # drift (fallback derivation only for a foreign stats dict)
            ratio = cache_stats.get("warm_hit_ratio")
            if ratio is None:
                hits = int(cache_stats.get("warm_hits", 0))
                cold = int(cache_stats.get("cold_traces", 0))
                ratio = round(hits / max(hits + cold, 1), 4)
            lines.extend([
                "# HELP lux_serve_warm_hit_ratio warm engine-cache "
                "hits / lookups",
                "# TYPE lux_serve_warm_hit_ratio gauge",
                f"lux_serve_warm_hit_ratio{sfx} {ratio}"])
        return "\n".join(lines) + "\n"

    def exemplars(self) -> dict:
        """Point-in-time copy of the per-bucket latency exemplars
        ({bucket_le: (trace_id, seconds)})."""
        with self._lock:
            return dict(self._exemplars)

    def scrape(self, queue_depth: int | None = None,
               cache_stats: dict | None = None, replica: str = "",
               extra_gauges=()) -> str:
        """The on-demand scrape surface (ISSUE 15 satellite): ``dump``
        plus the IN-FLIGHT window state a collector needs between the
        scheduler's periodic snapshots.  ``dump()`` alone answers a
        mid-burst scrape with no rate and no live depth — the QPS/depth
        picture existed only in ``serve.metrics`` snapshot events, so a
        scrape landing between snapshots looked stale-empty.  Here:

        * ``lux_serve_qps`` is always present, over the service's OWN
          lifetime clock (fresh start -> 0, never absent);
        * ``queue_depth`` (the caller's live scheduler depth) becomes a
          ``lux_serve_queue_depth`` gauge — the current value, next to
          ``dump``'s running max;
        * ``extra_gauges`` — (name, value, help) rows — lets the worker
          fold its live-path gauges (journal/overlay/cache occupancy)
          into the same replica-labelled exposition."""
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        text = self.dump(elapsed_s=elapsed, cache_stats=cache_stats,
                         replica=replica)
        lab = f'{{replica="{replica}"}}' if replica else ""
        lines = []
        if queue_depth is not None:
            lines.extend([
                "# HELP lux_serve_queue_depth current queued requests",
                "# TYPE lux_serve_queue_depth gauge",
                f"lux_serve_queue_depth{lab} {int(queue_depth)}"])
        for name, val, help_text in extra_gauges:
            lines.extend([f"# HELP {name} {help_text}",
                          f"# TYPE {name} gauge",
                          f"{name}{lab} {val}"])
        return text + ("\n".join(lines) + "\n" if lines else "")

    def emit_snapshot(self, rec=None, elapsed_s: float | None = None,
                      cache_stats: dict | None = None,
                      summary: dict | None = None) -> None:
        """One ``serve.metrics`` point into the event log — the periodic
        flight-recorder snapshot (scheduler emits one every
        ``MicroBatchScheduler.snapshot_every_s``; luxview's serve section
        reads the LAST one).  Callers that already built ``summary()``
        pass it via ``summary=`` so the reservoir percentiles are not
        recomputed for the point event.  Never raises."""
        try:
            from lux_tpu import obs

            if summary is None:
                summary = self.summary(elapsed_s=elapsed_s,
                                       cache_stats=cache_stats)
            r = rec if rec is not None else obs.recorder()
            r.point("serve.metrics", **summary)
        except Exception:  # noqa: BLE001 — telemetry must never cost a run
            pass
