"""Serving metrics: the request-side observability surface.

Collected by the scheduler per request/batch and summarized through the
same structured-stats helpers the analytics path uses
(utils/timing.percentiles + utils/roofline.serve_summarize), so a
serving run emits bench.py-parsable JSON just like an engine run emits
GTEPS lines.

Memory is bounded for a long-lived service: histograms reservoir-sample
past their cap (utils/timing.LatencyHistogram), batch records keep a
recent window plus running aggregates, and queue depth keeps only its
running max.
"""
from __future__ import annotations

import collections
import dataclasses
import threading

from lux_tpu.utils.roofline import serve_summarize
from lux_tpu.utils.timing import LatencyHistogram


@dataclasses.dataclass
class BatchRecord:
    q: int  # dispatched bucket size (incl. padding)
    real: int  # real (non-padding) queries
    warm: bool  # engine came from the warm cache
    service_s: float  # engine wall time for the batch


class ServeMetrics:
    """Thread-safe counters for one service lifetime."""

    #: recent BatchRecords kept for inspection; aggregates are unbounded
    RECENT_BATCHES = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self.latency = LatencyHistogram()  # enqueue -> result, per request
        self.queue_wait = LatencyHistogram()  # enqueue -> dispatch
        self.batches = collections.deque(maxlen=self.RECENT_BATCHES)
        self._batch_count = 0
        self._batch_slots = 0
        self._batch_real = 0
        self._batch_warm = 0
        self.completed = 0
        self.timeouts = 0
        self.rejected = 0
        self.traversed_edges = 0
        self._depth_max = 0
        self._depth_n = 0

    def record_batch(self, q: int, real: int, warm: bool, service_s: float):
        with self._lock:
            self.batches.append(BatchRecord(q, real, warm, service_s))
            self._batch_count += 1
            self._batch_slots += q
            self._batch_real += real
            self._batch_warm += int(warm)

    def record_done(self, latency_s: float, wait_s: float, traversed: int):
        with self._lock:
            self.completed += 1
            self.latency.record(latency_s)
            self.queue_wait.record(wait_s)
            self.traversed_edges += int(traversed)

    def record_timeout(self):
        with self._lock:
            self.timeouts += 1

    def record_rejected(self):
        with self._lock:
            self.rejected += 1

    def sample_queue_depth(self, depth: int):
        with self._lock:
            self._depth_n += 1
            self._depth_max = max(self._depth_max, int(depth))

    def summary(self, elapsed_s: float | None = None,
                cache_stats: dict | None = None) -> dict:
        """JSON-ready summary; ``elapsed_s`` (service wall time) enables
        the QPS/aggregate-GTEPS fields."""
        with self._lock:
            out = {
                "completed": self.completed,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "latency_ms": self.latency.summary_ms(),
                "queue_wait_ms": self.queue_wait.summary_ms(),
                "batches": self._batch_count,
            }
            if self._depth_n:
                out["queue_depth_max"] = self._depth_max
            if self._batch_count:
                out["batch_occupancy"] = round(
                    self._batch_real / max(self._batch_slots, 1), 4)
                out["warm_batch_ratio"] = round(
                    self._batch_warm / self._batch_count, 4)
            completed = self.completed
            traversed = self.traversed_edges
            lat = list(self.latency.samples)
        if elapsed_s is not None:
            out.update(serve_summarize(completed, elapsed_s, traversed,
                                       latencies_s=lat))
        if cache_stats:
            out["engine_cache"] = cache_stats
        return out
