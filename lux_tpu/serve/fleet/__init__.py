"""lux_tpu.serve.fleet — the multi-replica serving fleet.

The controller/worker split on top of ``lux_tpu.serve`` (ROADMAP item 2):

* ``fleet.hashring``   — deterministic consistent-hash routing of
  (app, graph_id, Q-slot) keys with bounded key movement on join/leave.
* ``fleet.wire``       — length-prefixed JSON + npy frames over loopback
  TCP (stdlib; no jax.distributed, no pickle) so the whole fleet runs
  and tests under ``JAX_PLATFORMS=cpu``.
* ``fleet.worker``     — the replica: a ``WarmEngineCache`` + per-app
  ``MicroBatchScheduler`` behind a socket, with prepare/commit
  zero-downtime republish and a ``kill()`` fault drill.
* ``fleet.controller`` — admission, routing, heartbeat-driven
  backpressure/shedding, death recovery, and the republish barrier.
* ``fleet.bench``      — the saturation harness shared by
  ``tools/fleet_bench.py`` and the bench.py ``fleet`` app: ramp offered
  QPS to the throughput knee, record QPS + p99 at the knee per fleet
  width.

This ``__init__`` exports only the controller half; the worker — the
only half that runs engines — is imported explicitly as
``lux_tpu.serve.fleet.worker`` or spawned as a process via
``python -m lux_tpu.serve.fleet.worker``.  ``hashring`` itself is
stdlib-only and loadable standalone (the cross-process determinism test
does exactly that).

Exports resolve LAZILY (PEP 562, same contract as ``lux_tpu.serve``):
the jax-free leaves (``wire``, ``pubproto``, ``hashring``) stay
importable under tools/_jaxfree.py's bare-package stub so the protocol
tier can import the real wire/publish constants without jax.
"""
_EXPORTS = {
    "FleetController": "lux_tpu.serve.fleet.controller",
    "FleetError": "lux_tpu.serve.fleet.controller",
    "FleetFuture": "lux_tpu.serve.fleet.controller",
    "FleetRejectedError": "lux_tpu.serve.fleet.controller",
    "FleetTimeoutError": "lux_tpu.serve.fleet.controller",
    "NoWorkersError": "lux_tpu.serve.fleet.controller",
    "StaleReadError": "lux_tpu.serve.fleet.controller",
    "WorkerRefusedError": "lux_tpu.serve.fleet.controller",
    "HashRing": "lux_tpu.serve.fleet.hashring",
    "route_key": "lux_tpu.serve.fleet.hashring",
    # ISSUE 19: wire-distributed snapshots, pods, process launching
    "StreamSink": "lux_tpu.serve.fleet.stream",
    "StreamTable": "lux_tpu.serve.fleet.stream",
    "negotiate_chunk_bytes": "lux_tpu.serve.fleet.stream",
    "stream_file": "lux_tpu.serve.fleet.stream",
    "PodError": "lux_tpu.serve.fleet.pod",
    "PodWorker": "lux_tpu.serve.fleet.pod",
    "pod_connect": "lux_tpu.serve.fleet.pod",
    "run_pull_pod": "lux_tpu.serve.fleet.pod",
    "LaunchError": "lux_tpu.serve.fleet.launcher",
    "ProcHandle": "lux_tpu.serve.fleet.launcher",
    "launch": "lux_tpu.serve.fleet.launcher",
    "launch_fleet_worker": "lux_tpu.serve.fleet.launcher",
    "launch_pod_worker": "lux_tpu.serve.fleet.launcher",
    "launch_script": "lux_tpu.serve.fleet.launcher",
    "process_spawner": "lux_tpu.serve.fleet.launcher",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
