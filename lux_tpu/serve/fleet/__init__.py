"""lux_tpu.serve.fleet — the multi-replica serving fleet.

The controller/worker split on top of ``lux_tpu.serve`` (ROADMAP item 2):

* ``fleet.hashring``   — deterministic consistent-hash routing of
  (app, graph_id, Q-slot) keys with bounded key movement on join/leave.
* ``fleet.wire``       — length-prefixed JSON + npy frames over loopback
  TCP (stdlib; no jax.distributed, no pickle) so the whole fleet runs
  and tests under ``JAX_PLATFORMS=cpu``.
* ``fleet.worker``     — the replica: a ``WarmEngineCache`` + per-app
  ``MicroBatchScheduler`` behind a socket, with prepare/commit
  zero-downtime republish and a ``kill()`` fault drill.
* ``fleet.controller`` — admission, routing, heartbeat-driven
  backpressure/shedding, death recovery, and the republish barrier.
* ``fleet.bench``      — the saturation harness shared by
  ``tools/fleet_bench.py`` and the bench.py ``fleet`` app: ramp offered
  QPS to the throughput knee, record QPS + p99 at the knee per fleet
  width.

This ``__init__`` exports only the controller half; the worker — the
only half that runs engines — is imported explicitly as
``lux_tpu.serve.fleet.worker`` or spawned as a process via
``python -m lux_tpu.serve.fleet.worker``.  ``hashring`` itself is
stdlib-only and loadable standalone (the cross-process determinism test
does exactly that).
"""
from lux_tpu.serve.fleet.controller import (  # noqa: F401
    FleetController,
    FleetError,
    FleetFuture,
    FleetRejectedError,
    FleetTimeoutError,
    NoWorkersError,
    StaleReadError,
    WorkerRefusedError,
)
from lux_tpu.serve.fleet.hashring import (  # noqa: F401
    HashRing,
    route_key,
)
