"""The two-phase publish protocol surface: token format + refusal
strings shared by the controller (``fleet/controller.py`` republish)
and the worker (``fleet/worker.py`` prepare/commit/discard).

Stdlib-only on purpose: the protocol model tier
(``lux_tpu.analysis.proto.publish_model``, tools/luxproto.py) imports
THIS module under tools/_jaxfree.py's bare-package stub, so the model's
tokens and refusal labels are the fleet's real ones — the conformance
bridge that keeps the model from drifting when a spelling changes.

The protocol, for reference (checked exhaustively by the model):

1. controller mints ``publish_token(incarnation, rid)`` — incarnation-
   scoped, so tokens from a dead controller can never collide with its
   successor's;
2. ``prepare {token}`` fans out; the worker records the token FIRST
   (latest prepare wins), builds the staged cache, and re-checks the
   token before staging — a prepare that lost the race must not stage;
3. any prepare failure → ``discard`` fan-out (clears staged + token,
   strands in-flight prepares);
4. all-staged → ``commit {token}``: the worker swaps ONLY on an exact
   token match (:func:`token_mismatch` is the refusal), so a commit can
   never install a cache staged for a different republish;
5. a successor controller re-arms worker token state by discarding
   before its own republish.
"""
from __future__ import annotations

from typing import Optional


def publish_token(incarnation: str, rid) -> str:
    """The republish barrier token: incarnation-fenced + per-request
    unique within that incarnation.  ``rid`` is the controller's
    request id VERBATIM (the wire format is ``pub-{inc}-r{seq}``)."""
    return f"pub-{incarnation}-{rid}"


#: commit refusal when no prepare staged anything (or a discard ran)
ERR_NOTHING_STAGED = "nothing staged"

#: prepare refusal when a discard / newer prepare won the token race
ERR_PREPARE_SUPERSEDED = "prepare superseded/discarded"


def token_mismatch(staged: Optional[str], want: Optional[str]) -> str:
    """The commit refusal for a staged cache belonging to a DIFFERENT
    republish than the one committing."""
    return (f"staged token {staged!r} does not match "
            f"commit token {want!r}")
