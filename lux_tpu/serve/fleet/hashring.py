"""Consistent-hash routing for the serving fleet (stdlib only).

The controller routes ``(app, graph_id, Q-slot)`` keys over the live
replica workers.  Requirements, in priority order:

* **Deterministic across processes** — the controller may restart, and a
  post-mortem must be able to replay routing from the event log.  Python's
  builtin ``hash`` is salted per process (PYTHONHASHSEED), so every hash
  here is a blake2b digest; ``tests/test_fleet.py`` pins cross-process
  agreement by re-deriving the route table in a fresh interpreter.
* **Bounded key movement** — adding a worker to a ring of R moves ~1/(R+1)
  of the keys (all of them TO the new worker); removing one moves exactly
  the keys it owned (all of them to ring successors).  That is the classic
  consistent-hashing contract (Karger et al.), and it is what makes a
  worker join/leave a local event instead of a fleet-wide cache flush:
  every moved key lands on a replica whose warm engines are already
  traced for the same graph, so the only cost is Q-batch refill.
* **Balance** — each worker is hashed onto the ring at ``vnodes`` points
  (virtual nodes), so R real workers present R*vnodes points and the
  per-worker load concentrates around 1/R.

Keys are **Q-slots**, not raw queries: ``route_key`` folds the query id
into one of ``slots`` buckets per (app, graph).  A bounded, enumerable
key set lets the controller precompute the slot->worker table once per
membership change (routing a request is then a single dict lookup) and
lets the movement property be asserted exactly over the whole key space.
Queries that hash to the same slot always land on the same replica, so
repeated/popular queries hit the same warm engines and coalesce into the
same Q-bucket batches.

This module must stay importable WITHOUT the lux_tpu package (stdlib
only): the determinism test loads it standalone in a subprocess, and the
controller's jax-free half depends on it.
"""
from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Sequence, Tuple

#: ring points per worker; 64 keeps the max/mean per-worker key load
#: within ~1.5x for small fleets (pinned loosely by tests/test_fleet.py)
DEFAULT_VNODES = 64

#: Q-slots per (app, graph): the routable key space.  512 slots over a
#: handful of replicas keeps per-slot granularity fine enough that the
#: ~1/R movement bound is visible, while the precomputed table stays tiny.
DEFAULT_SLOTS = 512


def h64(s: str) -> int:
    """64-bit deterministic hash (blake2b; never the salted builtin)."""
    return int.from_bytes(
        hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest(), "big")


def route_key(app: str, graph_id: str, query: int,
              slots: int = DEFAULT_SLOTS) -> str:
    """The routable key of one query: its (app, graph, Q-slot) tuple.
    The query id is hashed into a slot (not used raw) so the key space
    is bounded and popular query ids spread over slots uniformly."""
    return f"{app}|{graph_id}|q{h64(str(int(query))) % slots}"


class EmptyRingError(RuntimeError):
    """route() on a ring with no workers."""


class HashRing:
    """Sorted-ring consistent hashing with virtual nodes.

    Not thread-safe by itself: the controller mutates it only under its
    own registry lock (membership changes are rare; routing reads go
    through the precomputed slot table, not this object).
    """

    def __init__(self, vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._hashes: List[int] = []  # sorted ring point hashes
        self._owners: List[str] = []  # worker id at each ring point
        self._members: Dict[str, Tuple[int, ...]] = {}

    def __len__(self) -> int:
        return len(self._members)

    def workers(self) -> Tuple[str, ...]:
        return tuple(sorted(self._members))

    def add(self, worker_id: str) -> None:
        if worker_id in self._members:
            raise ValueError(f"worker {worker_id!r} already on the ring")
        points = tuple(h64(f"{worker_id}#{i}") for i in range(self.vnodes))
        for p in points:
            at = bisect.bisect_left(self._hashes, p)
            # digest collisions across distinct ids are ~impossible at
            # 64 bits and fleet scale; deterministic tiebreak anyway
            while at < len(self._hashes) and self._hashes[at] == p \
                    and self._owners[at] < worker_id:
                at += 1
            self._hashes.insert(at, p)
            self._owners.insert(at, worker_id)
        self._members[worker_id] = points

    def remove(self, worker_id: str) -> None:
        if worker_id not in self._members:
            raise ValueError(f"worker {worker_id!r} not on the ring")
        del self._members[worker_id]
        keep = [(h, w) for h, w in zip(self._hashes, self._owners)
                if w != worker_id]
        self._hashes = [h for h, _ in keep]
        self._owners = [w for _, w in keep]

    def route(self, key: str) -> str:
        """The worker owning ``key``: first ring point clockwise."""
        if not self._hashes:
            raise EmptyRingError("no workers on the ring")
        at = bisect.bisect_right(self._hashes, h64(key))
        if at == len(self._hashes):
            at = 0  # wrap past the top of the ring
        return self._owners[at]

    def successors(self, key: str, n: int) -> List[str]:
        """Up to ``n`` DISTINCT workers in ring order from ``key`` — the
        failover walk order: index 0 is the owner, the rest are where the
        key's load sheds to when earlier candidates are saturated/dead."""
        if not self._hashes:
            raise EmptyRingError("no workers on the ring")
        out: List[str] = []
        start = bisect.bisect_right(self._hashes, h64(key))
        for i in range(len(self._hashes)):
            w = self._owners[(start + i) % len(self._hashes)]
            if w not in out:
                out.append(w)
                if len(out) >= n:
                    break
        return out

    def table(self, keys: Sequence[str]) -> Dict[str, str]:
        """key -> owner for a whole key set (the controller's per-slot
        routing table, rebuilt on membership change)."""
        return {k: self.route(k) for k in keys}

    def rebalance_preview(self, keys: Sequence[str],
                          add: Sequence[str] = (),
                          remove: Sequence[str] = ()) -> dict:
        """DRY-RUN a membership change (ISSUE 16): the exact key
        movement ``add``/``remove`` would cause over ``keys``, with
        nothing mutated.  This is the autoscaler's cost gate — a scale
        decision can see, before acting, that a join moves ~1/(R+1) of
        the keys (all TO the joiner) while a retire moves exactly the
        leaver's share, and refuse actions whose warm-cache flush would
        cost more than the load problem they solve.

        Returns ``{total, moved, moved_frac, gained, lost, add,
        remove}`` where ``gained``/``lost`` count moved keys per
        receiving/yielding worker.  The preview is computed on throwaway
        ring clones built from the same deterministic vnode hashes, so
        it matches a real ``add()``/``remove()`` table diff EXACTLY
        (property-pinned against the join/leave movement tests)."""
        add = [str(w) for w in add]
        remove = [str(w) for w in remove]
        overlap = sorted(set(add) & set(remove))
        if overlap:
            raise ValueError(f"workers both added and removed: {overlap}")
        for w in add:
            if w in self._members:
                raise ValueError(f"worker {w!r} already on the ring")
        for w in remove:
            if w not in self._members:
                raise ValueError(f"worker {w!r} not on the ring")

        def _owners(members) -> Dict[str, str]:
            if not members:
                return {k: "" for k in keys}
            ring = HashRing(self.vnodes)
            for w in sorted(members):
                ring.add(w)
            return ring.table(keys)

        before = _owners(list(self._members))
        after = _owners([w for w in self._members if w not in remove]
                        + add)
        gained: Dict[str, int] = {}
        lost: Dict[str, int] = {}
        moved = 0
        for k in keys:
            b, a = before[k], after[k]
            if b == a:
                continue
            moved += 1
            if a:
                gained[a] = gained.get(a, 0) + 1
            if b:
                lost[b] = lost.get(b, 0) + 1
        total = len(keys)
        return {"total": total, "moved": moved,
                "moved_frac": (moved / total) if total else 0.0,
                "gained": dict(sorted(gained.items())),
                "lost": dict(sorted(lost.items())),
                "add": sorted(add), "remove": sorted(remove)}
