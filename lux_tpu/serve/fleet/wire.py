"""Fleet wire protocol: length-prefixed JSON + npy frames over loopback
TCP sockets (pure stdlib + numpy).

Why not jax.distributed / gRPC / pickle: the multihost tests show
``jax.distributed`` is unavailable on the CPU backend of the pinned jax,
gRPC is not in the container, and pickle over a socket is an arbitrary-
code-execution surface (luxcheck LUX-P001 bans it repo-wide).  A frame
here is::

    !II  header_len payload_len
    header_len bytes   UTF-8 JSON object (the message)
    payload_len bytes  optional np.save() bytes (one ndarray)

The npy container carries dtype/shape itself, so answers round-trip
bitwise with no schema drift; ``allow_pickle=False`` on the way back in
keeps the no-pickle policy airtight.  Every message is a JSON dict; the
conventional keys are ``op`` (requests), ``req_id`` (multiplexing), and
``ok``/``err`` (replies) — the framing layer does not interpret them.

``Conn`` wraps a connected socket with a send lock (many threads reply
on one connection: the worker's responder + op handlers) and a recv that
is only ever called from that connection's single reader thread.
"""
from __future__ import annotations

import io
import json
import socket
import struct
import threading
from typing import Optional, Tuple

import numpy as np

_HDR = struct.Struct("!II")

#: sanity bounds — a corrupt length prefix must fail loudly, not OOM the
#: controller (64 MiB covers a (nv,) answer for any graph serve handles)
MAX_HEADER = 16 * 1024 * 1024
MAX_PAYLOAD = 64 * 1024 * 1024


def max_frame_bytes() -> int:
    """The payload bound, env-tunable: ``LUX_FLEET_MAX_FRAME_MB``
    (default 64 MiB).  Resolved per call so a worker process launched
    with the knob and a controller sharing its environment agree — the
    first hardening step toward non-loopback workers shipping bigger
    snapshots/answers (ROADMAP item 2); both peers must raise it, since
    a frame one side can send and the other refuses to receive is a
    dropped connection, not an error reply."""
    from lux_tpu.utils.config import env_int

    return env_int("LUX_FLEET_MAX_FRAME_MB", MAX_PAYLOAD // (1024 * 1024),
                   minimum=1) * 1024 * 1024


class WireError(RuntimeError):
    """Malformed frame (bad length prefix, oversized, bad JSON)."""


class ConnectionClosed(ConnectionError):
    """Peer closed the connection (EOF mid-frame or between frames)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as e:
            raise ConnectionClosed(f"recv failed: {e}") from None
        if not chunk:
            raise ConnectionClosed("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def pack_array(arr: np.ndarray) -> bytes:
    bio = io.BytesIO()
    np.save(bio, np.ascontiguousarray(arr), allow_pickle=False)
    return bio.getvalue()


def unpack_array(payload: bytes) -> np.ndarray:
    return np.load(io.BytesIO(payload), allow_pickle=False)


class Conn:
    """One framed, thread-safe-for-send connection."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._closed = False

    @classmethod
    def connect(cls, host: str, port: int, timeout_s: float = 10.0) -> "Conn":
        sock = socket.create_connection((host, port), timeout=timeout_s)
        sock.settimeout(None)  # blocking from here on; reader owns recv
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return cls(sock)

    def send(self, msg: dict, arr: Optional[np.ndarray] = None) -> None:
        header = json.dumps(msg, separators=(",", ":")).encode("utf-8")
        payload = pack_array(arr) if arr is not None else b""
        if len(header) > MAX_HEADER or len(payload) > max_frame_bytes():
            raise WireError(
                f"frame too large: header={len(header)} "
                f"payload={len(payload)} (payload bound is "
                "LUX_FLEET_MAX_FRAME_MB)")
        frame = _HDR.pack(len(header), len(payload)) + header + payload
        with self._send_lock:
            try:
                self._sock.sendall(frame)
            except OSError as e:
                raise ConnectionClosed(f"send failed: {e}") from None

    def recv(self) -> Tuple[dict, Optional[np.ndarray]]:
        """Next (message, array-or-None).  Single-reader only."""
        hl, pl = _HDR.unpack(_recv_exact(self._sock, _HDR.size))
        if hl > MAX_HEADER or pl > max_frame_bytes():
            raise WireError(f"frame length out of bounds: {hl}/{pl} "
                            "(payload bound is LUX_FLEET_MAX_FRAME_MB)")
        try:
            msg = json.loads(_recv_exact(self._sock, hl).decode("utf-8"))
        except ValueError as e:
            raise WireError(f"bad frame header JSON: {e}") from None
        if not isinstance(msg, dict):
            raise WireError(f"frame header is not an object: {type(msg)}")
        arr = unpack_array(_recv_exact(self._sock, pl)) if pl else None
        return msg, arr

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def closed(self) -> bool:
        return self._closed
