"""Fleet wire protocol: length-prefixed JSON + npy frames over loopback
TCP sockets (pure stdlib + numpy).

Why not jax.distributed / gRPC / pickle: the multihost tests show
``jax.distributed`` is unavailable on the CPU backend of the pinned jax,
gRPC is not in the container, and pickle over a socket is an arbitrary-
code-execution surface (luxcheck LUX-P001 bans it repo-wide).  A frame
here is::

    !III  header_len payload_len payload_crc32
    header_len bytes   UTF-8 JSON object (the message)
    payload_len bytes  optional np.save() bytes (one ndarray)

The npy container carries dtype/shape itself, so answers round-trip
bitwise with no schema drift; ``allow_pickle=False`` on the way back in
keeps the no-pickle policy airtight.  The crc32 (ISSUE 14) makes
payload corruption DETECTABLE: a length prefix already fails loudly,
but flipped bits inside an npy's data region used to parse as a valid
— wrong — answer; now they are a WireError (both peers always run the
same code, so the frame layout can evolve atomically).  Every message is a JSON dict; the
conventional keys are ``op`` (requests), ``req_id`` (multiplexing), and
``ok``/``err`` (replies) — the framing layer does not interpret them.

``Conn`` wraps a connected socket with a send lock (many threads reply
on one connection: the worker's responder + op handlers) and a recv that
is only ever called from that connection's single reader thread.

**Deadlines** (ISSUE 14): once a frame is IN FLIGHT — the first header
byte arrived, or a send began — the rest must complete within
``LUX_FLEET_TIMEOUT_S`` (default 60 s; 0 disables).  Idle waits between
frames stay unbounded (a quiet peer is normal; a half-frame peer is
hung).  Timeouts are selectors-based, never ``settimeout`` — the reader
and the senders share one socket object, and a socket-level timeout set
by one would race the other.  A deadline expiring raises
:class:`WireTimeout` (a ``ConnectionClosed``: a peer that hangs
mid-frame has desynchronized the stream, so the connection is done)
naming the peer and the knob.

**Fault injection** (ISSUE 14): every send and every received frame
consults the process's installed :class:`lux_tpu.fault.FaultPlan` at
sites ``wire.send`` / ``wire.recv`` with (owner, peer, op) context —
drops, delays, truncated/partial writes, corrupt payloads, resets and
kills are injected HERE, at the layer where real networks fail, so
drills exercise the exact production frames.  No plan installed = one
``None`` check per frame.
"""
from __future__ import annotations

import io
import json
import selectors
import socket
import struct
import threading
import time
import zlib
from typing import Optional, Tuple

import numpy as np

from lux_tpu import fault as _fault
from lux_tpu.obs import dtrace as _dtrace

_HDR = struct.Struct("!III")

#: sanity bounds — a corrupt length prefix must fail loudly, not OOM the
#: controller (64 MiB covers a (nv,) answer for any graph serve handles)
MAX_HEADER = 16 * 1024 * 1024
MAX_PAYLOAD = 64 * 1024 * 1024


def max_frame_bytes() -> int:
    """The payload bound, env-tunable: ``LUX_FLEET_MAX_FRAME_MB``
    (default 64 MiB).  Resolved per call so a worker process launched
    with the knob and a controller sharing its environment agree — the
    first hardening step toward non-loopback workers shipping bigger
    snapshots/answers (ROADMAP item 2); both peers must raise it, since
    a frame one side can send and the other refuses to receive is a
    dropped connection, not an error reply."""
    from lux_tpu.utils.config import env_int

    return env_int("LUX_FLEET_MAX_FRAME_MB", MAX_PAYLOAD // (1024 * 1024),
                   minimum=1) * 1024 * 1024


def frame_timeout_s() -> Optional[float]:
    """Per-frame in-flight deadline: ``LUX_FLEET_TIMEOUT_S`` seconds
    (default 60; 0 disables).  Resolved per call like max_frame_bytes
    so both peers of a spawned-process fleet agree from one
    environment."""
    from lux_tpu.utils.config import env_float

    t = env_float("LUX_FLEET_TIMEOUT_S", 60.0, minimum=0.0)
    return None if not t else float(t)


class WireError(RuntimeError):
    """Malformed frame (bad length prefix, oversized, bad JSON)."""


class ConnectionClosed(ConnectionError):
    """Peer closed the connection (EOF mid-frame or between frames)."""


class WireTimeout(ConnectionClosed):
    """A frame in flight did not complete within LUX_FLEET_TIMEOUT_S.
    Subclasses ConnectionClosed: a peer hung mid-frame has
    desynchronized the byte stream, so every handler that survives a
    dropped peer (retire + re-dispatch) is the right handler here too
    — previously this peer would have blocked its reader thread
    forever."""

    def __init__(self, direction: str, peer: str, waiting_bytes: int,
                 timeout_s: float):
        super().__init__(
            f"{direction} to/from peer {peer!r} stalled mid-frame "
            f"({waiting_bytes} bytes outstanding after {timeout_s:g}s) "
            "— raise LUX_FLEET_TIMEOUT_S if this link is genuinely "
            "that slow")
        self.peer = peer
        self.timeout_s = timeout_s


def _wait_io(sock: socket.socket, direction: str, deadline: float,
             peer: str, nbytes: int, timeout_s: float) -> None:
    """Block until the socket is ready for ``direction`` or the
    deadline passes (WireTimeout).  selectors (epoll/poll under the
    hood), NOT select.select: fd-set select breaks on fds >= 1024
    (FD_SETSIZE), and a big fleet's controller — many workers, engine
    caches, journal files — crosses that line with perfectly healthy
    sockets."""
    rem = deadline - time.monotonic()
    if rem <= 0:
        raise WireTimeout(direction, peer, nbytes, timeout_s)
    ev = (selectors.EVENT_READ if direction == "recv"
          else selectors.EVENT_WRITE)
    try:
        with selectors.DefaultSelector() as sel:
            sel.register(sock, ev)
            ready = sel.select(rem)
    except (OSError, ValueError) as e:  # fd closed under us
        raise ConnectionClosed(f"{direction} to/from {peer}: {e}") \
            from None
    if not ready:
        raise WireTimeout(direction, peer, nbytes, timeout_s)


def _recv_exact(sock: socket.socket, n: int, peer: str = "peer",
                timeout_s: Optional[float] = None,
                idle_first: bool = False) -> bytes:
    """Read exactly ``n`` bytes.  ``idle_first`` lets the FIRST byte
    wait forever (the normal idle gap between frames); once any byte
    of a frame arrived, the rest must land within ``timeout_s``."""
    buf = bytearray()
    deadline: Optional[float] = (
        None if timeout_s is None or idle_first
        else time.monotonic() + timeout_s)
    while len(buf) < n:
        if deadline is not None:
            _wait_io(sock, "recv", deadline, peer, n - len(buf),
                     timeout_s)
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as e:
            raise ConnectionClosed(f"recv from {peer} failed: {e}") \
                from None
        if not chunk:
            raise ConnectionClosed(f"peer {peer} closed")
        buf.extend(chunk)
        if deadline is None and timeout_s is not None:
            deadline = time.monotonic() + timeout_s  # frame in flight
    return bytes(buf)


def _send_all(sock: socket.socket, data: bytes, peer: str,
              timeout_s: Optional[float]) -> None:
    view = memoryview(data)
    deadline = (None if timeout_s is None
                else time.monotonic() + timeout_s)
    while view.nbytes:
        if deadline is not None:
            _wait_io(sock, "send", deadline, peer, view.nbytes,
                     timeout_s)
        try:
            sent = sock.send(view)
        except OSError as e:
            raise ConnectionClosed(f"send to {peer} failed: {e}") \
                from None
        view = view[sent:]


def pack_array(arr: np.ndarray) -> bytes:
    bio = io.BytesIO()
    np.save(bio, np.ascontiguousarray(arr), allow_pickle=False)
    return bio.getvalue()


def unpack_array(payload: bytes) -> np.ndarray:
    return np.load(io.BytesIO(payload), allow_pickle=False)


class Conn:
    """One framed, thread-safe-for-send connection.  ``peer`` names the
    REMOTE end and ``owner`` the local one — purely observability +
    fault-rule matching labels (errors name the peer; FaultRules match
    both)."""

    #: class-level label defaults: a Conn built without __init__ (test
    #: doubles) still labels errors and matches fault rules sanely
    peer = "peer"
    owner: Optional[str] = None
    _tc_sent = 0
    _tc_rcvd = 0

    #: skew-stamp throttle: the first N traced frames per connection
    #: always stamp dtrace.send/recv points, then every Mth — the skew
    #: solver needs a SAMPLE of (send, recv) pairs per process pair
    #: (it takes minima), and stamping every frame of a saturated
    #: fleet would make the stamps themselves the overhead
    TC_STAMP_FIRST = 32
    TC_STAMP_EVERY = 16

    def __init__(self, sock: socket.socket, peer: str = "peer",
                 owner: Optional[str] = None):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._closed = False
        self._tc_sent = 0
        self._tc_rcvd = 0
        self.peer = str(peer)
        self.owner = owner

    def _stamp(self, count: int) -> bool:
        return (count <= self.TC_STAMP_FIRST
                or count % self.TC_STAMP_EVERY == 0)

    @classmethod
    def connect(cls, host: str, port: int, timeout_s: float = 10.0,
                peer: Optional[str] = None,
                owner: Optional[str] = None) -> "Conn":
        sock = socket.create_connection((host, port), timeout=timeout_s)
        sock.settimeout(None)  # blocking from here on; reader owns recv
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return cls(sock, peer=peer if peer else f"{host}:{port}",
                   owner=owner)

    def label(self, peer: Optional[str] = None,
              owner: Optional[str] = None) -> "Conn":
        """Re-label after identity is learned (the controller knows a
        worker's id only after its hello)."""
        if peer is not None:
            self.peer = str(peer)
        if owner is not None:
            self.owner = str(owner)
        return self

    # ------------------------------------------------------------------

    def send(self, msg: dict, arr: Optional[np.ndarray] = None) -> None:
        header = json.dumps(msg, separators=(",", ":")).encode("utf-8")
        payload = pack_array(arr) if arr is not None else b""
        if len(header) > MAX_HEADER or len(payload) > max_frame_bytes():
            raise WireError(
                f"frame too large: header={len(header)} "
                f"payload={len(payload)} (payload bound is "
                "LUX_FLEET_MAX_FRAME_MB)")
        frame = (_HDR.pack(len(header), len(payload),
                           zlib.crc32(payload)) + header + payload)
        rule = _fault.fire("wire.send", owner=self.owner, peer=self.peer,
                           op=msg.get("op"))
        if rule is not None:
            frame = self._faulted_send(rule, frame)
            if frame is None:
                return
        with self._send_lock:
            tc = msg.get("tc")
            if tc is not None:
                # the skew-correction stamp (ISSUE 15): paired with the
                # receiver's dtrace.recv by the header's span id, these
                # are the (send, recv) pairs luxstitch bounds
                # per-process clock offsets from.  Untraced frames
                # (heartbeats, no header) cost exactly this None check;
                # traced ones are throttled past TC_STAMP_FIRST (see
                # _stamp).  Counter + stamp live INSIDE the send lock:
                # the receiver counts frames in arrival (= send) order,
                # and both sides must pick the SAME frames to stamp or
                # the (send, recv) pairs never match up under
                # concurrent senders.
                self._tc_sent += 1
                if self._stamp(self._tc_sent):
                    _dtrace.wire_point("send", tc, msg.get("op"),
                                       self.peer, self.owner)
            _send_all(self._sock, frame, self.peer, frame_timeout_s())

    def _faulted_send(self, rule, frame: bytes) -> Optional[bytes]:
        """Apply a fired send-site rule; returns the (possibly altered)
        frame to transmit, or None when nothing should be sent."""
        act = rule.action
        if act == "drop":
            return None
        if act == "kill":
            raise _fault.InjectedKill("injected kill at wire.send")
        if act == "delay":
            if rule.delay_ms > 0:
                time.sleep(rule.delay_ms / 1e3)
            return frame
        if act == "corrupt":
            # flip bits near the end of the frame (payload when present,
            # header otherwise) — the peer must detect, not crash
            buf = bytearray(frame)
            buf[-1] ^= 0xFF
            buf[len(buf) // 2] ^= 0xFF
            return bytes(buf)
        if act in ("truncate", "partial"):
            cut = min(_HDR.size + max(rule.trunc_bytes, 0),
                      max(len(frame) - 1, 1))
            with self._send_lock:
                _send_all(self._sock, frame[:cut], self.peer,
                          frame_timeout_s())
            if act == "truncate":
                self.close()  # peer sees EOF mid-frame
            # "partial": stop mid-frame WITHOUT closing — the peer's
            # LUX_FLEET_TIMEOUT_S deadline is what unsticks it
            return None
        if act == "reset":
            self.close()
            raise ConnectionClosed(
                f"injected reset to peer {self.peer!r}")
        return frame  # noop

    def recv(self) -> Tuple[dict, Optional[np.ndarray]]:
        """Next (message, array-or-None).  Single-reader only."""
        timeout_s = frame_timeout_s()
        while True:
            hl, pl, crc = _HDR.unpack(_recv_exact(
                self._sock, _HDR.size, peer=self.peer,
                timeout_s=timeout_s, idle_first=True))
            if hl > MAX_HEADER or pl > max_frame_bytes():
                raise WireError(f"frame length out of bounds: {hl}/{pl} "
                                "(payload bound is LUX_FLEET_MAX_FRAME_MB)")
            try:
                msg = json.loads(_recv_exact(
                    self._sock, hl, peer=self.peer,
                    timeout_s=timeout_s).decode("utf-8"))
            except ValueError as e:
                raise WireError(f"bad frame header JSON: {e}") from None
            if not isinstance(msg, dict):
                raise WireError(
                    f"frame header is not an object: {type(msg)}")
            payload = _recv_exact(self._sock, pl, peer=self.peer,
                                  timeout_s=timeout_s) if pl else b""
            rule = _fault.fire("wire.recv", owner=self.owner,
                               peer=self.peer, op=msg.get("op"))
            if rule is not None:
                if rule.action == "drop":
                    continue  # the frame never happened
                if rule.action == "kill":
                    raise _fault.InjectedKill(
                        "injected kill at wire.recv")
                if rule.action == "delay" and rule.delay_ms > 0:
                    time.sleep(rule.delay_ms / 1e3)
                if rule.action == "reset":
                    self.close()
                    raise ConnectionClosed(
                        f"injected reset from peer {self.peer!r}")
                if rule.action == "corrupt" and payload:
                    buf = bytearray(payload)
                    buf[-1] ^= 0xFF
                    buf[len(buf) // 2] ^= 0xFF
                    payload = bytes(buf)
            tc = msg.get("tc")
            if tc is not None:
                self._tc_rcvd += 1
                if self._stamp(self._tc_rcvd):
                    _dtrace.wire_point("recv", tc, msg.get("op"),
                                       self.peer, self.owner)
            if not payload:
                return msg, None
            if zlib.crc32(payload) != crc:
                # flipped bits inside the npy DATA region would parse
                # as a valid (wrong!) array — the crc is the only
                # detector for silent payload corruption
                raise WireError(
                    f"corrupt payload from peer {self.peer!r}: crc "
                    "mismatch")
            try:
                return msg, unpack_array(payload)
            except ValueError as e:
                raise WireError(
                    f"corrupt npy payload from peer {self.peer!r}: {e}"
                ) from None

    def recv_wait(self, timeout_s: float) -> Tuple[dict, Optional[np.ndarray]]:
        """``recv()`` with a BOUNDED wait for the frame to START.

        Plain ``recv`` idles forever between frames (a quiet peer is
        normal for the data plane), but a lease probe (election.py's
        WireIncumbent) must treat silence itself as the signal: an
        incumbent that stops answering within the lease interval is
        dead.  Raises :class:`WireTimeout` when no frame begins within
        ``timeout_s``; once bytes flow, the normal in-flight deadline
        applies."""
        _wait_io(self._sock, "recv", time.monotonic() + timeout_s,
                 self.peer, _HDR.size, timeout_s)
        return self.recv()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def closed(self) -> bool:
        return self._closed
