"""Fleet controller: admission, routing, placement, republish.

The controller half of the controller/worker split.  It owns:

* **Membership** — a registry of `ReplicaWorker` endpoints and a
  consistent-hash ring (``fleet/hashring.py``) over the live ones.
  Worker death is detected two ways: the connection reset a kill causes,
  and heartbeat staleness for wedged-but-connected workers.  Either way
  the worker leaves the ring (moving only its ~1/R of the key space) and
  its in-flight queries are transparently re-dispatched to ring
  successors.
* **Routing** — every query maps to a ``(app, graph_id, Q-slot)`` key
  (``route_key``); the owner is the first live, unsaturated worker on
  the ring walk from that key.  Slot affinity keeps repeat queries on
  the same replica's warm engines so its Q-bucket batches run full.
* **Backpressure + shedding** — each worker's queue-depth/shed
  heartbeat (``serve/metrics.py`` counters over the ``stats`` op) marks
  it saturated past ``sat_frac`` of its admission bound; saturated
  workers are skipped on the ring walk, and when EVERY live worker is
  saturated the controller sheds at admission with a ``retry_after_ms``
  hint — the fleet-level analog of the scheduler's bounded-queue reject.
  A worker-side shed reply (the race where a queue filled between
  heartbeats) is retried on the next ring successor before any caller
  sees an error: degraded, never wrong.
* **Republish** — ``republish(path)`` is a two-phase barrier: every
  worker ``prepare``s (loads + prewarms the new snapshot NEXT TO the
  serving engines), and only when all preparations succeed does the
  controller send ``commit`` (an atomic cache-pointer swap per worker).
  Admission never pauses, so zero requests are rejected because of the
  swap; a failed prepare on any worker aborts the whole republish with
  the old graph still serving everywhere.

Everything here is stdlib + numpy: the controller process never imports
jax (graph math lives in the workers), so it stays responsive no matter
what the engines are doing.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set

import numpy as np

from lux_tpu.serve.fleet.hashring import (
    DEFAULT_SLOTS,
    DEFAULT_VNODES,
    EmptyRingError,
    HashRing,
    route_key,
)
from lux_tpu.serve.fleet.wire import Conn, ConnectionClosed, WireError


class FleetError(RuntimeError):
    """Fleet-level request failure (no retry succeeded)."""


class FleetRejectedError(FleetError):
    """Fleet-wide load shed: every live worker is saturated."""

    def __init__(self, retry_after_ms: float):
        super().__init__(
            f"fleet saturated; retry after {retry_after_ms:.0f} ms")
        self.retry_after_ms = retry_after_ms


class NoWorkersError(FleetError):
    """No live workers registered."""


class FleetTimeoutError(FleetError, TimeoutError):
    """The request's deadline expired (in a worker queue or on the wire)."""


class StaleReadError(FleetError):
    """A ``min_generation`` read bound no live worker currently
    satisfies (replication still in flight, or a recovering worker
    mid-catch-up).  Retry, or read without the bound and accept the
    generation tag the answer carries."""

    def __init__(self, min_generation: int, best: int):
        super().__init__(
            f"no live worker has replicated generation {min_generation} "
            f"yet (freshest replica serves {best}); retry or drop the "
            "min_generation bound")
        self.min_generation = int(min_generation)
        self.best = int(best)


class FleetFuture:
    """Handle to one fleet-routed query."""

    def __init__(self, app: str, source: int,
                 timeout_ms: Optional[float],
                 min_generation: Optional[int] = None):
        self.app = app
        self.source = int(source)
        self.timeout_ms = timeout_ms
        #: read-your-writes bound: only workers whose applied mutation
        #: generation is >= this may answer (None = any replica)
        self.min_generation = min_generation
        #: mutation generation the ANSWER reflects (None on a
        #: static-snapshot fleet) — always >= min_generation when set
        self.generation: Optional[int] = None
        self.worker_id: Optional[str] = None  # who answered
        self.rounds = 0
        self.traversed = 0
        self.attempts = 0
        self.t_submit = time.monotonic()
        self.t_done: Optional[float] = None
        self._cb_lock = threading.Lock()
        self._event = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._callbacks: List = []

    def done(self) -> bool:
        return self._event.is_set()

    def add_done_callback(self, fn) -> None:
        """Call ``fn(self)`` when the future resolves (immediately if it
        already did).  Runs on the resolving thread — keep it O(1); it
        exists so closed-loop clients can track in-flight counts without
        scanning (a scanning client measures itself, not the fleet)."""
        run_now = False
        with self._cb_lock:
            if self._event.is_set():
                run_now = True
            else:
                self._callbacks.append(fn)
        if run_now:
            fn(self)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise FleetTimeoutError("no result within wait timeout")
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def latency_s(self) -> Optional[float]:
        """Client-observed submit-to-resolve wall time (the number the
        saturation bench's percentiles are built from)."""
        return None if self.t_done is None else self.t_done - self.t_submit

    def _resolve(self, result=None, error=None) -> None:
        with self._cb_lock:
            if self._event.is_set():
                return  # first resolution wins — a racing duplicate
                # dispatch must never overwrite a result waiters saw
            self._result = result
            self._error = error
            self.t_done = time.monotonic()
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            fn(self)


class _HandedOff(Exception):
    """Internal: a send failed AND _retire had already harvested the
    pending — the future's ownership moved to the retire path, so the
    sender must NOT dispatch it again."""


class _Pending:
    """One outstanding frame awaiting a worker reply."""

    def __init__(self, kind: str, fut: Optional[FleetFuture] = None):
        self.kind = kind  # "query" | "rpc"
        self.fut = fut
        self.reply: Optional[dict] = None
        self.arr: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()


class _WorkerHandle:
    def __init__(self, wid: str, conn: Conn, info: dict):
        self.wid = wid
        self.conn = conn
        self.info = info
        self.alive = True
        self.saturated = False
        self.last_hb: dict = {}
        self.last_seen = time.monotonic()
        self.pending: Dict[str, _Pending] = {}
        self.reader: Optional[threading.Thread] = None
        #: highest mutation generation this worker acknowledged as
        #: SERVABLE (delta acks + heartbeats keep it fresh); the
        #: min_generation routing bound filters on it.  0 on a
        #: static-snapshot fleet — min_generation=None ignores it.
        self.delta_gen = 0


class FleetController:
    def __init__(self, hb_interval_s: float = 0.25,
                 hb_timeout_s: float = 3.0, sat_frac: float = 0.8,
                 retries: int = 3, slots: int = DEFAULT_SLOTS,
                 vnodes: int = DEFAULT_VNODES):
        self.hb_interval_s = float(hb_interval_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self.sat_frac = float(sat_frac)
        self.retries = int(retries)
        self.slots = int(slots)
        self._lock = threading.Lock()
        self._ring = HashRing(vnodes)
        self._workers: Dict[str, _WorkerHandle] = {}
        self._graph_id: Optional[str] = None
        self._seq = 0
        self._closed = False
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # fleet-level counters (the controller's own observability row)
        self._counts = {"submitted": 0, "completed": 0, "shed": 0,
                        "rerouted": 0, "worker_deaths": 0,
                        "republishes": 0, "errors": 0}

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    @property
    def graph_id(self) -> Optional[str]:
        with self._lock:
            return self._graph_id

    def add_worker(self, host: str, port: int,
                   timeout_s: float = 60.0) -> str:
        """Connect + handshake a worker and put it on the ring.  The
        first worker pins the fleet's graph_id; later joins must serve
        the same graph (a mismatched replica would answer WRONG, which
        is worse than answering slow)."""
        from lux_tpu import obs

        conn = Conn.connect(host, port, timeout_s=timeout_s)
        handle = _WorkerHandle("?", conn, {})
        handle.reader = threading.Thread(
            target=self._read_loop, args=(handle,),
            name="lux-fleet-ctl-read", daemon=True)
        handle.reader.start()
        p = self._send(handle, {"op": "hello"}, _Pending("rpc"))
        if not p.event.wait(timeout_s) or p.error or not p.reply:
            conn.close()
            raise FleetError(f"worker at {host}:{port} failed handshake: "
                             f"{p.error}")
        info = p.reply
        wid = str(info["worker_id"])
        with self._lock:
            if self._closed:
                conn.close()
                raise FleetError("controller closed")
            if wid in self._workers and self._workers[wid].alive:
                conn.close()
                raise FleetError(f"worker id {wid!r} already registered")
            if self._graph_id is None:
                self._graph_id = str(info["graph_id"])
            elif str(info["graph_id"]) != self._graph_id:
                conn.close()
                raise FleetError(
                    f"worker {wid} serves graph {info['graph_id']!r}, "
                    f"fleet serves {self._graph_id!r}")
            handle.wid = wid
            handle.info = info
            handle.last_seen = time.monotonic()
            self._workers[wid] = handle
            self._ring.add(wid)
        obs.point("fleet.worker.join", worker=wid,
                  graph=str(info["graph_id"]), nv=info.get("nv"))
        self._ensure_heartbeat()
        return wid

    def remove_worker(self, wid: str, shutdown: bool = True) -> None:
        """Graceful leave: take the worker off the ring (its keys move to
        ring successors), optionally ask it to drain and exit."""
        with self._lock:
            handle = self._workers.get(wid)
            if handle is None or not handle.alive:
                return
        if shutdown:
            try:
                self._rpc(handle, {"op": "shutdown"}, timeout_s=10.0)
            except FleetError:
                pass  # it may already be gone; the goal is absence
        self._retire(handle, cause="leave")

    def workers(self) -> Dict[str, dict]:
        with self._lock:
            return {
                wid: {"alive": h.alive, "saturated": h.saturated,
                      "last_hb": dict(h.last_hb)}
                for wid, h in self._workers.items()
            }

    def live_workers(self) -> List[str]:
        with self._lock:
            return sorted(w for w, h in self._workers.items() if h.alive)

    # ------------------------------------------------------------------
    # wire plumbing
    # ------------------------------------------------------------------

    def _next_rid(self) -> str:
        with self._lock:
            self._seq += 1
            return f"r{self._seq}"

    def _send(self, handle: _WorkerHandle, msg: dict,
              pending: _Pending) -> _Pending:
        rid = self._next_rid()
        msg = {**msg, "req_id": rid}
        with self._lock:
            handle.pending[rid] = pending
        try:
            handle.conn.send(msg)
        except ConnectionClosed:
            with self._lock:
                still_mine = handle.pending.pop(rid, None) is not None
            if not still_mine:
                # the reader's _retire beat us to it: it already
                # harvested this pending as an orphan and re-dispatched
                # (query) or failed (rpc) it — dispatching again from
                # here would put the SAME future in flight twice
                raise _HandedOff() from None
            self._on_conn_lost(handle)
            raise
        return pending

    def _rpc(self, handle: _WorkerHandle, msg: dict,
             timeout_s: float) -> dict:
        try:
            p = self._send(handle, msg, _Pending("rpc"))
        except (ConnectionClosed, _HandedOff):
            raise FleetError(f"worker {handle.wid} unreachable") from None
        if not p.event.wait(timeout_s):
            raise FleetError(
                f"worker {handle.wid} did not answer {msg.get('op')!r} "
                f"within {timeout_s}s")
        if p.error is not None:
            raise FleetError(str(p.error))
        if not p.reply.get("ok"):
            raise FleetError(
                f"worker {handle.wid} {msg.get('op')}: "
                f"{p.reply.get('kind')}: {p.reply.get('err')}")
        return p.reply

    def _read_loop(self, handle: _WorkerHandle) -> None:
        while True:
            try:
                msg, arr = handle.conn.recv()
            except (ConnectionClosed, WireError):
                break
            rid = msg.get("req_id")
            with self._lock:
                p = handle.pending.pop(rid, None)
                handle.last_seen = time.monotonic()
            if p is None:
                continue  # late reply for a retried/abandoned request
            if p.kind == "query":
                self._resolve_query(handle, p, msg, arr)
            else:
                p.reply = msg
                p.arr = arr
                p.event.set()
        self._on_conn_lost(handle)

    def _on_conn_lost(self, handle: _WorkerHandle) -> None:
        if handle.wid == "?":  # handshake never completed
            return
        self._retire(handle, cause="death")

    def _retire(self, handle: _WorkerHandle, cause: str) -> None:
        """Take a worker out of service; re-dispatch its in-flight
        queries on the survivors and fail its in-flight rpcs."""
        from lux_tpu import obs

        with self._lock:
            if not handle.alive:
                return
            if self._closed:
                # controller teardown closes every conn; the readers'
                # resulting ConnectionClosed is shutdown, not death —
                # a clean close must not mint worker_deaths or spray
                # fleet.worker.down events into the flight recorder.
                # In-flight work still RESOLVES (a dropped future hangs
                # its waiter forever; an error is strictly better)
                handle.alive = False
                leftovers = list(handle.pending.values())
                handle.pending.clear()
            else:
                leftovers = None
        if leftovers is not None:
            closed_err = FleetError("controller closed")
            for p in leftovers:
                if p.kind == "query":
                    p.fut._resolve(error=closed_err)
                else:
                    p.error = closed_err
                    p.event.set()
            return
        with self._lock:
            if not handle.alive:
                return
            handle.alive = False
            if handle.wid in self._ring.workers():
                self._ring.remove(handle.wid)
            orphans = list(handle.pending.values())
            handle.pending.clear()
            if cause == "death":
                self._counts["worker_deaths"] += 1
        obs.point("fleet.worker.down", worker=handle.wid, cause=cause,
                  orphans=len(orphans))
        handle.conn.close()
        for p in orphans:
            if p.kind == "query":
                with self._lock:
                    self._counts["rerouted"] += 1
                self._dispatch(p.fut, exclude={handle.wid})
            else:
                p.error = FleetError(f"worker {handle.wid} {cause}")
                p.event.set()

    # ------------------------------------------------------------------
    # admission + routing
    # ------------------------------------------------------------------

    def route(self, source: int, app: str = "sssp") -> str:
        """The ring OWNER of a query's (app, graph, Q-slot) key — where
        it lands when nothing is saturated (deterministic; tests replay
        this across processes)."""
        with self._lock:
            if self._graph_id is None:
                raise NoWorkersError("no workers registered")
            return self._ring.route(
                route_key(app, self._graph_id, source, self.slots))

    def _candidates(self, app: str, source: int,
                    exclude: Set[str]) -> List[_WorkerHandle]:
        with self._lock:
            if self._graph_id is None:
                return []
            try:
                order = self._ring.successors(
                    route_key(app, self._graph_id, source, self.slots),
                    len(self._ring))
            except EmptyRingError:
                return []
            return [self._workers[w] for w in order
                    if w not in exclude and self._workers[w].alive]

    def _retry_after_ms(self) -> float:
        hints = []
        with self._lock:
            for h in self._workers.values():
                if h.alive and h.last_hb:
                    hints.append(float(h.last_hb.get("queue_depth", 0)))
        # no service-time estimate fleet-wide: one coalescing window per
        # queued-batch of backlog is the same shape the scheduler uses
        return 10.0 * (1.0 + max(hints, default=0.0) / 8.0)

    def submit(self, source: int, app: str = "sssp",
               timeout_ms: Optional[float] = None,
               min_generation: Optional[int] = None) -> FleetFuture:
        """Route + dispatch one query; returns a FleetFuture.  Raises
        FleetRejectedError synchronously when the whole fleet is
        saturated (admission backpressure), NoWorkersError when empty,
        StaleReadError when ``min_generation`` (the read-your-writes
        bound: only replicas that have applied that mutation generation
        may answer) is ahead of every live replica."""
        fut = FleetFuture(app, source, timeout_ms,
                          min_generation=min_generation)
        with self._lock:
            self._counts["submitted"] += 1
        self._dispatch(fut, exclude=set(), sync_raise=True)
        return fut

    def _dispatch(self, fut: FleetFuture, exclude: Set[str],
                  sync_raise: bool = False) -> None:
        """Send ``fut`` to the first usable candidate on its ring walk.
        Resolution failures surface as exceptions only on the synchronous
        admission path; retries resolve the future instead."""
        from lux_tpu import obs

        exclude = set(exclude)
        while True:
            cands = self._candidates(fut.app, fut.source, exclude)
            fresh = cands if fut.min_generation is None else [
                h for h in cands if h.delta_gen >= fut.min_generation]
            usable = [h for h in fresh if not h.saturated]
            if not usable:
                if cands and not fresh:
                    # replicas exist but none has caught up to the read
                    # bound: a staleness miss, not load or absence
                    err = StaleReadError(
                        fut.min_generation,
                        max(h.delta_gen for h in cands))
                elif fresh:  # alive + fresh but saturated: fleet shed
                    with self._lock:
                        self._counts["shed"] += 1
                    err = FleetRejectedError(self._retry_after_ms())
                    obs.point("fleet.shed", app=fut.app, source=fut.source)
                else:
                    err = NoWorkersError(
                        "no live worker can take this query")
                if sync_raise:
                    raise err
                fut._resolve(error=err)
                return
            handle = usable[0]
            if fut.attempts > self.retries:
                fut._resolve(error=FleetError(
                    f"retries exhausted after {fut.attempts} attempts"))
                return
            fut.attempts += 1
            msg = {"op": "query", "app": fut.app, "source": fut.source}
            if fut.timeout_ms:
                msg["timeout_ms"] = float(fut.timeout_ms)
            try:
                self._send(handle, msg, _Pending("query", fut))
                return
            except _HandedOff:
                return  # _retire owns this future now; it re-dispatched
            except ConnectionClosed:
                exclude.add(handle.wid)  # this future never left _send's
                continue                 # hands; keep walking the ring

    def _resolve_query(self, handle: _WorkerHandle, p: _Pending,
                       msg: dict, arr) -> None:
        fut = p.fut
        if msg.get("ok"):
            fut.worker_id = handle.wid
            fut.rounds = int(msg.get("rounds", 0))
            fut.traversed = int(msg.get("traversed", 0))
            gen = msg.get("generation")
            fut.generation = None if gen is None else int(gen)
            with self._lock:
                self._counts["completed"] += 1
            fut._resolve(result=arr)
            return
        kind = msg.get("kind")
        if kind == "shed":
            # the between-heartbeats race: this worker's queue filled
            # before its saturation was visible — believe it immediately
            # and walk the ring before any caller sees an error
            with self._lock:
                handle.saturated = True
                self._counts["rerouted"] += 1
            self._dispatch(fut, exclude={handle.wid})
            return
        with self._lock:
            self._counts["errors"] += 1
        if kind == "timeout":
            fut._resolve(error=FleetTimeoutError(str(msg.get("err"))))
        else:
            fut._resolve(error=FleetError(
                f"worker {handle.wid}: {msg.get('err')}"))

    # ------------------------------------------------------------------
    # heartbeats
    # ------------------------------------------------------------------

    def _ensure_heartbeat(self) -> None:
        with self._lock:
            if self._hb_thread is not None or self._closed:
                return
            self._hb_thread = threading.Thread(
                target=self._hb_loop, name="lux-fleet-ctl-hb", daemon=True)
            self._hb_thread.start()

    def _hb_loop(self) -> None:
        from lux_tpu import obs

        while not self._hb_stop.wait(self.hb_interval_s):
            with self._lock:
                handles = [h for h in self._workers.values() if h.alive]
            now = time.monotonic()
            for h in handles:
                with self._lock:
                    stale = now - h.last_seen > self.hb_timeout_s
                if stale:
                    self._retire(h, cause="death")
                    continue
                try:
                    p = self._send(h, {"op": "stats"}, _Pending("rpc"))
                except (ConnectionClosed, _HandedOff):
                    continue  # worker retired under us; next round
                if not p.event.wait(self.hb_timeout_s):
                    continue  # staleness check next round settles it
                if p.error is not None or not p.reply:
                    continue
                hb = p.reply
                was = h.saturated
                sat = (hb.get("queue_depth", 0)
                       >= self.sat_frac * max(hb.get("max_queue", 1), 1))
                with self._lock:
                    h.last_hb = hb
                    h.saturated = sat
                    if "delta_generation" in hb:
                        # monotonic max: a heartbeat raced by a delta
                        # ack must never move the routing bound BACK
                        h.delta_gen = max(h.delta_gen,
                                          int(hb["delta_generation"]))
                if was != sat:
                    obs.point("fleet.saturation", worker=h.wid,
                              saturated=sat,
                              depth=hb.get("queue_depth"))

    # ------------------------------------------------------------------
    # republish
    # ------------------------------------------------------------------

    def republish(self, path: str, graph_id: Optional[str] = None,
                  prepare_timeout_s: float = 600.0,
                  commit_timeout_s: float = 30.0,
                  base_generation: Optional[int] = None) -> dict:
        """Zero-downtime graph republish across the whole fleet.

        Two-phase: (1) every live worker prepares (load + prewarm the new
        snapshot while the old engines keep serving — long, parallel);
        (2) only if EVERY prepare succeeded, every worker commits (an
        atomic cache-pointer swap — instant).  A failed prepare anywhere
        aborts with the old graph still serving everywhere; admission is
        never paused, so no request is ever rejected because of the swap.

        ``base_generation``: for LIVE (mutation-aware) fleets, the
        mutation generation the new snapshot embeds — workers stage a
        fresh LiveReplica on that epoch base alongside the staged cache
        (serve/live); a plain snapshot republish leaves it None.
        """
        from lux_tpu import obs

        gid = graph_id if graph_id is not None else os.path.basename(
            str(path))
        with self._lock:
            handles = [h for h in self._workers.values() if h.alive]
        if not handles:
            raise NoWorkersError("republish with no live workers")
        # the publish token ties each worker's staged cache to THIS
        # republish: a stale prepare from an aborted earlier republish
        # can neither re-stage after our discard nor be committed by us
        token = f"pub-{self._next_rid()}"
        with obs.span("fleet.republish", graph=gid, path=str(path),
                      token=token, workers=[h.wid for h in handles]):
            prep_msg = {"op": "prepare", "path": str(path),
                        "graph_id": gid, "token": token}
            if base_generation is not None:
                prep_msg["base_generation"] = int(base_generation)
            pendings = []
            for h in handles:
                try:
                    pendings.append((h, self._send(
                        h, {**prep_msg}, _Pending("rpc"))))
                except (ConnectionClosed, _HandedOff):
                    self._discard_staged(handles)
                    raise FleetError(
                        f"worker {h.wid} died before prepare") from None
            deadline = time.monotonic() + prepare_timeout_s
            for h, p in pendings:
                err = None
                if not p.event.wait(max(deadline - time.monotonic(),
                                        0.001)):
                    err = "prepare timed out"
                elif p.error is not None or not p.reply.get("ok"):
                    err = f"prepare failed: {p.error or p.reply.get('err')}"
                if err is not None:
                    # abort BEFORE any commit: old graph still serves
                    # everywhere; tell the workers whose prepare DID
                    # succeed to drop the staged cache (a fully-warmed
                    # second engine set must not sit resident forever)
                    self._discard_staged(handles)
                    raise FleetError(
                        f"worker {h.wid} {err}; republish aborted "
                        "(old graph still serving)")
            gens = {}
            commit_failed = []
            for h in handles:
                try:
                    rep = self._rpc(h, {"op": "commit", "token": token},
                                    timeout_s=commit_timeout_s)
                    gens[h.wid] = int(rep["generation"])
                except FleetError as e:
                    commit_failed.append((h, e))
            if not gens:
                # nothing swapped anywhere: clean abort on the old graph
                self._discard_staged(handles)
                raise FleetError(
                    "every commit failed; republish aborted (old graph "
                    f"still serving): {[str(e) for _, e in commit_failed]}")
            # point of no return: at least one replica serves the NEW
            # graph, so the fleet's graph IS gid now.  A worker whose
            # commit failed would keep serving the OLD graph under the
            # new id — mixed generations answer differently for the
            # same query, which is wrong, not degraded — so retire it
            # (its keys move to committed successors).
            for h, e in commit_failed:
                obs.point("fleet.commit_failed", worker=h.wid,
                          err=str(e))
                self._retire(h, cause="commit_failed")
            with self._lock:
                self._graph_id = gid
                self._counts["republishes"] += 1
        return {"graph_id": gid, "generations": gens,
                "retired": sorted(h.wid for h, _ in commit_failed)}

    def _discard_staged(self, handles) -> None:
        """Best-effort ``discard`` to every live worker: an aborted
        republish must not leave prewarmed second engine caches (and a
        second copy of the O(E) graph arrays) resident on the workers
        whose prepare succeeded."""
        for h in handles:
            if not h.alive:
                continue
            try:
                self._rpc(h, {"op": "discard"}, timeout_s=10.0)
            except FleetError:
                continue  # dying worker: its memory goes with it

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["graph_id"] = self._graph_id
            out["workers_alive"] = sum(
                1 for h in self._workers.values() if h.alive)
            out["workers_total"] = len(self._workers)
        return out

    def prom_dump(self) -> str:
        """One merged Prometheus exposition across the fleet: every
        series carries its ``replica`` label (serve/metrics.py), so the
        aggregate stays per-worker attributable.  ``# HELP``/``# TYPE``
        lines are emitted ONCE per metric name — the text format forbids
        repeating them, so a naive concatenation of per-worker dumps
        would not parse for any fleet wider than one worker."""
        texts = []
        with self._lock:
            handles = [h for h in self._workers.values() if h.alive]
        for h in handles:
            try:
                texts.append(self._rpc(h, {"op": "prom"},
                                       timeout_s=10.0)["text"])
            except FleetError:
                continue  # a dying worker's scrape is just absent
        order: List[str] = []          # families in first-appearance order
        meta: Dict[str, List[str]] = {}     # family -> [HELP, TYPE]
        samples: Dict[str, List[str]] = {}  # family -> sample lines
        for text in texts:
            fam = None
            for line in text.splitlines():
                if line.startswith(("# HELP ", "# TYPE ")):
                    fam = line.split(" ", 3)[2]
                    if fam not in meta:
                        order.append(fam)
                        meta[fam] = []
                        samples[fam] = []
                    if len(meta[fam]) < 2:  # HELP+TYPE once per family
                        meta[fam].append(line)
                elif line and fam is not None:
                    samples[fam].append(line)
        out: List[str] = []
        for fam in order:
            out.extend(meta[fam])
            out.extend(samples[fam])
        return "\n".join(out) + ("\n" if out else "")

    def close(self, shutdown_workers: bool = False) -> None:
        self._hb_stop.set()
        with self._lock:
            self._closed = True
            handles = list(self._workers.values())
        for h in handles:
            if shutdown_workers and h.alive:
                try:
                    self._rpc(h, {"op": "shutdown"}, timeout_s=10.0)
                except FleetError:
                    pass
            h.conn.close()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
