"""Fleet controller: admission, routing, placement, republish.

The controller half of the controller/worker split.  It owns:

* **Membership** — a registry of `ReplicaWorker` endpoints and a
  consistent-hash ring (``fleet/hashring.py``) over the live ones.
  Worker death is detected two ways: the connection reset a kill causes,
  and heartbeat staleness for wedged-but-connected workers.  Either way
  the worker leaves the ring (moving only its ~1/R of the key space) and
  its in-flight queries are transparently re-dispatched to ring
  successors.
* **Routing** — every query maps to a ``(app, graph_id, Q-slot)`` key
  (``route_key``); the owner is the first live, unsaturated worker on
  the ring walk from that key.  Slot affinity keeps repeat queries on
  the same replica's warm engines so its Q-bucket batches run full.
* **Backpressure + shedding** — each worker's queue-depth/shed
  heartbeat (``serve/metrics.py`` counters over the ``stats`` op) marks
  it saturated past ``sat_frac`` of its admission bound; saturated
  workers are skipped on the ring walk, and when EVERY live worker is
  saturated the controller sheds at admission with a ``retry_after_ms``
  hint — the fleet-level analog of the scheduler's bounded-queue reject.
  A worker-side shed reply (the race where a queue filled between
  heartbeats) is retried on the next ring successor before any caller
  sees an error: degraded, never wrong.
* **Republish** — ``republish(path)`` is a two-phase barrier: every
  worker ``prepare``s (loads + prewarms the new snapshot NEXT TO the
  serving engines), and only when all preparations succeed does the
  controller send ``commit`` (an atomic cache-pointer swap per worker).
  Admission never pauses, so zero requests are rejected because of the
  swap; a failed prepare on any worker aborts the whole republish with
  the old graph still serving everywhere.

Everything here is stdlib + numpy: the controller process never imports
jax (graph math lives in the workers), so it stays responsive no matter
what the engines are doing.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from lux_tpu import fault
from lux_tpu.obs import dtrace
from lux_tpu.obs.slo import SLOEngine
from lux_tpu.serve.fleet.hashring import (
    DEFAULT_SLOTS,
    DEFAULT_VNODES,
    EmptyRingError,
    HashRing,
    h64,
    route_key,
)
from lux_tpu.serve.fleet.pubproto import publish_token
from lux_tpu.serve.fleet.stream import negotiate_chunk_bytes, stream_file
from lux_tpu.serve.fleet.wire import (
    Conn,
    ConnectionClosed,
    WireError,
    max_frame_bytes,
)
from lux_tpu.utils.backoff import Backoff, retry_call
from lux_tpu.utils.config import env_float

#: admission-policy modes (ISSUE 16) and their prom gauge codes; must
#: match serve/autopilot/policy.MODES (pinned by tests/test_autopilot)
_POLICY_MODE_CODE = {"serve": 0, "queue": 1, "stale_degrade": 2,
                     "shed": 3}


class FleetError(RuntimeError):
    """Fleet-level request failure (no retry succeeded)."""


class WorkerRefusedError(FleetError):
    """A worker refused the handshake for a PERMANENT reason (e.g. the
    split-brain guard: its journal is ahead of this controller's) —
    retrying the same hello cannot succeed, so reconnect loops must
    surface this instead of backing off forever."""

    def __init__(self, kind: str, err: str):
        super().__init__(f"worker refused handshake ({kind}): {err}")
        self.kind = kind


class FleetRejectedError(FleetError):
    """Fleet-wide load shed: every live worker is saturated."""

    def __init__(self, retry_after_ms: float):
        super().__init__(
            f"fleet saturated; retry after {retry_after_ms:.0f} ms")
        self.retry_after_ms = retry_after_ms


class NoWorkersError(FleetError):
    """No live workers registered."""


class FleetTimeoutError(FleetError, TimeoutError):
    """The request's deadline expired (in a worker queue or on the wire)."""


class StaleReadError(FleetError):
    """A ``min_generation`` read bound no live worker currently
    satisfies (replication still in flight, or a recovering worker
    mid-catch-up).  Retry, or read without the bound and accept the
    generation tag the answer carries."""

    def __init__(self, min_generation: int, best: int):
        super().__init__(
            f"no live worker has replicated generation {min_generation} "
            f"yet (freshest replica serves {best}); retry or drop the "
            "min_generation bound")
        self.min_generation = int(min_generation)
        self.best = int(best)


class FleetFuture:
    """Handle to one fleet-routed query."""

    def __init__(self, app: str, source: int,
                 timeout_ms: Optional[float],
                 min_generation: Optional[int] = None,
                 stale_ok: bool = False,
                 request_id: Optional[str] = None):
        self.app = app
        self.source = int(source)
        self.timeout_ms = timeout_ms
        #: read-your-writes bound: only workers whose applied mutation
        #: generation is >= this may answer (None = any replica)
        self.min_generation = min_generation
        #: opt-in bounded-staleness degrade: when NO replica satisfies
        #: min_generation, serve from the freshest one anyway and tag
        #: the answer ``stale`` instead of raising StaleReadError — the
        #: caller inspects ``generation`` (always carried) to see HOW
        #: stale, which is the bound
        self.stale_ok = bool(stale_ok)
        #: True iff the answer's generation is below min_generation —
        #: the explicit degrade tag the stale_ok contract promises
        self.stale = False
        self._degrade_counted = False  # one counter bump per query
        #: idempotent client request id: ONE id across every retry /
        #: re-dispatch of this logical query (reads are idempotent, so
        #: replay is safe; the id keeps flight-recorder timelines and
        #: retry counters attributable to one logical request)
        self.request_id = request_id
        #: distributed trace context (obs/dtrace.py): the ROOT of this
        #: logical request's trace, minted by submit.  Derived from
        #: request_id when one exists, so envelope retries and replays
        #: against a promoted controller stay ONE trace.  None when
        #: tracing is disabled.
        self.tc: Optional[dtrace.TraceContext] = None
        #: mutation generation the ANSWER reflects (None on a
        #: static-snapshot fleet) — always >= min_generation when set
        #: unless ``stale`` is True
        self.generation: Optional[int] = None
        self.worker_id: Optional[str] = None  # who answered
        self.rounds = 0
        self.traversed = 0
        self.attempts = 0
        #: attempts already spent by the retry ENVELOPE on earlier
        #: futures of the same logical request — added to the wire
        #: ``attempt`` number (so replicas can count envelope retries)
        #: without consuming this future's own ring-walk retry budget
        self.attempt_base = 0
        self.t_submit = time.monotonic()
        self.t_done: Optional[float] = None
        #: controller-installed SLO observer, called INSIDE _resolve
        #: before waiters wake — a slo_status() right after result()
        #: must already include this request (done callbacks run after
        #: the event, which would race that read)
        self._slo_hook = None
        self._cb_lock = threading.Lock()
        self._event = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._callbacks: List = []

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        """The resolution error (None while pending or on success) —
        the SLO engine's good/bad split reads this without racing
        ``result()``'s raise."""
        with self._cb_lock:
            return self._error if self._event.is_set() else None

    @property
    def trace_id(self) -> Optional[str]:
        """The distributed trace id this request records under (link
        into a luxstitch timeline); None when tracing is off."""
        return None if self.tc is None else self.tc.trace_id

    def add_done_callback(self, fn) -> None:
        """Call ``fn(self)`` when the future resolves (immediately if it
        already did).  Runs on the resolving thread — keep it O(1); it
        exists so closed-loop clients can track in-flight counts without
        scanning (a scanning client measures itself, not the fleet)."""
        run_now = False
        with self._cb_lock:
            if self._event.is_set():
                run_now = True
            else:
                self._callbacks.append(fn)
        if run_now:
            fn(self)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise FleetTimeoutError("no result within wait timeout")
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def latency_s(self) -> Optional[float]:
        """Client-observed submit-to-resolve wall time (the number the
        saturation bench's percentiles are built from)."""
        return None if self.t_done is None else self.t_done - self.t_submit

    def _resolve(self, result=None, error=None) -> None:
        with self._cb_lock:
            if self._event.is_set():
                return  # first resolution wins — a racing duplicate
                # dispatch must never overwrite a result waiters saw
            self._result = result
            self._error = error
            self.t_done = time.monotonic()
            if self._slo_hook is not None:
                try:
                    # error passed explicitly: the hook runs under
                    # _cb_lock, and the .error property re-takes it
                    self._slo_hook(self, error)
                except Exception:  # noqa: BLE001 — scoring can never
                    pass           # fail a request
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        # the request's ROOT span, emitted retroactively (begin on the
        # submitting thread, end here on whichever thread resolved it
        # — emit_span bypasses the recorder's nesting stack on purpose)
        dtrace.emit_span(
            "fleet.request", self.tc, self.t_submit, self.t_done,
            ok=error is None, app=self.app, source=self.source,
            worker=self.worker_id, attempts=self.attempts,
            stale=self.stale or None,
            kind=None if error is None else type(error).__name__)
        for fn in cbs:
            fn(self)


class _HandedOff(Exception):
    """Internal: a send failed AND _retire had already harvested the
    pending — the future's ownership moved to the retire path, so the
    sender must NOT dispatch it again."""


class _Pending:
    """One outstanding frame awaiting a worker reply."""

    def __init__(self, kind: str, fut: Optional[FleetFuture] = None,
                 tc: Optional[dtrace.TraceContext] = None):
        self.kind = kind  # "query" | "rpc"
        self.fut = fut
        #: this ATTEMPT's trace context (a child of the future's root;
        #: the wire frame carried the same ids) — its span is emitted
        #: when the attempt concludes, on whichever path that happens
        self.tc = tc
        self.reply: Optional[dict] = None
        self.arr: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()
        self.t0 = time.monotonic()  # the abandoned-pending sweep key

    def end_span(self, name: str, ok: bool, **attrs) -> None:
        if self.tc is not None:
            dtrace.emit_span(name, self.tc, self.t0, time.monotonic(),
                             ok=ok, **attrs)


_INCARNATION_LOCK = threading.Lock()
_INCARNATION_SEQ = 0


def _next_incarnation() -> str:
    """Globally-unique controller incarnation tag (pid + a locked
    counter + an os.urandom token): two controllers — same process
    (tests), a restart (failover), a standby on ANOTHER host, or a
    successor that landed on a reused pid — can never mint colliding
    publish tokens.  pid+counter alone only holds within one process
    lifetime; the random token carries the guarantee across hosts and
    pid wraparound (a dead predecessor's staged cache must never
    exact-match a successor's commit)."""
    global _INCARNATION_SEQ
    with _INCARNATION_LOCK:
        _INCARNATION_SEQ += 1
        return (f"c{os.getpid()}x{_INCARNATION_SEQ}"
                f"-{os.urandom(6).hex()}")


class _WorkerHandle:
    def __init__(self, wid: str, conn: Conn, info: dict):
        self.wid = wid
        self.conn = conn
        self.info = info
        self.alive = True
        self.saturated = False
        self.last_hb: dict = {}
        self.last_seen = time.monotonic()
        self.pending: Dict[str, _Pending] = {}
        self.reader: Optional[threading.Thread] = None
        #: highest mutation generation this worker acknowledged as
        #: SERVABLE (delta acks + heartbeats keep it fresh); the
        #: min_generation routing bound filters on it.  0 on a
        #: static-snapshot fleet — min_generation=None ignores it.
        self.delta_gen = 0


class FleetController:
    def __init__(self, hb_interval_s: Optional[float] = None,
                 hb_timeout_s: Optional[float] = None,
                 sat_frac: float = 0.8,
                 retries: int = 3, slots: int = DEFAULT_SLOTS,
                 vnodes: int = DEFAULT_VNODES):
        # ISSUE 16 satellite: the heartbeat cadence and death threshold
        # were hard-coded ctor defaults, so standby election timeouts
        # (which must be a multiple of the death threshold to avoid
        # false promotions) had to GUESS them.  Both are env knobs now,
        # resolved HERE in the constructing thread (LUX-C003: never
        # inside the heartbeat loop), with bounds and errors that name
        # the knob (LUX-P002 contract).
        if hb_interval_s is None:
            hb_interval_s = env_float("LUX_FLEET_HEARTBEAT_S", 0.25,
                                      minimum=0.01, maximum=60.0)
        if hb_timeout_s is None:
            hb_timeout_s = env_float("LUX_FLEET_DEATH_S", 3.0,
                                     minimum=0.05, maximum=600.0)
        self.hb_interval_s = float(hb_interval_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self.sat_frac = float(sat_frac)
        self.retries = int(retries)
        self.slots = int(slots)
        self._lock = threading.Lock()
        self._ring = HashRing(vnodes)
        self._workers: Dict[str, _WorkerHandle] = {}
        self._graph_id: Optional[str] = None
        self._seq = 0
        self._closed = False
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # fleet-level counters (the controller's own observability row)
        self._counts = {"submitted": 0, "completed": 0, "shed": 0,
                        "rerouted": 0, "worker_deaths": 0,
                        "republishes": 0, "errors": 0, "retries": 0,
                        "timeouts": 0, "stale_degraded": 0,
                        "failovers": 0}
        #: per-worker retry/timeout/stale attribution (prom labels)
        self._per_worker: Dict[str, Dict[str, int]] = {}
        #: SLO burn-rate engine (obs/slo.py), installed via set_slos();
        #: fed from the resolve paths, read via slo_status()
        self._slo: Optional[SLOEngine] = None
        #: autopilot state (ISSUE 16): the installed AdmissionPolicy
        #: (serve/autopilot/policy.py — duck-typed: anything with
        #: .decide(status_rows) -> (mode, reason)), the mode it most
        #: recently chose, the pilot action counters the Prometheus
        #: surface exposes, and the SubscriptionHub attach point
        self._policy = None
        self._policy_mode = "serve"
        self._pilot_counts = {"scale_up": 0, "scale_down": 0,
                              "elections": 0, "policy_switches": 0,
                              "sub_pushes": 0, "sub_coalesced": 0}
        self._sub_hub = None
        #: lease listener (ISSUE 19): serve_lease() exposes ping() as a
        #: wire RPC so process-mode standbys can watch this incumbent
        self._lease_listener = None
        self._lease_conns: List[Conn] = []
        #: this controller incarnation's publish-token prefix: a
        #: PROMOTED controller restarts _seq at 0, and its tokens must
        #: never collide with a dead predecessor's still staged on a
        #: worker (the commit token check is exact-match)
        self._incarnation = _next_incarnation()

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    @property
    def graph_id(self) -> Optional[str]:
        with self._lock:
            return self._graph_id

    @property
    def incarnation(self) -> str:
        """This controller incarnation's fencing token: publish tokens
        carry it, takeover traces key on it, and a standby election is
        claimed AGAINST it (one election per dead incarnation)."""
        return self._incarnation

    def ping(self) -> dict:
        """Liveness probe for standby controllers (ISSUE 16): cheap,
        lock-only, and raising once the controller closed or was
        kill()ed — the in-process analog of a missed network heartbeat.
        Standbys probe on a jittered cadence and declare death only
        after the probe has failed for longer than the fleet's own
        worker death threshold (the knobs compose; see
        serve/autopilot/election.py).

        The reply IS a lease grant (ISSUE 19): it names this
        incarnation and the heartbeat/death intervals the prober should
        run at, so a WIRE standby (election.WireIncumbent over
        ``serve_lease``) needs no out-of-band agreement on cadence —
        the lease terms travel with every renewal."""
        with self._lock:
            if self._closed:
                raise FleetError("controller closed")
            return {"incarnation": self._incarnation,
                    "workers_alive": sum(
                        1 for h in self._workers.values() if h.alive),
                    "policy_mode": self._policy_mode,
                    "hb_interval_s": self.hb_interval_s,
                    "lease_s": self.hb_timeout_s}

    def serve_lease(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Expose ``ping()`` as a wire RPC so a standby in ANOTHER
        process can run the fenced election (ISSUE 19): a tiny listener
        answering ``{"op": "ping"|"lease"}`` frames with the lease
        grant.  Dies with the controller — ``kill()``/``close()`` drop
        the listener and every probe connection, which is exactly the
        death signal a wire standby watches for.  Returns the bound
        port (pass ``port=0`` for an ephemeral one)."""
        import socket as _socket

        with self._lock:
            if self._closed:
                raise FleetError("controller closed")
            if self._lease_listener is not None:
                return self._lease_listener.getsockname()[1]
            srv = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            srv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            srv.bind((host, int(port)))
            srv.listen(8)
            self._lease_listener = srv
        t = threading.Thread(target=self._lease_accept_loop, args=(srv,),
                             name="lux-fleet-lease", daemon=True)
        t.start()
        return srv.getsockname()[1]

    def _lease_accept_loop(self, srv) -> None:
        while True:
            try:
                sock, _addr = srv.accept()
            except OSError:
                return  # listener closed: controller death
            conn = Conn(sock, peer="standby", owner="controller-lease")
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._lease_conns.append(conn)
            threading.Thread(
                target=self._lease_conn_loop, args=(conn,),
                name="lux-fleet-lease-conn", daemon=True).start()

    def _lease_conn_loop(self, conn: Conn) -> None:
        while True:
            try:
                msg, _arr = conn.recv()
            except (ConnectionClosed, WireError):
                break
            op = msg.get("op")
            if op not in ("ping", "lease"):
                try:
                    conn.send({"req_id": msg.get("req_id"), "ok": False,
                               "err": f"lease port speaks ping/lease "
                                      f"only, not {op!r}"})
                except ConnectionClosed:
                    break
                continue
            try:
                pong = self.ping()
            except FleetError:
                # closed: drop the connection — silence IS the death
                # signal; an "I'm dead" reply would be a live answer
                break
            try:
                conn.send({"req_id": msg.get("req_id"), "ok": True,
                           **pong})
            except ConnectionClosed:
                break
        conn.close()

    def _close_lease(self) -> None:
        import socket as _socket

        with self._lock:
            srv, self._lease_listener = self._lease_listener, None
            conns, self._lease_conns = self._lease_conns, []
        if srv is not None:
            # shutdown() first: close() alone does not wake the lease
            # accept thread parked in accept() on Linux — it would sit
            # on the dead fd forever, one leaked thread per controller
            # lifetime (LUX-R002, the PR 16 stall shape)
            try:
                srv.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass  # never connected / already down
            try:
                srv.close()
            except OSError:
                pass
        for c in conns:
            c.close()

    def add_worker(self, host: str, port: int,
                   timeout_s: float = 60.0,
                   tc: Optional[dtrace.TraceContext] = None) -> str:
        """Connect + handshake a worker and put it on the ring.  The
        first worker pins the fleet's graph_id; later joins must serve
        the same graph (a mismatched replica would answer WRONG, which
        is worse than answering slow).  ``tc``: the trace context of
        the operation driving this join (a takeover's re-hello sweep),
        carried on the hello frame so the worker's hello span links
        causally."""
        from lux_tpu import obs

        conn = Conn.connect(host, port, timeout_s=timeout_s,
                            owner="controller")
        handle = _WorkerHandle("?", conn, {})
        handle.reader = threading.Thread(
            target=self._read_loop, args=(handle,),
            name="lux-fleet-ctl-read", daemon=True)
        handle.reader.start()
        hello = {"op": "hello", **self._hello_info()}
        htc = tc.child() if tc is not None else None
        t_hello = time.monotonic()
        if htc is not None:
            hello["tc"] = htc.to_wire()
        p = self._send(handle, hello, _Pending("rpc"))
        if not p.event.wait(timeout_s) or p.error or not p.reply:
            conn.close()
            dtrace.emit_span("fleet.hello", htc, t_hello,
                             time.monotonic(), ok=False,
                             peer=f"{host}:{port}")
            raise FleetError(f"worker at {host}:{port} failed handshake: "
                             f"{p.error}")
        if not p.reply.get("ok", True):
            # the worker REFUSED (split-brain guard and friends):
            # permanent — surfaced as its own type so reconnect/
            # failover loops stop instead of backing off forever
            conn.close()
            dtrace.emit_span("fleet.hello", htc, t_hello,
                             time.monotonic(), ok=False,
                             peer=f"{host}:{port}",
                             kind=str(p.reply.get("kind")))
            raise WorkerRefusedError(str(p.reply.get("kind")),
                                     str(p.reply.get("err")))
        info = p.reply
        wid = str(info["worker_id"])
        w_bound = info.get("max_frame_bytes")
        if w_bound is not None and int(w_bound) != max_frame_bytes():
            # the other direction of the handshake guard: a worker
            # framing to a different bound would drop OUR oversized
            # frames mid-protocol instead of erroring
            conn.close()
            raise FleetError(
                f"worker {wid} at {host}:{port} frames at most "
                f"{int(w_bound)} payload bytes but this controller "
                f"frames {max_frame_bytes()} — set "
                "LUX_FLEET_MAX_FRAME_MB identically in both "
                "environments")
        conn.label(peer=wid)
        with self._lock:
            if self._closed:
                conn.close()
                raise FleetError("controller closed")
            if wid in self._workers and self._workers[wid].alive:
                conn.close()
                raise FleetError(f"worker id {wid!r} already registered")
            if self._graph_id is None:
                self._graph_id = str(info["graph_id"])
            elif str(info["graph_id"]) != self._graph_id:
                conn.close()
                raise FleetError(
                    f"worker {wid} serves graph {info['graph_id']!r}, "
                    f"fleet serves {self._graph_id!r}")
            handle.wid = wid
            handle.info = info
            handle.last_seen = time.monotonic()
            self._workers[wid] = handle
            self._ring.add(wid)
        dtrace.emit_span("fleet.hello", htc, t_hello, time.monotonic(),
                         ok=True, worker=wid)
        obs.point("fleet.worker.join", worker=wid,
                  graph=str(info["graph_id"]), nv=info.get("nv"))
        self._ensure_heartbeat()
        return wid

    def _hello_info(self) -> dict:
        """Extra hello fields the worker validates us against: our
        payload frame bound (the worker refuses a mismatch — a frame
        one peer sends and the other refuses to receive is a dropped
        connection, not an error reply) and, for the live subclass, the
        journal generation the split-brain guard compares."""
        return {"max_frame_bytes": max_frame_bytes()}

    def remove_worker(self, wid: str, shutdown: bool = True) -> None:
        """Graceful leave: take the worker off the ring (its keys move to
        ring successors), optionally ask it to drain and exit."""
        with self._lock:
            handle = self._workers.get(wid)
            if handle is None or not handle.alive:
                return
        if shutdown:
            try:
                self._rpc(handle, {"op": "shutdown"}, timeout_s=10.0)
            except FleetError:
                pass  # it may already be gone; the goal is absence
        self._retire(handle, cause="leave")

    def takeover(self, endpoints: Sequence[Tuple[str, int]],
                 deadline_s: float = 30.0, seed: int = 0) -> dict:
        """Failover promotion (ISSUE 14): rebuild the ring on THIS
        (fresh/standby) controller from worker re-hellos.  Per
        endpoint, ``add_worker`` is retried with jittered exponential
        backoff until ``deadline_s`` — a worker mid-GC or mid-batch
        answers late, not never — EXCEPT a WorkerRefusedError
        (split-brain guard), which is permanent and recorded, not
        retried.  After the joins, every worker gets a ``discard``: a
        dead predecessor's aborted republish must not leave staged
        caches armed under tokens only it knew (this re-arms the
        publish-token state from zero).

        Returns {joined: [wid...], failed: {host:port: err}, refused:
        {host:port: err}}.  Subclasses recover their own state BEFORE
        calling this (the live controller replays its journal in
        __init__ and re-syncs behind workers inside add_worker)."""
        from lux_tpu import obs

        joined: List[str] = []
        failed: Dict[str, str] = {}
        refused: Dict[str, str] = {}
        # the takeover trace: keyed on THIS incarnation, so the
        # re-hello spans every worker records parent into one
        # timeline entry next to the write trace the failover
        # interrupted (the kill-mid-write drill's stitched view)
        ttc = dtrace.mint(key=f"takeover:{self._incarnation}")
        with dtrace.tspan("fleet.takeover", ttc, always=True,
                          endpoints=[f"{h}:{p}" for h, p in endpoints]):
            for i, (host, port) in enumerate(endpoints):
                bo = Backoff(seed=seed + i)
                deadline = time.monotonic() + float(deadline_s)
                while True:
                    try:
                        joined.append(self.add_worker(host, port,
                                                      timeout_s=10.0,
                                                      tc=ttc))
                        break
                    except WorkerRefusedError as e:
                        refused[f"{host}:{port}"] = str(e)
                        break
                    except (FleetError, OSError) as e:
                        if time.monotonic() >= deadline:
                            failed[f"{host}:{port}"] = str(e)
                            break
                        bo.sleep()
            with self._lock:
                handles = [h for h in self._workers.values() if h.alive]
                self._counts["failovers"] += 1
            self._discard_staged(handles)
        obs.point("fleet.takeover.done", joined=joined,
                  failed=sorted(failed), refused=sorted(refused))
        return {"joined": joined, "failed": failed, "refused": refused}

    def kill(self) -> None:
        """Fault drill: the controller VANISHES — every worker
        connection drops with no shutdown, no drain, no goodbye (the
        peer-visible shape of a controller SIGKILL; workers keep
        serving and wait to be re-helloed by a successor).  In-process
        waiters differ from a real crash in one deliberate way: their
        futures resolve with a 'controller closed' error instead of
        dying with the process, so drill clients unblock and exercise
        their retry envelopes."""
        from lux_tpu import obs

        obs.point("fleet.controller.kill")
        self._hb_stop.set()
        with self._lock:
            self._closed = True
            handles = list(self._workers.values())
        for h in handles:
            h.conn.close()
        self._close_lease()

    def workers(self) -> Dict[str, dict]:
        with self._lock:
            return {
                wid: {"alive": h.alive, "saturated": h.saturated,
                      "last_hb": dict(h.last_hb)}
                for wid, h in self._workers.items()
            }

    def live_workers(self) -> List[str]:
        with self._lock:
            return sorted(w for w, h in self._workers.items() if h.alive)

    # ------------------------------------------------------------------
    # wire plumbing
    # ------------------------------------------------------------------

    def _next_rid(self) -> str:
        with self._lock:
            self._seq += 1
            return f"r{self._seq}"

    def _send(self, handle: _WorkerHandle, msg: dict,
              pending: _Pending) -> _Pending:
        rid = self._next_rid()
        msg = {**msg, "req_id": rid}
        with self._lock:
            handle.pending[rid] = pending
        try:
            handle.conn.send(msg)
        except ConnectionClosed:
            with self._lock:
                still_mine = handle.pending.pop(rid, None) is not None
            if not still_mine:
                # the reader's _retire beat us to it: it already
                # harvested this pending as an orphan and re-dispatched
                # (query) or failed (rpc) it — dispatching again from
                # here would put the SAME future in flight twice
                raise _HandedOff() from None
            self._on_conn_lost(handle)
            raise
        return pending

    def _rpc(self, handle: _WorkerHandle, msg: dict,
             timeout_s: float) -> dict:
        try:
            p = self._send(handle, msg, _Pending("rpc"))
        except (ConnectionClosed, _HandedOff):
            raise FleetError(f"worker {handle.wid} unreachable") from None
        if not p.event.wait(timeout_s):
            raise FleetError(
                f"worker {handle.wid} did not answer {msg.get('op')!r} "
                f"within {timeout_s}s")
        if p.error is not None:
            raise FleetError(str(p.error))
        if not p.reply.get("ok"):
            raise FleetError(
                f"worker {handle.wid} {msg.get('op')}: "
                f"{p.reply.get('kind')}: {p.reply.get('err')}")
        return p.reply

    def _count_worker(self, wid: str, key: str, n: int = 1) -> None:
        """Per-worker counter bump (prom label attribution); caller
        must NOT hold self._lock."""
        with self._lock:
            d = self._per_worker.setdefault(
                wid, {"retries": 0, "timeouts": 0, "stale_served": 0})
            d[key] = d.get(key, 0) + n

    def _read_loop(self, handle: _WorkerHandle) -> None:
        while True:
            try:
                msg, arr = handle.conn.recv()
            except (ConnectionClosed, WireError, fault.InjectedKill):
                break
            rid = msg.get("req_id")
            with self._lock:
                p = handle.pending.pop(rid, None)
                handle.last_seen = time.monotonic()
            if p is None:
                continue  # late reply for a retried/abandoned request
            if p.kind == "query":
                self._resolve_query(handle, p, msg, arr)
            else:
                p.reply = msg
                p.arr = arr
                p.event.set()
        self._on_conn_lost(handle)

    def _on_conn_lost(self, handle: _WorkerHandle) -> None:
        if handle.wid == "?":  # handshake never completed
            return
        self._retire(handle, cause="death")

    def _retire(self, handle: _WorkerHandle, cause: str) -> None:
        """Take a worker out of service; re-dispatch its in-flight
        queries on the survivors and fail its in-flight rpcs."""
        from lux_tpu import obs

        with self._lock:
            if not handle.alive:
                return
            if self._closed:
                # controller teardown closes every conn; the readers'
                # resulting ConnectionClosed is shutdown, not death —
                # a clean close must not mint worker_deaths or spray
                # fleet.worker.down events into the flight recorder.
                # In-flight work still RESOLVES (a dropped future hangs
                # its waiter forever; an error is strictly better)
                handle.alive = False
                leftovers = list(handle.pending.values())
                handle.pending.clear()
            else:
                leftovers = None
        if leftovers is not None:
            closed_err = FleetError("controller closed")
            for p in leftovers:
                if p.kind == "query":
                    p.fut._resolve(error=closed_err)
                else:
                    p.error = closed_err
                    p.event.set()
            return
        with self._lock:
            if not handle.alive:
                return
            handle.alive = False
            if handle.wid in self._ring.workers():
                self._ring.remove(handle.wid)
            orphans = list(handle.pending.values())
            handle.pending.clear()
            if cause == "death":
                self._counts["worker_deaths"] += 1
        obs.point("fleet.worker.down", worker=handle.wid, cause=cause,
                  orphans=len(orphans))
        handle.conn.close()
        for p in orphans:
            p.end_span("fleet.attempt", ok=False, worker=handle.wid,
                       kind=f"worker_{cause}")
            if p.kind == "query":
                with self._lock:
                    self._counts["rerouted"] += 1
                    self._counts["retries"] += 1
                self._count_worker(handle.wid, "retries")
                self._dispatch(p.fut, exclude={handle.wid})
            else:
                p.error = FleetError(f"worker {handle.wid} {cause}")
                p.event.set()

    # ------------------------------------------------------------------
    # admission + routing
    # ------------------------------------------------------------------

    def route(self, source: int, app: str = "sssp") -> str:
        """The ring OWNER of a query's (app, graph, Q-slot) key — where
        it lands when nothing is saturated (deterministic; tests replay
        this across processes)."""
        with self._lock:
            if self._graph_id is None:
                raise NoWorkersError("no workers registered")
            return self._ring.route(
                route_key(app, self._graph_id, source, self.slots))

    def _candidates(self, app: str, source: int,
                    exclude: Set[str]) -> List[_WorkerHandle]:
        with self._lock:
            if self._graph_id is None:
                return []
            try:
                order = self._ring.successors(
                    route_key(app, self._graph_id, source, self.slots),
                    len(self._ring))
            except EmptyRingError:
                return []
            return [self._workers[w] for w in order
                    if w not in exclude and self._workers[w].alive]

    def _retry_after_ms(self) -> float:
        hints = []
        with self._lock:
            for h in self._workers.values():
                if h.alive and h.last_hb:
                    hints.append(float(h.last_hb.get("queue_depth", 0)))
        # no service-time estimate fleet-wide: one coalescing window per
        # queued-batch of backlog is the same shape the scheduler uses
        return 10.0 * (1.0 + max(hints, default=0.0) / 8.0)

    def submit(self, source: int, app: str = "sssp",
               timeout_ms: Optional[float] = None,
               min_generation: Optional[int] = None,
               stale_ok: bool = False,
               request_id: Optional[str] = None,
               attempt_offset: int = 0) -> FleetFuture:
        """Route + dispatch one query; returns a FleetFuture.  Raises
        FleetRejectedError synchronously when the whole fleet is
        saturated (admission backpressure), NoWorkersError when empty,
        StaleReadError when ``min_generation`` (the read-your-writes
        bound: only replicas that have applied that mutation generation
        may answer) is ahead of every live replica — unless
        ``stale_ok``, which DEGRADES that case instead: the freshest
        live replica answers, and the future comes back with
        ``stale=True`` plus the generation it actually served (the
        explicit bounded-staleness tag)."""
        fut = FleetFuture(app, source, timeout_ms,
                          min_generation=min_generation,
                          stale_ok=stale_ok, request_id=request_id)
        fut.attempt_base = int(attempt_offset)
        # the trace root: keyed on the request id when one exists, so
        # every envelope retry (and a replay against a PROMOTED
        # controller) lands in the same trace (obs/dtrace.py)
        fut.tc = dtrace.mint(
            key=None if request_id is None else f"q:{request_id}")
        with self._lock:
            if self._slo is not None:
                fut._slo_hook = self._slo_observe
        with self._lock:
            self._counts["submitted"] += 1
        try:
            self._dispatch(fut, exclude=set(), sync_raise=True)
        except FleetError as e:
            # synchronous admission failures (shed / staleness miss /
            # empty fleet) still close the trace root and score the
            # SLO — resolving the future is harmless (the caller gets
            # the raise and drops it) and keeps the availability
            # numbers honest about sheds
            fut._resolve(error=e)
            raise
        return fut

    def submit_retrying(self, source: int, app: str = "sssp",
                        deadline_s: float = 30.0,
                        attempt_timeout_s: float = 5.0,
                        timeout_ms: Optional[float] = None,
                        min_generation: Optional[int] = None,
                        stale_ok: bool = False,
                        request_id: Optional[str] = None,
                        backoff: Optional[Backoff] = None) -> FleetFuture:
        """The hardened client envelope (ISSUE 14): submit + wait with
        a CLIENT deadline, retrying fleet sheds (honoring their
        ``retry_after_ms`` hint, jitter on top), staleness misses,
        worker timeouts and transient fleet errors until ``deadline_s``
        of wall time is spent — then the LAST error raises.

        Retried: sheds, staleness misses, timeouts, and empty-fleet
        windows (a failover in progress).  NOT retried: plain
        FleetError — a worker-reported op error ("app not served",
        an engine exception), retries-exhausted, or a closed
        controller is the same answer every time, and burning the
        whole client deadline re-asking would just delay it.

        ``attempt_timeout_s`` bounds each TRY separately from the
        overall deadline: a request frame lost on the wire (or a
        worker that died holding it) resolves nothing, and waiting the
        whole client deadline on one dead attempt would turn every
        lost frame into a full-deadline stall — the classic
        per-request-timeout vs end-to-end-deadline split.  One
        ``request_id`` spans every attempt (minted from the submit
        counter when not given), so retries stay one logical request
        in the flight recorder and the retry counters; queries are
        idempotent reads, so replay is safe.  Returns the RESOLVED
        future (``result()`` cannot block or raise)."""
        if request_id is None:
            request_id = f"q{self._next_rid()[1:]}"
        # jitter seeded per LOGICAL REQUEST (the unique request id), not
        # per source: N clients retrying the same source must draw
        # DIFFERENT delay sequences, or a fleet-wide shed wakes them in
        # lockstep every round — the herd full jitter exists to prevent
        bo = backoff if backoff is not None else Backoff(
            seed=h64(f"{request_id}/{source}"))
        deadline = time.monotonic() + float(deadline_s)
        state = {"attempts": 0, "last": None}

        def on_retry(exc, n):
            state["attempts"] = n
            state["last"] = exc
            with self._lock:
                self._counts["retries"] += 1

        def attempt() -> FleetFuture:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # our deadline and retry_call's are computed from two
                # monotonic() reads microseconds apart — at expiry,
                # re-raise the LAST REAL error (the documented
                # contract) rather than minting a synthetic timeout
                # that would mask it; retry_call's own expired
                # deadline then re-raises it unchanged
                last = state["last"]
                if last is not None:
                    raise last
                raise FleetTimeoutError(
                    f"client deadline of {deadline_s}s spent "
                    f"(request {request_id})")
            fut = self.submit(source, app=app, timeout_ms=timeout_ms,
                              min_generation=min_generation,
                              stale_ok=stale_ok, request_id=request_id,
                              attempt_offset=state["attempts"])
            # raises the worker/fleet error; an unresolved future past
            # the attempt timeout raises FleetTimeoutError -> retried
            fut.result(timeout=min(remaining, float(attempt_timeout_s)))
            return fut

        out = retry_call(
            attempt,
            retry_on=(FleetRejectedError, StaleReadError,
                      FleetTimeoutError, NoWorkersError),
            deadline_s=deadline_s, backoff=bo, on_retry=on_retry)
        out.attempts += state["attempts"]  # envelope attempts included
        return out

    def _dispatch(self, fut: FleetFuture, exclude: Set[str],
                  sync_raise: bool = False) -> None:
        """Send ``fut`` to the first usable candidate on its ring walk.
        Resolution failures surface as exceptions only on the synchronous
        admission path; retries resolve the future instead."""
        from lux_tpu import obs

        with self._lock:
            mode = self._policy_mode
        if mode == "shed":
            # the AdmissionPolicy chose shed (ISSUE 16): reject at
            # admission before any routing work, exactly like the
            # all-saturated shed — degraded by POLICY, never wrong
            with self._lock:
                self._counts["shed"] += 1
            obs.point("fleet.shed", app=fut.app, source=fut.source,
                      policy="shed")
            err = FleetRejectedError(self._retry_after_ms())
            if sync_raise:
                raise err
            fut._resolve(error=err)
            return
        # stale_degrade mode widens EVERY bounded read to the stale_ok
        # contract (freshest replica + explicit stale tag) without the
        # caller opting in — the policy's answer to a burning
        # freshness/latency SLO is "serve stale rather than error"
        stale_ok = fut.stale_ok or (mode == "stale_degrade"
                                    and fut.min_generation is not None)
        exclude = set(exclude)
        while True:
            degraded = False
            cands = self._candidates(fut.app, fut.source, exclude)
            fresh = cands if fut.min_generation is None else [
                h for h in cands if h.delta_gen >= fut.min_generation]
            if cands and not fresh and stale_ok:
                degraded = True
                # bounded-staleness degrade (opt-in): no replica meets
                # the bound, so the FRESHEST one answers and the future
                # carries stale=True + the served generation — an
                # explicitly tagged stale read instead of an error
                fresh = sorted(cands, key=lambda h: -h.delta_gen)
                if not fut._degrade_counted:
                    # once per LOGICAL query: the ring walk can loop
                    # (dead candidate) and re-dispatch can re-enter —
                    # neither is a second degrade decision.  This event
                    # records the DECISION; the stale_degraded COUNTER
                    # bumps at resolve time from the answer's actual
                    # tag (a replica that catches up mid-flight serves
                    # fresh — the counter must not claim otherwise)
                    fut._degrade_counted = True
                    obs.point("fleet.stale_degrade", app=fut.app,
                              source=fut.source,
                              want=fut.min_generation,
                              best=fresh[0].delta_gen)
            usable = [h for h in fresh if not h.saturated]
            if not usable and fresh and mode == "queue":
                # queue mode (ISSUE 16): admit past the saturation skip
                # and let the workers' own bounded queues absorb the
                # burst — the policy prefers queueing delay over sheds
                # while the SLO is only warning, and the worker-side
                # admission bound still backstops it
                usable = fresh
            if not usable:
                if cands and not fresh:
                    # replicas exist but none has caught up to the read
                    # bound: a staleness miss, not load or absence
                    err = StaleReadError(
                        fut.min_generation,
                        max(h.delta_gen for h in cands))
                elif fresh:  # alive + fresh but saturated: fleet shed
                    with self._lock:
                        self._counts["shed"] += 1
                    err = FleetRejectedError(self._retry_after_ms())
                    obs.point("fleet.shed", app=fut.app, source=fut.source)
                else:
                    err = NoWorkersError(
                        "no live worker can take this query")
                if sync_raise:
                    raise err
                fut._resolve(error=err)
                return
            handle = usable[0]
            if fut.attempts > self.retries:
                fut._resolve(error=FleetError(
                    f"retries exhausted after {fut.attempts} attempts"))
                return
            fut.attempts += 1
            msg = {"op": "query", "app": fut.app, "source": fut.source,
                   "attempt": fut.attempt_base + fut.attempts}
            atc = None
            if fut.tc is not None:
                # one child context per ATTEMPT: the frame carries it,
                # the worker's span parents on it, and its span is
                # emitted when the attempt concludes — so a retried
                # request shows every attempt as a sibling under the
                # one fleet.request root
                atc = fut.tc.child()
                msg["tc"] = atc.to_wire()
            if fut.timeout_ms:
                msg["timeout_ms"] = float(fut.timeout_ms)
            if fut.request_id is not None:
                msg["client_rid"] = str(fut.request_id)
            if degraded:
                # carry the read bound itself, not a pre-judged hint:
                # the replica counts a stale SERVE from its answer's
                # ACTUAL generation vs this bound, so a replica that
                # catches up mid-flight serves fresh and counts nothing
                # — per-worker and fleet-level stale counters agree
                msg["stale_bound"] = int(fut.min_generation)
            try:
                self._send(handle, msg,
                           _Pending("query", fut, tc=atc))
                return
            except _HandedOff:
                return  # _retire owns this future now; it re-dispatched
            except ConnectionClosed:
                exclude.add(handle.wid)  # this future never left _send's
                continue                 # hands; keep walking the ring

    def _resolve_query(self, handle: _WorkerHandle, p: _Pending,
                       msg: dict, arr) -> None:
        fut = p.fut
        p.end_span("fleet.attempt", ok=bool(msg.get("ok")),
                   worker=handle.wid,
                   kind=None if msg.get("ok") else msg.get("kind"))
        if msg.get("ok"):
            fut.worker_id = handle.wid
            fut.rounds = int(msg.get("rounds", 0))
            fut.traversed = int(msg.get("traversed", 0))
            gen = msg.get("generation")
            fut.generation = None if gen is None else int(gen)
            if (fut.min_generation is not None
                    and fut.generation is not None
                    and fut.generation < fut.min_generation):
                # the stale_ok degrade actually happened: tag it and
                # count it HERE, from the answer's real generation —
                # the authoritative "stale reads served" number
                fut.stale = True
                with self._lock:
                    self._counts["stale_degraded"] += 1
                self._count_worker(handle.wid, "stale_served")
            with self._lock:
                self._counts["completed"] += 1
            fut._resolve(result=arr)
            return
        kind = msg.get("kind")
        if kind == "shed":
            # the between-heartbeats race: this worker's queue filled
            # before its saturation was visible — believe it immediately
            # and walk the ring before any caller sees an error
            with self._lock:
                handle.saturated = True
                self._counts["rerouted"] += 1
                self._counts["retries"] += 1
            self._count_worker(handle.wid, "retries")
            self._dispatch(fut, exclude={handle.wid})
            return
        with self._lock:
            self._counts["errors"] += 1
        if kind == "timeout":
            with self._lock:
                self._counts["timeouts"] += 1
            self._count_worker(handle.wid, "timeouts")
            fut._resolve(error=FleetTimeoutError(str(msg.get("err"))))
        else:
            fut._resolve(error=FleetError(
                f"worker {handle.wid}: {msg.get('err')}"))

    # ------------------------------------------------------------------
    # heartbeats
    # ------------------------------------------------------------------

    def _ensure_heartbeat(self) -> None:
        with self._lock:
            if self._hb_thread is not None or self._closed:
                return
            self._hb_thread = threading.Thread(
                target=self._hb_loop, name="lux-fleet-ctl-hb", daemon=True)
            self._hb_thread.start()

    #: a pending older than this is presumed unanswerable (a frame lost
    #: on the wire never gets a reply; the envelope abandoned its future
    #: long ago) — swept by the heartbeat loop so handle.pending cannot
    #: grow for the lifetime of a connection under a lossy-wire fault
    #: plan.  This is also a HARD CAP on unbounded queries: a swept
    #: future resolves with FleetTimeoutError (first resolution wins),
    #: so a genuine answer arriving later is dropped as a late reply.
    #: Generous on purpose — an engine run that legitimately needs
    #: longer than this should carry its own timeout_ms budget.
    PENDING_SWEEP_S = 600.0

    def _sweep_stale_pending(self, handle: _WorkerHandle,
                             now: float) -> None:
        with self._lock:
            stale = [rid for rid, p in handle.pending.items()
                     if now - p.t0 > self.PENDING_SWEEP_S]
            dead = [handle.pending.pop(rid) for rid in stale]
        for p in dead:
            err = FleetTimeoutError(
                f"request to worker {handle.wid} unanswered for "
                f"{self.PENDING_SWEEP_S:g}s (frame lost?)")
            p.end_span("fleet.attempt", ok=False, worker=handle.wid,
                       kind="swept")
            if p.kind == "query":
                p.fut._resolve(error=err)
            else:
                p.error = err
                p.event.set()

    def _hb_loop(self) -> None:
        from lux_tpu import obs

        while not self._hb_stop.wait(self.hb_interval_s):
            try:
                # the seeded controller-death drill fires here
                # (fault/drills.controller_kill_at_heartbeat): the
                # rule's callback ran kill() — every worker conn is
                # already down with no goodbye — so this sweep thread
                # just stops; standby detection takes it from there
                fault.ppoint("controller.heartbeat", owner="controller")
            except fault.InjectedKill:
                return
            with self._lock:
                handles = [h for h in self._workers.values() if h.alive]
            now = time.monotonic()
            for h in handles:
                self._sweep_stale_pending(h, now)
            for h in handles:
                with self._lock:
                    stale = now - h.last_seen > self.hb_timeout_s
                if stale:
                    self._retire(h, cause="death")
                    continue
                try:
                    p = self._send(h, {"op": "stats"}, _Pending("rpc"))
                except (ConnectionClosed, _HandedOff):
                    continue  # worker retired under us; next round
                if not p.event.wait(self.hb_timeout_s):
                    continue  # staleness check next round settles it
                if p.error is not None or not p.reply:
                    continue
                hb = p.reply
                was = h.saturated
                sat = (hb.get("queue_depth", 0)
                       >= self.sat_frac * max(hb.get("max_queue", 1), 1))
                with self._lock:
                    h.last_hb = hb
                    h.saturated = sat
                    if "delta_generation" in hb:
                        # monotonic max: a heartbeat raced by a delta
                        # ack must never move the routing bound BACK
                        h.delta_gen = max(h.delta_gen,
                                          int(hb["delta_generation"]))
                if was != sat:
                    obs.point("fleet.saturation", worker=h.wid,
                              saturated=sat,
                              depth=hb.get("queue_depth"))
            # the admission policy rides the heartbeat cadence: one
            # burn-rate evaluation per sweep, mode switches spanned
            self.policy_tick()

    # ------------------------------------------------------------------
    # republish
    # ------------------------------------------------------------------

    def republish(self, path: str, graph_id: Optional[str] = None,
                  prepare_timeout_s: float = 600.0,
                  commit_timeout_s: float = 30.0,
                  base_generation: Optional[int] = None) -> dict:
        """Zero-downtime graph republish across the whole fleet.

        Two-phase: (1) every live worker prepares (load + prewarm the new
        snapshot while the old engines keep serving — long, parallel);
        (2) only if EVERY prepare succeeded, every worker commits (an
        atomic cache-pointer swap — instant).  A failed prepare anywhere
        aborts with the old graph still serving everywhere; admission is
        never paused, so no request is ever rejected because of the swap.

        ``base_generation``: for LIVE (mutation-aware) fleets, the
        mutation generation the new snapshot embeds — workers stage a
        fresh LiveReplica on that epoch base alongside the staged cache
        (serve/live); a plain snapshot republish leaves it None.
        """
        from lux_tpu import obs

        gid = graph_id if graph_id is not None else os.path.basename(
            str(path))
        with self._lock:
            handles = [h for h in self._workers.values() if h.alive]
        if not handles:
            raise NoWorkersError("republish with no live workers")
        # the publish token ties each worker's staged cache to THIS
        # republish: a stale prepare from an aborted earlier republish
        # can neither re-stage after our discard nor be committed by us.
        # The incarnation prefix keeps tokens unique across controller
        # RESTARTS — a promoted controller's _seq starts over, and its
        # commit must never match a dead predecessor's staged cache
        token = publish_token(self._incarnation, self._next_rid())
        # the republish trace: two-phase barrier as one timeline —
        # every worker's prepare/commit spans parent into it
        rtc = dtrace.mint(key=f"republish:{token}")
        with dtrace.tspan("fleet.republish", rtc, always=True, graph=gid,
                          path=str(path), token=token,
                          workers=[h.wid for h in handles]):
            # wire distribution (ISSUE 19): the controller reads the
            # snapshot bytes LOCALLY and streams them to each worker
            # over the framed connection, so ``path`` only has to exist
            # HERE — workers reassemble into their own private tmpdirs
            # and no shared filesystem is assumed anywhere.  Chunk size
            # is negotiated down to the smallest frame bound any worker
            # advertised at hello (older workers advertise nothing and
            # are assumed to match — the hello guard enforced it).
            bounds = [h.info.get("max_frame_bytes") for h in handles]
            bounds = [b for b in bounds if b is not None]
            chunk = negotiate_chunk_bytes(
                max_frame_bytes(), min(bounds) if bounds else None)
            meta = None
            for h in handles:
                def _begin(m, _h=h):
                    if rtc is not None:
                        m = {**m, "tc": rtc.to_wire()}
                    return self._rpc(_h, m, timeout_s=60.0)

                try:
                    meta = stream_file(h.conn, str(path), token, chunk,
                                       rpc=_begin)
                except (ConnectionClosed, _HandedOff):
                    self._discard_staged(handles)
                    raise FleetError(
                        f"worker {h.wid} died mid snapshot stream; "
                        "republish aborted (old graph still serving)"
                    ) from None
                except (FleetError, OSError) as e:
                    self._discard_staged(handles)
                    raise FleetError(
                        f"snapshot stream to worker {h.wid} failed: {e};"
                        " republish aborted (old graph still serving)"
                    ) from None
            prep_msg = {"op": "prepare", "graph_id": gid, "token": token,
                        "stream": True, "sha256": meta["sha256"]}
            if base_generation is not None:
                prep_msg["base_generation"] = int(base_generation)
            pendings = []
            for h in handles:
                try:
                    msg = {**prep_msg}
                    if rtc is not None:
                        # the republish ROOT rides every frame: worker
                        # prepare/commit spans parent directly under
                        # fleet.republish (one barrier, one timeline)
                        msg["tc"] = rtc.to_wire()
                    pendings.append((h, self._send(
                        h, msg, _Pending("rpc"))))
                except (ConnectionClosed, _HandedOff):
                    self._discard_staged(handles)
                    raise FleetError(
                        f"worker {h.wid} died before prepare") from None
            deadline = time.monotonic() + prepare_timeout_s
            for h, p in pendings:
                err = None
                if not p.event.wait(max(deadline - time.monotonic(),
                                        0.001)):
                    err = "prepare timed out"
                elif p.error is not None or not p.reply.get("ok"):
                    err = f"prepare failed: {p.error or p.reply.get('err')}"
                if err is not None:
                    # abort BEFORE any commit: old graph still serves
                    # everywhere; tell the workers whose prepare DID
                    # succeed to drop the staged cache (a fully-warmed
                    # second engine set must not sit resident forever)
                    self._discard_staged(handles)
                    raise FleetError(
                        f"worker {h.wid} {err}; republish aborted "
                        "(old graph still serving)")
            gens = {}
            commit_failed = []
            for h in handles:
                try:
                    cmsg = {"op": "commit", "token": token}
                    if rtc is not None:
                        cmsg["tc"] = rtc.to_wire()
                    rep = self._rpc(h, cmsg,
                                    timeout_s=commit_timeout_s)
                    gens[h.wid] = int(rep["generation"])
                except FleetError as e:
                    commit_failed.append((h, e))
            if not gens:
                # nothing swapped anywhere: clean abort on the old graph
                self._discard_staged(handles)
                raise FleetError(
                    "every commit failed; republish aborted (old graph "
                    f"still serving): {[str(e) for _, e in commit_failed]}")
            # point of no return: at least one replica serves the NEW
            # graph, so the fleet's graph IS gid now.  A worker whose
            # commit failed would keep serving the OLD graph under the
            # new id — mixed generations answer differently for the
            # same query, which is wrong, not degraded — so retire it
            # (its keys move to committed successors).
            for h, e in commit_failed:
                obs.point("fleet.commit_failed", worker=h.wid,
                          err=str(e))
                self._retire(h, cause="commit_failed")
            with self._lock:
                self._graph_id = gid
                self._counts["republishes"] += 1
        return {"graph_id": gid, "generations": gens,
                "retired": sorted(h.wid for h, _ in commit_failed)}

    def _discard_staged(self, handles) -> None:
        """Best-effort ``discard`` to every live worker: an aborted
        republish must not leave prewarmed second engine caches (and a
        second copy of the O(E) graph arrays) resident on the workers
        whose prepare succeeded."""
        for h in handles:
            if not h.alive:
                continue
            try:
                self._rpc(h, {"op": "discard"}, timeout_s=10.0)
            except FleetError:
                continue  # dying worker: its memory goes with it

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["graph_id"] = self._graph_id
            out["workers_alive"] = sum(
                1 for h in self._workers.values() if h.alive)
            out["workers_total"] = len(self._workers)
            out["pilot"] = dict(self._pilot_counts)
            out["policy_mode"] = self._policy_mode
        return out

    # -- SLOs (obs/slo.py, ISSUE 15) -----------------------------------

    def set_slos(self, specs) -> SLOEngine:
        """Install declarative SLO specs; every resolved query (and,
        on the live controller, every admitted write) feeds the
        burn-rate engine from here on.  Returns the engine."""
        engine = SLOEngine(specs)
        with self._lock:
            self._slo = engine
        return engine

    def slo_status(self) -> List[dict]:
        """One verdict row per installed spec (empty when none):
        multi-window burn rates, ok/warn/burning verdict, and the
        exemplar trace ids linking a burning SLO to stitched
        timelines."""
        with self._lock:
            engine = self._slo
        return [] if engine is None else engine.status()

    # -- autopilot surface (serve/autopilot, ISSUE 16) -----------------

    def set_policy(self, policy) -> None:
        """Install an AdmissionPolicy (``None`` clears it back to plain
        serving).  The policy is re-evaluated against ``slo_status()``
        every heartbeat sweep (and once right here): its chosen mode
        gates ``_dispatch`` — ``shed`` rejects at admission, ``queue``
        admits past the saturation skip, ``stale_degrade`` serves
        bounded reads from the freshest replica with the explicit stale
        tag.  Mode switches emit a ``pilot.policy.switch`` incident
        span and bump the switch counter."""
        with self._lock:
            self._policy = policy
            if policy is None:
                self._policy_mode = "serve"
        if policy is not None:
            self.policy_tick()

    def policy_mode(self) -> str:
        with self._lock:
            return self._policy_mode

    def policy_tick(self) -> str:
        """One policy evaluation (the heartbeat loop's cadence; tests
        and the demo call it directly).  Returns the current mode."""
        with self._lock:
            policy = self._policy
        if policy is None:
            return "serve"
        mode, reason = policy.decide(self.slo_status())
        with self._lock:
            prev = self._policy_mode
            if mode == prev:
                return mode
            self._policy_mode = mode
            self._pilot_counts["policy_switches"] += 1
            seq = self._pilot_counts["policy_switches"]
        # a mode switch is an autonomous action: keyed incident trace,
        # always-recorded span — luxstitch renders the switch next to
        # the burning SLO windows that caused it
        ptc = dtrace.incident(f"policy:{self._incarnation}:{seq}")
        with dtrace.tspan("pilot.policy.switch", ptc, always=True,
                          prev=prev, mode=mode, reason=reason):
            pass
        return mode

    def _pilot_count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._pilot_counts[key] = self._pilot_counts.get(key, 0) + n

    def rebalance_preview(self, add: Sequence[str] = (),
                          remove: Sequence[str] = (),
                          app: str = "sssp") -> dict:
        """Dry-run a membership change over THIS fleet's routable key
        space (every Q-slot of ``app`` on the pinned graph) — the
        autoscaler's cost gate.  See ``HashRing.rebalance_preview``."""
        with self._lock:
            gid = self._graph_id if self._graph_id is not None else "g"
            keys = [f"{app}|{gid}|q{s}" for s in range(self.slots)]
            return self._ring.rebalance_preview(keys, add=add,
                                                remove=remove)

    def _slo_observe(self, fut: FleetFuture, error) -> None:
        """Resolve-time hook scoring one query: availability from the
        error class, latency from the future's own stamps, staleness
        from the explicit degrade tag — exemplar'd with the request's
        trace id.  Runs inside the future's resolve (before waiters
        wake), so a scrape right after ``result()`` already counts it."""
        with self._lock:
            engine = self._slo
        if engine is None:
            return
        engine.observe_query(fut.latency_s, ok=error is None,
                             stale=fut.stale, trace_id=fut.trace_id)

    def prom_dump(self) -> str:
        """One merged Prometheus exposition across the fleet: every
        series carries its ``replica`` label (serve/metrics.py), so the
        aggregate stays per-worker attributable.  ``# HELP``/``# TYPE``
        lines are emitted ONCE per metric name — the text format forbids
        repeating them, so a naive concatenation of per-worker dumps
        would not parse for any fleet wider than one worker."""
        texts = [self._own_prom_text()]
        with self._lock:
            handles = [h for h in self._workers.values() if h.alive]
        for h in handles:
            try:
                texts.append(self._rpc(h, {"op": "prom"},
                                       timeout_s=10.0)["text"])
            except FleetError:
                continue  # a dying worker's scrape is just absent
        order: List[str] = []          # families in first-appearance order
        meta: Dict[str, List[str]] = {}     # family -> [HELP, TYPE]
        samples: Dict[str, List[str]] = {}  # family -> sample lines
        for text in texts:
            fam = None
            for line in text.splitlines():
                if line.startswith(("# HELP ", "# TYPE ")):
                    fam = line.split(" ", 3)[2]
                    if fam not in meta:
                        order.append(fam)
                        meta[fam] = []
                        samples[fam] = []
                    if len(meta[fam]) < 2:  # HELP+TYPE once per family
                        meta[fam].append(line)
                elif line and fam is not None:
                    samples[fam].append(line)
        out: List[str] = []
        for fam in order:
            out.extend(meta[fam])
            out.extend(samples[fam])
        return "\n".join(out) + ("\n" if out else "")

    def _own_prom_text(self) -> str:
        """The controller's OWN exposition families (ISSUE 14):
        fleet-level counters, per-worker retry/timeout/stale
        attribution, and the installed fault plan's injection counts —
        merged ahead of the worker scrapes by prom_dump."""
        with self._lock:
            counts = dict(self._counts)
            per_worker = {w: dict(d) for w, d in self._per_worker.items()}
        lines: List[str] = []
        help_txt = {
            "retries": "queries re-dispatched or envelope-retried",
            "timeouts": "queries whose deadline expired fleet-wide",
            "failovers": "controller takeover promotions",
            "stale_degraded": "reads served under the bounded-staleness"
                              " degrade",
            "shed": "fleet-wide admission sheds",
            "worker_deaths": "workers retired by death detection",
        }
        for key, help_text in help_txt.items():
            name = f"lux_fleet_{key}_total"
            lines.extend([f"# HELP {name} {help_text}",
                          f"# TYPE {name} counter",
                          f"{name} {counts.get(key, 0)}"])
        wk_keys = (("retries", "lux_fleet_worker_retries_total",
                    "retries attributed to this worker"),
                   ("timeouts", "lux_fleet_worker_timeouts_total",
                    "timeouts attributed to this worker"),
                   ("stale_served", "lux_fleet_worker_stale_reads_total",
                    "stale-degraded reads this worker served"))
        for key, name, help_text in wk_keys:
            rows = [(w, d.get(key, 0)) for w, d in
                    sorted(per_worker.items()) if d.get(key, 0)]
            if not rows:
                continue
            lines.extend([f"# HELP {name} {help_text}",
                          f"# TYPE {name} counter"])
            lines.extend(f'{name}{{worker="{w}"}} {n}' for w, n in rows)
        # -- autopilot families (ISSUE 16) -----------------------------
        with self._lock:
            pilot = dict(self._pilot_counts)
            mode = self._policy_mode
            has_policy = self._policy is not None
            hub = self._sub_hub
        if pilot["scale_up"] or pilot["scale_down"]:
            name = "lux_pilot_scale_actions_total"
            lines.extend([
                f"# HELP {name} autoscaler spawn/retire actions",
                f"# TYPE {name} counter"])
            lines.extend(
                f'{name}{{direction="{d}"}} {pilot[k]}'
                for d, k in (("up", "scale_up"), ("down", "scale_down"))
                if pilot[k])
        if pilot["elections"]:
            name = "lux_pilot_elections_total"
            lines.extend([
                f"# HELP {name} standby elections won by this "
                "controller", f"# TYPE {name} counter",
                f"{name} {pilot['elections']}"])
        if has_policy:
            name = "lux_pilot_policy_mode"
            lines.extend([
                f"# HELP {name} admission-policy mode (0 serve, 1 "
                "queue, 2 stale_degrade, 3 shed)",
                f"# TYPE {name} gauge",
                f"{name} {_POLICY_MODE_CODE.get(mode, 0)}"])
            name = "lux_pilot_policy_switches_total"
            lines.extend([
                f"# HELP {name} admission-policy mode switches",
                f"# TYPE {name} counter",
                f"{name} {pilot['policy_switches']}"])
        if pilot["sub_pushes"] or pilot["sub_coalesced"]:
            for key, name, help_text in (
                    ("sub_pushes", "lux_pilot_subscription_pushes_total",
                     "standing-query answers pushed to subscribers"),
                    ("sub_coalesced",
                     "lux_pilot_subscription_coalesced_total",
                     "subscription updates superseded before delivery")):
                lines.extend([f"# HELP {name} {help_text}",
                              f"# TYPE {name} counter",
                              f"{name} {pilot[key]}"])
        if hub is not None:
            name = "lux_pilot_subscriptions"
            lines.extend([
                f"# HELP {name} active standing-query subscriptions",
                f"# TYPE {name} gauge", f"{name} {hub.active()}"])
            lag = hub.max_lag()
            if lag is not None:
                name = "lux_pilot_subscription_lag"
                lines.extend([
                    f"# HELP {name} max generations between the journal "
                    "and a subscriber's delivered cursor",
                    f"# TYPE {name} gauge", f"{name} {lag}"])
        with self._lock:
            engine = self._slo
        if engine is not None:
            lines.extend(engine.prom_lines())
        plan = fault.active_plan()
        if plan is not None and plan.total_fired():
            name = "lux_fault_injected_total"
            lines.extend([
                f"# HELP {name} faults injected by the installed "
                f"FaultPlan ({plan.name})",
                f"# TYPE {name} counter"])
            lines.extend(
                f'{name}{{site="{r["site"]}",target="{r["target"]}",'
                f'action="{r["action"]}"}} {r["count"]}'
                for r in plan.counters())
        return "\n".join(lines) + ("\n" if lines else "")

    def close(self, shutdown_workers: bool = False) -> None:
        self._hb_stop.set()
        with self._lock:
            self._closed = True
            handles = list(self._workers.values())
            hub = self._sub_hub
            self._sub_hub = None
        if hub is not None:
            hub.close()
        for h in handles:
            if shutdown_workers and h.alive:
                try:
                    self._rpc(h, {"op": "shutdown"}, timeout_s=10.0)
                except FleetError:
                    pass
            h.conn.close()
        self._close_lease()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
