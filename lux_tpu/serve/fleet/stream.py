"""Snapshot streaming over the fleet wire protocol (jax-free leaf).

Retires the fleet's LAST shared-filesystem assumption: ``prepare`` used
to carry a ``path`` both peers could read, which silently required the
controller and every worker to share a disk.  Now the controller reads
the snapshot bytes LOCALLY and streams them to each worker over the
existing bounded-frame wire protocol (``fleet/wire.py``); the worker
reassembles them into its own PRIVATE tmpdir and stages from that local
copy.  Process-mode fleets run with fully disjoint tmpdirs — the
pod_smoke ci stage pins it.

Protocol (rides the ordinary framed connection; npy payloads carry the
raw bytes as uint8, so the no-pickle policy holds end to end)::

    stream_begin {token, nbytes, chunks, chunk_bytes}   -> RPC (ok)
    stream_chunk {token, seq} + uint8 payload            x chunks, casts
    <consumer op> {token, stream: true, sha256, ...}    -> RPC

Chunks are CASTS (no per-chunk ack): ordering is the TCP stream's, flow
control is the kernel's send buffer, and any receive-side error (bad
seq, overflow, disk) is RECORDED in the sink and surfaced by the final
consumer op — degraded to one loud error, never a silent half-file.
The final op carries the sha256 of the whole byte stream; the sink
verifies it against its own rolling digest before handing the local
path over, so a corrupt or truncated reassembly can never be staged.

Chunk size is NEGOTIATED: both peers advertise ``max_frame_bytes()``
in the hello handshake (``LUX_FLEET_MAX_FRAME_MB``), and the sender
chunks to the smaller bound minus frame overhead — a fleet with
mismatched bounds fails loudly at hello, not mid-stream.
"""
from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

#: frame overhead headroom under the payload bound: the npy container
#: (~128 B) + the JSON header; 64 KiB is orders of magnitude more than
#: either needs and keeps the arithmetic obviously safe
FRAME_SLACK = 64 * 1024

#: floor for a negotiated chunk — a pathological bound must not degrade
#: to byte-at-a-time framing
MIN_CHUNK = 256 * 1024


def negotiate_chunk_bytes(local_bound: int, remote_bound: Optional[int]
                          ) -> int:
    """Chunk size both peers can frame: min of the two advertised
    payload bounds minus slack (remote None = an old peer that never
    advertised; assume it matches ours, which the hello guard already
    enforced for new peers)."""
    bound = int(local_bound)
    if remote_bound is not None:
        bound = min(bound, int(remote_bound))
    return max(MIN_CHUNK, bound - FRAME_SLACK)


def file_chunks(path: str, chunk_bytes: int
                ) -> Tuple[int, int, Iterator[np.ndarray]]:
    """(nbytes, nchunks, iterator of uint8 chunk arrays) for one local
    file.  One sequential read pass; the sender folds the same bytes
    into its sha256 as it goes (see :func:`stream_file`)."""
    nbytes = os.path.getsize(path)
    nchunks = max(1, -(-nbytes // chunk_bytes))

    def gen():
        with open(path, "rb") as f:
            while True:
                buf = f.read(chunk_bytes)
                if not buf:
                    break
                yield np.frombuffer(buf, dtype=np.uint8)

    return nbytes, nchunks, gen()


class StreamSink:
    """Receiver-side reassembly of ONE token's byte stream into a
    private local file.  Single-writer (the connection reader thread
    feeds it in arrival order); errors latch — the first one wins and
    the final consumer op surfaces it."""

    def __init__(self, token: str, dirpath: str, nbytes: int,
                 nchunks: int):
        self.token = str(token)
        self.nbytes = int(nbytes)
        self.nchunks = int(nchunks)
        self.path = os.path.join(dirpath, f"stream-{os.getpid()}-"
                                 f"{abs(hash(token)) % (1 << 32):08x}.lux")
        self.next_seq = 0
        self.received = 0
        self.error: Optional[str] = None
        self._sha = hashlib.sha256()
        self._f = open(self.path, "wb")

    def add(self, seq: int, arr: Optional[np.ndarray]) -> None:
        if self.error is not None:
            return  # latched; drain the rest silently
        if arr is None or arr.dtype != np.uint8 or arr.ndim != 1:
            self.error = (f"stream chunk {seq} for token {self.token!r}"
                          " carries no uint8 payload")
            return
        if int(seq) != self.next_seq:
            self.error = (f"stream chunk out of order for token "
                          f"{self.token!r}: got seq {seq}, expected "
                          f"{self.next_seq} (frames reordered or lost)")
            return
        buf = arr.tobytes()
        if self.received + len(buf) > self.nbytes:
            self.error = (f"stream overflow for token {self.token!r}: "
                          f"{self.received + len(buf)} > announced "
                          f"{self.nbytes} bytes")
            return
        try:
            self._f.write(buf)
        except OSError as e:
            self.error = f"stream sink write failed: {e}"
            return
        self._sha.update(buf)
        self.received += len(buf)
        self.next_seq += 1

    def finalize(self, sha256: str) -> str:
        """Verify completeness + digest; returns the local path.  Raises
        ValueError on any defect (the consumer op turns it into an error
        reply; the controller aborts the republish)."""
        try:
            self._f.close()
        except OSError as e:
            self.error = self.error or f"stream sink close failed: {e}"
        if self.error is not None:
            raise ValueError(self.error)
        if self.received != self.nbytes or self.next_seq != self.nchunks:
            raise ValueError(
                f"incomplete stream for token {self.token!r}: "
                f"{self.received}/{self.nbytes} bytes in "
                f"{self.next_seq}/{self.nchunks} chunks")
        got = self._sha.hexdigest()
        if got != str(sha256):
            raise ValueError(
                f"stream digest mismatch for token {self.token!r}: "
                f"reassembled {got}, sender announced {sha256}")
        return self.path

    def abort(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class StreamTable:
    """The receiver's token -> sink map plus its private spool dir.
    One per worker; NOT thread-safe by itself — callers serialize on
    the connection reader (begin/chunk) and take their own lock around
    pop()."""

    def __init__(self, prefix: str = "lux-stream-"):
        self._dir: Optional[str] = None
        self._prefix = prefix
        self._sinks: Dict[str, StreamSink] = {}

    @property
    def dirpath(self) -> str:
        if self._dir is None:
            self._dir = tempfile.mkdtemp(prefix=self._prefix)
        return self._dir

    def begin(self, token: str, nbytes: int, nchunks: int) -> StreamSink:
        old = self._sinks.pop(str(token), None)
        if old is not None:
            old.abort()  # a restarted stream supersedes its own token
        sink = StreamSink(token, self.dirpath, nbytes, nchunks)
        self._sinks[str(token)] = sink
        return sink

    def chunk(self, token: str, seq: int,
              arr: Optional[np.ndarray]) -> None:
        sink = self._sinks.get(str(token))
        if sink is not None:
            sink.add(int(seq), arr)
        # unknown token: a chunk for an already-aborted stream — drop

    def pop(self, token: str) -> Optional[StreamSink]:
        return self._sinks.pop(str(token), None)

    def clear(self) -> None:
        sinks, self._sinks = list(self._sinks.values()), {}
        for s in sinks:
            s.abort()
        if self._dir is not None:
            import shutil

            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None


def stream_file(conn, path: str, token: str, chunk_bytes: int,
                begin_op: str = "stream_begin",
                chunk_op: str = "stream_chunk",
                rpc=None, timeout_s: float = 600.0) -> dict:
    """Sender side: announce + stream one local file to ``conn``.

    ``rpc(msg) -> reply`` performs the begin RPC (the controller passes
    its pending-table sender; the pod driver its blocking call).
    Chunks go out as casts on the same connection — ordered behind the
    begin by TCP.  Returns {nbytes, chunks, sha256} for the caller to
    attach to its final consumer op."""
    nbytes, nchunks, chunks = file_chunks(path, chunk_bytes)
    rpc({"op": begin_op, "token": token, "nbytes": nbytes,
         "chunks": nchunks, "chunk_bytes": int(chunk_bytes)})
    sha = hashlib.sha256()
    for seq, arr in enumerate(chunks):
        sha.update(arr.tobytes())
        conn.send({"op": chunk_op, "token": token, "seq": seq}, arr)
    return {"nbytes": nbytes, "chunks": nchunks,
            "sha256": sha.hexdigest()}
