"""luxpod: fleet workers that ARE mesh slices (ISSUE 19).

The dist engines (parallel/dist.py, ring.py, scatter.py) shard a graph
across DEVICES under one process; the fleet (serve/fleet) replicates a
graph across PROCESSES.  This module closes the diagonal: a *pod* is a
set of worker processes that together hold ONE sharded graph, each
worker owning the contiguous part range a shared
:class:`~lux_tpu.parallel.placement.PlacementTree` assigns to its host
coordinate — the same tree, the same balanced split, and therefore the
same part->host arithmetic as a real multi-host TPU launch
(parallel/multihost.py).  CPU process-mode pods are the wire twin of a
TPU pod slice: one process per "host", loopback TCP for ICI.

Per iteration the pod runs the pull engine's EXACT per-part step
(engine/pull.local_pull_step) on each worker's resident parts, with the
driver assembling the full gathered state between rounds — the
all_gather halo leg of parallel/placement.py, spelled as frames instead
of ICI.  Because every part's math is the single-host step verbatim and
the gathered state is assembled in part order, pod answers are BITWISE
equal to the single-host engine for every (parts x hosts) shape,
including under live mutation overlays (lux_tpu.mutate.overlay rows are
sliced to each worker by the same tree).

No shared filesystem: the snapshot reaches each worker as a byte
stream over the bounded-frame wire protocol (serve/fleet/stream.py)
and is reassembled in a private tmpdir; each worker then does a
PARTIAL load of only its own parts' byte ranges
(graph/sharded_load.load_pull_shards), so no worker ever holds the
whole edge list.

Run a worker: ``python -m lux_tpu.serve.fleet.pod --worker-id p0``
(prints one READY JSON line; see serve/fleet/launcher.py for the
subprocess harness).  Drive a pod: :func:`run_pull_pod`.
"""
from __future__ import annotations

import io
import json
import os
import socket
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from lux_tpu.parallel.placement import PlacementTree
from lux_tpu.serve.fleet.stream import (
    StreamTable,
    negotiate_chunk_bytes,
    stream_file,
)
from lux_tpu.serve.fleet.wire import (
    Conn,
    ConnectionClosed,
    WireError,
    max_frame_bytes,
)

#: apps a pod can run: name -> (program builder, runs-until-quiescent).
#: Quiescent apps stop on total changed-count == 0 (run_pull_until
#: semantics); fixed apps run exactly ``num_iters`` rounds.
POD_APPS = ("sssp", "components", "pagerank")


class PodError(RuntimeError):
    pass


def _build_prog(app: str, start: int, nv: int):
    """(program, until) for one pod app — the same model classes the
    single-host drivers use, so parity is by construction."""
    if app == "sssp":
        from lux_tpu.models.sssp import SSSPProgram

        return SSSPProgram(nv=nv, start=int(start)), True
    if app == "components":
        from lux_tpu.models.components import MaxLabelProgram

        return MaxLabelProgram(), True
    if app == "pagerank":
        from lux_tpu.models.pagerank import PageRankProgram

        return PageRankProgram(nv=nv), False
    raise PodError(
        f"unknown pod app {app!r}; expected one of {POD_APPS}")


def _pack_overlay(oarrays) -> np.ndarray:
    """OverlayArrays rows -> one uint8 npz blob (a single npy payload
    frame; np.savez of plain ndarrays — the no-pickle policy holds)."""
    buf = io.BytesIO()
    np.savez(buf, **{f: np.asarray(getattr(oarrays, f))
                     for f in type(oarrays)._fields})
    return np.frombuffer(buf.getvalue(), dtype=np.uint8)


def _unpack_overlay(blob: np.ndarray):
    from lux_tpu.mutate.overlay import OverlayArrays

    with np.load(io.BytesIO(blob.tobytes()), allow_pickle=False) as z:
        return OverlayArrays(**{f: z[f] for f in OverlayArrays._fields})


class PodWorker:
    """One pod member: owns the parts a PlacementTree assigns to its
    host coordinate, steps them with the pull engine's per-part math,
    and speaks the fleet wire protocol (hello / stream_begin /
    stream_chunk / pod_build / pod_overlay / pod_step / stats /
    shutdown).  Single driver connection at a time is the intended
    shape; extra connections are served but share the one engine."""

    def __init__(self, worker_id: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.worker_id = str(worker_id)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(4)
        self.host, self.port = self._srv.getsockname()[:2]
        self._streams = StreamTable(prefix=f"lux-pod-{worker_id}-")
        self._lock = threading.Lock()  # engine + stream table
        self._running = False
        self._threads: List[threading.Thread] = []
        self._conns: List[Conn] = []
        # engine state (built by pod_build)
        self._shards = None
        self._prog = None
        self._until = True
        self._method = "scan"
        self._tree: Optional[PlacementTree] = None
        self._host_index = 0
        self._lo = 0
        self._hi = 0
        self._overlay = None  # (OverlayStatic, device OverlayArrays)
        self._step_fn = None
        self.counts = {"steps": 0, "builds": 0, "compute_s": 0.0,
                       "plan_s": 0.0}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "PodWorker":
        self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name=f"lux-pod-{self.worker_id}",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._running = False
        # shutdown() first: close() alone does not wake a thread blocked
        # in accept() on Linux, so _accept_loop would sit parked until
        # its join below burned the whole timeout (LUX-R002 — the PR 16
        # stall, recurred here and caught by the checker this time)
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # never connected / already down
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            c.close()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=10.0)
        with self._lock:
            self._streams.clear()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _addr = self._srv.accept()
            except OSError:
                return
            conn = Conn(sock, peer="pod-driver",
                        owner=f"pod-{self.worker_id}")
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _conn_loop(self, conn: Conn) -> None:
        while self._running:
            try:
                msg, arr = conn.recv()
            except (ConnectionClosed, WireError):
                break
            try:
                if not self._dispatch(conn, msg, arr):
                    break
            except ConnectionClosed:
                break
            except Exception as e:  # noqa: BLE001 — op errors reply, not die
                try:
                    conn.send({"req_id": msg.get("req_id"), "ok": False,
                               "err": f"{type(e).__name__}: {e}"})
                except ConnectionClosed:
                    break
        conn.close()

    def _reply(self, conn: Conn, msg: dict, arr=None, **fields) -> None:
        conn.send({"req_id": msg.get("req_id"), "ok": True, **fields},
                  arr)

    # -- ops -------------------------------------------------------------

    def _dispatch(self, conn: Conn, msg: dict, arr) -> bool:
        op = msg.get("op")
        if op == "hello":
            self._reply(conn, msg, worker_id=self.worker_id,
                        max_frame_bytes=max_frame_bytes(),
                        pid=os.getpid())
        elif op == "stream_begin":
            with self._lock:
                self._streams.begin(str(msg["token"]),
                                    int(msg["nbytes"]),
                                    int(msg["chunks"]))
            self._reply(conn, msg)
        elif op == "stream_chunk":  # cast: no reply (stream.py contract)
            with self._lock:
                self._streams.chunk(str(msg["token"]),
                                    int(msg["seq"]), arr)
        elif op == "pod_build":
            self._op_build(conn, msg)
        elif op == "pod_overlay":
            self._op_overlay(conn, msg, arr)
        elif op == "pod_step":
            self._op_step(conn, msg, arr)
        elif op == "stats":
            with self._lock:
                lo, hi, counts = self._lo, self._hi, dict(self.counts)
            self._reply(conn, msg, worker_id=self.worker_id,
                        lo=lo, hi=hi, **counts)
        elif op == "shutdown":
            self._reply(conn, msg)
            self.stop()
            return False
        else:
            self._reply_err(conn, msg, f"unknown pod op {op!r}")
        return True

    def _reply_err(self, conn: Conn, msg: dict, err: str) -> None:
        conn.send({"req_id": msg.get("req_id"), "ok": False, "err": err})

    def _op_build(self, conn: Conn, msg: dict) -> None:
        import jax

        from lux_tpu.engine import methods, pull
        from lux_tpu.graph.sharded_load import load_pull_shards

        t0 = time.perf_counter()
        token = str(msg["token"])
        with self._lock:
            sink = self._streams.pop(token)
        if sink is None:
            self._reply_err(conn, msg,
                            f"no snapshot stream staged for token "
                            f"{token!r}")
            return
        try:
            path = sink.finalize(str(msg.get("sha256")))
        except ValueError as e:
            sink.abort()
            self._reply_err(conn, msg, str(e))
            return
        num_parts = int(msg["num_parts"])
        tree = PlacementTree.from_wire(msg["placement"])
        host_index = int(msg["host"])
        if tree.num_parts != num_parts:
            self._reply_err(conn, msg,
                            f"placement tree covers {tree.num_parts} "
                            f"parts, graph is cut into {num_parts}")
            return
        parts = tree.parts_of(host_index)
        # partial load: only MY parts' byte ranges enter memory — the
        # pod never holds the whole edge list on any one worker
        shards = load_pull_shards(path, num_parts,
                                  parts_subset=list(parts))
        try:
            os.unlink(path)  # spool served its purpose
        except OSError:
            pass
        prog, until = _build_prog(str(msg.get("app", "sssp")),
                                  int(msg.get("start", 0)),
                                  shards.spec.nv)
        with self._lock:
            self._shards = shards
            self._prog = prog
            self._until = until
            self._method = methods.resolve_sum(
                str(msg.get("method", "auto")), prog.reduce)
            self._tree = tree
            self._host_index = host_index
            self._lo, self._hi = parts.start, parts.stop
            self._overlay = None
            self._step_fn = self._make_step_locked(None)
            state0 = pull.init_state(prog, shards.arrays)
            self.counts["builds"] += 1
            lo, hi = self._lo, self._hi
        plan_s = time.perf_counter() - t0
        with self._lock:
            self.counts["plan_s"] += plan_s
        self._reply(conn, msg, np.asarray(jax.device_get(state0)),
                    lo=lo, hi=hi, nv=shards.spec.nv,
                    nv_pad=shards.spec.nv_pad, plan_s=plan_s)

    def _make_step_locked(self, ostatic):
        """Jit the per-round step over MY resident parts: vmapped
        local_pull_step against the driver-assembled full gathered
        state — literally engine/pull._pull_iteration restricted to the
        rows this host owns, so pod math IS single-host math.  Callers
        hold ``self._lock`` (the ``_locked`` suffix is the LUX-G
        contract: the reads of ``_prog``/``_method`` below are covered
        by the caller's acquisition)."""
        import jax
        import jax.numpy as jnp

        from lux_tpu.engine.pull import local_pull_step
        from lux_tpu.program.spec import active_changed

        prog, method = self._prog, self._method

        @jax.jit
        def step(arrays, full, local, oarrays=None):
            def one(arr, loc, oa=None):
                return local_pull_step(
                    prog, arr, full, loc, method,
                    overlay=(ostatic, oa) if ostatic is not None
                    else None)

            if ostatic is None:
                new = jax.vmap(lambda a, s: one(a, s))(arrays, local)
            else:
                new = jax.vmap(
                    lambda a, s, oa: one(a, s, oa)
                )(arrays, local, oarrays)
            return new, jnp.sum(active_changed(local, new))

        return step

    def _op_overlay(self, conn: Conn, msg: dict, blob) -> None:
        import jax.numpy as jnp
        import jax

        from lux_tpu.mutate.overlay import OverlayStatic

        with self._lock:
            built = self._shards is not None
            lo, hi = self._lo, self._hi
        if not built:
            self._reply_err(conn, msg, "pod_overlay before pod_build")
            return
        if blob is None:
            with self._lock:
                self._overlay = None
                self._step_fn = self._make_step_locked(None)
            self._reply(conn, msg)
            return
        oarrays = _unpack_overlay(blob)
        k = hi - lo
        if oarrays.del_val.shape[0] != k:
            self._reply_err(conn, msg,
                            f"overlay rows {oarrays.del_val.shape[0]} "
                            f"!= my {k} resident parts")
            return
        ostatic = OverlayStatic(cap=int(msg["cap"]),
                                weighted=bool(msg.get("weighted")))
        with self._lock:
            self._overlay = (ostatic,
                             jax.tree.map(jnp.asarray, oarrays))
            self._step_fn = self._make_step_locked(ostatic)
        self._reply(conn, msg)

    def _op_step(self, conn: Conn, msg: dict, full) -> None:
        import jax
        import jax.numpy as jnp

        with self._lock:
            shards = self._shards
            step = self._step_fn
            ovl = self._overlay
            lo, hi = self._lo, self._hi
        if step is None:
            self._reply_err(conn, msg, "pod_step before pod_build")
            return
        if full is None:
            self._reply_err(conn, msg,
                            "pod_step carries no gathered-state payload")
            return
        t0 = time.perf_counter()
        V = shards.spec.nv_pad
        full = jnp.asarray(full)
        local = full.reshape((shards.spec.num_parts, V)
                             + full.shape[1:])[lo:hi]
        new, active = step(shards.arrays, full, local,
                           ovl[1] if ovl is not None else None)
        new = np.asarray(jax.device_get(new))
        active = int(active)
        compute_s = time.perf_counter() - t0
        with self._lock:
            self.counts["steps"] += 1
            self.counts["compute_s"] += compute_s
        self._reply(conn, msg, new, active=active, compute_s=compute_s)


# ----------------------------------------------------------------------
# the driver: a pod as one logical engine
# ----------------------------------------------------------------------


def _rpc(conn: Conn, msg: dict,
         arr: Optional[np.ndarray] = None) -> Tuple[dict, object]:
    """One blocking request/reply on a driver connection (the driver is
    the connection's only reader, one op in flight per worker)."""
    conn.send(msg, arr)
    reply, payload = conn.recv()
    if not reply.get("ok"):
        raise PodError(reply.get("err", f"pod op {msg.get('op')!r} "
                                        "failed"))
    return reply, payload


class PodHandle:
    """Driver-side view of one pod member."""

    def __init__(self, conn: Conn, worker_id: str, bound: Optional[int]):
        self.conn = conn
        self.worker_id = worker_id
        self.max_frame_bytes = bound
        self.lo = 0
        self.hi = 0
        self.compute_s = 0.0


def pod_connect(endpoints: Sequence[Tuple[str, int]],
                timeout_s: float = 30.0) -> List[PodHandle]:
    """Dial every pod member and hello — returns one handle per worker,
    in endpoint order (endpoint order IS host-coordinate order)."""
    handles = []
    for i, (host, port) in enumerate(endpoints):
        conn = Conn.connect(host, int(port), timeout_s=timeout_s,
                            peer=f"pod{i}@{host}:{port}",
                            owner="pod-driver")
        reply, _ = _rpc(conn, {"op": "hello"})
        handles.append(PodHandle(conn, str(reply["worker_id"]),
                                 reply.get("max_frame_bytes")))
    return handles


def run_pull_pod(
    endpoints: Sequence[Tuple[str, int]],
    path: str,
    num_parts: int,
    app: str = "sssp",
    start: int = 0,
    method: str = "auto",
    num_iters: int = 10,
    max_iters: int = 10_000,
    tree: Optional[PlacementTree] = None,
    overlay=None,
    shutdown: bool = True,
) -> dict:
    """Drive one pull computation across a pod of worker processes.

    ``endpoints``: (host, port) per pod member; position in the list is
    the member's host coordinate in ``tree`` (default
    ``PlacementTree.build(num_parts, len(endpoints))`` — the exact
    multi-host split).  ``path`` is a ``.lux`` snapshot readable by the
    DRIVER only; it streams to each worker over the wire.  ``overlay``
    is an optional ``(OverlayStatic, OverlayArrays)`` over the full
    part stack — rows are sliced to each worker by the tree.

    Returns {state, iters, phases, workers}: ``state`` is the stacked
    (P, V, ...) final state — bitwise equal to the single-host pull
    engine's — and ``phases`` attributes wall time to plan (stream +
    partial load + warmup), exchange (frames + assembly), and converge
    (worker compute, max over workers per round).
    """
    tree = tree or PlacementTree.build(num_parts, len(endpoints))
    if tree.num_hosts != len(endpoints):
        raise PodError(f"placement tree names {tree.num_hosts} hosts "
                       f"but {len(endpoints)} endpoints were given")
    handles = pod_connect(endpoints)
    t_plan = time.perf_counter()
    bounds = [h.max_frame_bytes for h in handles
              if h.max_frame_bytes is not None]
    chunk = negotiate_chunk_bytes(max_frame_bytes(),
                                  min(bounds) if bounds else None)
    until = True
    state = None
    V = None
    try:
        for i, h in enumerate(handles):
            token = f"pod-{i}"
            meta = stream_file(h.conn, str(path), token, chunk,
                               rpc=lambda m, _h=h: _rpc(_h.conn, m)[0])
            reply, init_local = _rpc(h.conn, {
                "op": "pod_build", "token": token,
                "sha256": meta["sha256"], "num_parts": int(num_parts),
                "placement": tree.to_wire(), "host": i, "app": app,
                "start": int(start), "method": method})
            h.lo, h.hi = int(reply["lo"]), int(reply["hi"])
            if (h.lo, h.hi) != (tree.parts_of(i).start,
                                tree.parts_of(i).stop):
                raise PodError(f"pod member {h.worker_id} claims parts "
                               f"[{h.lo},{h.hi}) but the tree assigns "
                               f"{tree.parts_of(i)}")
            V = int(reply["nv_pad"])
            if state is None:
                state = np.zeros((num_parts,) + init_local.shape[1:],
                                 init_local.dtype)
            state[h.lo:h.hi] = init_local
        until = app != "pagerank"
        if overlay is not None:
            ostatic, oarrays = overlay
            for i, h in enumerate(handles):
                rows = type(oarrays)(
                    *(np.asarray(f)[h.lo:h.hi] for f in oarrays))
                _rpc(h.conn, {"op": "pod_overlay",
                              "cap": int(ostatic.cap),
                              "weighted": bool(ostatic.weighted)},
                     _pack_overlay(rows))
        plan_s = time.perf_counter() - t_plan

        t_loop = time.perf_counter()
        compute_s = 0.0
        iters = 0
        limit = max_iters if until else num_iters
        while iters < limit:
            full = state.reshape((num_parts * V,) + state.shape[2:])
            # fan the round out first (all sends), then drain replies —
            # workers compute concurrently, the driver's recv order is
            # just reply collection
            for h in handles:
                h.conn.send({"op": "pod_step"}, full)
            active = 0
            round_compute = 0.0
            for h in handles:
                reply, new_local = h.conn.recv()
                if not reply.get("ok"):
                    raise PodError(
                        f"pod member {h.worker_id} step failed: "
                        f"{reply.get('err')}")
                state[h.lo:h.hi] = new_local
                active += int(reply["active"])
                h.compute_s += float(reply["compute_s"])
                round_compute = max(round_compute,
                                    float(reply["compute_s"]))
            compute_s += round_compute
            iters += 1
            if until and active == 0:
                break
        converge_s = time.perf_counter() - t_loop
        return {
            "state": state,
            "iters": iters,
            "phases": {"plan": plan_s,
                       "exchange": max(converge_s - compute_s, 0.0),
                       "converge": compute_s},
            "workers": {h.worker_id: {"lo": h.lo, "hi": h.hi,
                                      "compute_s": h.compute_s}
                        for h in handles},
        }
    finally:
        for h in handles:
            try:
                if shutdown:
                    _rpc(h.conn, {"op": "shutdown"})
            except (PodError, ConnectionClosed, WireError):
                pass
            h.conn.close()


def main(argv=None) -> int:
    """Pod worker process entry: bind, print ONE ready line (JSON:
    worker_id/port/pid) and block until shutdown or SIGTERM.  The graph
    arrives over the wire (stream + pod_build) — there is no --graph
    flag, which is the point."""
    import argparse
    import signal
    import sys

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)

    w = PodWorker(args.worker_id, host=args.host, port=args.port)
    w.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    print(json.dumps({"ready": True, "worker_id": w.worker_id,
                      "port": w.port, "pid": os.getpid()}), flush=True)
    try:
        while not stop.is_set() and w._running:
            stop.wait(0.2)
    except KeyboardInterrupt:
        pass
    if w._running:
        w.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
