"""Process launcher for fleet and pod workers (ISSUE 19).

The autoscaler's ``spawn`` hook has always taken "start me a worker"
as a callable, and every harness so far passed a closure that built a
ReplicaWorker IN-PROCESS — threads as processes.  This module is the
real thing: ``python -m lux_tpu.serve.fleet.worker`` (or ``.pod``)
subprocesses with PRIVATE tmpdirs, found by parsing the one READY JSON
line each entrypoint prints.  Nothing is shared between processes but
the loopback sockets — which is exactly the claim the pod_smoke ci
stage pins.

``process_spawner`` adapts this to the Autoscaler contract
(``spawn(index) -> object with .worker_id/.port``, optional
``reap(worker)``), so a scale-up decision can fork real OS processes.

Jax-free: stdlib only (the subprocesses import jax, the launcher does
not), so controllers and tests can import it under the bare-package
stub (tools/_jaxfree.py).
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
from typing import Dict, List, Optional, Sequence


class LaunchError(RuntimeError):
    pass


class ProcHandle:
    """One launched worker process: identity, address, and teardown."""

    def __init__(self, proc: subprocess.Popen, worker_id: str,
                 port: int, pid: int, tmpdir: Optional[str],
                 ready: dict):
        self.proc = proc
        self.worker_id = str(worker_id)
        self.port = int(port)
        self.pid = int(pid)
        self.tmpdir = tmpdir
        self.ready = ready  # the full READY line (delta_generation etc)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL: the fault-drill shape — no flush, no goodbye."""
        try:
            if self.alive():
                self.proc.kill()
            self.proc.wait(timeout=30.0)
        finally:
            # wait() raising TimeoutExpired (an unreapable child) must
            # not leak the private tmpdir on top of the stuck process
            self._cleanup()

    def terminate(self, timeout_s: float = 30.0) -> int:
        """SIGTERM + wait (the entrypoints translate it to a clean
        stop); escalates to SIGKILL past the deadline."""
        try:
            if self.alive():
                self.proc.terminate()
                try:
                    self.proc.wait(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    self.proc.kill()
                    self.proc.wait(timeout=30.0)
            return self.proc.returncode
        finally:
            # as in kill(): reclaim the tmpdir even when the post-KILL
            # wait itself times out
            self._cleanup()

    def _cleanup(self) -> None:
        tmp, self.tmpdir = self.tmpdir, None
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def _read_ready_line(proc: subprocess.Popen,
                     timeout_s: float) -> dict:
    """Block for the entrypoint's single READY JSON line (a thread owns
    the blocking readline so a hung child can't hang the launcher past
    its deadline)."""
    out: Dict[str, object] = {}
    lines: List[str] = []

    def reader():
        while True:
            line = proc.stdout.readline()
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            lines.append(line)
            try:
                msg = json.loads(line)
            except ValueError:
                continue  # jax/XLA chatter on stdout — skip it
            if msg.get("ready"):
                out["ready"] = msg
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if "ready" not in out:
        fate = ("still running" if proc.poll() is None
                else f"exited {proc.returncode}")
        proc.kill()
        raise LaunchError(
            f"worker process never printed a READY line within "
            f"{timeout_s:g}s ({fate}); last output: {lines[-3:]}")
    return out["ready"]  # type: ignore[return-value]


def launch(module: str, args: Sequence[str],
           private_tmp: bool = True,
           env: Optional[dict] = None,
           ready_timeout_s: float = 180.0) -> ProcHandle:
    """Start ``python -m <module> <args>`` and wait for its READY line.

    ``private_tmp``: give the child its OWN TMPDIR (deleted at
    teardown) — the no-shared-filesystem guarantee is enforced by
    construction, not convention.  The child inherits the parent env
    (plus JAX_PLATFORMS=cpu unless already set: pods are CPU
    process-mode by default) with ``env`` overrides applied last.
    """
    return _launch_argv(["-m", module, *args], private_tmp, env,
                        ready_timeout_s)


def launch_script(path: str, args: Sequence[str] = (),
                  private_tmp: bool = True,
                  env: Optional[dict] = None,
                  ready_timeout_s: float = 180.0) -> ProcHandle:
    """Like :func:`launch` for a standalone script file that speaks the
    READY-line protocol (tests write small incumbent/worker harness
    scripts and run them as real processes)."""
    return _launch_argv([str(path), *args], private_tmp, env,
                        ready_timeout_s)


def _launch_argv(argv: Sequence[str], private_tmp: bool,
                 env: Optional[dict],
                 ready_timeout_s: float) -> ProcHandle:
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    tmpdir = None
    if private_tmp:
        tmpdir = tempfile.mkdtemp(prefix="lux-launch-")
        child_env["TMPDIR"] = tmpdir
        child_env["TMP"] = tmpdir
    if env:
        child_env.update({k: str(v) for k, v in env.items()})
    proc = subprocess.Popen(
        [sys.executable, *argv],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=child_env,
        start_new_session=True)  # SIGINT to the parent never strays
    try:
        ready = _read_ready_line(proc, ready_timeout_s)
        return ProcHandle(proc, str(ready["worker_id"]),
                          int(ready["port"]), int(ready["pid"]),
                          tmpdir, ready)
    except BaseException:
        # ANY exit without a handle orphans the child and its tmpdir:
        # a malformed READY line (KeyError/ValueError building the
        # ProcHandle above) is just as much a failed launch as a
        # missing one, and nobody else holds a reference to reap
        try:
            proc.kill()
            proc.wait(timeout=30.0)
        except Exception:  # noqa: BLE001 — never mask the launch error
            pass
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)
        raise


def launch_pod_worker(worker_id: str, host: str = "127.0.0.1",
                      port: int = 0, **kw) -> ProcHandle:
    """One pod member process (serve/fleet/pod.py main)."""
    return launch("lux_tpu.serve.fleet.pod",
                  ["--worker-id", str(worker_id), "--host", host,
                   "--port", str(int(port))], **kw)


def launch_fleet_worker(worker_id: str, extra_args: Sequence[str] = (),
                        host: str = "127.0.0.1", port: int = 0,
                        **kw) -> ProcHandle:
    """One ReplicaWorker process (serve/fleet/worker.py main) — the
    full serving stack, for process-mode fleets and failover drills."""
    return launch("lux_tpu.serve.fleet.worker",
                  ["--worker-id", str(worker_id), "--host", host,
                   "--port", str(int(port)), *extra_args], **kw)


def process_spawner(prefix: str = "pw",
                    extra_args: Sequence[str] = (),
                    pod: bool = False,
                    ready_timeout_s: float = 180.0):
    """(spawn, reap) pair matching the Autoscaler contract: ``spawn(i)``
    forks a real worker process and returns its handle (exposing
    ``.worker_id`` and ``.port`` — the scaler then add_worker()s it);
    ``reap(handle)`` SIGTERMs and reclaims the private tmpdir."""

    def spawn(index: int) -> ProcHandle:
        wid = f"{prefix}{int(index)}"
        if pod:
            return launch_pod_worker(wid,
                                     ready_timeout_s=ready_timeout_s)
        return launch_fleet_worker(wid, extra_args=extra_args,
                                   ready_timeout_s=ready_timeout_s)

    def reap(handle: ProcHandle) -> None:
        handle.terminate()

    return spawn, reap
