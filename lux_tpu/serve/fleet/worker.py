"""Replica worker: one shared-nothing serving process of the fleet.

This is the worker half of the controller/worker split that
``serve/driver.py`` (PR 1) fused into one monolith: the worker owns a
``WarmEngineCache`` + one ``MicroBatchScheduler`` per served app and
exposes them over the loopback wire protocol (``fleet/wire.py``).  The
controller owns everything the worker deliberately does NOT: admission,
routing, placement, and the republish barrier.

Protocol (all frames are JSON dicts, answers carry one npy array)::

    hello                       -> worker identity + layout + warm buckets
    query    {app, source, ...} -> answer | shed | timeout | error
    stats                       -> queue depth / shed / completed heartbeat
    prom                        -> Prometheus text (replica-labelled)
    prepare  {path, graph_id}   -> stage + prewarm a NEW engine cache
    commit                      -> atomically swap the staged cache in
    shutdown                    -> drain and exit

**Zero-downtime republish** is the prepare/commit pair: ``prepare`` loads
the new ``.lux`` snapshot and prewarms a complete second
``WarmEngineCache`` on a background thread while the OLD cache keeps
serving every query; ``commit`` is a pointer swap under the worker lock
(the schedulers' ``cache`` attribute), so no request ever observes a
half-warm service.  When the new snapshot has the same shard geometry,
program and method, the staged cache's prewarm hits the SAME jitted
loops (``serve/batched.py``'s ``lru_cache`` twins) — the serving analog
of PR 2's per-bucket incremental plan cache: only what actually changed
is rebuilt.

A worker never blocks its connection reader: queries are enqueued and
answered by the responder thread when their ``ServeFuture`` resolves;
``prepare`` runs on its own thread.  ``kill()`` exists for fault drills —
it drops the sockets without draining, which is what a SIGKILL'd worker
process looks like to the controller.

Run standalone (the multi-process fleet)::

    python -m lux_tpu.serve.fleet.worker --port 0 --graph g.lux \
        --worker-id w0   # prints one READY JSON line with the bound port
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from lux_tpu import fault
from lux_tpu.obs import dtrace
from lux_tpu.serve.fleet.pubproto import (
    ERR_NOTHING_STAGED,
    ERR_PREPARE_SUPERSEDED,
    token_mismatch,
)
from lux_tpu.serve.fleet.stream import StreamTable
from lux_tpu.serve.fleet.wire import (
    Conn,
    ConnectionClosed,
    WireError,
    max_frame_bytes,
)
from lux_tpu.parallel.placement import PlacementTree
from lux_tpu.serve.metrics import ServeMetrics
from lux_tpu.serve.scheduler import (
    MicroBatchScheduler,
    RejectedError,
    ServeTimeoutError,
)
from lux_tpu.serve.warm import WarmEngineCache


class ReplicaWorker:
    """One replica: engines + schedulers behind a loopback socket."""

    #: responder poll cadence while futures are outstanding (seconds);
    #: bounds added answer latency, not correctness
    POLL_S = 0.001

    def __init__(self, shards, worker_id: str, graph_id: str = "g",
                 apps: Tuple[str, ...] = ("sssp",),
                 q_buckets: Tuple[int, ...] = (1, 8),
                 host: str = "127.0.0.1", port: int = 0,
                 method: str = "auto", num_iters: int = 10,
                 max_iters: int = 10_000, max_wait_ms: float = 2.0,
                 max_queue: int = 256, max_engines: Optional[int] = None,
                 live=None, placement: Optional[PlacementTree] = None,
                 placement_host: int = 0):
        self.worker_id = str(worker_id)
        self.host = host
        self._req_port = int(port)
        self.apps = tuple(apps)
        self.q_buckets = tuple(q_buckets)
        self._method = method
        self._num_iters = int(num_iters)
        self._max_iters = int(max_iters)
        self._max_wait_ms = float(max_wait_ms)
        self._max_queue = int(max_queue)
        self._max_engines = max_engines
        self._num_parts = shards.spec.num_parts
        #: replica == mesh slice (ISSUE 19): every worker carries its
        #: coordinates in the ONE placement tree the dist engines use.
        #: A loopback replica owning the whole graph is just the
        #: single-host tree — the controller routes both identically.
        self.placement = (placement if placement is not None
                          else PlacementTree.single_host(self._num_parts))
        self.placement_host = int(placement_host)
        #: wire-streamed snapshot reassembly (fleet/stream.py): token ->
        #: sink, spooled into this worker's PRIVATE tmpdir (no shared
        #: filesystem with the controller); _stream_lock serializes the
        #: conn-reader begin/chunk feed against the prepare thread's pop
        self._streams = StreamTable(prefix=f"lux-w-{worker_id}-")
        self._stream_lock = threading.Lock()
        self.metrics = ServeMetrics()
        self._lock = threading.Lock()
        self._graph_id = str(graph_id)
        self._generation = 0
        #: serve/live.LiveReplica -> this worker serves a MUTATING
        #: graph: the cache compiles overlay twins, ``delta`` batches
        #: install new overlays (never a retrace or swap), ``refresh``
        #: warms the standing states, and answers carry generation tags.
        #: _live_lock serializes the write path (delta apply, refresh,
        #: live commit) — queries never take it, they read the cache's
        #: atomic overlay tuple.  Acquisition order is _live_lock
        #: BEFORE _lock on every path (checker-enforced: LUX-L002)
        self._live = live
        self._live_lock = threading.Lock()
        # (cache, graph_id, token, staged LiveReplica | None): token
        # ties the staged cache to the ONE republish that requested it
        # — a slow prepare finishing after an abort/discard (or after a
        # newer prepare superseded it) must never stage, or a later
        # commit would swap in the WRONG graph
        self._staged: Optional[
            Tuple[WarmEngineCache, str, str, object]] = None
        self._publish_token: Optional[str] = None
        self._cache = self._make_cache(shards)
        self._scheds: Dict[str, MicroBatchScheduler] = {
            app: MicroBatchScheduler(
                self._cache, app=app, max_wait_ms=self._max_wait_ms,
                max_queue=self._max_queue, metrics=self.metrics)
            for app in self.apps
        }
        self._listener: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[Conn] = []
        self._running = False
        # (conn, req_id, ServeFuture) triples the responder resolves
        self._resp_wake = threading.Condition(self._lock)
        self._unanswered: List[tuple] = []

    def _make_cache(self, shards, live=None) -> WarmEngineCache:
        live = live if live is not None else self._live
        cache = WarmEngineCache(
            shards, apps=self.apps, q_buckets=self.q_buckets,
            method=self._method, num_iters=self._num_iters,
            max_iters=self._max_iters, metrics=self.metrics,
            max_engines=self._max_engines,
            overlay_static=None if live is None else live.overlay_static)
        if live is not None:
            oarr, deg = live.serving_overlay()
            cache.set_overlay(live.servable_generation(), oarr, deg)
        return cache

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self, prewarm: bool = True) -> "ReplicaWorker":
        from lux_tpu import obs

        with obs.span("fleet.worker.start", worker=self.worker_id,
                      graph=self._graph_id, apps=list(self.apps),
                      buckets=list(self.q_buckets)):
            if prewarm:
                self._cache.prewarm()
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind((self.host, self._req_port))
            self._listener.listen(32)
            self.port = self._listener.getsockname()[1]
            self._running = True
            for sched in self._scheds.values():
                sched.start()
            for fn, name in ((self._accept_loop, "accept"),
                             (self._respond_loop, "respond")):
                t = threading.Thread(
                    target=fn, name=f"lux-fleet-{self.worker_id}-{name}",
                    daemon=True)
                t.start()
                self._threads.append(t)
        return self

    def stop(self) -> None:
        """Graceful: drain schedulers, let the responder flush every
        resolved answer, then close."""
        from lux_tpu.utils.backoff import poll_until

        for sched in self._scheds.values():
            sched.stop(drain=True)

        def _flushed() -> bool:
            with self._resp_wake:
                return not self._unanswered

        poll_until(_flushed, timeout_s=2.0)
        with self._resp_wake:
            self._running = False
            self._resp_wake.notify_all()
        self._close_sockets()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        with self._stream_lock:
            self._streams.clear()

    def kill(self) -> None:
        """Fault drill: vanish abruptly — close every socket WITHOUT
        draining, exactly the peer-visible shape of a SIGKILL.  The
        controller learns about it from the connection reset, not from
        any goodbye."""
        from lux_tpu import obs

        obs.point("fleet.worker.kill", worker=self.worker_id)
        with self._resp_wake:
            self._running = False
            self._resp_wake.notify_all()
        self._close_sockets()
        for sched in self._scheds.values():
            sched.stop(drain=False)
        with self._stream_lock:
            self._streams.clear()

    def kill_at(self, point: str, count: int = 1,
                after: int = 0) -> None:
        """Arm a fault-plan kill of THIS worker at a named process
        point (``lux_tpu.fault.ppoint`` sites, e.g.
        ``"after_delta_before_marker"`` — the PR 12 drill's window,
        aliased to ``journal.before_marker``).  Generalizes the
        hand-placed monkeypatch drills: when the point fires inside one
        of this worker's op threads, ``kill()`` drops the sockets first
        (the peer-visible SIGKILL shape) and the op aborts with
        InjectedKill — no ack, no reply, exactly a crash."""
        fault.arm_kill(point, self.kill, owner_id=self.worker_id,
                       count=count, after=after)

    def _close_sockets(self) -> None:
        if self._listener is not None:
            try:
                # shutdown() first: close() alone does not wake a
                # thread blocked in accept() (the fd stays parked in
                # the syscall), which turns every stop() into a full
                # join timeout on the accept thread
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            c.close()

    # ------------------------------------------------------------------
    # socket service
    # ------------------------------------------------------------------

    def _accept_loop(self) -> None:
        # luxcheck: disable=LUX-G001 -- _running is a monotonic shutdown latch (set True once before this thread exists, cleared once); a stale True costs one accept() that _close_sockets' shutdown() interrupts
        while self._running and self._listener is not None:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                break  # listener closed: stop()/kill()
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Conn(sock, peer="controller", owner=self.worker_id)
            with self._lock:
                self._conns.append(conn)
            # daemon + untracked: a standing replica accepts unboundedly
            # many connections over its lifetime, so per-conn threads
            # must not accumulate in a join list; stop()/kill() closes
            # their sockets, which ends their recv loops promptly
            threading.Thread(
                target=self._conn_loop, args=(conn,),
                name=f"lux-fleet-{self.worker_id}-conn",
                daemon=True).start()

    def _conn_loop(self, conn: Conn) -> None:
        with fault.owner(self.worker_id):
            # luxcheck: disable=LUX-G001 -- monotonic shutdown latch, as in _accept_loop: a stale True costs one recv() that the conn close interrupts; holding _lock here would serialize every connection
            while self._running:
                try:
                    msg, arr = conn.recv()
                except (ConnectionClosed, WireError):
                    break
                except fault.InjectedKill:
                    break  # drill: the rule's callback (kill()) already
                    # dropped every socket; this thread just ends
                try:
                    self._dispatch(conn, msg, arr)
                except ConnectionClosed:
                    break
                except fault.InjectedKill:
                    break  # as above — a killed worker answers nothing
                except Exception as e:  # noqa: BLE001 — a bad op must
                    # answer, not kill the connection serving every
                    # other request
                    self._reply_err(conn, msg, "error", err=repr(e))
        conn.close()

    def _reply_err(self, conn: Conn, msg: dict, kind: str, **extra) -> None:
        reply = {"req_id": msg.get("req_id"), "ok": False,
                 "kind": kind, **extra}
        ctx = dtrace.child_of(msg)
        if ctx is not None:
            # error replies ride the trace too: the reply frame pairs
            # its own send/recv skew points under a fresh span id
            reply["tc"] = ctx.to_wire()
        try:
            conn.send(reply)
        except ConnectionClosed:
            pass

    def _spawn_op(self, fn, args, name: str) -> None:
        """One op on its own daemon thread, carrying this worker's
        fault-owner identity (thread-locals do not cross threads — a
        drill targeting w1's journal points must fire in w1's op
        threads, not whoever shares the process)."""
        def run():
            with fault.owner(self.worker_id):
                try:
                    fn(*args)
                except fault.InjectedKill:
                    pass  # killed mid-op: sockets already dropped by
                    # the rule's callback; a crashed worker says nothing
        threading.Thread(target=run, name=name, daemon=True).start()

    def _dispatch(self, conn: Conn, msg: dict, arr=None) -> None:
        op = msg.get("op")
        rid = msg.get("req_id")
        if op == "hello":
            # re-hellos are traced (ISSUE 15): a failover takeover's
            # hello carries its context, so the stitched timeline links
            # takeover -> this worker's re-enrollment causally
            with dtrace.tspan("worker.hello", dtrace.child_of(msg),
                              always=True,
                              worker=self.worker_id) as hsp:
                ctl_bound = msg.get("max_frame_bytes")
                mine = max_frame_bytes()
                if ctl_bound is not None and int(ctl_bound) != mine:
                    # frame-bound mismatch (ISSUE 19): a frame one peer
                    # can send and the other refuses to receive is a
                    # DROPPED CONNECTION mid-protocol, not an error
                    # reply — so mismatched bounds must fail here, at
                    # the handshake, naming the knob on both sides
                    hsp.set(refused="frame_bound_mismatch")
                    self._reply_err(
                        conn, msg, "frame_bound_mismatch",
                        err=(f"worker {self.worker_id} frames at most "
                             f"{mine} payload bytes but the controller "
                             f"advertises {int(ctl_bound)} — set "
                             "LUX_FLEET_MAX_FRAME_MB identically in "
                             "both environments"),
                        max_frame_bytes=mine)
                    return
                ctl_gen = msg.get("journal_generation")
                if (self._live is not None and ctl_gen is not None
                        and self._live.generation() > int(ctl_gen)):
                    # SPLIT-BRAIN GUARD (ISSUE 14): this worker's local
                    # journal holds writes the hello'ing controller's
                    # journal does not.  Enrolling would let a stale /
                    # wiped controller re-sequence generations the fleet
                    # already acked — refuse from OUR side too (the
                    # controller-side add_worker check protects a good
                    # controller from a bad worker; this protects a good
                    # worker from a bad controller).
                    hsp.set(refused="stale_controller")
                    self._reply_err(
                        conn, msg, "stale_controller",
                        err=(f"worker {self.worker_id} is at journaled "
                             f"generation {self._live.generation()}, ahead "
                             f"of this controller's journal ({int(ctl_gen)})"
                             " — refusing a controller behind my own "
                             "journal; recover the controller from the "
                             "authoritative journal dir"),
                        journal_generation=self._live.generation())
                    return
                conn.send({"req_id": rid, "ok": True, **self.info()})
        elif op == "query":
            self._op_query(conn, msg)
        elif op in ("delta", "refresh", "read"):
            # every live op serializes on _live_lock, which a running
            # refresh holds for engine-loop seconds — so ALL of them run
            # off the connection reader (daemon threads, like prepare):
            # the reader keeps draining query frames and the schedulers
            # keep answering while the write path waits its turn.
            # Ordering stays intact: the controller's single-writer
            # admission never has two deltas in flight per worker.
            fn = {"delta": self._op_delta, "refresh": self._op_refresh,
                  "read": self._op_read}[op]
            args = (conn, msg, arr) if op == "delta" else (conn, msg)
            self._spawn_op(fn, args,
                           name=f"lux-fleet-{self.worker_id}-{op}")
        elif op == "stats":
            conn.send({"req_id": rid, "ok": True, **self.heartbeat()})
        elif op == "prom":
            conn.send({"req_id": rid, "ok": True,
                       "text": self.prom_text()})
        elif op == "stream_begin":
            # wire-streamed snapshot (fleet/stream.py): open a sink in
            # the private spool dir; cheap enough for the reader thread
            with self._stream_lock:
                self._streams.begin(str(msg.get("token")),
                                    int(msg.get("nbytes", 0)),
                                    int(msg.get("chunks", 0)))
            conn.send({"req_id": rid, "ok": True})
        elif op == "stream_chunk":
            # casts: no reply, errors latch in the sink and surface at
            # the final consumer op (prepare {stream: true})
            with self._stream_lock:
                self._streams.chunk(str(msg.get("token")),
                                    int(msg.get("seq", -1)), arr)
        elif op == "prepare":
            # daemon + untracked, like the conn threads: one per
            # republish, replies through the conn's send lock
            self._spawn_op(self._op_prepare, (conn, msg),
                           name=f"lux-fleet-{self.worker_id}-prepare")
        elif op == "commit":
            self._op_commit(conn, msg)
        elif op == "discard":
            # aborted republish: drop the staged cache (and its second
            # copy of the graph arrays) instead of holding it forever;
            # clearing the token also strands any still-running prepare
            # so it cannot re-stage after this
            with self._lock:
                had = self._staged is not None
                self._staged = None
                self._publish_token = None
            with self._stream_lock:
                # half-streamed snapshots of the aborted republish must
                # not sit spooled on disk forever either
                self._streams.clear()
            conn.send({"req_id": rid, "ok": True, "discarded": had})
        elif op == "shutdown":
            conn.send({"req_id": rid, "ok": True})
            threading.Thread(target=self.stop, daemon=True,
                             name=f"lux-fleet-{self.worker_id}-stop").start()
        else:
            self._reply_err(conn, msg, "error", err=f"unknown op {op!r}")

    # ------------------------------------------------------------------
    # ops
    # ------------------------------------------------------------------

    def info(self) -> dict:
        with self._lock:
            cache, gid, gen = self._cache, self._graph_id, self._generation
            live = self._live
        spec = cache.shards.spec
        out = {
            "worker_id": self.worker_id,
            "graph_id": gid,
            "generation": gen,
            "nv": int(spec.nv),
            "ne": int(spec.ne),
            "num_parts": int(spec.num_parts),
            "apps": list(self.apps),
            "buckets": list(self.q_buckets),
            "max_queue": self._max_queue,
            "max_frame_bytes": max_frame_bytes(),
            "placement": self.placement.to_wire(),
            "placement_host": self.placement_host,
        }
        if live is not None:
            out["live"] = True
            out["delta_generation"] = live.servable_generation()
            out["journal_generation"] = live.generation()
            out["standing"] = [[a, s] for a, s in live.standing_spec]
        return out

    def heartbeat(self) -> dict:
        """The queue-depth/shed heartbeat the controller's backpressure
        and shedding decisions ride on (plus republish visibility)."""
        with self._lock:
            gid, gen = self._graph_id, self._generation
            staged = self._staged is not None
            cache = self._cache
        counts = self.metrics.counters()
        shed, completed = counts["rejected"], counts["completed"]
        depth = sum(s.pending() for s in self._scheds.values())
        out = {
            "queue_depth": depth,
            "max_queue": self._max_queue,
            # queue occupancy as a ready-made fraction (ISSUE 16): the
            # autoscaler's hot/idle signal, precomputed here so every
            # consumer divides by the same admission bound
            "occupancy": round(depth / max(self._max_queue, 1), 4),
            "shed_total": int(shed),
            "completed": int(completed),
            "graph_id": gid,
            "generation": gen,
            "staged": staged,
            "warm_buckets": {app: list(cache.warm_buckets(app))
                             for app in self.apps},
        }
        if self._live is not None:
            out["delta_generation"] = self._live.servable_generation()
        return out

    def prom_text(self) -> str:
        """This replica's scrape (the ``prom`` op): the full
        counter/histogram set via ``ServeMetrics.scrape`` — never
        stale-empty between snapshots — plus the live-path gauges the
        Prometheus surface was missing (ISSUE 15 satellite), all
        replica-labelled: servable-vs-journaled generation lag (the
        overflow window made visible), delta-overlay occupancy (how
        close this replica is to the compaction escalation), and
        warm-engine-cache occupancy (LRU pressure)."""
        depth = sum(s.pending() for s in self._scheds.values())
        with self._lock:
            cache = self._cache
            live = self._live
        stats = cache.stats()
        extra = [("lux_serve_engine_cache_occupancy",
                  stats.get("occupancy", 0.0),
                  "resident warm engines / LRU cap")]
        if live is not None:
            lag = max(live.generation() - live.servable_generation(), 0)
            extra.extend([
                ("lux_live_generation_lag", lag,
                 "journaled minus servable generations (nonzero only "
                 "in the overflow window)"),
                ("lux_live_servable_generation",
                 live.servable_generation(),
                 "mutation generation the installed overlay serves"),
                ("lux_live_delta_occupancy",
                 round(float(
                     live.stats()["delta_occupancy"]["frac"]), 4),
                 "fraction of the per-part insert capacity in use "
                 "(max part)"),
            ])
        return self.metrics.scrape(queue_depth=depth, cache_stats=stats,
                                   replica=self.worker_id,
                                   extra_gauges=extra)

    def _op_query(self, conn: Conn, msg: dict) -> None:
        rid = msg.get("req_id")
        app = msg.get("app", "sssp")
        # THIS hop's trace context: a child of the frame's header
        # (the controller's attempt span is the causal parent); the
        # worker.query span it names covers receipt -> answer sent,
        # i.e. queue wait + batch + responder — the worker's share of
        # the request's latency in the stitched timeline
        wtc = dtrace.child_of(msg)
        t_recv = time.monotonic()
        if int(msg.get("attempt", 1) or 1) > 1:
            # a re-dispatched / envelope-retried query landing here —
            # the per-replica retry counter the prom surface labels
            self.metrics.record_retry()
        # stale_bound rides to _answer: whether this degraded dispatch
        # actually SERVED stale is decided by the answer's generation
        stale_bound = msg.get("stale_bound")
        sched = self._scheds.get(app)
        if sched is None:
            dtrace.emit_span("worker.query", wtc, t_recv,
                             time.monotonic(), ok=False,
                             worker=self.worker_id, kind="error")
            self._reply_err(conn, msg, "error",
                            err=f"app {app!r} not served here")
            return
        try:
            fut = sched.submit(
                int(msg["source"]), timeout_ms=msg.get("timeout_ms"),
                trace=(wtc.trace_id if wtc is not None and wtc.sampled
                       else None))
        except RejectedError as e:
            dtrace.emit_span("worker.query", wtc, t_recv,
                             time.monotonic(), ok=False,
                             worker=self.worker_id, kind="shed")
            self._reply_err(conn, msg, "shed",
                            retry_after_ms=e.retry_after_ms)
            return
        except (KeyError, TypeError, ValueError) as e:
            dtrace.emit_span("worker.query", wtc, t_recv,
                             time.monotonic(), ok=False,
                             worker=self.worker_id, kind="error")
            self._reply_err(conn, msg, "error", err=repr(e))
            return
        with self._resp_wake:
            self._unanswered.append((conn, rid, fut, stale_bound,
                                     wtc, t_recv))
            self._resp_wake.notify_all()

    def _respond_loop(self) -> None:
        """Single responder: answers resolve in scheduler batches, so one
        thread polling ``done()`` at POLL_S keeps up with any rate the
        engines themselves sustain (no thread-per-request)."""
        import time

        while True:
            with self._resp_wake:
                while self._running and not self._unanswered:
                    self._resp_wake.wait(timeout=0.1)
                if not self._running and not self._unanswered:
                    return
                pending, self._unanswered = self._unanswered, []
            still: List[tuple] = []
            for conn, rid, fut, bound, wtc, t_recv in pending:
                if not fut.done():
                    # luxcheck: disable=LUX-G001 -- monotonic shutdown latch: a stale True re-queues the future for ONE extra poll; the locked re-read at the loop top settles it
                    if self._running:
                        still.append((conn, rid, fut, bound, wtc,
                                      t_recv))
                    else:  # shutting down: never leave a hung future
                        self._reply_err(conn, {"req_id": rid}, "error",
                                        err="worker stopping")
                    continue
                self._answer(conn, rid, fut, stale_bound=bound,
                             tc=wtc, t_recv=t_recv)
            if still:
                with self._resp_wake:
                    self._unanswered.extend(still)
                time.sleep(self.POLL_S)

    def _answer(self, conn: Conn, rid, fut,
                stale_bound: Optional[int] = None, tc=None,
                t_recv: Optional[float] = None) -> None:
        def span(ok: bool, **extra) -> None:
            if tc is not None and t_recv is not None:
                dtrace.emit_span("worker.query", tc, t_recv,
                                 time.monotonic(), ok=ok,
                                 worker=self.worker_id, **extra)

        try:
            state = fut.result(timeout=0)
        except ServeTimeoutError as e:
            span(False, kind="timeout")
            self._reply_err(conn, {"req_id": rid}, "timeout", err=str(e))
            return
        except Exception as e:  # noqa: BLE001 — engine errors travel to
            # the controller as answers, never as a dropped connection
            span(False, kind="error")
            self._reply_err(conn, {"req_id": rid}, "error", err=repr(e))
            return
        reply = {"req_id": rid, "ok": True,
                 "rounds": int(fut.rounds),
                 "traversed": int(fut.traversed_edges)}
        if tc is not None:
            # the reply frame carries the WORKER's context so its
            # send/recv skew points pair under a unique span id
            reply["tc"] = tc.to_wire()
        if fut.generation is not None:
            # the mutation generation the answering batch served — the
            # read-your-writes tag (a lower bound on what it saw)
            reply["generation"] = int(fut.generation)
            if stale_bound is not None and fut.generation < int(stale_bound):
                # a stale_ok degrade that actually SERVED below its
                # bound — counted from the answer, where it lands
                self.metrics.record_stale_read()
        span(True, generation=fut.generation)
        try:
            conn.send(reply, arr=state)
        except ConnectionClosed:
            pass  # controller went away; nothing to tell it

    # ------------------------------------------------------------------
    # live ops (mutation-aware serving, serve/live)
    # ------------------------------------------------------------------

    def _live_or_refuse(self, conn: Conn, msg: dict):
        """The CURRENT replica, read by the CALLER inside _live_lock —
        a capture taken before the lock could be a replica a concurrent
        commit already retired, and applying to it while installing
        into the new cache is the exact cross-epoch race the lock
        exists to prevent."""
        live = self._live
        if live is None:
            self._reply_err(conn, msg, "error",
                            err="worker is not live (start it with a "
                                "LiveReplica / --live)")
        return live

    def _op_delta(self, conn: Conn, msg: dict, arr) -> None:
        """Apply ONE replicated mutation batch: journal it durably,
        rebuild + install the serving overlay, ack the generation.
        O(delta) host work on its own daemon thread (see _dispatch —
        the conn reader must keep draining query frames while this
        waits out a running refresh's _live_lock); ordering comes from
        the controller's single-writer admission, not the reader."""
        from lux_tpu import obs
        from lux_tpu.mutate.deltalog import DeltaOverflow
        from lux_tpu.serve.live.replica import GenerationGap

        if arr is None:
            self._reply_err(conn, msg, "error",
                            err="delta op needs the (rows, 4) batch "
                                "payload")
            return
        gen = msg.get("generation")
        # the replication hop's span (ISSUE 15): child of the
        # controller's replicate context, covering journal append +
        # overlay rebuild + install — where a write's latency actually
        # goes on the worker side.  The fault points inside (torn
        # writes, before-marker / before-ack kills) land within its
        # time range, so a stitched timeline shows the injected fault
        # next to the hop it perturbed.
        ctx = dtrace.child_of(msg)
        with dtrace.tspan("worker.delta", ctx, always=True,
                          worker=self.worker_id,
                          generation=int(gen) if gen is not None else None,
                          rows=int(arr.shape[0])) as dsp:
            with self._live_lock:
                live = self._live_or_refuse(conn, msg)
                if live is None:
                    return
                try:
                    oarr, deg = live.apply_batch(arr, int(gen))
                except GenerationGap as e:
                    dsp.set(kind="gen_gap")
                    self._reply_err(conn, msg, "gen_gap", have=e.have,
                                    want=e.want)
                    return
                except DeltaOverflow as e:
                    # the batch IS journaled (durable) but exceeds the
                    # overlay capacity: escalate — the controller answers
                    # with a fleet-wide compaction + republish
                    obs.point("live.overflow", worker=self.worker_id,
                              generation=int(gen))
                    dsp.set(kind="overflow")
                    self._reply_err(
                        conn, msg, "overflow", err=str(e),
                        generation=live.servable_generation(),
                        journal_generation=live.generation())
                    return
                except ConnectionClosed:
                    return
                except Exception as e:  # noqa: BLE001 — off the conn
                    # reader now: an unanswered delta would stall the
                    # controller's write path for its full timeout
                    dsp.set(kind="error")
                    self._reply_err(conn, msg, "error", err=repr(e))
                    return
                with self._lock:
                    cache = self._cache
                cache.set_overlay(int(gen), oarr, deg)
            obs.point("live.delta", worker=self.worker_id,
                      generation=int(gen), rows=int(arr.shape[0]))
            # applied + journaled + overlay installed, ack not yet sent:
            # a kill here is the "durable but silent" window the
            # controller's gen_gap/rejoin machinery must absorb
            fault.ppoint("worker.before_delta_ack", generation=int(gen))
            ack = {"req_id": msg.get("req_id"), "ok": True,
                   "generation": int(gen)}
            if ctx is not None:
                ack["tc"] = ctx.to_wire()
            try:
                conn.send(ack)
            except ConnectionClosed:
                pass  # controller went away; the apply itself is durable

    def _op_refresh(self, conn: Conn, msg: dict) -> None:
        """Warm-refresh the standing states to the current servable
        generation (PR 10's refresh machinery) — BETWEEN queries: the
        schedulers keep answering through the installed overlay while
        this runs."""
        try:
            with dtrace.tspan("worker.refresh", dtrace.child_of(msg),
                              always=True,
                              worker=self.worker_id):
                with self._live_lock:
                    live = self._live_or_refuse(conn, msg)
                    if live is None:
                        return
                    res = live.refresh()
        except ConnectionClosed:
            return
        except Exception as e:  # noqa: BLE001 — a failed refresh is an
            # answer; the overlay path still serves every query
            self._reply_err(conn, msg, "error", err=repr(e))
            return
        try:
            conn.send({"req_id": msg.get("req_id"), "ok": True, **res})
        except ConnectionClosed:
            pass
        except Exception as e:  # noqa: BLE001 — e.g. an over-bound
            # frame: answer with the error, never hang the controller
            self._reply_err(conn, msg, "error", err=repr(e))

    def _op_read(self, conn: Conn, msg: dict) -> None:
        """Serve a STANDING state (O(1): the refreshed array + its
        generation tag)."""
        app = msg.get("app", "sssp")
        with dtrace.tspan("worker.read", dtrace.child_of(msg),
                          worker=self.worker_id, app=app), \
                self._live_lock:
            live = self._live_or_refuse(conn, msg)
            if live is None:
                return
            try:
                ent = live.standing(app)
            except KeyError:
                self._reply_err(
                    conn, msg, "error",
                    err=f"no refreshed standing state for {app!r} "
                        f"(configured: {[a for a, _ in live.standing_spec]};"
                        " send a refresh first)")
                return
            state = ent["state"]
            reply = {"req_id": msg.get("req_id"), "ok": True,
                     "generation": int(ent["generation"]),
                     "iters": int(ent["iters"]), "app": app,
                     "arg": ent.get("arg"),
                     # the tolerance tag (luxmerge): the declared
                     # served-error bound the refresh quiesced under —
                     # 0.0 means the exact fixpoint.  Rides every
                     # standing read exactly like the stale tag rides
                     # degraded queries: the caller always sees the
                     # error contract of what it was served.
                     "tolerance": float(ent.get("tolerance") or 0.0)}
        try:
            conn.send(reply, arr=state)
        except ConnectionClosed:
            pass

    # ------------------------------------------------------------------
    # republish (prepare / commit)
    # ------------------------------------------------------------------

    def _op_prepare(self, conn: Conn, msg: dict) -> None:
        rid = msg.get("req_id")
        token = str(msg.get("token") or rid)
        spooled = None
        if msg.get("stream"):
            # wire-distributed snapshot: resolve the token's reassembled
            # local copy (streamed into OUR tmpdir — no path the
            # controller and this worker both see is ever required)
            with self._stream_lock:
                sink = self._streams.pop(token)
            if sink is None:
                self._reply_err(
                    conn, msg, "error",
                    err=f"no snapshot stream staged for token {token!r}"
                        " (stream_begin/stream_chunk must precede a "
                        "stream prepare)")
                return
            try:
                spooled = sink.finalize(str(msg.get("sha256")))
            except ValueError as e:
                sink.abort()
                self._reply_err(conn, msg, "error", err=str(e))
                return
            path = spooled
        else:
            path = msg.get("path")
        gid = msg.get("graph_id") or str(msg.get("path") or path)
        base_gen = msg.get("base_generation")
        if self._live is not None and base_gen is None:
            # a live worker republished WITHOUT an epoch base would keep
            # an old-epoch delta log under a new base — wrong answers
            # forever after; refuse loudly (the live controller always
            # sends the base generation)
            self._reply_err(
                conn, msg, "error",
                err="live worker needs base_generation in prepare "
                    "(republish through LiveFleetController.compact_fleet"
                    " / republish(base_generation=...))")
            return
        with self._lock:
            # latest prepare wins from the start: an older in-flight
            # prepare sees its token superseded and will not stage
            self._publish_token = token
        ctx = dtrace.child_of(msg)
        try:
            with dtrace.tspan("fleet.publish.prepare", ctx, always=True,
                              worker=self.worker_id, graph=gid):
                from lux_tpu.graph.format import read_lux
                from lux_tpu.graph.shards import build_pull_shards

                g = read_lux(str(path))
                shards = build_pull_shards(g, self._num_parts)
                live2 = None
                if self._live is not None:
                    from lux_tpu.serve.live.replica import LiveReplica

                    # journal-less while staged: the dir still holds the
                    # OLD epoch; rebind_journal rotates it at commit
                    live2 = LiveReplica(
                        g, shards, cap=self._live.cap,
                        base_generation=int(base_gen),
                        standing=self._live.standing_spec,
                        method=self._live.method,
                        max_iters=self._live.max_iters,
                        route_family=self._live.route_family,
                        tolerance=self._live.tolerance)
                cache = self._make_cache(shards, live=live2)
                cache.prewarm()  # old cache serves throughout this
                if spooled is not None:
                    import os as _os

                    try:  # spool is consumed; mmap'd views (POSIX)
                        _os.unlink(spooled)  # survive the unlink
                    except OSError:
                        pass
            with self._lock:
                if self._publish_token != token:
                    # a discard (abort) or a newer prepare happened
                    # while we built: this cache must NOT stage — a
                    # later commit would swap in the wrong graph
                    stale = True
                else:
                    stale = False
                    self._staged = (cache, gid, token, live2)
                gen_next = self._generation + 1
            if stale:
                self._reply_err(conn, msg, "error",
                                err=ERR_PREPARE_SUPERSEDED)
                return
            conn.send({"req_id": rid, "ok": True, "staged": True,
                       "graph_id": gid, "generation_next": gen_next,
                       "token": token})
        except ConnectionClosed:
            raise
        except Exception as e:  # noqa: BLE001 — a failed prepare is an
            # answer (controller aborts the republish), not a dead worker
            if spooled is not None:
                import os as _os

                try:
                    _os.unlink(spooled)
                except OSError:
                    pass
            with self._lock:
                if self._publish_token == token:
                    self._publish_token = None
                    self._staged = None
            self._reply_err(conn, msg, "error", err=repr(e))

    def _op_commit(self, conn: Conn, msg: dict) -> None:
        rid = msg.get("req_id")
        want = msg.get("token")
        # the WHOLE swap (cache + schedulers + live replica) happens
        # under _live_lock so a racing delta can never apply to the old
        # replica and then install its overlay into the new cache (old
        # epoch's edge slots under new engines = silent wrong answers).
        # Lock order _live_lock -> _lock matches _op_delta; LUX-L002
        # fails the build on any path that inverts it.
        with self._live_lock:
            self._op_commit_locked(conn, msg, rid, want)

    def _op_commit_locked(self, conn: Conn, msg: dict, rid, want) -> None:
        from lux_tpu import obs

        with self._lock:
            if self._staged is None:
                err = ERR_NOTHING_STAGED
                staged = None
            elif want is not None and self._staged[2] != str(want):
                # the staged cache belongs to a DIFFERENT republish than
                # the one committing — swapping it in would serve the
                # wrong graph under the committer's graph_id
                err = token_mismatch(self._staged[2], str(want))
                staged = None
            else:
                err = None
                staged, self._staged = self._staged, None
                cache, gid, _tok, live2 = staged
                self._publish_token = None
                self._cache = cache
                self._graph_id = gid
                self._generation += 1
                gen = self._generation
        if staged is None:
            self._reply_err(conn, msg, "error", err=err)
            return
        # the swap the schedulers observe: one attribute store per app.
        # A pump mid-step keeps the cache object it already grabbed —
        # both caches are fully warmed, so either answers correctly.
        for sched in self._scheds.values():
            sched.cache = cache
        if live2 is not None:
            # live epoch handover (caller holds _live_lock): the new
            # base embeds every batch up to its base_generation, so
            # epoch-boundary standing states carry over warm and the
            # local journal rotates (crash order matches
            # mutate/compact.py: the snapshot was durable first)
            old = self._live
            live2.inherit_standing(old)
            live2.rebind_journal(old.journal_dir, prior=old)
            self._live = live2
        ctx = dtrace.child_of(msg)
        obs.point("fleet.publish.commit", worker=self.worker_id,
                  graph=gid, generation=gen,
                  **(ctx.attrs() if ctx is not None and ctx.sampled
                     else {}))
        reply = {"req_id": rid, "ok": True, "generation": gen,
                 "graph_id": gid}
        if live2 is not None:
            reply["delta_generation"] = live2.servable_generation()
        conn.send(reply)


# ----------------------------------------------------------------------
# standalone process entry
# ----------------------------------------------------------------------


def main(argv=None) -> int:
    """Worker process entry: build the graph + shards, start serving,
    print ONE ready line (JSON: worker_id/port/pid) and block until a
    ``shutdown`` op or SIGTERM."""
    import argparse
    import json
    import os
    import signal
    import sys

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--graph", default="",
                    help=".lux snapshot path (overrides --rmat)")
    ap.add_argument("--rmat", default="10,8",
                    help="scale,edge-factor synthetic graph")
    ap.add_argument("--graph-id", default="")
    ap.add_argument("--parts", type=int, default=1)
    ap.add_argument("--apps", default="sssp")
    ap.add_argument("--buckets", default="1,8")
    ap.add_argument("--method", default="auto")
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--max-iters", type=int, default=10_000)
    ap.add_argument("--wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--live", action="store_true",
                    help="serve a MUTATING graph: compile overlay-twin "
                         "engines, accept delta/refresh/read ops, tag "
                         "answers with mutation generations (serve/live)")
    ap.add_argument("--journal-dir", default="",
                    help="live mode: durable local delta journal "
                         "(npz+.ok; a killed worker recovers its exact "
                         "committed prefix and catches up on rejoin)")
    ap.add_argument("--delta-cap", type=int, default=0,
                    help="live mode: per-part insert capacity "
                         "(0 = LUX_DELTA_CAP/default)")
    ap.add_argument("--base-generation", type=int, default=0,
                    help="live mode: the mutation generation the loaded "
                         "snapshot embeds (the controller's epoch base)")
    ap.add_argument("--standing", default="sssp:0",
                    help="live mode: comma list of standing apps kept "
                         "warm by refresh ops — sssp:<start>, pagerank, "
                         "components")
    ap.add_argument("--route-gather", default="",
                    help="live mode: gather-plan family the standing "
                         "PageRank refresh rides (fused-pf/fused-mx/"
                         "fused/expand/expand-pf; 'none' = direct; "
                         "'' = LUX_LIVE_ROUTE env, default fused-pf). "
                         "All families are bitwise-equal — perf only")
    ap.add_argument("--tolerance", type=float, default=0.0,
                    help="live mode: frontier-tolerance band for the "
                         "standing PageRank refresh — declared per-entry "
                         "served-error bound, surfaced on every read as "
                         "the tolerance tag (0 = exact fixpoint)")
    ap.add_argument("--cpus", default="",
                    help="pin this replica to these cores (comma list) — "
                         "the shared-nothing unit sizing the saturation "
                         "bench measures; affinity is process-wide, so "
                         "XLA's intra-op pool obeys it too")
    args = ap.parse_args(argv)

    if args.cpus and hasattr(os, "sched_setaffinity"):
        os.sched_setaffinity(
            0, {int(c) for c in args.cpus.split(",") if c.strip()})

    from lux_tpu.graph import generate
    from lux_tpu.graph.format import read_lux
    from lux_tpu.graph.shards import build_pull_shards

    if args.graph:
        g = read_lux(args.graph)
        gid = args.graph_id or os.path.basename(args.graph)
    else:
        scale, ef = (int(x) for x in args.rmat.split(","))
        g = generate.rmat(scale, ef, seed=0)
        gid = args.graph_id or f"rmat{scale}"
    shards = build_pull_shards(g, args.parts)
    live = None
    if args.live:
        from lux_tpu.serve.live.replica import LiveReplica, parse_standing

        live = LiveReplica(
            g, shards, cap=args.delta_cap or None,
            journal_dir=args.journal_dir or None,
            base_generation=args.base_generation,
            standing=parse_standing(args.standing),
            method=args.method, max_iters=args.max_iters,
            route_family=args.route_gather or None,
            tolerance=args.tolerance)
    worker = ReplicaWorker(
        shards, worker_id=args.worker_id, graph_id=gid,
        apps=tuple(a for a in args.apps.split(",") if a),
        q_buckets=tuple(int(b) for b in args.buckets.split(",") if b),
        host=args.host, port=args.port, method=args.method,
        num_iters=args.num_iters, max_iters=args.max_iters,
        max_wait_ms=args.wait_ms, max_queue=args.max_queue,
        live=live,
    )
    worker.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    ready = {"ready": True, "worker_id": worker.worker_id,
             "port": worker.port, "pid": os.getpid()}
    if live is not None:
        ready["delta_generation"] = live.servable_generation()
    print(json.dumps(ready), flush=True)
    try:
        while not stop.is_set() and worker._running:
            stop.wait(0.2)
    except KeyboardInterrupt:
        pass
    if worker._running:
        worker.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
