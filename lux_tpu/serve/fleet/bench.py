"""Fleet saturation measurement core, shared by ``tools/fleet_bench.py``
and the bench.py ``fleet`` app (one implementation, one row shape).

The measurement is an **open-loop offered-QPS ramp**: at each level the
harness submits queries at a fixed rate for a window (it does NOT wait
for answers before submitting the next — closed-loop clients can never
overrun a service, so they never find the knee), then resolves every
future and scores the level:

* sustained  — goodput >= ``GOODPUT_FRAC`` x offered, and the
  shed+error+timeout fraction <= ``FAIL_FRAC``;
* the **knee** is the highest sustained goodput; the ramp stops at the
  first unsustained level (the service is past saturation: queues grow
  without bound, p99 explodes, the controller sheds).

Each fleet width gets its own ramp and its own bench row
(``sssp_fleet_qps_w{W}_rmat{scale}_cpu``) so _relay's best-per-family
contest never folds widths together.  Workers are spawned as real
processes by default (`mode="proc"`, shared-nothing, loopback sockets);
``mode="thread"`` runs them in-process for fast tests — same protocol,
same controller path, same bytes on the wire.

Everything runs on CPU by design: the fleet layer is host-side
coordination, and its scaling story (2 workers beat 1 at the knee) must
be demonstrable in tier-1 with no chip window.
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from lux_tpu.obs import dtrace
from lux_tpu.obs.slo import default_fleet_slos
from lux_tpu.serve.fleet.controller import (
    FleetController,
    FleetError,
    FleetRejectedError,
    FleetTimeoutError,
)

#: a level is sustained when goodput >= this fraction of offered load...
GOODPUT_FRAC = 0.85
#: ...and at most this fraction of requests shed / errored / timed out
FAIL_FRAC = 0.05


# ----------------------------------------------------------------------
# fleet lifecycle (thread + process modes)
# ----------------------------------------------------------------------


class Fleet:
    """A controller plus the workers it was started with; ``close()``
    tears everything down regardless of mode or health."""

    def __init__(self, controller: FleetController, thread_workers: list,
                 procs: List[subprocess.Popen]):
        self.controller = controller
        self.thread_workers = thread_workers
        self.procs = procs

    def close(self) -> None:
        try:
            self.controller.close(shutdown_workers=bool(self.procs))
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        for w in self.thread_workers:
            try:
                if w._running:
                    w.stop()
            except Exception:  # noqa: BLE001
                pass
        for p in self.procs:
            try:
                p.terminate()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                    p.wait(timeout=10)  # reap — a zombie holds its fds
                except Exception:  # noqa: BLE001
                    pass


def _spawn_proc_worker(graph_path: str, worker_id: str, parts: int,
                       buckets: str, max_queue: int, wait_ms: float,
                       run_id: str, cpu: Optional[int] = None
                       ) -> Tuple[subprocess.Popen, int]:
    """One worker process; returns (proc, bound_port) once it is READY."""
    import json

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # the fleet layer is CPU by design
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if run_id:
        env["LUX_OBS_RUN_ID"] = run_id  # one fleet-wide luxtrace run id
    # workers share the persistent XLA cache: replicas 2..N (and repeat
    # runs) skip the batched-loop compile the first replica paid
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lux_jax_cache")
    cmd = [sys.executable, "-m", "lux_tpu.serve.fleet.worker",
           "--worker-id", worker_id, "--port", "0",
           "--graph", graph_path, "--parts", str(parts),
           "--buckets", buckets, "--max-queue", str(max_queue),
           "--wait-ms", str(wait_ms)]
    if cpu is not None:
        cmd += ["--cpus", str(cpu)]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env, cwd=repo_root)
    line = proc.stdout.readline()
    try:
        ready = json.loads(line)
        return proc, int(ready["port"])
    except (ValueError, KeyError, TypeError):
        proc.terminate()
        raise FleetError(
            f"worker {worker_id} failed to start (got {line!r})") from None


def start_fleet(n_workers: int, graph_path: str = "", shards=None,
                graph_id: str = "g", mode: str = "proc", parts: int = 1,
                buckets: Sequence[int] = (1, 8), max_queue: int = 256,
                wait_ms: float = 2.0, hb_interval_s: float = 0.25,
                pin: bool = True) -> Fleet:
    """Start ``n_workers`` replicas + a controller wired to all of them.

    ``mode="proc"`` spawns worker processes serving ``graph_path`` (the
    honest shared-nothing fleet); ``mode="thread"`` builds in-process
    workers over ``shards`` (fast: they share jitted loops through the
    process-wide jit cache, so N workers warm in ~the time of one).

    ``pin`` (proc mode, Linux) pins worker ``i`` to core ``i % ncores``:
    a replica is a FIXED-SIZE unit — one core — so the width ramp
    measures scale-out, not one XLA thread pool re-spreading itself over
    the whole box between runs.  Without pinning, knee(1w) on an idle
    multi-core host is really knee(1 process using every core), and the
    1-vs-2-worker comparison is noise.
    """
    from lux_tpu import obs

    bstr = ",".join(str(b) for b in buckets)
    ctl = FleetController(hb_interval_s=hb_interval_s)
    procs: List[subprocess.Popen] = []
    threads: list = []
    fleet = Fleet(ctl, threads, procs)
    try:
        with obs.span("fleet.start", workers=n_workers, mode=mode,
                      graph=graph_id):
            for i in range(n_workers):
                wid = f"w{i}"
                if mode == "proc":
                    if not graph_path:
                        raise ValueError("proc mode needs graph_path")
                    cpu = None
                    if pin and hasattr(os, "sched_getaffinity"):
                        cores = sorted(os.sched_getaffinity(0))
                        cpu = cores[i % len(cores)]
                    proc, port = _spawn_proc_worker(
                        graph_path, wid, parts, bstr, max_queue, wait_ms,
                        run_id=obs.run_id(), cpu=cpu)
                    procs.append(proc)
                else:
                    if shards is None:
                        raise ValueError("thread mode needs shards")
                    from lux_tpu.serve.fleet.worker import ReplicaWorker

                    w = ReplicaWorker(
                        shards, worker_id=wid, graph_id=graph_id,
                        q_buckets=tuple(buckets), max_queue=max_queue,
                        max_wait_ms=wait_ms).start()
                    threads.append(w)
                    port = w.port
                ctl.add_worker("127.0.0.1", port)
    except BaseException:
        fleet.close()
        raise
    return fleet


# ----------------------------------------------------------------------
# the ramp
# ----------------------------------------------------------------------


def offered_level(ctl: FleetController, sources: np.ndarray, rate: float,
                  window_s: float, timeout_ms: float = 4000.0,
                  grace_s: float = 15.0) -> dict:
    """One open-loop level: submit at ``rate`` QPS for ``window_s``,
    resolve everything, score it."""
    n = max(int(rate * window_s), 1)
    futs = []
    shed = 0
    t0 = time.monotonic()  # FleetFuture stamps t_done on this clock
    for i in range(n):
        target = t0 + i / rate
        now = time.monotonic()
        if target > now:
            time.sleep(target - now)
        try:
            futs.append(ctl.submit(int(sources[i % len(sources)]),
                                   timeout_ms=timeout_ms))
        except FleetRejectedError:
            shed += 1  # admission backpressure IS the datapoint
    ok = timeouts = errors = 0
    last_done = t0 + window_s
    lat: List[float] = []
    for f in futs:
        try:
            f.result(timeout=grace_s)
            ok += 1
            if f.latency_s is not None:
                lat.append(f.latency_s)
            if f.t_done is not None:
                last_done = max(last_done, f.t_done)
        except FleetTimeoutError:
            timeouts += 1
        except FleetError:
            errors += 1
    fail_frac = (shed + timeouts + errors) / max(n, 1)
    lat_ms = sorted(x * 1e3 for x in lat)
    # goodput horizon: submit window + completion tail, from the futures'
    # own resolve stamps (NOT the wall time of this thread's sequential
    # result() loop), minus one typical latency — a healthy level's last
    # answer lands ~p50 after its last submit, and charging that tail
    # against the rate would mis-score a 100%-complete level as
    # unsustained whenever p50/window_s exceeds 1-GOODPUT_FRAC.  Past
    # the knee the backlog drains for MANY multiples of p50, so the
    # correction never hides real saturation.
    p50_s = (lat_ms[len(lat_ms) // 2] / 1e3) if lat_ms else 0.0
    elapsed = max(last_done - t0 - p50_s, window_s)
    goodput = ok / elapsed

    def pct(p):
        if not lat_ms:
            return 0.0
        return round(lat_ms[min(int(p / 100 * len(lat_ms)),
                                len(lat_ms) - 1)], 2)

    return {
        "offered_qps": round(rate, 1),
        "submitted": n,
        "completed": ok,
        "shed": shed,
        "timeouts": timeouts,
        "errors": errors,
        "goodput_qps": round(goodput, 2),
        "fail_frac": round(fail_frac, 4),
        "p50_ms": pct(50),
        "p99_ms": pct(99),
        "sustained": bool(goodput >= GOODPUT_FRAC * rate
                          and fail_frac <= FAIL_FRAC),
    }


def ramp_to_knee(ctl: FleetController, sources: np.ndarray,
                 start_qps: float = 8.0, growth: float = 1.6,
                 max_levels: int = 12, window_s: float = 1.5,
                 timeout_ms: float = 4000.0, settle_s: float = 0.25,
                 refine_levels: int = 3) -> dict:
    """Ramp offered QPS geometrically until the fleet stops sustaining
    it, then bisect the bracket; the knee is the best sustained goodput
    (QPS + p99 there).

    The refinement phase exists because a geometric grid alone is too
    coarse at the top: with growth 1.6 the true capacity can sit ~anywhere
    in a 60% span between the last sustained and first failing level, and
    whether the boundary level "sustains" becomes a coin flip between
    runs.  Bisecting the (sustained, failed) bracket pins the knee to a
    ~growth^(1/2^refine_levels) band instead."""
    from lux_tpu import obs

    levels: List[dict] = []
    knee: Optional[dict] = None
    fail_rate: Optional[float] = None

    def run_level(rate: float, i, phase: str) -> dict:
        with obs.span("fleet.bench.level", offered=round(rate, 1),
                      level=i, phase=phase) as sp:
            lv = offered_level(ctl, sources, rate, window_s,
                               timeout_ms=timeout_ms)
            sp.set(goodput=lv["goodput_qps"], sustained=lv["sustained"])
        lv["phase"] = phase
        levels.append(lv)
        time.sleep(settle_s)  # let queues drain between levels
        return lv

    rate = float(start_qps)
    unsustained_run = 0
    for i in range(max_levels):
        lv = run_level(rate, i, "ramp")
        if lv["sustained"]:
            unsustained_run = 0
            if knee is None or lv["goodput_qps"] > knee["goodput_qps"]:
                knee = lv
                fail_rate = None  # a fail below a later knee is stale
        else:
            if fail_rate is None or rate < fail_rate:
                fail_rate = rate
            # one bad level can be a transient (GC pause, page-in burst
            # on an oversubscribed host) — a KNEE needs the collapse to
            # hold, so stop only on two unsustained levels in a row.
            # That rule applies with NO knee found too: a start rate
            # already past capacity must not ramp geometrically through
            # every level of pure timeouts
            unsustained_run += 1
            if unsustained_run >= 2:
                break
        rate *= growth
    if knee is not None and fail_rate is not None:
        lo, hi = knee["offered_qps"], fail_rate
        for i in range(refine_levels):
            if hi / max(lo, 1e-9) < 1.15:
                break  # bracket already tight
            mid = (lo * hi) ** 0.5  # geometric midpoint
            lv = run_level(mid, i, "refine")
            if lv["sustained"]:
                lo = mid
                if lv["goodput_qps"] > knee["goodput_qps"]:
                    knee = lv
            else:
                hi = mid
    if knee is None:
        knee = max(levels, key=lambda l: l["goodput_qps"])
    return {"levels": levels, "knee_qps": knee["goodput_qps"],
            "knee_offered_qps": knee["offered_qps"],
            "knee_p50_ms": knee["p50_ms"], "knee_p99_ms": knee["p99_ms"],
            "knee_sustained": knee["sustained"]}


# ----------------------------------------------------------------------
# paired width comparison
# ----------------------------------------------------------------------


def closed_loop_slice(ctl: FleetController, sources: np.ndarray,
                      dur_s: float, inflight: int = 64,
                      grace_s: float = 60.0) -> float:
    """Closed-loop goodput for one slice: keep ``inflight`` requests
    outstanding for ``dur_s``, then drain; returns completed QPS.
    In-flight accounting is a semaphore released by each future's done
    callback — O(1) per request, so the client never becomes the thing
    being measured."""
    import threading

    slots = threading.Semaphore(inflight)
    t0 = time.perf_counter()
    futs: List = []
    i = 0
    while time.perf_counter() - t0 < dur_s:
        if not slots.acquire(timeout=0.05):
            continue  # fleet has inflight outstanding; re-check clock
        f = ctl.submit(int(sources[i % len(sources)]))
        f.add_done_callback(lambda _f: slots.release())
        futs.append(f)
        i += 1
    ok = 0
    for f in futs:
        try:
            f.result(timeout=grace_s)
            ok += 1
        except FleetError:
            pass
    return ok / (time.perf_counter() - t0)


def paired_probe(ctl_a: FleetController, ctl_b: FleetController,
                 sources: np.ndarray, slices: int = 6,
                 slice_s: float = 2.5, inflight: int = 48) -> dict:
    """Interleaved paired capacity comparison of two LIVE fleets.

    Why this exists: on a shared/CPU-quota'd host, throughput swings 2x+
    on ~30 s timescales, so sequential per-width ramps compare two
    different machines-in-time and the width ratio is noise (measured
    here: sequential 2w/1w ratios of 0.5-1.7 across reps at a true ratio
    of ~1.9).  Keeping BOTH fleets alive and alternating short
    closed-loop slices between them pairs the host noise out; the MEDIAN
    per-slice ratio is the robust scale-out number, and a quota burst
    shows up as one outlier slice instead of poisoning a whole width."""
    from lux_tpu import obs

    # one discarded warmup alternation: both fleets page in their hot
    # paths under load before any recorded slice
    closed_loop_slice(ctl_a, sources, slice_s / 2, inflight)
    closed_loop_slice(ctl_b, sources, slice_s / 2, inflight)
    qps_a: List[float] = []
    qps_b: List[float] = []
    for k in range(slices):
        with obs.span("fleet.bench.paired_slice", index=k) as sp:
            a = closed_loop_slice(ctl_a, sources, slice_s, inflight)
            b = closed_loop_slice(ctl_b, sources, slice_s, inflight)
            sp.set(qps_a=round(a, 1), qps_b=round(b, 1))
        qps_a.append(round(a, 2))
        qps_b.append(round(b, 2))
    ratios = sorted(b / a for a, b in zip(qps_a, qps_b) if a > 0)
    n = len(ratios)
    if not n:
        median = 0.0
    elif n % 2:  # true median: even counts average the middle pair
        median = ratios[n // 2]
    else:
        median = 0.5 * (ratios[n // 2 - 1] + ratios[n // 2])
    return {"qps_a": qps_a, "qps_b": qps_b,
            "ratios": [round(r, 2) for r in ratios],
            "median_ratio": round(median, 2)}


# ----------------------------------------------------------------------
# trace overhead (ISSUE 15 acceptance: measured <= 3% at the knee)
# ----------------------------------------------------------------------


def measure_trace_overhead(ctl: FleetController, sources: np.ndarray,
                           slices: int = 6, slice_s: float = 1.5,
                           inflight: int = 48) -> dict:
    """Paired traced-vs-untraced throughput on ONE live fleet: the same
    interleaved closed-loop methodology as the width probe (host noise
    pairs out; a sequential A/B on a quota-swinging host measures the
    host).  Odd/even slices flip ``dtrace.set_enabled`` — everything
    else (fleet, engines, sockets) is identical.  Returns the per-slice
    QPS lists, the median traced/untraced ratio, and
    ``overhead_frac = 1 - median`` (the number the <=3% acceptance bar
    reads).  The override is always restored."""
    qps_on: List[float] = []
    qps_off: List[float] = []

    def one(enabled: bool) -> None:
        dtrace.set_enabled(enabled)
        q = round(closed_loop_slice(ctl, sources, slice_s, inflight), 2)
        (qps_on if enabled else qps_off).append(q)

    try:
        # warmup alternation, discarded (page in both configurations)
        dtrace.set_enabled(False)
        closed_loop_slice(ctl, sources, slice_s / 2, inflight)
        dtrace.set_enabled(True)
        closed_loop_slice(ctl, sources, slice_s / 2, inflight)
        for k in range(slices):
            # ABBA ordering: alternate which config goes first so a
            # linear host-throughput drift cancels out of the pairs
            # instead of biasing every pair the same way
            first_off = (k % 2 == 0)
            one(not first_off)
            one(first_off)
    finally:
        dtrace.set_enabled(None)
    ratios = sorted(on / off for on, off in zip(qps_on, qps_off)
                    if off > 0)
    n = len(ratios)
    if not n:
        median = 0.0
    elif n % 2:
        median = ratios[n // 2]
    else:
        median = 0.5 * (ratios[n // 2 - 1] + ratios[n // 2])
    return {"qps_traced": qps_on, "qps_untraced": qps_off,
            "ratios": [round(r, 4) for r in ratios],
            "median_ratio": round(median, 4),
            "overhead_frac": round(1.0 - median, 4)}


# ----------------------------------------------------------------------
# the standing row
# ----------------------------------------------------------------------


def measure_fleet_saturation(scale: int = 12, ef: int = 8,
                             workers: Sequence[int] = (1, 2, 4),
                             mode: str = "proc", parts: int = 1,
                             buckets: Sequence[int] = (1, 8),
                             start_qps: float = 8.0, growth: float = 1.6,
                             max_levels: int = 12, window_s: float = 1.5,
                             seed: int = 0, graph_path: str = "",
                             pin: bool = True, paired: bool = True,
                             trace_probe: bool = True) -> dict:
    """Ramp a 1/2/4-worker fleet (each width its own fresh fleet) on one
    rmat graph; returns bench-parsable rows plus the width comparison.
    ``graph_path`` reuses an existing ``.lux`` snapshot; otherwise the
    graph is generated and written to a temp snapshot (proc workers load
    it from disk — the same file a republish would ship)."""
    from lux_tpu import obs
    from lux_tpu.graph import generate
    from lux_tpu.graph.format import write_lux
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.serve.benchmarks import pick_sources

    g = None
    tmp = None
    if not graph_path:
        g = generate.rmat(scale, ef, seed=seed)
        tmp = tempfile.NamedTemporaryFile(
            suffix=".lux", prefix=f"fleet_rmat{scale}_", delete=False)
        tmp.close()
        write_lux(tmp.name, g)
        graph_path = tmp.name
    else:
        from lux_tpu.graph.format import read_lux

        g = read_lux(graph_path)
    sources = pick_sources(g, 256, seed=seed)
    shards = build_pull_shards(g, parts) if mode == "thread" else None
    gid = f"rmat{scale}"
    rows: List[dict] = []
    knees = {}
    try:
        overhead = None
        for w in workers:
            with obs.span("fleet.bench.width", workers=int(w), mode=mode):
                fleet = start_fleet(
                    int(w), graph_path=graph_path, shards=shards,
                    graph_id=gid, mode=mode, parts=parts,
                    buckets=buckets, pin=pin)
                # the standing serving SLOs, scored over the ramp's own
                # traffic — every width row records a verdict
                fleet.controller.set_slos(default_fleet_slos())
                try:
                    res = ramp_to_knee(
                        fleet.controller, sources, start_qps=start_qps,
                        growth=growth, max_levels=max_levels,
                        window_s=window_s)
                    if trace_probe and int(w) == max(
                            int(x) for x in workers):
                        # the <=3% acceptance number, measured at the
                        # widest fleet right after its ramp (the knee's
                        # QPS regime, paired slices)
                        with obs.span("fleet.bench.trace_overhead"):
                            overhead = measure_trace_overhead(
                                fleet.controller, sources)
                    ctl_stats = fleet.controller.stats()
                    slo_rows = fleet.controller.slo_status()
                finally:
                    fleet.close()
            knees[int(w)] = res["knee_qps"]
            rows.append({
                "metric": f"sssp_fleet_qps_w{w}_rmat{scale}_cpu",
                "value": res["knee_qps"],
                "unit": "QPS",
                "p99_ms": res["knee_p99_ms"],
                "p50_ms": res["knee_p50_ms"],
                "offered_at_knee": res["knee_offered_qps"],
                "workers": int(w),
                "mode": mode,
                "pinned": bool(pin and mode == "proc"),
                "app": "sssp",
                "platform": "cpu",
                "nv": int(g.nv),
                "ne": int(g.ne),
                "levels": res["levels"],
                "controller": ctl_stats,
                "slo": slo_rows,
                "run_id": obs.run_id(),
            })
        if paired and 1 in knees and 2 in knees:
            # the acceptance ratio (2 replicas beat 1) measured the
            # noise-robust way: both fleets live, load alternating
            with obs.span("fleet.bench.paired", widths=[1, 2]):
                fa = start_fleet(1, graph_path=graph_path, shards=shards,
                                 graph_id=gid, mode=mode, parts=parts,
                                 buckets=buckets, pin=pin)
                try:
                    fb = start_fleet(2, graph_path=graph_path,
                                     shards=shards, graph_id=gid,
                                     mode=mode, parts=parts,
                                     buckets=buckets, pin=pin)
                    try:
                        probe = paired_probe(fa.controller, fb.controller,
                                             sources)
                    finally:
                        fb.close()
                finally:
                    fa.close()
            for row in rows:
                if row["workers"] == 2:
                    row["paired_vs_w1"] = probe
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp.name)
            except OSError:
                pass
    out = {"rows": rows, "knees": knees, "graph": gid}
    if overhead is not None:
        out["trace_overhead"] = overhead
    if 1 in knees and 2 in knees and knees[1] > 0:
        out["scaleup_2v1_knee"] = round(knees[2] / knees[1], 2)
    if 1 in knees and 4 in knees and knees[1] > 0:
        out["scaleup_4v1_knee"] = round(knees[4] / knees[1], 2)
    for row in rows:
        if row.get("paired_vs_w1"):
            # the headline scale-out number: paired median, not the
            # sequential-knee ratio the host noise owns
            out["scaleup_2v1"] = row["paired_vs_w1"]["median_ratio"]
    return out


# ----------------------------------------------------------------------
# the autoscale ramp (ISSUE 16)
# ----------------------------------------------------------------------


def measure_autoscale(scale: int = 10, ef: int = 8, parts: int = 2,
                      start_workers: int = 1, max_workers: int = 2,
                      buckets: Sequence[int] = (1, 8),
                      start_qps: float = 8.0, growth: float = 1.6,
                      max_levels: int = 10, window_s: float = 1.0,
                      overload_factor: float = 1.5,
                      overload_levels: int = 2,
                      max_shed_frac: float = 0.5,
                      seed: int = 0) -> dict:
    """The closed-loop bench (ISSUE 16 acceptance): measure the knee at
    ``start_workers``, install the default AdmissionPolicy plus an
    Autoscaler fed that knee, offer load ABOVE it, and let the pilot
    act — the scaler must spawn+join replicas (previewed, cooldown-
    gated), then a second ramp measures the recovered knee.  The row
    records knee-before, knee-after, every scale action, and the shed
    fraction of the overload window against the policy's
    ``max_shed_frac`` budget (``shed_bounded`` is the acceptance bit).

    Thread-mode workers by design: the pilot's spawn callable must
    build replicas in-process, and the knee COMPARISON (not its
    absolute value) is the datapoint."""
    from lux_tpu import obs
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.serve.autopilot import (
        Autoscaler,
        AutoscalerConfig,
        default_fleet_policy,
    )
    from lux_tpu.serve.benchmarks import pick_sources
    from lux_tpu.serve.fleet.worker import ReplicaWorker

    g = generate.rmat(scale, ef, seed=seed)
    sources = pick_sources(g, 256, seed=seed)
    shards = build_pull_shards(g, parts)
    gid = f"rmat{scale}"
    fleet = start_fleet(start_workers, shards=shards, graph_id=gid,
                        mode="thread", parts=parts, buckets=buckets)
    ctl = fleet.controller
    policy = default_fleet_policy(max_shed_frac=max_shed_frac)
    ctl.set_slos(default_fleet_slos())
    ctl.set_policy(policy)

    def spawn(i: int):
        w = ReplicaWorker(
            shards, worker_id=f"w{start_workers + i}", graph_id=gid,
            q_buckets=tuple(buckets)).start()
        fleet.thread_workers.append(w)
        return w

    scaler = Autoscaler(
        ctl, spawn,
        config=AutoscalerConfig(
            min_workers=start_workers, max_workers=max_workers,
            up_consecutive=2, down_consecutive=1000, cooldown_s=0.0,
            max_move_frac=0.95))
    try:
        with obs.span("fleet.bench.autoscale", scale=scale,
                      start_workers=start_workers,
                      max_workers=max_workers):
            before = ramp_to_knee(
                ctl, sources, start_qps=start_qps, growth=growth,
                max_levels=max_levels, window_s=window_s)
            scaler.set_capacity(before["knee_qps"])
            overload_qps = before["knee_qps"] * float(overload_factor)
            scaler.note_offered_qps(overload_qps)
            shed = submitted = 0
            overload = []
            for i in range(int(overload_levels)):
                with obs.span("fleet.bench.overload", level=i,
                              offered=round(overload_qps, 1)):
                    lv = offered_level(ctl, sources, overload_qps,
                                       window_s)
                overload.append(lv)
                shed += lv["shed"]
                submitted += lv["submitted"]
                act = scaler.tick()
                if act is not None:
                    overload[-1]["scale_action"] = act
                if len(ctl.live_workers()) >= max_workers:
                    break
            scaler.note_offered_qps(None)  # the recovery ramp sets its
            # own rate per level; a stale overload note would pin "hot"
            after = ramp_to_knee(
                ctl, sources, start_qps=start_qps, growth=growth,
                max_levels=max_levels, window_s=window_s)
            ctl_stats = ctl.stats()
            slo_rows = ctl.slo_status()
    finally:
        scaler.stop()
        fleet.close()
    actions = scaler.actions()
    shed_frac = round(shed / max(submitted, 1), 4)
    workers_after = start_workers + sum(
        1 for a in actions if a["action"] == "scale_up") - sum(
        1 for a in actions if a["action"] == "scale_down")
    row = {
        "metric": (f"sssp_autoscale_w{start_workers}to{workers_after}"
                   f"_rmat{scale}_cpu"),
        "value": after["knee_qps"],
        "unit": "QPS",
        "knee_before_qps": before["knee_qps"],
        "knee_after_qps": after["knee_qps"],
        "knee_before_p99_ms": before["knee_p99_ms"],
        "knee_after_p99_ms": after["knee_p99_ms"],
        "workers_before": start_workers,
        "workers_after": workers_after,
        "scale_actions": actions,
        "overload_qps": round(overload_qps, 1),
        "shed": shed,
        "submitted": submitted,
        "shed_frac": shed_frac,
        "max_shed_frac": policy.max_shed_frac,
        "shed_bounded": bool(shed_frac <= policy.max_shed_frac),
        "policy": policy.to_dict(),
        "pilot": ctl_stats.get("pilot"),
        "app": "sssp",
        "platform": "cpu",
        "mode": "thread",
        "nv": int(g.nv),
        "ne": int(g.ne),
        "controller": ctl_stats,
        "slo": slo_rows,
        "run_id": obs.run_id(),
    }
    return {"rows": [row], "before": before, "after": after,
            "overload": overload, "graph": gid}
