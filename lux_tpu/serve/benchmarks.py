"""Serving measurement core, shared by tools/serve_bench.py and the
bench.py ``sssp_qps_*`` row (one implementation so the tracked artifact
and the standalone tool can never measure different things).

Throughput contract (the acceptance bar of the serve subsystem): warm
Q-batched QPS vs warm Q=1 SEQUENTIAL QPS on the same graph — both
through pre-traced engines, so the ratio isolates batching, not compile
amortization.  Latency percentiles come from a burst pushed through the
real scheduler path (queue wait + batch service), not from engine time
alone.
"""
from __future__ import annotations

import time

import numpy as np

from lux_tpu.serve.metrics import ServeMetrics
from lux_tpu.serve.scheduler import MicroBatchScheduler
from lux_tpu.serve.warm import WarmEngineCache
from lux_tpu.utils.roofline import serve_summarize


def pick_sources(g, n: int, seed: int = 0) -> np.ndarray:
    """Exactly n query vertices with out-edges (a zero-out-degree source
    converges instantly and measures nothing — conftest.hub_vertex
    rationale, applied to a whole batch).  Distinct while the graph has
    enough eligible vertices; repeats otherwise — callers rely on
    getting n back (a short burst would misreport QPS)."""
    deg = np.bincount(g.col_idx, minlength=g.nv)
    cand = np.flatnonzero(deg > 0)
    if not len(cand):
        raise ValueError("graph has no vertex with out-edges to query")
    rng = np.random.default_rng(seed)
    return rng.choice(cand, size=n, replace=len(cand) < n).astype(np.int32)


def measure_serving(g, shards, app: str = "sssp", q: int = 64,
                    num_seq: int = 8, batched_reps: int = 2,
                    method: str = "auto", seed: int = 0,
                    max_wait_ms: float = 2.0) -> dict:
    """Measure the serving path on ``shards`` (a PullShards bundle of
    ``g``); returns a JSON-ready dict.  Steps:

      1. prewarm Q=1 and Q=``q`` engines (wall cost reported separately);
      2. warm Q=1 sequential baseline over ``num_seq`` queries;
      3. warm Q=``q`` batched throughput over ``batched_reps`` full
         batches (distinct sources per batch);
      4. a ``q``-request burst through the MicroBatchScheduler for
         end-to-end latency percentiles and occupancy.
    """
    import jax

    cache = WarmEngineCache(shards, apps=(app,), q_buckets=(1, q),
                            method=method)
    warm_s = cache.prewarm()

    sources = pick_sources(g, max(num_seq, q * batched_reps, q), seed=seed)

    # --- warm Q=1 sequential baseline ---
    eng1, _ = cache.get(app, 1)
    t0 = time.perf_counter()
    for s in sources[:num_seq]:
        eng1.run([int(s)])
    seq_elapsed = time.perf_counter() - t0
    qps_seq = num_seq / seq_elapsed

    # --- warm Q=q batched throughput ---
    engq, _ = cache.get(app, q)
    batch_times = []
    traversed_total = 0
    iters_seen = []
    t0 = time.perf_counter()
    for rep in range(batched_reps):
        batch = np.resize(sources[rep * q:(rep + 1) * q], q)
        tb = time.perf_counter()
        out = engq.run(batch)
        batch_times.append(time.perf_counter() - tb)
        traversed_total += sum(out.traversed)
        iters_seen.append(out.iters)
    bat_elapsed = time.perf_counter() - t0
    qps_batched = (q * batched_reps) / bat_elapsed

    # --- scheduler burst: end-to-end latency through the real path ---
    metrics = ServeMetrics()
    sched = MicroBatchScheduler(cache, app=app, max_wait_ms=max_wait_ms,
                                max_queue=4 * q, metrics=metrics)
    futs = [sched.submit(int(s)) for s in sources[:q]]
    t0 = time.perf_counter()
    sched.drain()
    burst_elapsed = time.perf_counter() - t0
    for f in futs:
        f.result(timeout=0)  # already resolved; raises on any error
    summary = metrics.summary(elapsed_s=burst_elapsed,
                              cache_stats=cache.stats())
    # flight-recorder snapshot: luxview's serve section for a bench run
    metrics.emit_snapshot(summary=summary)

    out = {
        "app": app,
        "q": q,
        "method": engq.method,
        "platform": jax.default_backend(),
        "qps_batched": round(qps_batched, 3),
        "qps_q1_sequential": round(qps_seq, 3),
        "batched_vs_q1": round(qps_batched / qps_seq, 2),
        "batch_ms": round(float(np.mean(batch_times)) * 1e3, 1),
        "iters": iters_seen[0] if iters_seen else 0,
        "warm_trace_s": round(warm_s, 1),
        # end-to-end request latency through the scheduler path, promoted
        # to the top level so artifact parsers need not dig
        "latency_ms": summary.get("latency_ms", {}),
        "scheduler": summary,
    }
    out.update(serve_summarize(q * batched_reps, bat_elapsed,
                               traversed_total))
    return out
