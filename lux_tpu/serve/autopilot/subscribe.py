"""Standing-query subscriptions: push, don't poll (ISSUE 16).

PR 12 gave every replica standing answers (``read_standing``) that a
warm refresh keeps current, but a client that wanted to FOLLOW one had
to poll the controller and diff generation tags.  This module inverts
it: register a :class:`Subscription` once, and the
:class:`SubscriptionHub` pushes every refreshed answer — on
write-commit and on fleet refresh — with the generation tag as the
cursor.

Design constraints, in order:

* **The write path never blocks on subscribers.**  ``notify()`` (called
  by ``admit_writes``/``refresh_fleet``) only folds the new generation
  into a pending slot under the hub lock; a single dispatcher thread
  does the ``read_standing`` fetches and queue pushes.  A burst of
  writes COALESCES: standing answers are absolute states, so an
  undelivered generation-5 update is strictly obsolete the moment
  generation 7 commits — superseded updates are counted
  (``lux_pilot_subscription_coalesced_total``), never delivered late.
* **Generation tags are the cursor.**  Every pushed update carries the
  served generation; a subscriber's ``cursor`` is the last generation
  it was handed, pushes are strictly cursor-monotonic, and the
  fleet-level ``lux_pilot_subscription_lag`` gauge is the max distance
  between the journal and any subscriber's cursor.
* **Subscriptions survive controller death.**  The hub holds the
  controller by reference; an elected successor ADOPTS the hub
  (``rebind``) and re-notifies at its recovered generation, so clients
  register once per fleet, not once per controller incarnation —
  ``close()`` on the controller (a clean shutdown) closes the hub,
  ``kill()`` (the death drill) deliberately does not.
* **Pushes are traced.**  Each dispatch emits a ``pilot.subscribe.push``
  span as a CHILD of the admitting write's (or refresh's) trace
  context, so a stitched write timeline ends with the fan-out to its
  subscribers.

Pure stdlib — the hub lives in the jax-free controller process.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from lux_tpu.obs import dtrace


class SubscriptionClosed(RuntimeError):
    """get() on a subscription whose hub shut down or unsubscribed
    it."""


class Subscription:
    """One registered standing query.  ``get(timeout_s)`` blocks for
    the next pushed update — ``{app, generation, state, iters, worker,
    tolerance, refreshed}`` — strictly newer than ``cursor``; iteration
    yields updates until the subscription closes."""

    def __init__(self, sub_id: int, app: str, cursor: int = 0):
        self.sub_id = int(sub_id)
        self.app = str(app)
        self.cursor = int(cursor)  # last delivered generation
        self.delivered = 0
        self._cond = threading.Condition()
        self._latest: Optional[dict] = None
        self._closed = False

    def _push(self, update: dict) -> bool:
        """Hub-side: offer an update; False when it did not supersede
        (stale vs cursor) or the subscription closed."""
        with self._cond:
            if self._closed:
                return False
            if int(update["generation"]) <= self.cursor and \
                    not update.get("refreshed"):
                return False
            self._latest = update
            self._cond.notify_all()
            return True

    def _close(self) -> None:
        with self._cond:
            self._closed = True
            self._latest = None  # unsubscribed: drop, don't drain
            self._cond.notify_all()

    def get(self, timeout_s: Optional[float] = 30.0) -> dict:
        """The next undelivered update (the LATEST one — intermediate
        states superseded while waiting are never replayed).  Raises
        ``TimeoutError`` on timeout, :class:`SubscriptionClosed` once
        the hub closed this subscription."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + float(timeout_s))
        with self._cond:
            while self._latest is None:
                if self._closed:
                    raise SubscriptionClosed(
                        f"subscription {self.sub_id} closed")
                if deadline is None:
                    self._cond.wait()
                else:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._cond.wait(left):
                        if self._latest is not None:
                            break
                        raise TimeoutError(
                            f"no update for app {self.app!r} within "
                            f"{timeout_s}s (cursor {self.cursor})")
            update, self._latest = self._latest, None
            self.cursor = max(self.cursor, int(update["generation"]))
            self.delivered += 1
            return update

    def __iter__(self):
        while True:
            try:
                yield self.get(timeout_s=None)
            except SubscriptionClosed:
                return


class SubscriptionHub:
    """The controller-side registry + dispatcher.  Attach by
    construction (``LiveFleetController.subscribe`` builds one lazily
    and stores it as ``_sub_hub``); detach/adopt via ``rebind``."""

    def __init__(self, controller):
        self._ctl = controller
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._subs: Dict[int, Subscription] = {}
        self._next_id = 0
        #: the pending (coalesced) notification: highest generation +
        #: the trace context of the write/refresh that raised it
        self._pending_gen: Optional[int] = None
        self._pending_tc = None
        self._pending_refreshed = False
        self._push_errors = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- registration ---------------------------------------------------

    def subscribe(self, app: str, cursor: int = 0) -> Subscription:
        with self._lock:
            self._next_id += 1
            sub = Subscription(self._next_id, app, cursor=cursor)
            self._subs[sub.sub_id] = sub
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="lux-pilot-subs", daemon=True)
                self._thread.start()
        # seed the new subscriber with the CURRENT standing answer (if
        # any generation is committed past its cursor) — "register
        # once" must not mean "wait for the next write"
        try:
            gen = int(self._ctl.generation())
        except Exception:  # noqa: BLE001 — mid-failover registration
            gen = 0
        if gen > cursor:
            self.notify(gen)
        return sub

    def unsubscribe(self, sub) -> None:
        sub_id = sub.sub_id if isinstance(sub, Subscription) else int(sub)
        with self._lock:
            got = self._subs.pop(sub_id, None)
        if got is not None:
            got._close()

    def active(self) -> int:
        with self._lock:
            return len(self._subs)

    def max_lag(self) -> Optional[int]:
        """Max (journal generation - subscriber cursor) over active
        subscriptions; None with no subscribers or no controller."""
        with self._lock:
            subs = list(self._subs.values())
        if not subs:
            return None
        try:
            gen = int(self._ctl.generation())
        except Exception:  # noqa: BLE001 — dead incumbent, pre-rebind
            return None
        return max(max(gen - s.cursor, 0) for s in subs)

    # -- the push path --------------------------------------------------

    def notify(self, generation: int, tc=None,
               refreshed: bool = False) -> None:
        """Fold a committed generation into the pending slot (cheap —
        the write path calls this).  An undispatched older notification
        is superseded, counted as coalesced, and never fetched."""
        with self._cond:
            if self._stop:
                return
            if self._pending_gen is not None:
                if generation < self._pending_gen:
                    return  # late notify for an already-superseded gen
                self._count(self._ctl, "sub_coalesced")
            self._pending_gen = max(generation,
                                    self._pending_gen or 0)
            self._pending_tc = tc
            self._pending_refreshed = (refreshed
                                       or self._pending_refreshed)
            self._cond.notify()

    def rebind(self, controller) -> None:
        """Adopt this hub onto a NEW controller (the elected successor):
        subscribers keep their registrations and cursors, and a
        notification at the successor's recovered generation restarts
        delivery (any update the dead incumbent never dispatched is
        re-fetched from the recovered journal line)."""
        with self._cond:
            old = self._ctl
            self._ctl = controller
        if old is not None:
            with old._lock:
                if old._sub_hub is self:
                    old._sub_hub = None
        with controller._lock:
            displaced = getattr(controller, "_sub_hub", None)
            controller._sub_hub = self
        if displaced is not None and displaced is not self:
            # the successor's OWN lazily-built hub just lost its
            # controller binding: nothing would ever notify it again,
            # so its dispatcher thread idles forever and its
            # subscribers hang silently.  close() wakes them with
            # SubscriptionClosed and JOINS the dispatcher — a clean
            # end beats a leaked thread plus a silent hang.
            displaced.close()
        try:
            gen = int(controller.generation())
        except Exception:  # noqa: BLE001 — static controller adoption
            gen = 0
        self.notify(gen, refreshed=True)

    def _count(self, ctl, key: str, n: int = 1) -> None:
        """Route a counter increment to ``ctl`` — passed in, never read
        from ``self._ctl`` here: ``_cond``'s lock is non-reentrant, so a
        caller already under it reads the field itself, and the
        dispatcher passes the snapshot it took under the lock (the
        controller that actually served the dispatch, not whatever a
        concurrent ``rebind`` swapped in mid-push)."""
        if ctl is not None:
            try:
                ctl._pilot_count(key, n)
            except Exception:  # noqa: BLE001 — torn-down controller
                pass

    def _run(self) -> None:
        while True:
            with self._cond:
                while (self._pending_gen is None and not self._stop):
                    self._cond.wait()
                if self._stop:
                    return
                gen = self._pending_gen
                tc = self._pending_tc
                refreshed = self._pending_refreshed
                self._pending_gen = None
                self._pending_tc = None
                self._pending_refreshed = False
                ctl = self._ctl
                by_app: Dict[str, list] = {}
                for s in self._subs.values():
                    if s.cursor < gen or refreshed:
                        by_app.setdefault(s.app, []).append(s)
            for app in sorted(by_app):
                t0 = time.monotonic()
                ctx = tc.child() if tc is not None else None
                try:
                    ans = ctl.read_standing(app)
                except Exception as e:  # noqa: BLE001 — dead/failing ctl
                    # delivery stalls, registration survives: the next
                    # notify (a later write, or a successor's rebind)
                    # restarts it.  No retry loop here — a dead
                    # incumbent would make it a busy-wait.
                    with self._cond:
                        self._push_errors += 1
                    dtrace.emit_span("pilot.subscribe.push", ctx, t0,
                                     time.monotonic(), ok=False,
                                     app=app, err=str(e))
                    continue
                update = {"app": app,
                          "generation": int(ans["generation"]),
                          "state": ans["state"],
                          "iters": ans.get("iters"),
                          "worker": ans.get("worker"),
                          # the served-error contract of the pushed
                          # answer (luxmerge tolerance tag; 0.0 = exact)
                          "tolerance": float(ans.get("tolerance") or 0.0),
                          "refreshed": bool(refreshed)}
                pushed = 0
                for s in by_app[app]:
                    if s._push(dict(update)):
                        pushed += 1
                if pushed:
                    self._count(ctl, "sub_pushes", pushed)
                dtrace.emit_span("pilot.subscribe.push", ctx, t0,
                                 time.monotonic(), ok=True, app=app,
                                 generation=update["generation"],
                                 subscribers=pushed,
                                 refreshed=bool(refreshed))

    def stats(self) -> dict:
        with self._lock:
            return {"active": len(self._subs),
                    "push_errors": self._push_errors,
                    "pending_generation": self._pending_gen}

    def close(self) -> None:
        with self._cond:
            self._stop = True
            subs = list(self._subs.values())
            self._subs.clear()
            self._cond.notify_all()
        for s in subs:
            s._close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
