"""luxpilot — the self-driving fleet (ISSUE 16).

The closed control loop over the serving fleet's own telemetry:

* :mod:`.policy` — admission policy as JSON-round-trip data: which
  degraded mode (serve / queue / stale_degrade / shed) each SLO
  verdict buys, evaluated on the controller's heartbeat cadence;
* :mod:`.autoscaler` — SLO- and occupancy-driven scale decisions with
  hysteresis, cooldown and a rebalance-preview move budget;
* :mod:`.election` — standby controllers that detect incumbent death
  and run a deterministic, incarnation-fenced election through
  ``promote_live_controller``;
* :mod:`.subscribe` — standing-query subscriptions: register once,
  get pushed every refreshed answer with the generation tag as
  cursor, surviving controller elections via hub rebind.

Every autonomous action (scale, elect, promote, policy switch,
subscription push) emits a causally-linked dtrace span on a keyed
incident trace, so ``luxstitch`` renders one timeline per incident.

Exports resolve LAZILY (PEP 562, same contract as ``lux_tpu.serve``):
``election``/``policy`` are jax-free and the protocol tier
(``lux_tpu.analysis.proto.election_model``) model-checks the REAL
``StandbyGroup`` under tools/_jaxfree.py's bare-package stub.
"""
_EXPORTS = {
    "Autoscaler": "lux_tpu.serve.autopilot.autoscaler",
    "AutoscalerConfig": "lux_tpu.serve.autopilot.autoscaler",
    "Standby": "lux_tpu.serve.autopilot.election",
    "StandbyGroup": "lux_tpu.serve.autopilot.election",
    "live_promoter": "lux_tpu.serve.autopilot.election",
    "MODES": "lux_tpu.serve.autopilot.policy",
    "AdmissionPolicy": "lux_tpu.serve.autopilot.policy",
    "PolicyError": "lux_tpu.serve.autopilot.policy",
    "PolicyRule": "lux_tpu.serve.autopilot.policy",
    "default_fleet_policy": "lux_tpu.serve.autopilot.policy",
    "Subscription": "lux_tpu.serve.autopilot.subscribe",
    "SubscriptionClosed": "lux_tpu.serve.autopilot.subscribe",
    "SubscriptionHub": "lux_tpu.serve.autopilot.subscribe",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
