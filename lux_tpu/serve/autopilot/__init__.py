"""luxpilot — the self-driving fleet (ISSUE 16).

The closed control loop over the serving fleet's own telemetry:

* :mod:`.policy` — admission policy as JSON-round-trip data: which
  degraded mode (serve / queue / stale_degrade / shed) each SLO
  verdict buys, evaluated on the controller's heartbeat cadence;
* :mod:`.autoscaler` — SLO- and occupancy-driven scale decisions with
  hysteresis, cooldown and a rebalance-preview move budget;
* :mod:`.election` — standby controllers that detect incumbent death
  and run a deterministic, incarnation-fenced election through
  ``promote_live_controller``;
* :mod:`.subscribe` — standing-query subscriptions: register once,
  get pushed every refreshed answer with the generation tag as
  cursor, surviving controller elections via hub rebind.

Every autonomous action (scale, elect, promote, policy switch,
subscription push) emits a causally-linked dtrace span on a keyed
incident trace, so ``luxstitch`` renders one timeline per incident.
"""
from lux_tpu.serve.autopilot.autoscaler import (Autoscaler,
                                                AutoscalerConfig)
from lux_tpu.serve.autopilot.election import (Standby, StandbyGroup,
                                              live_promoter)
from lux_tpu.serve.autopilot.policy import (MODES, AdmissionPolicy,
                                            PolicyError, PolicyRule,
                                            default_fleet_policy)
from lux_tpu.serve.autopilot.subscribe import (Subscription,
                                               SubscriptionClosed,
                                               SubscriptionHub)

__all__ = [
    "AdmissionPolicy", "Autoscaler", "AutoscalerConfig", "MODES",
    "PolicyError", "PolicyRule", "Standby", "StandbyGroup",
    "Subscription", "SubscriptionClosed", "SubscriptionHub",
    "default_fleet_policy", "live_promoter",
]
