"""Automatic controller election: standbys that promote themselves
(ISSUE 16).

PR 14 made controller failover a LIBRARY call —
``promote_live_controller`` rebuilds a controller from the journal and
re-enrolls survivors with token-fenced re-hellos — but a harness still
had to notice the death and make the call.  This module is the
noticing: each :class:`Standby` probes the incumbent on the fleet's
heartbeat cadence, declares death after ``death_after_s`` of silence
(with seeded jitter so standbys don't stampede), and runs a
deterministic election through the shared :class:`StandbyGroup`:

* **lowest live standby id wins** — no rounds, no randomized ballots:
  the group accepts a ``claim`` only from the smallest currently
  registered id, so every standby computes the same winner;
* **one election per incumbent incarnation** — claims are FENCED by
  the dead controller's incarnation token: the group refuses a second
  claim against an incarnation already claimed, so a slow standby that
  declares death late cannot start a rival promotion (split-brain
  guard on the election side; the journal-generation hello refusal
  from PR 14 guards the worker side);
* **losers adopt, retries survive winner death** — a losing standby
  waits on the group's promoted event and adopts the winner's
  controller; if the winner dies mid-promotion its claim is released
  and the next-lowest standby retries.

Every phase is traced onto ONE keyed incident
(``election:{incarnation}``): each standby's ``pilot.detect`` span,
the winner's ``pilot.elect`` and ``pilot.promote`` spans — because the
key is the dead incarnation, every standby independently mints the
SAME trace id and luxstitch renders detection, election and promotion
as a single causal timeline without any coordination.

Pure stdlib; a Standby is a thread in the (jax-free) controller
process, not a separate OS process — matching the repo's
threads-as-processes fleet idiom.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional

from lux_tpu import fault
from lux_tpu.obs import dtrace


class StandbyGroup:
    """The shared election state: the registered standby ids, the
    incarnation fence, and the promoted-controller slot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids: set = set()
        self._claimed: Dict[str, int] = {}  # incarnation -> winner id
        self._promoted = None  # (ctl, report) once a winner finished
        self._event = threading.Event()
        self.elections = 0

    def register(self, standby_id: int) -> None:
        with self._lock:
            self._ids.add(int(standby_id))

    def deregister(self, standby_id: int) -> None:
        with self._lock:
            self._ids.discard(int(standby_id))

    def claim(self, standby_id: int, incarnation: str) -> bool:
        """Try to win the election for a dead incarnation.  True for
        exactly one caller: the LOWEST live standby id, first claim
        against this incarnation."""
        standby_id = int(standby_id)
        with self._lock:
            if incarnation in self._claimed:
                return False  # fenced: this death is already being
                # handled (or was); a late detector must adopt, not race
            if not self._ids or standby_id != min(self._ids):
                return False
            self._claimed[incarnation] = standby_id
            return True

    def release(self, standby_id: int, incarnation: str) -> None:
        """Winner died / promotion failed: lift the fence so the
        next-lowest standby can retry."""
        with self._lock:
            if self._claimed.get(incarnation) == int(standby_id):
                del self._claimed[incarnation]

    def claimed_by(self, incarnation: str) -> Optional[int]:
        with self._lock:
            return self._claimed.get(incarnation)

    def set_promoted(self, standby_id: int, ctl, report) -> None:
        with self._lock:
            self._promoted = (ctl, report)
            self.elections += 1
        self._event.set()

    @property
    def promoted(self):
        """(controller, takeover_report) once an election completed,
        else None."""
        with self._lock:
            return self._promoted

    def wait_promoted(self, timeout_s: Optional[float] = None):
        """Block until some standby finished promoting; returns the
        (controller, report) pair or None on timeout."""
        self._event.wait(timeout_s)
        return self.promoted


class Standby:
    """One standby controller candidate.

    ``promote(tc) -> (ctl, report)`` does the actual promotion — the
    live-fleet harnesses hand in a closure over
    ``promote_live_controller`` (see :func:`live_promoter`); the
    standby only decides WHEN to call it and fences WHO may.

    Timing defaults compose with the fleet knobs (ISSUE 16 satellite):
    the probe interval defaults to the incumbent's ``hb_interval_s``
    (itself ``LUX_FLEET_HEARTBEAT_S``) and the death threshold to its
    ``hb_timeout_s`` (``LUX_FLEET_DEATH_S``) — a standby declares
    death on the same clock the controller uses to declare workers
    dead.  Probe jitter is a seeded ``random.Random`` per standby
    (deterministic under test, desynchronized in a fleet).
    """

    def __init__(self, group: StandbyGroup, standby_id: int,
                 incumbent,
                 promote: Callable[[Optional[dtrace.TraceContext]],
                                   tuple],
                 on_promoted: Optional[Callable] = None,
                 hb_interval_s: Optional[float] = None,
                 death_after_s: Optional[float] = None,
                 seed: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.group = group
        self.standby_id = int(standby_id)
        self.incumbent = incumbent
        self.promote = promote
        self.on_promoted = on_promoted
        self.hb_interval_s = (float(incumbent.hb_interval_s)
                              if hb_interval_s is None
                              else float(hb_interval_s))
        self.death_after_s = (float(incumbent.hb_timeout_s)
                              if death_after_s is None
                              else float(death_after_s))
        self.incumbent_incarnation = str(incumbent.incarnation)
        self._rng = random.Random(int(seed) * 1000003
                                  + self.standby_id)
        self.clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.probes_ok = 0
        self.probes_failed = 0
        self.detected_at: Optional[float] = None
        self.outcome: Optional[str] = None  # "won" | "adopted" | None
        group.register(self.standby_id)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Standby":
        self._thread = threading.Thread(
            target=self._run, name=f"lux-standby-{self.standby_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.group.deregister(self.standby_id)
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    # -- the probe loop --------------------------------------------------

    def _probe_once(self) -> bool:
        try:
            self.incumbent.ping()
            return True
        except Exception:  # noqa: BLE001 — closed/errored == silent
            return False

    def _run(self) -> None:
        last_ok = self.clock()
        while not self._stop.is_set():
            # jittered probe interval: +-25% so standbys that started
            # together drift apart instead of probing in lockstep
            interval = self.hb_interval_s * (
                0.75 + 0.5 * self._rng.random())
            if self._stop.wait(interval):
                return
            now = self.clock()
            if self._probe_once():
                self.probes_ok += 1
                last_ok = now
                continue
            self.probes_failed += 1
            if now - last_ok < self.death_after_s:
                continue
            # -- death declared ------------------------------------------
            # named process point: a delay rule here makes THIS standby
            # a late detector (the TOCTOU schedule luxproto's election
            # model explores); kill dies silently pre-claim
            fault.ppoint("election.detect",
                         owner=f"standby-{self.standby_id}",
                         incumbent=self.incumbent_incarnation)
            self.detected_at = now
            etc = dtrace.incident(
                f"election:{self.incumbent_incarnation}")
            dtrace.emit_span(
                "pilot.detect", etc, last_ok, now, ok=True,
                standby=self.standby_id,
                incumbent=self.incumbent_incarnation,
                silence_s=round(now - last_ok, 4))
            self._elect(etc)
            return

    def _elect(self, etc) -> None:
        deadline = self.clock() + max(self.death_after_s * 20, 30.0)
        while not self._stop.is_set() and self.clock() < deadline:
            if self.group.promoted is not None:
                self._adopt()
                return
            if not self.group.claim(self.standby_id,
                                    self.incumbent_incarnation):
                # lost (or fenced out): wait for the winner, then
                # re-check — if the winner released, claim again
                self.group.wait_promoted(self.death_after_s)
                continue
            # claim won, promotion not yet run: a delay rule holds the
            # promotion window open (the detached-promotion schedule);
            # kill dies HOLDING the claim — the fence then wedges the
            # election shut rather than admit a rival (by design)
            fault.ppoint("election.promote",
                         owner=f"standby-{self.standby_id}",
                         incumbent=self.incumbent_incarnation)
            t0 = self.clock()
            try:
                with dtrace.tspan(
                        "pilot.elect", etc, always=True,
                        winner=self.standby_id,
                        incumbent=self.incumbent_incarnation):
                    pass
                ctl, report = self.promote(etc)
            except Exception as e:  # noqa: BLE001 — failed promotion
                dtrace.emit_span(
                    "pilot.promote", etc, t0, self.clock(), ok=False,
                    standby=self.standby_id, err=str(e))
                self.group.release(self.standby_id,
                                   self.incumbent_incarnation)
                continue
            dtrace.emit_span(
                "pilot.promote", etc, t0, self.clock(), ok=True,
                standby=self.standby_id,
                incarnation=str(ctl.incarnation),
                joined=len(report.get("joined", ()))
                if isinstance(report, dict) else None)
            try:
                ctl._pilot_count("elections")
            except Exception:  # noqa: BLE001 — non-fleet test double
                pass
            self.outcome = "won"
            self.group.set_promoted(self.standby_id, ctl, report)
            if self.on_promoted is not None:
                self.on_promoted(ctl, report)
            return
        # stopped/deadlined while waiting: if some winner finished in
        # the meantime, that's an adoption, not a timeout (stop() races
        # the promoted event on the losing standbys)
        if self.outcome is None and self.group.promoted is not None:
            self._adopt()
        self.outcome = self.outcome or "timeout"

    def _adopt(self) -> None:
        self.outcome = "adopted"


class WireIncumbent:
    """A process-mode incumbent as a Standby sees it (ISSUE 19): dials
    the controller's ``serve_lease`` port and renews the lease with one
    RPC per probe.  Satisfies the Standby's incumbent duck type
    (``.ping()`` raising on death, ``.hb_interval_s``,
    ``.hb_timeout_s``, ``.incarnation``) — so the SAME fenced election
    that watches an in-process controller watches one across a process
    boundary, and the only out-of-band fact a standby needs is the
    lease address: the cadence and death threshold arrive IN the first
    grant.

    Death is SILENCE, in either of its wire shapes: a refused dial, a
    dropped connection, or a reply that doesn't start within the death
    threshold (``Conn.recv_wait``) all raise — which is exactly what
    ``Standby._probe_once`` counts as a failed probe.  A renewal that
    answers with a DIFFERENT incarnation also raises: that's a new
    controller at the old address, and the election against the one we
    were watching must still run (adoption handles the successor).
    """

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 10.0):
        self.host = str(host)
        self.port = int(port)
        self.connect_timeout_s = float(connect_timeout_s)
        self._lock = threading.Lock()
        self._conn = None
        self._seq = 0
        self.incarnation: Optional[str] = None
        self.hb_interval_s = 0.25
        self.hb_timeout_s = 3.0
        grant = self.ping()  # first renewal: learn the lease terms
        self.incarnation = str(grant["incarnation"])
        self.hb_interval_s = float(grant.get("hb_interval_s",
                                             self.hb_interval_s))
        self.hb_timeout_s = float(grant.get("lease_s",
                                            self.hb_timeout_s))

    def _drop(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()

    def ping(self) -> dict:
        from lux_tpu.serve.fleet.wire import Conn

        with self._lock:
            conn = self._conn
        if conn is None:
            # dial OUTSIDE the lock (LUX-L003): a hung connect to a
            # dead address must not wedge close() behind the probe
            conn = Conn.connect(
                self.host, self.port,
                timeout_s=self.connect_timeout_s,
                peer=f"incumbent@{self.host}:{self.port}",
                owner="standby")
        with self._lock:
            if self._conn is None:
                # luxcheck: disable=LUX-G003 -- deliberate CAS: the dial ran unlocked (holding _lock across connect() is the PR 19 wedge), and this second acquisition RE-CHECKS before installing; the losing racer is closed below
                self._conn = conn
            elif self._conn is not conn:
                # lost a dial race to another probe; keep the installed
                # connection (ONE renewal stream per incumbent)
                conn.close()
            try:
                conn = self._conn
                self._seq += 1
                conn.send({"op": "lease",
                           "req_id": f"l{self._seq}"})
                # the probe's own deadline: a grant that doesn't START
                # within the death threshold IS a missed renewal
                msg, _ = conn.recv_wait(
                    max(self.hb_timeout_s, self.connect_timeout_s))
            except Exception:
                self._drop()
                raise
            if not msg.get("ok"):
                self._drop()
                raise ConnectionError(
                    f"lease refused: {msg.get('err', msg)}")
            inc = str(msg.get("incarnation"))
            if self.incarnation is not None and inc != self.incarnation:
                self._drop()
                raise ConnectionError(
                    f"incumbent incarnation changed "
                    f"({self.incarnation} -> {inc}): the controller we "
                    "were watching is gone")
            return msg

    def close(self) -> None:
        with self._lock:
            self._drop()


def live_promoter(base, journal_dir: str, snapshot_path: Optional[str],
                  endpoints_fn: Callable[[], list], deadline_s: float = 30.0,
                  seed: int = 0, **kw) -> Callable:
    """Build the ``promote`` closure a live-fleet Standby needs:
    wraps ``promote_live_controller`` over the authoritative journal
    dir, resolving the surviving-worker endpoint list AT PROMOTION
    TIME (``endpoints_fn`` — workers may have scaled since the standby
    started).  Lazy import keeps this module import-light for the
    pure-policy callers."""
    def promote(tc=None):
        from lux_tpu.serve.live.controller import promote_live_controller
        endpoints = list(endpoints_fn())
        return promote_live_controller(
            base, journal_dir, snapshot_path, endpoints,
            deadline_s=deadline_s, seed=seed, **kw)
    return promote
