"""SLO-driven autoscaling for the replica fleet (ISSUE 16).

The :class:`Autoscaler` closes the loop the earlier PRs left open: the
fleet already MEASURES everything that matters — per-worker queue
occupancy rides every heartbeat (serve/fleet/worker.py), the SLO
engine turns latency/availability into multiwindow burn verdicts
(obs/slo.py), and the saturation bench (PR 8) locates the knee in
queries/s per worker — but a human still had to read the dashboards
and call ``add_worker``.  ``tick()`` does that reading:

* **hot** when mean occupancy crosses ``up_occupancy``, when any SLO
  verdict is ``burning``, or when the knee-derived desired worker
  count (``ceil(offered_qps / knee_qps_per_worker)``) exceeds the
  live count -> spawn ONE worker and join it;
* **idle** when occupancy sits under ``down_occupancy`` with clean
  verdicts and no knee pressure -> retire the NEWEST spawned worker
  (LIFO, so the baseline fleet the operator started is never reaped).

Flap resistance comes from two mechanisms, both required: a signal
must hold for ``up_consecutive``/``down_consecutive`` ticks
(hysteresis — one bursty heartbeat is not a trend), and any action
starts a ``cooldown_s`` window during which no further action fires
(the join's ~1/(R+1) rebalance and replica warmup must land before the
signals are trusted again).  Every action is additionally gated on a
``rebalance_preview`` dry run: if the membership change would move
more than ``max_move_frac`` of the keyspace, the action is refused and
counted, because a rebalance that invalidates most of the fleet's
locality is worse than the congestion it fixes.

Scale actions are INCIDENTS: each emits a ``pilot.scale`` span on a
keyed incident trace (``scale:{incarnation}:{seq}``) carrying
direction, worker id, the previewed move fraction, and the occupancy/
verdict evidence — luxstitch renders the decision and the resulting
join/leave as one timeline.

The scaler owns only worker PROCESS lifecycle via the ``spawn`` /
``reap`` callables the harness provides; ring membership, key movement
and token fencing stay in the controller paths PRs 9-14 hardened.
Pure stdlib; jax-free.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Callable, List, Optional

from lux_tpu.obs import dtrace
from lux_tpu.obs.slo import worst_verdict
from lux_tpu.utils.config import env_float, env_int


class AutoscalerConfig:
    """Knobs with env overrides (resolved ONCE at construction — never
    read os.environ from the tick loop/thread):

    ==============================  =========================  =======
    knob                            env                        default
    ==============================  =========================  =======
    ``up_occupancy``                ``LUX_PILOT_UP_OCC``       0.6
    ``down_occupancy``              ``LUX_PILOT_DOWN_OCC``     0.15
    ``up_consecutive``              ``LUX_PILOT_UP_TICKS``     2
    ``down_consecutive``            ``LUX_PILOT_DOWN_TICKS``   4
    ``cooldown_s``                  ``LUX_PILOT_COOLDOWN_S``   2.0
    ``interval_s``                  ``LUX_PILOT_INTERVAL_S``   0.25
    ``max_move_frac``               ``LUX_PILOT_MAX_MOVE_FRAC``0.75
    ==============================  =========================  =======

    Explicit constructor arguments beat the environment; a garbage env
    value raises ``ValueError`` naming the knob (config.env_float's
    contract)."""

    def __init__(self, min_workers: int = 1, max_workers: int = 4,
                 up_occupancy: Optional[float] = None,
                 down_occupancy: Optional[float] = None,
                 up_consecutive: Optional[int] = None,
                 down_consecutive: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 max_move_frac: Optional[float] = None):
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.up_occupancy = (
            env_float("LUX_PILOT_UP_OCC", 0.6, minimum=0.0, maximum=1.0)
            if up_occupancy is None else float(up_occupancy))
        self.down_occupancy = (
            env_float("LUX_PILOT_DOWN_OCC", 0.15, minimum=0.0,
                      maximum=1.0)
            if down_occupancy is None else float(down_occupancy))
        self.up_consecutive = (
            env_int("LUX_PILOT_UP_TICKS", 2, minimum=1, maximum=1000)
            if up_consecutive is None else int(up_consecutive))
        self.down_consecutive = (
            env_int("LUX_PILOT_DOWN_TICKS", 4, minimum=1, maximum=1000)
            if down_consecutive is None else int(down_consecutive))
        self.cooldown_s = (
            env_float("LUX_PILOT_COOLDOWN_S", 2.0, minimum=0.0,
                      maximum=3600.0)
            if cooldown_s is None else float(cooldown_s))
        self.interval_s = (
            env_float("LUX_PILOT_INTERVAL_S", 0.25, minimum=0.01,
                      maximum=60.0)
            if interval_s is None else float(interval_s))
        self.max_move_frac = (
            env_float("LUX_PILOT_MAX_MOVE_FRAC", 0.75, minimum=0.0,
                      maximum=1.0)
            if max_move_frac is None else float(max_move_frac))
        self.validate()

    def validate(self) -> None:
        if not (1 <= self.min_workers <= self.max_workers):
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{self.min_workers}..{self.max_workers}")
        if self.down_occupancy >= self.up_occupancy:
            raise ValueError(
                f"down_occupancy ({self.down_occupancy}) must sit below "
                f"up_occupancy ({self.up_occupancy}) — equal thresholds "
                f"flap")


class Autoscaler:
    """The scaling loop.  ``spawn(index) -> worker`` must return a
    STARTED worker object exposing ``.worker_id`` and ``.port`` (the
    live-fleet harnesses build the LiveReplica + ReplicaWorker pair);
    ``reap(worker)`` tears the process down after a scale-down
    (optional — workers also exit on the controller's shutdown RPC).

    Drive it either by calling ``tick()`` from the harness (tests and
    the bench do — deterministic with an injected ``clock``) or via
    ``start()``'s background thread at ``config.interval_s``."""

    def __init__(self, controller,
                 spawn: Callable[[int], object],
                 reap: Optional[Callable[[object], None]] = None,
                 config: Optional[AutoscalerConfig] = None,
                 knee_qps_per_worker: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.ctl = controller
        self.spawn = spawn
        self.reap = reap
        self.cfg = config if config is not None else AutoscalerConfig()
        self.knee_qps_per_worker = (
            None if knee_qps_per_worker is None
            else float(knee_qps_per_worker))
        self.clock = clock
        self._lock = threading.Lock()
        self._offered_qps: Optional[float] = None
        self._hot_streak = 0
        self._idle_streak = 0
        self._last_action_t: Optional[float] = None
        self._seq = 0
        self._spawned: List[object] = []  # LIFO retirement order
        self._refused_moves = 0
        self._actions: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- inputs ----------------------------------------------------------

    def note_offered_qps(self, qps: Optional[float]) -> None:
        """Tell the scaler the CURRENT offered load (the bench/ingest
        layer knows it; the controller only sees what it admits)."""
        with self._lock:
            self._offered_qps = None if qps is None else float(qps)

    def set_capacity(self, knee_qps_per_worker: Optional[float]) -> None:
        """Install (or refresh) the measured saturation knee — the
        per-worker capacity estimate the desired-count signal divides
        by.  Feed it from ``ramp_to_knee``'s estimate."""
        with self._lock:
            self.knee_qps_per_worker = (
                None if knee_qps_per_worker is None
                else float(knee_qps_per_worker))

    def signals(self) -> dict:
        """The current evidence, as ``tick()`` will read it: mean
        queue occupancy over live workers' last heartbeats, the worst
        SLO verdict, the live count, and the knee-derived desired
        count (None without both a knee and an offered-qps note)."""
        workers = self.ctl.workers()
        occs = []
        for info in workers.values():
            if not info.get("alive"):
                continue
            hb = info.get("last_hb") or {}
            if "occupancy" in hb:
                occs.append(float(hb["occupancy"]))
            elif "queue_depth" in hb:
                occs.append(float(hb["queue_depth"])
                            / max(float(hb.get("max_queue", 256)), 1.0))
        alive = sum(1 for i in workers.values() if i.get("alive"))
        with self._lock:
            offered = self._offered_qps
            knee = self.knee_qps_per_worker
        desired = None
        if offered is not None and knee is not None and knee > 0:
            desired = max(self.cfg.min_workers,
                          min(self.cfg.max_workers,
                              int(math.ceil(offered / knee))))
        return {"occupancy": (sum(occs) / len(occs)) if occs else 0.0,
                "verdict": worst_verdict(self.ctl.slo_status()),
                "alive": alive, "desired": desired,
                "offered_qps": offered, "knee": knee}

    # -- the loop --------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One control-loop evaluation.  Returns the action report —
        ``{action, worker, moved_frac, occupancy, verdict, ...}`` —
        when an action fired, ``None`` when the loop held steady."""
        now = self.clock() if now is None else float(now)
        sig = self.signals()
        occ, verdict, alive = (sig["occupancy"], sig["verdict"],
                               sig["alive"])
        desired = sig["desired"]
        hot = (occ >= self.cfg.up_occupancy or verdict == "burning"
               or (desired is not None and desired > alive))
        idle = (occ <= self.cfg.down_occupancy
                and verdict in ("ok", "no_data")
                and (desired is None or desired < alive))
        with self._lock:
            self._hot_streak = self._hot_streak + 1 if hot else 0
            self._idle_streak = self._idle_streak + 1 if idle else 0
            hot_ready = self._hot_streak >= self.cfg.up_consecutive
            idle_ready = self._idle_streak >= self.cfg.down_consecutive
            cooling = (self._last_action_t is not None
                       and now - self._last_action_t < self.cfg.cooldown_s)
        if cooling:
            return None
        if hot_ready and alive < self.cfg.max_workers:
            return self._scale_up(now, sig)
        if idle_ready and alive > self.cfg.min_workers and self._spawned:
            return self._scale_down(now, sig)
        return None

    def _incident(self, direction: str):
        with self._lock:
            self._seq += 1
            seq = self._seq
        key = f"scale:{self.ctl.incarnation}:{seq}"
        return dtrace.incident(key), seq

    def _scale_up(self, now: float, sig: dict) -> Optional[dict]:
        w = self.spawn(len(self._spawned))
        preview = self.ctl.rebalance_preview(add=[w.worker_id])
        if preview["moved_frac"] > self.cfg.max_move_frac:
            # refuse the join, reap the orphan: moving this much of
            # the keyspace mid-congestion does more harm than one
            # more replica does good
            with self._lock:
                self._refused_moves += 1
            if self.reap is not None:
                self.reap(w)
            return None
        stc, seq = self._incident("up")
        with dtrace.tspan("pilot.scale", stc, always=True,
                          direction="up", worker=w.worker_id,
                          moved_frac=preview["moved_frac"],
                          occupancy=round(sig["occupancy"], 4),
                          verdict=sig["verdict"], seq=seq):
            self.ctl.add_worker("127.0.0.1", w.port, tc=stc)
        self.ctl._pilot_count("scale_up")
        report = {"action": "scale_up", "worker": w.worker_id,
                  "moved_frac": preview["moved_frac"],
                  "occupancy": sig["occupancy"],
                  "verdict": sig["verdict"], "alive": sig["alive"] + 1,
                  "seq": seq}
        with self._lock:
            self._spawned.append(w)
            self._last_action_t = now
            self._hot_streak = 0
            self._idle_streak = 0
            self._actions.append(report)
        return report

    def _scale_down(self, now: float, sig: dict) -> Optional[dict]:
        with self._lock:
            if not self._spawned:
                return None
            w = self._spawned[-1]
        preview = self.ctl.rebalance_preview(remove=[w.worker_id])
        if preview["moved_frac"] > self.cfg.max_move_frac:
            with self._lock:
                self._refused_moves += 1
            return None
        stc, seq = self._incident("down")
        with dtrace.tspan("pilot.scale", stc, always=True,
                          direction="down", worker=w.worker_id,
                          moved_frac=preview["moved_frac"],
                          occupancy=round(sig["occupancy"], 4),
                          verdict=sig["verdict"], seq=seq):
            self.ctl.remove_worker(w.worker_id, shutdown=True)
        self.ctl._pilot_count("scale_down")
        if self.reap is not None:
            self.reap(w)
        report = {"action": "scale_down", "worker": w.worker_id,
                  "moved_frac": preview["moved_frac"],
                  "occupancy": sig["occupancy"],
                  "verdict": sig["verdict"], "alive": sig["alive"] - 1,
                  "seq": seq}
        with self._lock:
            self._spawned.pop()
            self._last_action_t = now
            self._hot_streak = 0
            self._idle_streak = 0
            self._actions.append(report)
        return report

    # -- lifecycle -------------------------------------------------------

    def actions(self) -> List[dict]:
        with self._lock:
            return list(self._actions)

    def stats(self) -> dict:
        with self._lock:
            return {"actions": len(self._actions),
                    "spawned_live": len(self._spawned),
                    "refused_moves": self._refused_moves,
                    "hot_streak": self._hot_streak,
                    "idle_streak": self._idle_streak}

    def start(self) -> "Autoscaler":
        """Run ``tick()`` on a daemon thread every ``interval_s``."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="lux-pilot-scale",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a failed tick must not
                pass           # kill the loop; next tick re-reads state

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
