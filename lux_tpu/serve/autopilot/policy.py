"""Degraded-mode admission policy as DATA (ISSUE 16).

PR 14 gave the fleet three degraded answers — shed at admission,
serve-stale with an explicit tag, queue behind saturated replicas —
and PR 15 gave it the multiwindow burn-rate verdicts that say when
each is warranted.  Choosing between them was still code: whoever
called ``submit`` picked ``stale_ok`` or ate the shed.  An
:class:`AdmissionPolicy` makes the choice a JSON-round-trip spec, like
FaultPlans, SLOSpecs and VertexProgramSpecs before it:

    AdmissionPolicy([
        PolicyRule(slo="read_latency",      verdict="burning",
                   mode="shed"),
        PolicyRule(slo="read_freshness",    verdict="burning",
                   mode="stale_degrade"),
        PolicyRule(slo="*",                 verdict="warn",
                   mode="queue"),
    ], max_shed_frac=0.5)

Semantics: ``decide(status_rows)`` scans the rules IN ORDER against
the SLO engine's verdict rows (``slo`` is an fnmatch glob over spec
names, ``verdict`` matches that spec's current verdict); the first
rule whose (slo, verdict) pair is live wins and names the fleet's
admission mode.  No match -> ``default_mode`` (normally ``serve``).
The controller re-evaluates on its heartbeat cadence and gates
``_dispatch`` on the result; every mode SWITCH emits a
``pilot.policy.switch`` incident span and bumps
``lux_pilot_policy_switches_total``.

``max_shed_frac`` is the policy's load-shedding budget: a DOCUMENTED
bound on the shed fraction the operator accepts while the policy
holds the fleet in ``shed`` mode.  The autoscale bench records its
measured shed fraction against it — the acceptance criterion that
"shed stays bounded by the installed AdmissionPolicy".

Pure stdlib, importable by the jax-free controller process.
"""
from __future__ import annotations

import fnmatch
import json
from typing import List, Optional, Sequence, Tuple

#: admission modes, mildest first; must match the controller's prom
#: gauge coding (fleet/controller._POLICY_MODE_CODE — test-pinned)
MODES = ("serve", "queue", "stale_degrade", "shed")

#: verdicts a rule may match (obs/slo.py's vocabulary)
VERDICTS = ("no_data", "ok", "warn", "burning")


class PolicyError(ValueError):
    """Malformed policy/rule (unknown mode/verdict, bad bounds)."""


class PolicyRule:
    """One (slo glob, verdict) -> mode mapping.  ``slo`` is an fnmatch
    glob over SLO spec names (``"*"`` matches any); ``verdict`` is the
    exact verdict that arms the rule; ``note`` documents intent and
    rides the switch span as the reason."""

    def __init__(self, slo: str = "*", verdict: str = "burning",
                 mode: str = "shed", note: str = ""):
        self.slo = str(slo)
        self.verdict = str(verdict)
        self.mode = str(mode)
        self.note = str(note)
        self.validate()

    def validate(self) -> None:
        if self.mode not in MODES:
            raise PolicyError(
                f"unknown mode {self.mode!r}; expected one of {MODES}")
        if self.verdict not in VERDICTS:
            raise PolicyError(
                f"unknown verdict {self.verdict!r}; expected one of "
                f"{VERDICTS}")

    def matches(self, rows: Sequence[dict]) -> Optional[str]:
        """The name of the first status row arming this rule, or
        None."""
        for r in rows:
            if (fnmatch.fnmatchcase(str(r.get("name")), self.slo)
                    and str(r.get("verdict")) == self.verdict):
                return str(r.get("name"))
        return None

    def to_dict(self) -> dict:
        out = {"slo": self.slo, "verdict": self.verdict,
               "mode": self.mode}
        if self.note:
            out["note"] = self.note
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "PolicyRule":
        known = {"slo", "verdict", "mode", "note"}
        unknown = set(d) - known
        if unknown:
            raise PolicyError(
                f"unknown rule fields {sorted(unknown)} (known: "
                f"{sorted(known)})")
        return cls(**d)


class AdmissionPolicy:
    """An ordered rule list plus the defaults around it.

    ``decide(status_rows) -> (mode, reason)``: first armed rule wins;
    ``reason`` names the rule's slo/verdict (and note) for the switch
    span.  ``default_mode`` is the answer when nothing is armed —
    ``serve`` for production policies; tests and drills use it to
    force a mode without fabricating burn."""

    def __init__(self, rules: Sequence[PolicyRule] = (),
                 default_mode: str = "serve",
                 max_shed_frac: float = 1.0, name: str = "policy"):
        self.rules = list(rules)
        self.default_mode = str(default_mode)
        self.max_shed_frac = float(max_shed_frac)
        self.name = str(name)
        self.validate()

    def validate(self) -> None:
        if self.default_mode not in MODES:
            raise PolicyError(
                f"unknown default_mode {self.default_mode!r}; expected "
                f"one of {MODES}")
        if not (0.0 <= self.max_shed_frac <= 1.0):
            raise PolicyError(
                f"max_shed_frac must be in [0, 1], got "
                f"{self.max_shed_frac}")
        for r in self.rules:
            r.validate()

    def decide(self, status_rows: Sequence[dict]
               ) -> Tuple[str, str]:
        """The policy's answer for the CURRENT verdicts: first rule
        (in list order) whose (slo glob, verdict) pair is live."""
        for r in self.rules:
            hit = r.matches(status_rows)
            if hit is not None:
                reason = f"{hit}={r.verdict}"
                if r.note:
                    reason = f"{reason} ({r.note})"
                return r.mode, reason
        return self.default_mode, "default"

    # -- data form ------------------------------------------------------

    def to_dict(self) -> dict:
        return {"name": self.name, "default_mode": self.default_mode,
                "max_shed_frac": self.max_shed_frac,
                "rules": [r.to_dict() for r in self.rules]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_dict(cls, d: dict) -> "AdmissionPolicy":
        if not isinstance(d, dict) or "rules" not in d:
            raise PolicyError(
                f"policy must be an object with a 'rules' list, got "
                f"{d!r}")
        known = {"name", "default_mode", "max_shed_frac", "rules"}
        unknown = set(d) - known
        if unknown:
            raise PolicyError(
                f"unknown policy fields {sorted(unknown)} (known: "
                f"{sorted(known)})")
        return cls([PolicyRule.from_dict(r) for r in d["rules"]],
                   default_mode=str(d.get("default_mode", "serve")),
                   max_shed_frac=float(d.get("max_shed_frac", 1.0)),
                   name=str(d.get("name", "policy")))

    @classmethod
    def from_json(cls, text: str) -> "AdmissionPolicy":
        try:
            d = json.loads(text)
        except ValueError as e:
            raise PolicyError(f"bad policy JSON: {e}") from None
        return cls.from_dict(d)


def default_fleet_policy(max_shed_frac: float = 0.5
                         ) -> AdmissionPolicy:
    """The standing degrade ladder over ``default_fleet_slos``: shed
    only for burning availability/latency, serve-stale for burning
    freshness, queue through any warning — mildest sufficient answer
    first, shedding budget bounded."""
    return AdmissionPolicy([
        PolicyRule(slo="read_freshness", verdict="burning",
                   mode="stale_degrade",
                   note="stale beats absent for freshness burn"),
        PolicyRule(slo="read_availability", verdict="burning",
                   mode="shed", note="protect the survivors"),
        PolicyRule(slo="read_latency", verdict="burning", mode="shed",
                   note="latency burn means the queues are the problem"),
        PolicyRule(slo="*", verdict="warn", mode="queue",
                   note="absorb warns in the worker queues"),
    ], max_shed_frac=max_shed_frac, name="default_fleet_policy")
