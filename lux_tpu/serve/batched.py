"""Batched multi-source engines: a TRAILING query axis over shared shards.

One compiled iteration answers Q queries at once: the per-vertex state is
(P, V, Q) instead of (P, V), the per-edge gather reads (E, Q) rows, and
the segmented reducers (ops/segment.py) reduce each query lane
independently — the batched-aggregation idea behind Tascade's reduction
trees (arXiv:2311.15810) and the MXU-friendly batched reduces of
arXiv:1811.09736 mapped onto the existing pull hot loop.

Why TRAILING (not a vmapped leading axis): the per-edge index work
(src_pos gather decode, segment bookkeeping, scatter index handling) is
Q-independent; with Q on the minor axis each edge's indices are decoded
ONCE and move Q contiguous lanes, so the per-edge overhead amortizes by
Q.  A leading-axis vmap replays the index work per query — measured ~2x
SLOWER per query than sequential runs on the CPU fallback, while the
trailing layout measures >10x FASTER at Q=64 (tools/serve_bench.py).

Numerics: every reducer strategy (scan/scatter/cumsum/mxsum; "mxscan"
falls back to the VPU scan bitwise — the batched (E, Q) value shape is
outside its 1-D kernel) combines along the edge axis with query lanes
independent, so column q of a batched run is BITWISE equal to a
single-query run.  For SSSP the
converged distances are additionally a unique fixpoint of min-relaxation,
so the dense-iteration loop below lands on exactly the distances the
direction-optimized push engine (engine/push.py) produces —
tests/test_serve_batched.py pins both equalities.

Convergence is PER QUERY: a query whose state stopped changing is masked
out of the per-query round counters, so finished queries stop
contributing traversed edges while stragglers in the same batch keep
relaxing (relaxing a converged query is a no-op on its state — min
relaxation is idempotent at the fixpoint).
"""
from __future__ import annotations

import dataclasses
import threading
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import methods
from lux_tpu.graph.shards import PullShards, ShardSpec
from lux_tpu.ops import segment
from lux_tpu.program import BatchedSpecBacked, library


class QueryProgram:
    """Contract of a batched query app (the PullProgram analog with a
    trailing query axis).  ``queries`` is a traced (Q,) int32 vector of
    per-query parameters (sssp sources / ppr seeds)."""

    #: "sum" | "min" | "max" per-destination combiner.
    reduce: str
    #: True = iterate until every query's state stops changing (frontier
    #: apps); False = fixed iteration count (pagerank-style).
    fixpoint: bool

    def init_part(self, global_vid, degree, vtx_mask, queries):
        """(V,) part arrays + (Q,) queries -> (V, Q) initial state."""
        raise NotImplementedError

    def edge_value(self, src_state, weights):
        """(E, Q) gathered source states + (E,) weights -> (E, Q)."""
        raise NotImplementedError

    def apply(self, old_local, acc, arr, queries):
        """(V, Q) old state + (V, Q) reduced acc -> (V, Q) new state."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class MultiSourceSSSP(BatchedSpecBacked, QueryProgram):
    """Q-source BFS-SSSP (reference parity: unweighted hop counts,
    INF == nv): the Q-axis lift of the SAME declarative spec the
    one-shot program evaluates (program.library.SSSP with the
    ``start`` query parameter bound to the traced query vector —
    ISSUE 13), so each lane is models/sssp.SSSPProgram bitwise by
    construction, not by parallel implementation."""

    nv: int

    @property
    def spec(self):
        return library.SSSP

    @property
    def inf(self) -> int:
        return self.nv

    def _env(self):
        return {"inf": self.inf}


@dataclasses.dataclass(frozen=True)
class MultiSourcePPR(BatchedSpecBacked, QueryProgram):
    """Q-seed personalized PageRank: the Q-axis lift of
    program.library.PPR (``seed`` bound to the query vector) — column q
    equals a single-seed models/pagerank.PPRProgram pull run bitwise,
    because both EVALUATE the one spec."""

    nv: int
    alpha: float = library.ALPHA  # reference ALPHA

    @property
    def spec(self):
        return library.PPR

    def _env(self):
        # the serve engines are float32 (driver-enforced); the spec's
        # trailing cast is a no-op at that dtype
        return {"nv": self.nv, "alpha": self.alpha, "dtype": "float32"}


def _batched_iteration(prog, spec: ShardSpec, method, arrays, state,
                       queries, oarrays=None):
    """One batched pull iteration over the whole (P, V, Q) shard stack.

    ``oarrays`` (lux_tpu.mutate.overlay.OverlayArrays, vmapped with the
    shards) runs the step against the MUTATING graph: tombstoned base
    edges neutralize their (E, Q) values (reduce identity — exact for
    min/max, IEEE no-op addend for sums), then the fixed-capacity insert
    buffer gathers D extra source rows ((D, Q) — every query lane sees
    every insert) and scatter-combines them into the accumulator before
    apply.  Shapes are static, so delta occupancy never retraces the
    serving loop (the same LUX-J1 contract the one-shot engines pin)."""
    full = state.reshape((spec.gathered_size,) + state.shape[2:])
    reducer = segment.reducers()[prog.reduce]

    def part(arr, loc, oa=None):
        src = full[arr.src_pos]  # (E, Q)
        vals = prog.edge_value(src, arr.weights)
        if oa is not None:
            from lux_tpu.mutate import overlay as _ovl

            # (E,) mask against (E, Q) values: one broadcast lane axis
            vals = _ovl.mask_deleted(vals, oa.del_val[:, None],
                                     prog.reduce)
        acc = reducer(vals, arr.row_ptr, arr.head_flag, arr.dst_local,
                      method=method)
        if oa is not None:
            from lux_tpu.mutate import overlay as _ovl

            acc = _ovl.delta_scatter(
                acc, full, oa,
                lambda s, w: prog.edge_value(s, w), prog.reduce)
        return prog.apply(loc, acc, arr, queries)

    if oarrays is None:
        return jax.vmap(lambda arr, loc: part(arr, loc))(arrays, state)
    return jax.vmap(
        lambda arr, loc, oa: part(arr, loc, oa=oa)
    )(arrays, state, oarrays)


def _batched_init(prog, arrays, queries):
    return jax.vmap(
        lambda gvid, deg, mask: prog.init_part(gvid, deg, mask, queries)
    )(arrays.global_vid, arrays.degree, arrays.vtx_mask)


@lru_cache(maxsize=64)
def _compile_batched_init(prog):
    """Jitted per-batch state init, separate from the loop so the loop
    can DONATE the state buffer it receives (a fused init would leave
    nothing to donate; split, the loop's ping-pong reuses the init
    buffer's HBM instead of holding a second (P, V, Q) copy — the
    serving analog of the pull engine's ``donate=`` contract)."""

    @jax.jit
    def init(arrays, queries):
        return _batched_init(prog, arrays, queries)

    return init


@lru_cache(maxsize=64)
def _compile_batched_fixpoint(prog, spec: ShardSpec, method: str,
                              overlay_static=None):
    """Jitted multi-query fixpoint loop: iterate while ANY query is still
    changing; per-query round counters freeze as queries converge.  The
    compiled program is shape-specialized on Q (the warm cache keys on
    the Q bucket for exactly this reason).  ``state0`` (from
    _compile_batched_init) is DONATED — luxaudit LUX-J2 asserts the
    alias lands in the lowered module.  ``overlay_static``
    (mutate.overlay.OverlayStatic) compiles the mutation-overlay twin:
    the loop takes a trailing ``oarrays`` pytree and serves the merged
    graph — one trace per capacity, occupancy is data."""

    if overlay_static is None:
        @partial(jax.jit, donate_argnums=2)
        def run(arrays, queries, state0, max_iters):
            return _fixpoint_body(prog, spec, method, arrays, queries,
                                  state0, max_iters)
    else:
        @partial(jax.jit, donate_argnums=2)
        def run(arrays, queries, state0, max_iters, oarrays):
            return _fixpoint_body(prog, spec, method, arrays, queries,
                                  state0, max_iters, oarrays)

    return run


def _fixpoint_body(prog, spec, method, arrays, queries, state0, max_iters,
                   oarrays=None):
    q = queries.shape[0]

    def cond(c):
        _, it, active, _ = c
        return (it < max_iters) & jnp.any(active > 0)

    def body(c):
        state, it, active, rounds = c
        new = _batched_iteration(prog, spec, method, arrays, state,
                                 queries, oarrays=oarrays)
        changed = jnp.sum(
            (new != state).astype(jnp.int32), axis=(0, 1)
        )  # (Q,)
        # a query active at iteration entry walked every edge this
        # round; converged queries' counters stay frozen
        rounds = rounds + (active > 0).astype(jnp.int32)
        return new, it + 1, changed, rounds

    state, it, _, rounds = jax.lax.while_loop(
        cond, body,
        (state0, jnp.int32(0), jnp.ones((q,), jnp.int32),
         jnp.zeros((q,), jnp.int32)),
    )
    return state, it, rounds


@lru_cache(maxsize=64)
def _compile_batched_fixed(prog, spec: ShardSpec, method: str,
                           overlay_static=None):
    """Jitted fixed-iteration multi-query loop (ppr-style apps);
    ``state0`` donated and ``overlay_static`` compiling the overlay twin
    exactly like the fixpoint factory."""

    def _body(arrays, queries, state0, num_iters, oarrays=None):
        def body(_, state):
            return _batched_iteration(prog, spec, method, arrays, state,
                                      queries, oarrays=oarrays)

        state = jax.lax.fori_loop(0, num_iters, body, state0)
        q = queries.shape[0]
        return state, num_iters, jnp.full((q,), num_iters, jnp.int32)

    if overlay_static is None:
        @partial(jax.jit, donate_argnums=2)
        def run(arrays, queries, state0, num_iters):
            return _body(arrays, queries, state0, num_iters)
    else:
        @partial(jax.jit, donate_argnums=2)
        def run(arrays, queries, state0, num_iters, oarrays):
            return _body(arrays, queries, state0, num_iters, oarrays)

    return run


@dataclasses.dataclass
class BatchedResult:
    """One batch answer: per-query global state + work accounting."""

    state: np.ndarray  # (Q, nv)
    iters: int  # loop iterations the batch ran (max over queries)
    rounds: np.ndarray  # (Q,) int32 dense rounds each query was active
    traversed: list  # (Q,) python ints: edges walked per query

    def query_state(self, i: int) -> np.ndarray:
        return self.state[i]


def make_program(app: str, nv: int) -> QueryProgram:
    """The served app registry ('sssp' | 'ppr')."""
    if app == "sssp":
        return MultiSourceSSSP(nv=nv)
    if app == "ppr":
        return MultiSourcePPR(nv=nv)
    raise ValueError(f"unknown served app {app!r}; expected 'sssp' or 'ppr'")


class BatchedEngine:
    """One compiled batched engine bound to a (shards, app, Q, method)
    tuple.  ``run`` answers exactly ``q`` queries per call (the scheduler
    pads short batches); ``warm()`` executes one dummy batch so the XLA
    compile happens at service start, not on the first request.

    ``overlay_static`` (mutate.overlay.OverlayStatic) builds the LIVE
    twin: every ``run`` then REQUIRES the current OverlayArrays (and,
    for degree-consuming programs like ppr, the merged degree stack) —
    an engine compiled for a mutating graph must never silently answer
    from the base graph.  Occupancy is data: empty through full buffers
    hit one compiled program."""

    def __init__(self, shards: PullShards, app: str, q: int,
                 method: str = "auto", num_iters: int = 10,
                 max_iters: int = 10_000, device_arrays=None,
                 overlay_static=None):
        if q < 1:
            raise ValueError(f"q must be >= 1, got {q}")
        self.shards = shards
        self.app = app
        self.q = q
        self.prog = make_program(app, shards.spec.nv)
        self.method = methods.resolve(method, self.prog.reduce)
        self.num_iters = num_iters
        self.max_iters = max_iters
        self.overlay_static = overlay_static
        # ``device_arrays``: a pre-placed shard tree SHARED across
        # engines (the warm cache passes one per layout) — without it
        # every (app, Q-bucket) engine would hold its own full copy of
        # the O(E) graph arrays on device
        self._arrays = (device_arrays if device_arrays is not None
                        else jax.tree.map(jnp.asarray, shards.arrays))
        self._init = _compile_batched_init(self.prog)
        if self.prog.fixpoint:
            self._run = _compile_batched_fixpoint(
                self.prog, shards.spec, self.method, overlay_static)
            self._stop = max_iters
        else:
            self._run = _compile_batched_fixed(
                self.prog, shards.spec, self.method, overlay_static)
            self._stop = num_iters
        self._warmed = False
        self._warm_lock = threading.Lock()

    def _empty_oarrays(self):
        from lux_tpu.mutate import overlay as _ovl

        return jax.tree.map(jnp.asarray, _ovl.empty_overlay_arrays(
            self.shards, self.overlay_static.cap))

    def warm(self, oarrays=None) -> "BatchedEngine":
        """Trace + compile + execute one dummy batch (queries = vertex 0).
        Serialized: concurrent pumps (scheduler thread + a draining
        caller) must not duplicate a multi-second compile.  An overlay
        engine warms against the given (or the empty) OverlayArrays —
        same trace as any occupancy."""
        with self._warm_lock:
            if not self._warmed:
                q0 = jnp.zeros((self.q,), jnp.int32)
                extra = ()
                if self.overlay_static is not None:
                    extra = (oarrays if oarrays is not None
                             else self._empty_oarrays(),)
                out = self._run(self._arrays, q0,
                                self._init(self._arrays, q0),
                                jnp.int32(1), *extra)
                jax.block_until_ready(out[0])
                self._warmed = True
        return self

    def run(self, queries, oarrays=None, degree=None) -> BatchedResult:
        """Answer ``queries`` ((q,) int vertex ids) -> BatchedResult.
        ``oarrays``: the current mutation OverlayArrays (required iff the
        engine was built with ``overlay_static``).  ``degree``: merged
        (P, V) out-degree stack substituting the base degrees for
        degree-consuming programs (an ordinary array arg — no retrace)."""
        queries = np.asarray(queries, np.int32)
        if queries.shape != (self.q,):
            raise ValueError(
                f"engine is compiled for Q={self.q}; got {queries.shape}")
        nv = self.shards.spec.nv
        if queries.size and (queries.min() < 0 or queries.max() >= nv):
            raise ValueError(f"query vertex out of range [0, {nv})")
        if (self.overlay_static is None) != (oarrays is None):
            # mirror engine/push.py's pairing guard: a silently-ignored
            # overlay would serve base-graph answers under a live graph
            raise ValueError(
                "overlay_static and oarrays must be passed together: "
                "BatchedEngine(..., overlay_static=ostatic) and "
                "run(..., oarrays=oarr)")
        q_dev = jnp.asarray(queries)
        arrays = self._arrays
        if degree is not None:
            arrays = arrays._replace(degree=jnp.asarray(degree))
        extra = () if oarrays is None else (oarrays,)
        # the freshly-initialized state is donated to the loop: one
        # (P, V, Q) buffer in the hot loop, not two
        state, it, rounds = self._run(
            arrays, q_dev, self._init(arrays, q_dev),
            jnp.int32(self._stop), *extra)
        self._warmed = True
        rounds = np.asarray(rounds)
        # (P, V, Q) -> (nv, Q) -> (Q, nv); per-query traversed edges are
        # exact host ints (dense rounds walk every real edge once)
        glob = self.shards.scatter_to_global(np.asarray(state))
        return BatchedResult(
            state=np.ascontiguousarray(glob.T),
            iters=int(it),
            rounds=rounds,
            traversed=[int(r) * self.shards.spec.ne for r in rounds],
        )
