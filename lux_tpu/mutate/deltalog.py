"""Edge delta-log: batched insert/delete against a frozen `.lux` base.

Every engine in the repo consumes an immutable snapshot; Lux itself
(PAPER.md) reloads and replans on any change.  The delta-log makes edge
mutation first-class WITHOUT reshaping anything the engines trace over:

  * mutations arrive as batches of ``(src, dst, op[, weight])`` rows and
    are resolved eagerly against the base CSC — a delete tombstones one
    matching live edge (the newest insert first, else the newest base
    edge), an insert appends to the in-memory insert arrays;
  * the resolved state is two fixed-meaning structures: a boolean
    tombstone mask over the base edge slots, and an append-ordered live
    insert list.  ``overlay.py`` turns those into the statically-shaped
    per-part device buffers the hot loops consume (capacity
    ``LUX_DELTA_CAP``; overflow triggers compaction, never a reshape);
  * an optional on-disk JOURNAL makes the log crash-safe in the repo's
    no-pickle npz+json idiom: each batch is one npz (tmp + fsync +
    rename) followed by a separate fsync'd ``.ok`` marker — replay
    consumes committed batches in sequence and stops at the first
    missing marker, so a kill between the append and the marker loses
    exactly that uncommitted batch and nothing else.

The MERGED graph is defined deterministically: base edges in base CSC
order minus tombstones, then live inserts in append order, through
``graph.csc.from_edge_list`` (whose stable dst-sort keeps that relative
order per destination).  Compaction (``compact.py``) materializes
exactly this definition, so "delta-log then compact" is bitwise equal
to building the merged graph from scratch — pinned by
tests/test_mutate.py's property test.
"""
from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from lux_tpu.graph.csc import HostGraph, from_edge_list

#: journal layout version — bump on any change to the meta/batch format
JOURNAL_FORMAT = 1

OP_DELETE = 0
OP_INSERT = 1


def _fsync_write(path: str, data: bytes) -> None:
    """Write bytes durably: tmp file + flush + fsync + atomic rename +
    DIRECTORY fsync — without the last, the rename's directory entry
    can flush after a later file's, and the batch-before-marker
    ordering the crash-replay protocol depends on would not be
    durable.

    Fault site ``journal.write`` (lux_tpu.fault): a ``torn`` rule lands
    HALF the bytes at the final path with no rename and no fsync — the
    on-disk shape of a non-atomic writer caught by a crash — then
    raises InjectedKill; the replay protocol must discard exactly that
    file (no marker ever follows it)."""
    from lux_tpu import fault

    rule = fault.ppoint("journal.write", file=os.path.basename(path))
    if rule is not None and rule.action == "torn":
        with open(path, "wb") as f:
            f.write(data[:max(len(data) // 2, 1)])
        raise fault.InjectedKill(f"injected torn write at {path}")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _base_sha(g: HostGraph) -> str:
    """Content fingerprint of a base graph (row_ptr + col_idx + weights
    bytes).  The journal meta carries it so a journal can never be
    replayed against the WRONG base — nv/ne alone cannot tell two
    epochs apart when churn conserves the edge count (exactly the
    bench's balanced-churn pattern)."""
    import hashlib

    h = hashlib.sha1()
    h.update(np.ascontiguousarray(g.row_ptr).tobytes())
    h.update(np.ascontiguousarray(g.col_idx).tobytes())
    if g.weights is not None:
        h.update(np.ascontiguousarray(g.weights).tobytes())
    return h.hexdigest()[:16]


class DeltaOverflow(RuntimeError):
    """A part's live-insert count exceeded the overlay capacity — the
    caller must compact (MutableGraph does so automatically)."""


class DeltaLog:
    """Resolved edge mutations against one base HostGraph.

    The log owns NO device state: it is the host-side source of truth
    the overlay builders (``overlay.py``) and the compactor
    (``compact.py``) read.  ``journal_dir=None`` keeps the log purely
    in-memory (tests, ephemeral churn); a directory makes every applied
    batch durable before ``apply`` returns.
    """

    def __init__(self, base: HostGraph,
                 journal_dir: Optional[str] = None,
                 replay: bool = True):
        self.base = base
        self._dst_of_edge = base.dst_of_edges() if base.ne else \
            np.zeros(0, np.int32)
        self.del_base = np.zeros(base.ne, bool)
        self.ins_src = np.zeros(0, np.int64)
        self.ins_dst = np.zeros(0, np.int64)
        self.ins_w = np.zeros(0, np.int64)
        self.ins_live = np.zeros(0, bool)
        self.batches_applied = 0
        self.journal_dir = journal_dir
        if journal_dir is not None:
            self._journal_open(replay=replay)

    # ------------------------------------------------------------------
    # mutation API
    # ------------------------------------------------------------------

    def apply(self, src, dst, op, weight=None,
              journal_extra: Optional[dict] = None) -> None:
        """Apply ONE batch of edge mutations (arrays of equal length;
        ``op`` rows are OP_INSERT/OP_DELETE).  Rows resolve in order —
        a batch may insert an edge and delete it again.  Deleting an
        edge that does not exist (in base or live inserts) raises
        KeyError: silent no-op deletes would let the log and the true
        graph drift apart.

        ``journal_extra``: extra named uint8/int arrays journaled WITH
        the batch npz and ignored by replay (the live sequencer rides
        its idempotent write-ids here) — same format version, older
        readers skip unknown keys.

        Atomicity: the WHOLE batch resolves against the in-memory
        state first (an invalid row restores the pre-batch state and
        raises — memory never holds half a batch), and only a batch
        that resolved is journaled (durably, marker last) — the
        journal can never commit a batch that cannot replay.  A crash
        after the resolve but before the marker loses exactly this
        batch; ``apply`` had not returned, so nothing was promised."""
        src = np.atleast_1d(np.asarray(src, np.int64))
        dst = np.atleast_1d(np.asarray(dst, np.int64))
        op = np.atleast_1d(np.asarray(op, np.int8))
        w = (np.zeros(len(src), np.int64) if weight is None
             else np.atleast_1d(np.asarray(weight, np.int64)))
        if not (len(src) == len(dst) == len(op) == len(w)):
            raise ValueError("batch arrays must share one length")
        if len(src) and (src.min() < 0 or src.max() >= self.base.nv
                         or dst.min() < 0 or dst.max() >= self.base.nv):
            raise ValueError("edge endpoints out of [0, nv) — the delta"
                             " log mutates edges, never the vertex set")
        bad = set(journal_extra or ()) & {"src", "dst", "op", "w"}
        if bad:
            # validated BEFORE the batch touches memory: a savez kwarg
            # collision would raise after _apply_resolved committed —
            # memory one batch ahead of the journal, the drift the
            # atomicity contract forbids
            raise ValueError(
                f"journal_extra keys {sorted(bad)} collide with the "
                "reserved batch fields ('src', 'dst', 'op', 'w')")
        # snapshot the resolution state: growth rebinds the ins_*
        # arrays (never mutates them), so references suffice there;
        # del_base / ins_live ARE mutated in place and copy
        snap = (self.del_base.copy(), self.ins_src, self.ins_dst,
                self.ins_w, self.ins_live.copy(), self.batches_applied)
        try:
            self._apply_resolved(src, dst, op, w)
        except BaseException:
            (self.del_base, self.ins_src, self.ins_dst, self.ins_w,
             self.ins_live, self.batches_applied) = snap
            raise
        if self.journal_dir is not None:
            from lux_tpu import fault

            seq = self._journal_write_batch(src, dst, op, w,
                                            self.batches_applied - 1,
                                            extra=journal_extra)
            # THE crash window the replay protocol is built around:
            # batch npz durable, marker not yet — fault drills
            # (kill_before_marker / kill_at("after_delta_before_marker"))
            # inject the kill exactly here
            fault.ppoint("journal.before_marker", seq=seq)
            self._journal_mark(seq)

    def _apply_resolved(self, src, dst, op, w) -> None:
        """Resolve one batch in row order, growing the insert arrays
        ONCE at the end (np.append per row is O(rows^2) in copies —
        a 1% churn batch at scale 20 is ~8e4 rows)."""
        add_s: list = []
        add_d: list = []
        add_w: list = []
        add_live: list = []
        for i in range(len(src)):
            o, u, v = int(op[i]), int(src[i]), int(dst[i])
            if o == OP_INSERT:
                add_s.append(u)
                add_d.append(v)
                add_w.append(int(w[i]))
                add_live.append(True)
            elif o == OP_DELETE:
                # newest matching live insert from THIS batch first ...
                for j in range(len(add_s) - 1, -1, -1):
                    if add_live[j] and add_s[j] == u and add_d[j] == v:
                        add_live[j] = False
                        break
                else:
                    # ... then the committed inserts / base edges
                    self._delete_one(u, v)
            else:
                raise ValueError(f"unknown op {o} at row {i}")
        if add_s:
            self.ins_src = np.concatenate(
                [self.ins_src, np.asarray(add_s, np.int64)])
            self.ins_dst = np.concatenate(
                [self.ins_dst, np.asarray(add_d, np.int64)])
            self.ins_w = np.concatenate(
                [self.ins_w, np.asarray(add_w, np.int64)])
            self.ins_live = np.concatenate(
                [self.ins_live, np.asarray(add_live, bool)])
        self.batches_applied += 1

    def _delete_one(self, u: int, v: int) -> None:
        # newest matching live insert first …
        hits = np.flatnonzero(self.ins_live & (self.ins_src == u)
                              & (self.ins_dst == v))
        if len(hits):
            self.ins_live[hits[-1]] = False
            return
        # … else the newest matching live base edge in v's CSC segment
        lo, hi = int(self.base.row_ptr[v]), int(self.base.row_ptr[v + 1])
        seg = np.flatnonzero(
            (np.asarray(self.base.col_idx[lo:hi]) == u)
            & ~self.del_base[lo:hi])
        if not len(seg):
            raise KeyError(f"delete({u}, {v}): no live edge matches")
        self.del_base[lo + seg[-1]] = True

    # ------------------------------------------------------------------
    # resolved views
    # ------------------------------------------------------------------

    def live_inserts(self):
        """(src, dst, w) int64 arrays of live inserts, append order."""
        m = self.ins_live
        return self.ins_src[m], self.ins_dst[m], self.ins_w[m]

    def deleted_edges(self) -> np.ndarray:
        """Sorted base CSC edge indices currently tombstoned."""
        return np.flatnonzero(self.del_base)

    @property
    def empty(self) -> bool:
        return not (self.del_base.any() or self.ins_live.any())

    def stats(self) -> dict:
        return {
            "inserts_live": int(self.ins_live.sum()),
            "inserts_total": int(len(self.ins_live)),
            "deletes_base": int(self.del_base.sum()),
            "batches": self.batches_applied,
        }

    def merged_edge_list(self):
        """The merged graph's deterministic edge sequence: live base
        edges in base CSC order, then live inserts in append order.
        Weights keep the base dtype (int64 when the base is unweighted
        but inserts carry weights — the merged graph is then weighted
        iff the base was; insert weights are dropped, matching the
        engines' unweighted contract)."""
        g = self.base
        live = ~self.del_base
        bsrc = np.asarray(g.col_idx, np.int64)[live]
        bdst = np.asarray(self._dst_of_edge, np.int64)[live]
        isrc, idst, iw = self.live_inserts()
        src = np.concatenate([bsrc, isrc])
        dst = np.concatenate([bdst, idst])
        if g.weights is None:
            return src, dst, None
        bw = np.asarray(g.weights)[live]
        w = np.concatenate([bw, iw.astype(bw.dtype)])
        return src, dst, w

    def merged_graph(self) -> HostGraph:
        """The merged HostGraph — bitwise equal to from_edge_list over
        merged_edge_list (this IS that call; compaction and the test
        oracle both anchor on it)."""
        src, dst, w = self.merged_edge_list()
        return from_edge_list(src, dst, self.base.nv, weights=w)

    def merged_out_degrees(self) -> np.ndarray:
        """Out-degrees of the merged graph in O(delta) on top of the
        base histogram (pagerank's apply divides by these)."""
        deg = self.base.out_degrees().astype(np.int64)
        dele = self.deleted_edges()
        if len(dele):
            np.subtract.at(deg, np.asarray(self.base.col_idx,
                                           np.int64)[dele], 1)
        isrc, _, _ = self.live_inserts()
        if len(isrc):
            np.add.at(deg, isrc, 1)
        return deg.astype(np.int32)

    # ------------------------------------------------------------------
    # journal (npz + json, crash-safe, no pickle)
    # ------------------------------------------------------------------

    def _meta_path(self) -> str:
        return os.path.join(self.journal_dir, "meta.json")

    def _batch_path(self, seq: int) -> str:
        return os.path.join(self.journal_dir, f"batch_{seq:08d}.npz")

    def _marker_path(self, seq: int) -> str:
        return os.path.join(self.journal_dir, f"batch_{seq:08d}.ok")

    def _journal_open(self, replay: bool) -> None:
        os.makedirs(self.journal_dir, mode=0o700, exist_ok=True)
        meta_path = self._meta_path()
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                meta = json.loads(f.read().decode())
            if meta.get("format") != JOURNAL_FORMAT:
                raise ValueError(
                    f"journal {self.journal_dir}: format "
                    f"{meta.get('format')} != {JOURNAL_FORMAT}")
            sha = _base_sha(self.base)
            if ((meta["nv"], meta["ne"]) != (self.base.nv, self.base.ne)
                    or meta.get("base_sha") != sha):
                raise ValueError(
                    f"journal {self.journal_dir} was written against a "
                    f"different base (nv={meta['nv']} ne={meta['ne']} "
                    f"sha={meta.get('base_sha')}; this base is "
                    f"nv={self.base.nv} ne={self.base.ne} sha={sha})")
            if replay:
                self._journal_replay()
        else:
            _fsync_write(meta_path, json.dumps({
                "format": JOURNAL_FORMAT,
                "nv": int(self.base.nv),
                "ne": int(self.base.ne),
                "weighted": self.base.weights is not None,
                "base_sha": _base_sha(self.base),
            }).encode())

    def _journal_replay(self) -> None:
        """Re-apply committed batches in sequence; stop at the first
        missing marker (an uncommitted append from a crashed writer —
        its npz, if present, is ignored AND removed so the sequence
        number is reusable)."""
        seq = 0
        while True:
            bpath, mpath = self._batch_path(seq), self._marker_path(seq)
            if not os.path.exists(mpath):
                if os.path.exists(bpath):
                    os.remove(bpath)  # torn append: marker never landed
                break
            if not os.path.exists(bpath):
                # marker without batch: a torn directory state from a
                # crash on a filesystem that reordered the entries —
                # treat as uncommitted (the batch bytes are gone)
                os.remove(mpath)
                break
            with np.load(bpath, allow_pickle=False) as z:
                self._apply_resolved(z["src"], z["dst"], z["op"], z["w"])
            seq += 1

    def _journal_write_batch(self, src, dst, op, w, seq=None,
                             extra: Optional[dict] = None) -> int:
        """Durably append ONE batch npz; the batch is NOT committed
        until _journal_mark writes its marker (the crash-window the
        replay protocol is built around)."""
        if seq is None:
            seq = self.batches_applied
        import io

        buf = io.BytesIO()
        np.savez(buf, src=src, dst=dst, op=op, w=w, **(extra or {}))
        _fsync_write(self._batch_path(seq), buf.getvalue())
        return seq

    def _journal_mark(self, seq: int) -> None:
        _fsync_write(self._marker_path(seq), b"ok\n")

    def journal_reset(self) -> None:
        """Drop all committed batches AND the meta (post-compaction
        rotation): the new base snapshot already contains them, and the
        next DeltaLog opened on this dir (against the NEW base) writes
        a fresh meta.  Crash-safe for the CALLER's protocol: compact.py
        persists the merged snapshot (fsync'd) BEFORE calling this, so
        a kill anywhere in here leaves either the full old journal
        (replayable against the old base — stale but consistent) or a
        marker-gapped prefix that replay correctly ignores; it can
        never half-apply a batch."""
        if self.journal_dir is None:
            return
        last = 0
        while os.path.exists(self._marker_path(last)):
            last += 1
        # remove DESCENDING, marker before npz: a crash anywhere in
        # here leaves an intact committed PREFIX (a consistent
        # old-epoch journal) — ascending removal would leave a stale
        # committed SUFFIX that later sequence numbers could resurrect
        # into the new epoch
        for seq in range(last - 1, -1, -1):
            os.remove(self._marker_path(seq))
            if os.path.exists(self._batch_path(seq)):
                os.remove(self._batch_path(seq))
        if os.path.exists(self._meta_path()):
            os.remove(self._meta_path())
