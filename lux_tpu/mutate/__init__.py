"""lux_tpu.mutate — dynamic-graph mutation as a first-class workload.

The frozen-`.lux` engines gain live edge churn without retrace:

  deltalog  — batched insert/delete resolved against the base CSC,
              with a crash-safe npz+json journal (no pickle);
  overlay   — statically-shaped per-part device buffers (tombstone
              mask + fixed-capacity insert slots, ``LUX_DELTA_CAP``)
              the overlay-aware hot loops consume — empty/half/full
              buffers trace identically (luxaudit LUX-J1);
  graph     — MutableGraph: base + log + layouts + auto-compaction;
  refresh   — warm-restart PageRank/CC/SSSP from prior converged
              state, seeding only delta-touched vertices;
  compact   — merge the log into a new snapshot, invalidate only the
              plan-cache buckets whose index arrays changed
              (PLAN_FORMAT 5), publish to a live fleet.

``refresh``/``compact`` import the engines, and the engines lazily
import ``overlay`` — so this package eagerly exposes only the
engine-free half and resolves the rest on first attribute access.
"""
from __future__ import annotations

from lux_tpu.mutate.deltalog import (  # noqa: F401
    DeltaLog,
    DeltaOverflow,
    OP_DELETE,
    OP_INSERT,
)
from lux_tpu.mutate.overlay import (  # noqa: F401 — before graph: it
    OverlayArrays,                    # imports overlay through the
    OverlayStatic,                    # package, mid-initialization
    build_pull_overlay,
    build_push_overlay,
    delta_cap,
)
from lux_tpu.mutate.graph import MutableGraph  # noqa: F401

_LAZY = ("refresh", "compact")


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return importlib.import_module(f"lux_tpu.mutate.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
