"""MutableGraph: one live graph = base snapshot + delta-log + layouts.

The driver-facing bundle tying the mutation subsystem together: it owns
the base HostGraph, the DeltaLog (optionally journaled), the lazily
built pull/push shard layouts of the BASE (which the overlay-aware hot
loops keep consuming unchanged across churn), the cached push CSR
permutations (so tombstone patching is O(deleted) per refresh, not a
re-sort), and the compaction trigger: a batch that overflows any
part's delta capacity compacts FIRST (merging the log into a new base,
reusing the old cuts so untouched plan-cache buckets survive —
PLAN_FORMAT 5 keys per bucket), then applies.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from lux_tpu.graph.csc import HostGraph
from lux_tpu.mutate import overlay as ovl
from lux_tpu.mutate.deltalog import DeltaLog


class MutableGraph:
    """A mutating graph the engines can serve without retrace.

    ``num_parts`` fixes the shard layout; ``cap`` (default
    ``LUX_DELTA_CAP``) the per-part delta capacity; ``journal_dir``
    makes mutations durable (crash-replay on reopen).  ``snapshot``
    names where compaction writes merged ``.lux`` snapshots (falls
    back to in-memory-only compaction when None)."""

    def __init__(self, g: HostGraph, num_parts: int = 1,
                 cap: Optional[int] = None,
                 journal_dir: Optional[str] = None,
                 snapshot: Optional[str] = None):
        self.base = g
        self.num_parts = num_parts
        self.cap = ovl.delta_cap(cap)
        self.snapshot = snapshot
        self.log = DeltaLog(g, journal_dir=journal_dir)
        self.compactions = 0
        self._pull = None
        self._push = None
        self._csr = None          # base out-edge view (refresh cascades)
        self._csr_perms = None    # push CSC->CSR slot maps
        self._version = 0         # bumps on every applied batch/compact

    # ------------------------------------------------------------------
    # layouts (base graph, default fill order — the overlay contract)
    # ------------------------------------------------------------------

    @property
    def pull_shards(self):
        if self._pull is None:
            from lux_tpu.graph.shards import build_pull_shards

            self._pull = build_pull_shards(self.base, self.num_parts)
        return self._pull

    @property
    def push_shards(self):
        if self._push is None:
            from lux_tpu.graph.push_shards import build_push_shards

            self._push = build_push_shards(self.base, self.num_parts)
            # share the pull layout (one O(E) build, one overlay target)
            self._pull = self._push.pull
        return self._push

    def base_csr(self):
        """(csr_row_ptr, csr_dst, csr_perm) of the BASE graph, cached —
        the refresh deletion cascades walk out-edges through this."""
        if self._csr is None:
            self._csr = self.base.to_csr()
        return self._csr

    def csr_perms(self):
        if self._csr_perms is None:
            self._csr_perms = ovl.push_csr_perms(self.push_shards,
                                                 self.base)
        return self._csr_perms

    # ------------------------------------------------------------------
    # mutation
    # ------------------------------------------------------------------

    def apply(self, src, dst, op, weight=None) -> dict:
        """Apply one mutation batch; when it would overflow any part's
        delta capacity, compact FIRST (fold the standing log into the
        base — the prior converged app states equal that merged graph,
        so warm refresh stays sound) and THEN apply, keeping the new
        batch in the log.  Never reshapes a device buffer — the
        overlay cap is invariant, the BASE absorbs the log.  A batch
        that ALONE exceeds the capacity raises DeltaOverflow (folding
        it too would silently invalidate every caller-held prior
        state): split it, raise LUX_DELTA_CAP, or compact() and
        cold-recompute.  Returns the log stats, with ``compacted`` set
        when a compaction ran."""
        from lux_tpu import obs
        from lux_tpu.mutate.deltalog import DeltaOverflow

        with obs.span("mutate.apply", rows=int(np.size(src))) as sp:
            compacted = False
            if not self.log.empty and self._would_overflow(dst, op):
                self.compact()
                compacted = True
            self.log.apply(src, dst, op, weight)
            self._version += 1
            if self._overflowed():
                raise DeltaOverflow(
                    "one batch exceeds the per-part delta capacity "
                    f"{self.cap} (LUX_DELTA_CAP) on its own — split the "
                    "batch, raise the capacity, or compact() and "
                    "cold-recompute the app states")
            sp.set(compacted=compacted)
        return {**self.log.stats(), "compacted": compacted}

    def _would_overflow(self, dst, op) -> bool:
        """Conservative pre-check: standing per-part occupancy plus the
        batch's inserts (in-batch insert/delete pairs are not netted —
        compacting a little early is harmless, late is a hard error)."""
        from lux_tpu.graph.partition import part_of_vertex
        from lux_tpu.mutate.deltalog import OP_INSERT

        occ = np.asarray(
            ovl.occupancy(self.pull_shards, self.log,
                          self.cap)["per_part"], np.int64)
        dstb = np.atleast_1d(np.asarray(dst, np.int64))
        opb = np.atleast_1d(np.asarray(op, np.int64))
        ins = dstb[opb == OP_INSERT]
        if len(ins):
            occ = occ + np.bincount(
                part_of_vertex(np.asarray(self.pull_shards.cuts), ins),
                minlength=len(occ))
        return bool(occ.max() > self.cap)

    def _overflowed(self) -> bool:
        occ = ovl.occupancy(self.pull_shards, self.log, self.cap)
        return occ["max"] > self.cap

    # ------------------------------------------------------------------
    # overlays
    # ------------------------------------------------------------------

    def pull_overlay(self):
        """(OverlayStatic, OverlayArrays) for the pull engine."""
        return ovl.build_pull_overlay(self.pull_shards, self.log,
                                      self.cap)

    def push_overlay(self):
        """(OverlayStatic, OverlayArrays, patched PushArrays)."""
        return ovl.build_push_overlay(self.push_shards, self.log,
                                      self.cap,
                                      csr_perms=self.csr_perms())

    def occupancy(self) -> dict:
        return ovl.occupancy(self.pull_shards, self.log, self.cap)

    # ------------------------------------------------------------------
    # compaction
    # ------------------------------------------------------------------

    def compact(self, path: Optional[str] = None,
                reuse_cuts: bool = True) -> dict:
        """Merge the delta-log into a new base (see mutate.compact for
        the snapshot/journal/invalidation protocol); rebuilt layouts
        keep the old cuts by default so only the plan-cache buckets
        whose index arrays changed are invalidated."""
        from lux_tpu.mutate import compact as compact_mod

        report = compact_mod.compact_mutable(
            self, path=path if path is not None else self.snapshot,
            reuse_cuts=reuse_cuts)
        self.compactions += 1
        self._version += 1
        return report

    @property
    def version(self) -> int:
        return self._version
