"""Incremental refresh: warm-restart the apps after edge churn.

Cold recompute pays load + shard build + plan + compile + full
iteration; refresh pays an O(delta) host analysis plus a warm re-entry
of the ALREADY-COMPILED overlay hot loop from the prior converged
state — the ROADMAP's ">=10x over cold recompute at 1% churn" bar
(measured table in docs/DYNAMIC.md).

Per-app exactness contracts (pinned by tests/test_mutate.py):

  * SSSP (min, int32) / CC (max, int32): the merged graph's fixpoint is
    UNIQUE, so any sound refresh converges to the cold rebuild's exact
    bits.  Soundness under deletion needs an invalidation pass — a
    monotone engine cannot un-relax:
      - SSSP: dirty = destinations of deleted TIGHT edges
        (dist[v] == dist[u] + w), closed over tight out-edges (the
        classic decremental cascade, over-approximation is safe);
        dirty resets to INF, the frontier seeds with every LIVE
        in-neighbor of the dirty set (they never change, so they must
        push first) plus insert endpoints.  Needs strictly positive
        weights (a zero-weight tight cycle breaks the cascade's
        induction) — BFS hops are 1, weighted graphs are validated.
      - CC: dirty = every vertex whose label belongs to a component
        touched by a deletion (labels of deleted-edge endpoints);
        dirty resets to own-id and seeds ACTIVE (the cold contract,
        restricted to the dirty region) plus the region's live
        in-neighbors.
  * PageRank (f32 sum): warm state = prior ranks rescaled for changed
    out-degrees; converge to an EXACT f32 fixpoint (residual == 0) of
    the overlay map.  Sum associations differ between the overlay
    decomposition and a cold-rebuilt layout, so per-iteration equality
    is exact-arithmetic only; the CONVERGED fixpoints are compared
    (bitwise in practice under the alpha=0.15 contraction — the bench
    rows and tests check, never assume).
"""
from __future__ import annotations

from collections import deque
from functools import lru_cache

import numpy as np

from lux_tpu.graph.shards import global_to_stacked


def _stack(shards, vec, fill=0):
    """Global (nv,) -> the shards' (P, nv_pad) stacked layout with
    ``fill`` on padding slots (global_to_stacked zero-fills; the push
    apps keep INF there, matching init_state)."""
    out = global_to_stacked(np.asarray(shards.cuts),
                            shards.arrays.vtx_mask.shape[1], vec)
    if fill:
        out = np.where(np.asarray(shards.arrays.vtx_mask), out, fill)
    return out.astype(vec.dtype)


# ---------------------------------------------------------------------------
# deletion-invalidation analysis (host, O(affected))
# ---------------------------------------------------------------------------


def _dead_edges(mg, weighted: bool):
    """(src, dst, w) of EVERY edge removed by the log: base tombstones
    plus dead inserts.  Dead inserts matter when refreshes interleave
    with batches without compaction — the prior state may have depended
    on an insert a later batch deleted; over-including inserts that
    were never live during the prior convergence is safe (the dirty
    analysis only over-approximates)."""
    g = mg.base
    dele = mg.log.deleted_edges()
    dst_of = np.searchsorted(np.asarray(g.row_ptr, np.int64), dele,
                             side="right") - 1
    src_of = np.asarray(g.col_idx, np.int64)[dele]
    w_of = (np.asarray(g.weights, np.int64)[dele]
            if weighted else np.ones(len(dele), np.int64))
    dead = ~mg.log.ins_live
    dsrc = mg.log.ins_src[dead]
    ddst = mg.log.ins_dst[dead]
    dw = (mg.log.ins_w[dead] if weighted
          else np.ones(int(dead.sum()), np.int64))
    return (np.concatenate([src_of, dsrc]),
            np.concatenate([dst_of, ddst]),
            np.concatenate([w_of, dw]))


def sssp_dirty(mg, dist: np.ndarray, start: int,
               weighted: bool = False) -> np.ndarray:
    """(nv,) bool: vertices whose distance a deletion may invalidate.
    Over-approximating closure over TIGHT out-edges (live base edges
    AND live inserts) of the old distance field — a non-dirty vertex
    keeps a shortest path avoiding every removed edge, so its old
    distance stays exact (the boundary the warm relaxation restarts
    from)."""
    g = mg.base
    dirty = np.zeros(g.nv, bool)
    rs, rd, rw = _dead_edges(mg, weighted)
    if not len(rs):
        return dirty
    if weighted:
        wall = np.asarray(g.weights, np.int64)
        _, _, liw = mg.log.live_inserts()
        if ((len(wall) and wall.min() <= 0)
                or (len(rw) and rw.min() <= 0)
                or (len(liw) and liw.min() <= 0)):
            raise ValueError(
                "sssp refresh under deletion needs strictly positive "
                "weights (zero-weight tight cycles break the "
                "invalidation cascade) — compact instead")
    dist = np.asarray(dist, np.int64)
    tight = dist[rd] == dist[rs] + rw
    seeds = np.unique(rd[tight])
    seeds = seeds[seeds != start]  # the source's 0 never depends on edges
    if not len(seeds):
        return dirty
    csr_row_ptr, csr_dst, csr_perm = mg.base_csr()
    w_of = (np.asarray(g.weights, np.int64) if weighted
            else np.ones(g.ne, np.int64))
    csr_w = w_of[csr_perm]
    csr_live = (~mg.log.del_base)[csr_perm]
    # live-insert out-adjacency for the cascade (src -> [(dst, w)])
    ins_adj: dict = {}
    isrc, idst, iw = mg.log.live_inserts()
    for j in range(len(isrc)):
        ins_adj.setdefault(int(isrc[j]), []).append(
            (int(idst[j]), int(iw[j]) if weighted else 1))
    dirty[seeds] = True
    dq = deque(int(v) for v in seeds)
    while dq:
        v = dq.popleft()
        lo, hi = int(csr_row_ptr[v]), int(csr_row_ptr[v + 1])
        nbrs = [(int(csr_dst[k]), int(csr_w[k]))
                for k in range(lo, hi) if csr_live[k]]
        nbrs += ins_adj.get(v, [])
        for t, w in nbrs:
            # removed edges were handled by the seed rule
            if (not dirty[t] and t != start
                    and dist[t] == dist[v] + w):
                dirty[t] = True
                dq.append(t)
    return dirty


def cc_dirty(mg, labels: np.ndarray) -> np.ndarray:
    """(nv,) bool: every vertex whose converged label belongs to a
    label-component containing a removed edge endpoint (base tombstones
    AND dead inserts) — a deletion may split the component, so the
    whole region recomputes from own-ids (max-label cannot decrease
    incrementally)."""
    g = mg.base
    rs, rd, _ = _dead_edges(mg, weighted=False)
    if not len(rs):
        return np.zeros(g.nv, bool)
    labels = np.asarray(labels, np.int64)
    bad = np.unique(np.concatenate([labels[rs], labels[rd]]))
    return np.isin(labels, bad)


def _live_in_neighbors(mg, region: np.ndarray) -> np.ndarray:
    """(nv,) bool: sources of LIVE base in-edges into ``region`` plus
    live insert sources targeting it — the boundary that must seed the
    warm frontier (its members never change, so only the initial queue
    can make them push)."""
    g = mg.base
    seeds = np.zeros(g.nv, bool)
    if region.any():
        dst_of = g.dst_of_edges()
        m = region[dst_of] & ~mg.log.del_base
        seeds[np.asarray(g.col_idx, np.int64)[m]] = True
    isrc, idst, _ = mg.log.live_inserts()
    if len(isrc):
        seeds[isrc[region[idst]]] = True
    return seeds


# ---------------------------------------------------------------------------
# warm-restart drivers
# ---------------------------------------------------------------------------


def _warm_push_carry(prog, pshards, state_stacked, frontier_stacked,
                     force_active: bool):
    """A PushCarry seeded from a prior state + frontier mask (the warm
    twin of push._init_carry).  ``force_active`` keeps the loop alive
    for at least one round when the frontier is empty but delta edges
    exist (the insert fold runs inside the round)."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from lux_tpu.engine import push

    arrays = jax.tree.map(jnp.asarray, pshards.arrays)
    state0 = jnp.asarray(state_stacked)
    mask0 = jnp.asarray(frontier_stacked) & arrays.vtx_mask
    q_vid, q_val, cnt = jax.vmap(partial(push.build_queue,
                                         pshards.pspec))(
        arrays, mask0, state0)
    active = jnp.sum(cnt)
    if force_active:
        active = jnp.maximum(active, jnp.int32(1))
    num_parts = arrays.global_vid.shape[0]
    return arrays, push.PushCarry(
        state0, q_vid, q_val, cnt, jnp.int32(0), active,
        push._zero_edges(), jnp.zeros((num_parts,), jnp.uint32),
        jnp.int32(0))


def _run_push_overlay(prog, mg, state_g, frontier_g, method, max_iters,
                      pad_fill):
    """Shared warm push loop: overlay + patched CSR through the
    ALREADY-COMPILED chunk loop (same lru family as cold runs of this
    (prog, spec, ostatic) — re-entry is compile-free)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu import obs
    from lux_tpu.engine import push

    pshards = mg.push_shards
    with obs.span("mutate.overlay", kind="push"):
        ostatic, oarr, parr = mg.push_overlay()
    state = _stack(pshards.pull, state_g, fill=pad_fill)
    frontier = _stack(pshards.pull, frontier_g.astype(np.int32)) > 0
    arrays, carry0 = _warm_push_carry(
        prog, pshards, state, frontier, force_active=not mg.log.empty)
    loop = push.compile_push_chunk(prog, pshards.pspec, pshards.spec,
                                   method, overlay_static=ostatic)
    with obs.span("mutate.refresh", app=prog.__class__.__name__,
                  kind="push") as sp:
        out = loop(arrays, jax.tree.map(jnp.asarray, parr), carry0,
                   jnp.int32(max_iters),
                   oarrays=jax.tree.map(jnp.asarray, oarr))
        jax.block_until_ready(out.state)
        sp.set(iters=int(out.it))
    return out.state, int(out.it)


def refresh_sssp(mg, prior_state_g: np.ndarray, start: int,
                 method: str = "auto", weighted: bool = False,
                 max_iters: int = 10_000):
    """Warm SSSP refresh.  ``prior_state_g``: the (nv,) converged
    distances on the PRE-churn graph.  Returns (dist (nv,), rounds) on
    the merged graph — bitwise equal to a cold rebuild (unique int
    fixpoint; pinned by tests)."""
    from lux_tpu.models.sssp import SSSPProgram, WeightedSSSPProgram

    cls = WeightedSSSPProgram if weighted else SSSPProgram
    prog = cls(nv=mg.base.nv, start=start)
    dist = np.asarray(prior_state_g).copy()
    dirty = sssp_dirty(mg, dist, start, weighted)
    seeds = _live_in_neighbors(mg, dirty)
    # boundary members must hold a REACHED value to be worth pushing
    seeds &= np.asarray(dist) < prog.inf
    seeds &= ~dirty
    isrc, _, _ = mg.log.live_inserts()
    if len(isrc):
        s = np.unique(isrc)
        seeds[s[dist[s] < prog.inf]] = True
    dist[dirty] = prog.inf
    dist[start] = 0
    if dirty[start]:
        seeds[start] = True
    state, it = _run_push_overlay(prog, mg, dist, seeds, method,
                                  max_iters, pad_fill=prog.inf)
    return mg.push_shards.scatter_to_global(np.asarray(state)), it


def refresh_components(mg, prior_labels_g: np.ndarray,
                       method: str = "auto", max_iters: int = 10_000):
    """Warm CC refresh from prior converged labels; returns
    (labels (nv,), rounds) — bitwise equal to a cold rebuild."""
    from lux_tpu.models.components import MaxLabelProgram

    prog = MaxLabelProgram()
    labels = np.asarray(prior_labels_g).copy()
    dirty = cc_dirty(mg, labels)
    seeds = _live_in_neighbors(mg, dirty) | dirty
    isrc, idst, _ = mg.log.live_inserts()
    if len(isrc):
        seeds[np.unique(isrc)] = True
        seeds[np.unique(idst)] = True
    labels[dirty] = np.flatnonzero(dirty)  # reset to own id (cold init)
    state, it = _run_push_overlay(prog, mg, labels, seeds, method,
                                  max_iters, pad_fill=-1)
    return mg.push_shards.scatter_to_global(np.asarray(state)), it


def _changed_count(old, new):
    """Top-level (hashable) residual probe: count of entries that moved
    — residual 0 is the exact-fixpoint convergence the refresh contract
    uses."""
    import jax.numpy as jnp

    return jnp.sum(old != new,
                   axis=tuple(range(1, old.ndim))).astype(jnp.int32)


def pagerank_tolerance_threshold(tolerance: float,
                                 alpha: float | None = None) -> float:
    """The per-entry residual threshold a declared served-error bound
    ``tolerance`` buys the frontier-tolerance refresh.

    The PageRank update contracts the rank error by ``alpha`` per step
    (models/pagerank.py: new = (1-alpha)/nv + alpha*acc — the classic
    Banach bound puts a state whose step residual is r within
    r*alpha/(1-alpha) of the fixpoint in the contraction norm).  The
    probe measures the PER-ENTRY movement of the stored (pre-divided)
    state while the contraction argument lives in the undivided ranks'
    L1 norm, so the threshold is declared CONSERVATIVELY at
    tolerance*(1-alpha) — an extra alpha/(1-alpha) (~0.18 at the
    reference alpha=0.15) of slack against the norm gap.  The CONTRACT
    is the tested one: max observed served error vs an f64 oracle stays
    <= the declared tolerance across churn sequences
    (tests/test_merge_tree.py) — the formula is the sizing argument,
    the test is the promise."""
    if alpha is None:
        from lux_tpu.models.pagerank import ALPHA

        alpha = ALPHA
    if tolerance < 0:
        raise ValueError(f"tolerance must be >= 0, got {tolerance}")
    return float(tolerance) * (1.0 - float(alpha))


@lru_cache(maxsize=None)
def _tolerance_probe(threshold: float):
    """Hashable residual probe for the frontier-tolerance refresh:
    counts entries that moved by MORE than ``threshold`` — the loop
    quiesces when every entry's step movement is inside the band.
    lru_cache returns the SAME function object per threshold, so the
    compiled loop caches exactly like the exact probe (one compile per
    declared tolerance, zero retrace across refreshes)."""

    def probe(old, new):
        import jax.numpy as jnp

        d = jnp.abs(new.astype(jnp.float32) - old.astype(jnp.float32))
        return jnp.sum(d > jnp.float32(threshold),
                       axis=tuple(range(1, old.ndim))).astype(jnp.int32)

    return probe


def pagerank_probe(tolerance: float = 0.0):
    """The convergence probe for a declared served-error bound:
    ``tolerance=0`` returns ``_changed_count`` ITSELF — the exact
    residual==0 path, same function object, same compiled program,
    bitwise the exact refresh (the degrade-to-exact leg of the
    tolerance contract)."""
    if tolerance <= 0:
        return _changed_count
    return _tolerance_probe(pagerank_tolerance_threshold(tolerance))


def converge_pagerank(shards, method: str = "auto", route=None,
                      overlay=None, state0=None, max_iters: int = 512,
                      dtype: str = "float32",
                      degree_override=None, tolerance: float = 0.0):
    """Iterate PageRank to an EXACT f32 fixpoint (residual == 0) —
    shared by the warm refresh and the cold comparison leg.  Returns
    (stacked state, iters).  ``degree_override`` substitutes the merged
    out-degrees ((P, V) int32 array — an ordinary jit argument).
    ``tolerance`` > 0 switches to the frontier-tolerance band: the loop
    quiesces once every entry's step movement is inside
    pagerank_tolerance_threshold(tolerance) — served error vs the true
    fixpoint stays <= tolerance (the tested contract); 0 is bitwise the
    exact path (pagerank_probe returns _changed_count itself)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import pull
    from lux_tpu.models.pagerank import PageRankProgram

    prog = PageRankProgram(nv=shards.spec.nv, dtype=dtype)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    if degree_override is not None:
        arrays = arrays._replace(degree=jnp.asarray(degree_override))
    if state0 is None:
        state0 = pull.init_state(prog, arrays)
    else:
        state0 = jnp.asarray(state0)
    return pull.run_pull_until(
        prog, shards.spec, arrays, state0, max_iters,
        pagerank_probe(tolerance),
        method=method, route=route, overlay=overlay)


def refresh_pagerank(mg, prior_state_stacked, method: str = "auto",
                     route=None, max_iters: int = 512,
                     dtype: str = "float32", tolerance: float = 0.0):
    """Warm PageRank refresh: prior converged ranks rescaled for the
    merged out-degrees (the state stores rank/deg), then the overlay
    step iterates to an exact f32 fixpoint.  ``route``: a BASE-graph
    plan — expand (unfused or pass-fused) OR a fused family
    (fused/fused-pf/fused-mx tombstone in group space since luxmerge);
    the base gather is unchanged by churn, so the cached plan keeps
    serving.  ``tolerance``: the frontier-tolerance band (see
    converge_pagerank) — 0 is bitwise the exact refresh, > 0 trades a
    declared served-error bound for fewer warm iterations; a serving
    layer MUST surface the bound on every read of the refreshed state
    (the tolerance tag, serve/fleet).  Returns (stacked state, iters)."""
    from lux_tpu import obs
    from lux_tpu.mutate import overlay as ovl

    shards = mg.pull_shards
    with obs.span("mutate.overlay", kind="pull"):
        ostatic, oarr = mg.pull_overlay()
        deg_new = ovl.merged_degree_stacked(shards, mg.log)
    deg_old = np.asarray(shards.arrays.degree, np.float32)
    dn = deg_new.astype(np.float32)
    scale = np.where(deg_old > 0, deg_old, 1.0) / np.where(dn > 0, dn,
                                                           1.0)
    warm = (np.asarray(prior_state_stacked, np.float32)
            * scale).astype(dtype)
    with obs.span("mutate.refresh", app="pagerank", kind="pull") as sp:
        state, it = converge_pagerank(
            shards, method=method, route=route, overlay=(ostatic, oarr),
            state0=warm, max_iters=max_iters, dtype=dtype,
            degree_override=deg_new, tolerance=tolerance)
        sp.set(iters=int(it), tolerance=float(tolerance))
    return state, int(it)
