"""Compaction: merge the delta-log into a new `.lux` base snapshot.

Protocol (crash-safe, each step durable before the next):

  1. materialize the merged graph (deltalog.merged_graph — the ONE
     deterministic definition the property tests pin);
  2. write it as a `.lux` snapshot via a tmp + fsync + rename (a crash
     mid-write leaves the old snapshot intact);
  3. rotate the journal (deltalog.journal_reset — batches now live in
     the snapshot; a crash between 2 and 3 replays them against the
     OLD base: stale but consistent, never half-applied);
  4. rebuild the shard layouts REUSING the old vertex cuts, so the
     per-bucket plan cache (ops/expand PLAN_FORMAT 5: one npz entry per
     part keyed on that part's OWN index arrays) invalidates ONLY the
     buckets whose arrays actually changed — ``invalidation_report``
     computes exactly which, from the same key derivation the cache
     uses (never a parallel reimplementation);
  5. optionally publish the snapshot to a live serving fleet through
     PR 8's token-guarded prepare/commit republish
     (``publish_to_fleet``) — zero-downtime, bitwise-equal answers.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from lux_tpu.graph.format import write_lux


def snapshot_write(path: str, g) -> None:
    """Durable `.lux` write: tmp + fsync + atomic rename (write_lux
    itself streams straight to the target, which a crash would tear)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    write_lux(tmp, g)
    with open(tmp, "rb+") as f:
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def plan_bucket_paths(shards, cache_dir: Optional[str] = None):
    """The expand plan family's per-bucket cache PATHS for a shard
    bundle — derived by the cache's own key functions
    (ops/expand._expand_key_one/_entry_path), so this report can never
    drift from what the cache actually keys on.  None when the cache
    dir is untrusted (the cache itself degrades the same way)."""
    from lux_tpu.ops import expand

    cache_dir = cache_dir or expand._default_cache_dir()
    if not expand._cache_dir_trusted(cache_dir):
        return None
    key_one = expand._expand_key_one(shards)
    return [expand._entry_path(cache_dir, "expand", key_one, i)
            for i in range(shards.arrays.src_pos.shape[0])]


def invalidation_report(old_shards, new_shards,
                        cache_dir: Optional[str] = None) -> dict:
    """Which plan-cache buckets a compaction invalidates: a bucket
    survives iff its content-derived cache path is UNCHANGED (same
    index arrays -> same sha -> same npz entry replays).  Returns
    {parts, changed, fraction, changed_parts}."""
    P = old_shards.arrays.src_pos.shape[0]
    old_p = plan_bucket_paths(old_shards, cache_dir)
    new_p = plan_bucket_paths(new_shards, cache_dir)
    if old_p is None or new_p is None or \
            new_shards.arrays.src_pos.shape[0] != P:
        # untrusted cache dir or a recut that changed the part count:
        # everything rebuilds
        changed = list(range(new_shards.arrays.src_pos.shape[0]))
    else:
        changed = [i for i in range(P) if old_p[i] != new_p[i]]
    total = new_shards.arrays.src_pos.shape[0]
    return {
        "parts": total,
        "changed": len(changed),
        "fraction": round(len(changed) / total, 4) if total else 0.0,
        "changed_parts": changed,
    }


def compact_mutable(mg, path: Optional[str] = None,
                    reuse_cuts: bool = True) -> dict:
    """Compact a MutableGraph in place (step list in the module
    docstring).  Returns a report: snapshot path (or None), merged
    sizes, and the per-layout bucket-invalidation summary."""
    from lux_tpu import obs
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.graph.shards import build_pull_shards

    with obs.span("mutate.compact", inserts=int(mg.log.ins_live.sum()),
                  deletes=int(mg.log.del_base.sum())) as sp:
        if mg.log.journal_dir is not None and path is None:
            raise ValueError(
                "a journaled MutableGraph needs a snapshot path to "
                "compact: rotating the journal without persisting the "
                "merged base would drop durable mutations (set "
                "MutableGraph(snapshot=...) or pass compact(path=...))")
        merged = mg.log.merged_graph()
        if path is not None:
            snapshot_write(path, merged)
        mg.log.journal_reset()

        old_pull = mg._pull
        cuts = (np.asarray(old_pull.cuts) if (reuse_cuts
                                              and old_pull is not None)
                else None)
        report = {"path": path, "nv": int(merged.nv),
                  "ne": int(merged.ne)}
        new_pull = new_push = None
        if mg._push is not None:
            new_push = build_push_shards(merged, mg.num_parts, cuts=cuts)
            new_pull = new_push.pull
        elif old_pull is not None:
            new_pull = build_pull_shards(merged, mg.num_parts, cuts=cuts)
        if old_pull is not None and new_pull is not None:
            report["invalidation"] = invalidation_report(old_pull,
                                                         new_pull)
        # swap the base LAST so a build failure leaves mg consistent
        mg.base = merged
        mg.log = type(mg.log)(merged, journal_dir=mg.log.journal_dir)
        mg._pull = new_pull
        mg._push = new_push
        mg._csr = None
        mg._csr_perms = None
        sp.set(ne=report["ne"],
               invalidated=report.get("invalidation", {}).get("changed"))
    return report


def publish_to_fleet(controller, path: str,
                     graph_id: Optional[str] = None) -> dict:
    """Publish a compacted snapshot to a live fleet through the
    controller's token-guarded two-phase republish (serve/fleet:
    prepare a second engine cache while the old graph serves, then an
    atomic commit — zero shed, bitwise-equal answers; a failed prepare
    anywhere aborts with the old graph still serving)."""
    from lux_tpu import obs

    gid = graph_id if graph_id is not None else os.path.basename(path)
    with obs.span("mutate.publish", graph=gid):
        return controller.republish(path, graph_id=gid)
