"""Statically-shaped mutation overlays for the hot loops.

The engines must consume a mutating graph WITHOUT retracing: every
overlay structure here is a fixed-shape jit ARGUMENT (never a static),
so empty / half-full / full delta buffers produce byte-identical traces
(luxaudit LUX-J1 pins this).  Two pieces:

  * ``del_val`` — a (P, E) bool tombstone mask over the base CSC edge
    slots.  The engines neutralize tombstoned VALUES (reduce identity:
    +0.0 for sum — exact no-op in IEEE for the non-negative rank
    states; dtype max/min for integer min/max — exactly absorbed), so
    the base segmented reduce runs unchanged over unchanged arrays.
  * ``d_src_pos / d_dst_local / d_weight`` — (P, D) fixed-capacity
    insert buffers (D = ``LUX_DELTA_CAP`` rounded to the TPU lane
    width; overflow raises DeltaOverflow and triggers compaction,
    never a reshape).  Empty/tombstoned slots carry the ``nv_pad``
    destination sentinel, so the device-side scatter (mode="drop")
    subsumes the validity mask — the same sentinel idiom as
    ``ShardArrays.dst_local`` padding and the push engine's CSR pads.

Exactness contract: for min/max/integer reduces the overlay step is
BITWISE equal to a cold-rebuilt step on the merged graph (the combiner
is exactly associative/commutative and the neutral absorbs exactly).
For float32 sums the delta pass is a separate deterministic
association (base-segment sum + scatter-add), so per-iteration equality
is exact-arithmetic only — converged fixpoints are compared instead
(docs/DYNAMIC.md "shape contract"; tests/test_mutate.py pins both).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import numpy as np

from lux_tpu.graph.partition import part_of_vertex
from lux_tpu.mutate.deltalog import DeltaLog, DeltaOverflow
from lux_tpu.utils.config import env_int

LANE = 128

#: default per-part insert capacity (slots) when LUX_DELTA_CAP is unset
DEFAULT_CAP = 1024

#: the ONE overlay-vs-plan-family rejection message (engine/pull.py and
#: any future CF consumer raise it): it must name the escape hatches,
#: not just the incompatibility — a serving operator hitting this mid-
#: incident needs the next command, not a design note.  Since luxmerge
#: the note covers ONLY the CF (colfilter) route: the fused-pf and
#: fused-mx families tombstone in GROUP SPACE through the plan's gslot
#: route (ops/expand.apply_fused ``del_val=``), so overlays ride the
#: fastest kernels directly.
FUSED_OVERLAY_NOTE = (
    "mutation overlays compose with the direct gather, the routed "
    "EXPAND family, and the FUSED families (fused/fused-pf/fused-mx "
    "tombstone deleted edges in group space via the plan's gslot "
    "route) — but NOT the CF route: its dst-state-dependent error term "
    "re-reads the destination per edge, and the overlay's insert "
    "buffer carries no dst-state replay for it.  Escape hatches: "
    "(1) re-plan the route with route_base=\"expand\" "
    "(LUX_ROUTE_MODE=routed or routed-pf keeps pass-fusion), or "
    "(2) compact() the MutableGraph — the merged base serves any plan "
    "family again (capacity knob: LUX_DELTA_CAP)")


def delta_cap(cap: Optional[int] = None) -> int:
    """Resolve the per-part delta-buffer capacity: explicit argument,
    else ``LUX_DELTA_CAP``, else DEFAULT_CAP — always rounded UP to the
    lane width so the buffers tile like every other device array.  The
    capacity is part of the overlay's STATIC shape: changing it (not
    filling it) is what recompiles."""
    if cap is None:
        cap = env_int("LUX_DELTA_CAP", DEFAULT_CAP, minimum=1)
    return -(-cap // LANE) * LANE


@dataclasses.dataclass(frozen=True)
class OverlayStatic:
    """Hashable overlay descriptor (safe as a jit static): only the
    SHAPE-defining facts live here — occupancy is data."""

    cap: int
    weighted: bool


class OverlayArrays(NamedTuple):
    """Stacked per-part overlay arrays (leading axis = part); a pytree.

    Shapes (P parts, E = e_pad base edge slots, D = cap):
      del_val:     (P, E) bool  True where the base edge is tombstoned.
      d_src_pos:   (P, D) int32 insert source position in the (P*V,)
                   gathered state (same encoding as ShardArrays.src_pos);
                   empty slots hold 0 (their scatter is dropped anyway).
      d_dst_local: (P, D) int32 local destination, or the nv_pad
                   SENTINEL on empty slots (scatter mode="drop").
      d_weight:    (P, D) float32 insert weights (zeros when unweighted).
    """

    del_val: np.ndarray
    d_src_pos: np.ndarray
    d_dst_local: np.ndarray
    d_weight: np.ndarray


def _neutral(reduce: str, dtype):
    """Reduce identity matching ops/segment.py's empty-row convention
    (and ops/expand._neutral_like)."""
    import jax.numpy as jnp

    if reduce == "sum":
        return jnp.asarray(0, dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if reduce == "min" else info.min, dtype)
    return jnp.asarray(jnp.inf if reduce == "min" else -jnp.inf, dtype)


# ---------------------------------------------------------------------------
# device-side replay (called from inside the engines' jitted bodies)
# ---------------------------------------------------------------------------


def mask_deleted(vals, del_val, reduce: str):
    """Neutralize tombstoned base-edge VALUES before the segmented
    reduce — for sum the +0.0 is an exact IEEE no-op on the remaining
    addends, for min/max the dtype extreme is exactly absorbed, so the
    base reduce's association (and for min/max its bits) is that of the
    merged graph."""
    import jax.numpy as jnp

    return jnp.where(del_val, _neutral(reduce, vals.dtype), vals)


def delta_scatter(acc, full_state, oarr, value_fn, reduce: str):
    """Fold the part's insert buffer into a per-destination accumulator
    ``acc`` (shape (V,) — dst sentinel nv_pad lands out of bounds and
    drops): gather the D source states from the gathered full state,
    apply ``value_fn(src_state, weight)`` (the program's edge_value /
    relax), scatter-combine by local destination.  O(D) on top of the
    O(E) base pass; D is static, so occupancy never retraces."""
    import jax.numpy as jnp

    src = full_state[jnp.clip(oarr.d_src_pos, 0, full_state.shape[0] - 1)]
    vals = value_fn(src, oarr.d_weight)
    if reduce == "sum":
        return acc.at[oarr.d_dst_local].add(vals, mode="drop")
    if reduce == "min":
        return acc.at[oarr.d_dst_local].min(vals, mode="drop")
    return acc.at[oarr.d_dst_local].max(vals, mode="drop")


# ---------------------------------------------------------------------------
# host-side builders
# ---------------------------------------------------------------------------


def _csc_slot_of_base_edge(shards, edge_idx: np.ndarray, base_row_ptr):
    """Map base CSC edge indices -> (part, slot) under the shards' cuts.
    Requires the default fill_part layout (no sort_segments): slot =
    edge index rebased to the part's edge range."""
    cuts = np.asarray(shards.cuts, np.int64)
    dst = (np.searchsorted(base_row_ptr, edge_idx, side="right") - 1)
    part = part_of_vertex(cuts, dst).astype(np.int64)
    elo = np.asarray(base_row_ptr, np.int64)[cuts[part]]
    return part, (edge_idx - elo)


def build_pull_overlay(shards, dlog: DeltaLog, cap: Optional[int] = None):
    """(OverlayStatic, OverlayArrays) for a PullShards bundle built from
    ``dlog.base`` with the DEFAULT layout (sort_segments / compact
    mirrors reorder edge slots and are rejected — the tombstone mask
    addresses slots by base CSC position).

    Raises DeltaOverflow when any part's live inserts exceed the
    capacity — the caller compacts (MutableGraph does automatically)."""
    arrays = shards.arrays
    if arrays.mirror_pos.shape[-1] > 0:
        raise ValueError("mutation overlays require the default pull "
                         "layout (compact_gather reorders the gather; "
                         "rebuild shards without it)")
    P = arrays.src_pos.shape[0]
    e_pad = arrays.src_pos.shape[1]
    nv_pad = arrays.vtx_mask.shape[1]
    cuts = np.asarray(shards.cuts, np.int64)
    D = delta_cap(cap)
    static = OverlayStatic(cap=D, weighted=shards.spec.weighted)

    del_val = np.zeros((P, e_pad), bool)
    dele = dlog.deleted_edges()
    if len(dele):
        part, slot = _csc_slot_of_base_edge(shards, dele,
                                            dlog.base.row_ptr)
        # the mask addresses base slots by position — verify the layout
        # assumption on the (small) deleted set instead of trusting it
        own = part_of_vertex(cuts, np.asarray(dlog.base.col_idx,
                                              np.int64)[dele]).astype(np.int64)
        want = (own * nv_pad
                + (np.asarray(dlog.base.col_idx, np.int64)[dele]
                   - cuts[own])).astype(np.int64)
        got = np.asarray(arrays.src_pos, np.int64)[part, slot]
        if not np.array_equal(got, want):
            raise ValueError(
                "shards edge layout does not match the base CSC order "
                "(sort_segments layout?) — mutation overlays need the "
                "default fill order")
        del_val[part, slot] = True

    d_src_pos = np.zeros((P, D), np.int32)
    d_dst_local = np.full((P, D), nv_pad, np.int32)
    d_weight = np.zeros((P, D), np.float32)
    isrc, idst, iw = dlog.live_inserts()
    if len(isrc):
        p_of = part_of_vertex(cuts, idst).astype(np.int64)
        counts = np.bincount(p_of, minlength=P)
        if counts.max() > D:
            raise DeltaOverflow(
                f"part {int(counts.argmax())} holds {int(counts.max())} "
                f"live inserts > capacity {D} (LUX_DELTA_CAP) — compact")
        own = part_of_vertex(cuts, isrc).astype(np.int64)
        spos = (own * nv_pad + (isrc - cuts[own])).astype(np.int32)
        # append order within each part: stable sort by part keeps it
        order = np.argsort(p_of, kind="stable")
        slot = np.arange(len(isrc), dtype=np.int64)
        starts = np.zeros(P + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        slot = slot - starts[p_of[order]]
        rows = p_of[order]
        d_src_pos[rows, slot] = spos[order]
        d_dst_local[rows, slot] = (idst[order]
                                   - cuts[rows]).astype(np.int32)
        d_weight[rows, slot] = iw[order].astype(np.float32)
    return static, OverlayArrays(del_val, d_src_pos, d_dst_local,
                                 d_weight)


def empty_overlay_arrays(shards, cap: Optional[int] = None) -> OverlayArrays:
    """The zero-churn OverlayArrays for a shard bundle: no tombstones,
    every insert slot empty (nv_pad dst sentinel).  An engine compiled
    with an OverlayStatic but handed these arrays is BITWISE equal to
    the no-overlay engine — the warm path live serving starts from
    before any delta arrives (and what a freshly republished replica
    resets to)."""
    arrays = shards.arrays
    P = arrays.src_pos.shape[0]
    e_pad = arrays.src_pos.shape[1]
    nv_pad = arrays.vtx_mask.shape[1]
    D = delta_cap(cap)
    return OverlayArrays(
        del_val=np.zeros((P, e_pad), bool),
        d_src_pos=np.zeros((P, D), np.int32),
        d_dst_local=np.full((P, D), nv_pad, np.int32),
        d_weight=np.zeros((P, D), np.float32),
    )


def occupancy(shards, dlog: DeltaLog, cap: Optional[int] = None) -> dict:
    """Host-side buffer accounting: per-part live-insert counts against
    the capacity (the bench rows' ``delta_occupancy``)."""
    P = shards.arrays.src_pos.shape[0]
    _, idst, _ = dlog.live_inserts()
    counts = np.bincount(
        part_of_vertex(np.asarray(shards.cuts, np.int64), idst),
        minlength=P)
    D = delta_cap(cap)
    return {"cap": D, "max": int(counts.max()) if len(counts) else 0,
            "per_part": counts.astype(int).tolist(),
            "frac": round(float(counts.max()) / D, 4) if len(counts)
            else 0.0, "deletes": int(dlog.del_base.sum())}


def push_csr_perms(pshards, base) -> list:
    """Per-part CSC-slot -> CSR-slot maps of the push layout (the
    stable source sort build_push_shards performs).  O(E log E) once
    per snapshot — MutableGraph caches these so per-refresh tombstone
    patching is O(deleted), not a re-sort."""
    cuts = np.asarray(pshards.cuts, np.int64)
    rp = np.asarray(base.row_ptr, np.int64)
    perms = []
    for p in range(pshards.spec.num_parts):
        elo, ehi = int(rp[cuts[p]]), int(rp[cuts[p + 1]])
        srcs = np.asarray(base.col_idx[elo:ehi], np.int64)
        order = np.argsort(srcs, kind="stable")
        inv = np.empty(len(srcs), np.int64)
        inv[order] = np.arange(len(srcs), dtype=np.int64)
        perms.append(inv)
    return perms


def build_push_overlay(pshards, dlog: DeltaLog,
                       cap: Optional[int] = None, csr_perms=None):
    """(OverlayStatic, OverlayArrays, patched PushArrays) for a
    PushShards bundle: the overlay arrays drive the DENSE rounds (the
    embedded pull layout) and the insert scatter; the patched CSR
    arrays retire deleted edges from the SPARSE walk by pointing their
    destinations at the nv_pad sentinel — the walk's existing
    drop-scatter handles the rest, no kernel change."""
    from lux_tpu.graph.push_shards import PushArrays

    static, oarr = build_pull_overlay(pshards.pull, dlog, cap)
    parr = pshards.parrays
    dele = dlog.deleted_edges()
    if not len(dele):
        return static, oarr, parr
    if csr_perms is None:
        csr_perms = push_csr_perms(pshards, dlog.base)
    nv_pad = pshards.pull.arrays.vtx_mask.shape[1]
    part, slot = _csc_slot_of_base_edge(pshards.pull, dele,
                                        dlog.base.row_ptr)
    csr_dst = np.array(parr.csr_dst_local, copy=True)
    for p in np.unique(part):
        sl = slot[part == p]
        csr_dst[p, csr_perms[int(p)][sl]] = nv_pad
    return static, oarr, PushArrays(parr.uniq_src, parr.csr_row_ptr,
                                    csr_dst, parr.csr_weight)


def merged_degree_stacked(shards, dlog: DeltaLog) -> np.ndarray:
    """The merged graph's out-degrees in the shards' (P, V) stacked
    layout (padding slots 0) — pagerank's apply divides by these, and
    they are an ordinary jit argument, so the patch never retraces."""
    from lux_tpu.graph.shards import global_to_stacked

    deg = dlog.merged_out_degrees()
    return global_to_stacked(np.asarray(shards.cuts),
                             shards.arrays.degree.shape[1], deg)
