"""Delta-stepping weighted SSSP: bucketed priority frontiers.

The chaotic-relaxation weighted SSSP (models/sssp.WeightedSSSPProgram on
the plain push engine) expands every improved vertex immediately, so a
vertex whose tentative distance later improves is expanded AGAIN — on
weighted graphs the wasted cascades dominate (Bellman-Ford behavior).
Delta-stepping (Meyer & Sanders 2003) processes vertices in distance
buckets of width Δ: only pending vertices with ``dist < thr`` (the
current bucket) may expand; improved vertices park in ``pending`` until
their bucket opens, so most expand exactly once, with their final
distance — Dijkstra-like edge counts with frontier-level parallelism.

BASELINE.json's config list names a "frontier delta-stepping kernel" as
the SSSP framing; the reference has no weighted SSSP at all (its app is
BFS, sssp/sssp_gpu.cu:122), so this is target parity, not code parity.

TPU-first shape: ONE extra (P, V) bool mask + ONE int32 threshold on
top of the push carry; every round expands via the push engine's OWN
prep/relax bodies (queue build, two-tier sparse walk, global direction
switch — through a synthesized PushCarry), with the threshold advance
FUSED in front (a masked min + round-up — when the current bucket is
empty the threshold jumps past the smallest pending distance in the
same round, so there are no advance-only rounds to dispatch).  The
whole loop stays on device in `lax.while_loop`.
A dense expansion round relaxes every edge (all sources, not just the
bucket), which is still exact — min-relaxation is monotone — and clears
ALL pending work for the round; the accounting (edges walked) uses the
push engine's exact [hi, lo] uint32 counter either way.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from lux_tpu.engine import methods, push
from lux_tpu.graph.push_shards import PushShards, PushSpec
from lux_tpu.graph.shards import ShardSpec


class DeltaCarry(NamedTuple):
    state: Any    # (P, V) tentative distances
    pending: Any  # (P, V) bool: improved but not yet expanded
    thr: Any      # int32 scalar: current bucket's EXCLUSIVE upper bound
    it: Any       # int32 expansion rounds run (advances are fused)
    active: Any   # int32 total pending count (0 = converged)
    edges: Any    # exact traversed-edge counter ([hi, lo] uint32 pair)


def _init_carry(prog, pspec: PushSpec, arrays, delta: int) -> DeltaCarry:
    state0 = jax.vmap(prog.init_state)(
        arrays.global_vid, arrays.degree, arrays.vtx_mask
    )
    pending0 = jax.vmap(prog.init_frontier)(
        arrays.global_vid, state0, arrays.vtx_mask
    ) & arrays.vtx_mask
    return DeltaCarry(
        state0, pending0, jnp.int32(delta), jnp.int32(0),
        jnp.sum(pending0.astype(jnp.int32)), push._zero_edges(),
    )


def _advanced_thr(prog, delta: int, c: DeltaCarry, n_in,
                  min_pend=None):
    """The bucket threshold for THIS round: unchanged while the current
    bucket still has pending work; otherwise jump past the smallest
    pending distance (skipping empty buckets in one hop).  Fused into
    the expansion round — a separate advance-only round would pay a
    whole dispatch to move one scalar, and at small Δ advance rounds
    are ~half of all rounds.  ``min_pend`` overrides the local masked
    min (the SPMD path passes its pmin) so the jump arithmetic lives in
    exactly one place."""
    if min_pend is None:
        inf = jnp.int32(prog.inf)
        min_pend = jnp.min(jnp.where(c.pending, c.state, inf))
    jumped = (min_pend // jnp.int32(delta) + 1) * jnp.int32(delta)
    return jnp.where(n_in > 0, c.thr, jumped)


def _delta_iteration(prog, pspec: PushSpec, spec: ShardSpec, method,
                     delta: int, arrays, parrays, c: DeltaCarry,
                     route_static=None, route_arrays=None, interpret=False
                     ) -> DeltaCarry:
    in_bucket = c.pending & (c.state < c.thr)
    n_in = jnp.sum(in_bucket.astype(jnp.int32))
    thr = _advanced_thr(prog, delta, c, n_in)
    # recompute under the (possibly advanced) threshold: non-empty
    # whenever any work is pending, so every round expands
    in_bucket = c.pending & (c.state < thr)
    q_vid, q_val, cnt = jax.vmap(partial(push.build_queue, pspec))(
        arrays, in_bucket, c.state
    )
    num_parts = arrays.global_vid.shape[0]
    tmp = push.PushCarry(
        c.state, q_vid, q_val, cnt, jnp.int32(0), jnp.int32(1),
        push._zero_edges(), jnp.zeros((num_parts,), jnp.uint32),
        jnp.int32(0),
    )
    q_vids_all, q_vals_all, preps, use_dense = push._push_prep(
        pspec, spec, parrays, tmp
    )
    new = push._push_relax(
        prog, pspec, spec, method, arrays, parrays, tmp,
        q_vids_all, q_vals_all, preps, use_dense,
        route_static, route_arrays, interpret,
    )
    changed = (new != c.state) & arrays.vtx_mask
    # sparse rounds expand exactly the bucket; a dense round relaxes
    # every source, so EVERYTHING pending counts as expanded
    kept = jnp.where(use_dense, False, c.pending & ~in_bucket)
    pending = kept | changed
    edges = push._acc_edges(c.edges, spec.ne, preps[3].sum(), use_dense)
    return DeltaCarry(
        new, pending, thr, c.it + 1,
        jnp.sum(pending.astype(jnp.int32)), edges,
    )


@lru_cache(maxsize=64)
def _compile_delta_loop(prog, pspec: PushSpec, spec: ShardSpec,
                        method: str, delta: int, route_static=None,
                        interpret=False):
    @jax.jit
    def loop(arrays, parrays, c0, max_iters, route_arrays=None):
        def cond(c):
            return (c.active > 0) & (c.it < max_iters)

        def body(c):
            return _delta_iteration(
                prog, pspec, spec, method, delta, arrays, parrays, c,
                route_static, route_arrays, interpret
            )

        return jax.lax.while_loop(cond, body, c0)

    return loop


def _validate(prog, delta: int) -> None:
    """Shared driver-entry guards (single-device AND distributed)."""
    if delta <= 0:
        raise ValueError(f"delta must be positive, got {delta}")
    if prog.reduce != "min":
        raise ValueError("delta-stepping is a min-relaxation driver")


def _spmd_delta_iteration(prog, pspec: PushSpec, spec: ShardSpec, method,
                          delta: int, arr_blk, parr_blk, c: DeltaCarry
                          ) -> DeltaCarry:
    """One delta round from a device's perspective inside shard_map
    (k resident parts as the leading axis).  The bucket-occupancy vote
    (one psum) and the fused threshold advance (one pmin) are GLOBAL —
    every device takes the identical single path, so the collectives
    inside never diverge; expansion reuses the push engine's OWN SPMD
    prep/relax bodies via a synthesized PushCarry."""
    lax = jax.lax

    in_bucket = c.pending & (c.state < c.thr)
    n_in = lax.psum(jnp.sum(in_bucket.astype(jnp.int32)), push.PARTS_AXIS)
    # fused threshold advance — same _advanced_thr arithmetic, with the
    # masked min pmin'd over the parts axis
    inf = jnp.int32(prog.inf)
    local_min = jnp.min(jnp.where(c.pending, c.state, inf))
    thr = _advanced_thr(prog, delta, c, n_in,
                        min_pend=lax.pmin(local_min, push.PARTS_AXIS))
    in_bucket = c.pending & (c.state < thr)
    q_vid, q_val, cnt = jax.vmap(partial(push.build_queue, pspec))(
        arr_blk, in_bucket, c.state
    )
    k = arr_blk.global_vid.shape[0]
    tmp = push.PushCarry(
        c.state, q_vid, q_val, cnt, jnp.int32(0), jnp.int32(1),
        push._zero_edges(), jnp.zeros((k,), jnp.uint32), jnp.int32(0),
    )
    plan = push._spmd_push_prep(pspec, spec, parr_blk, tmp)
    new = push._spmd_push_relax(
        prog, pspec, spec, parr_blk, arr_blk,
        push._allgather_dense_fn(prog, arr_blk, method), tmp, plan,
    )
    use_dense = plan[3]
    changed = (new != c.state) & arr_blk.vtx_mask
    kept = jnp.where(use_dense, False, c.pending & ~in_bucket)
    pending = kept | changed
    active = lax.psum(
        jnp.sum(pending.astype(jnp.int32)), push.PARTS_AXIS
    )
    totals = plan[2][3]
    g_total = lax.psum(
        jnp.sum(totals.astype(jnp.uint32)), push.PARTS_AXIS
    )
    edges = push._acc_edges(c.edges, spec.ne, g_total, use_dense)
    return DeltaCarry(new, pending, thr, c.it + 1, active, edges)


@lru_cache(maxsize=64)
def _compile_delta_dist(prog, mesh, pspec: PushSpec, spec: ShardSpec,
                        method: str, delta: int):
    from jax.sharding import PartitionSpec as P

    from lux_tpu.graph.shards import ShardArrays
    from lux_tpu.graph.push_shards import PushArrays

    Pp = P(push.PARTS_AXIS)
    arr_specs = ShardArrays(*([Pp] * len(ShardArrays._fields)))
    parr_specs = PushArrays(*([Pp] * len(PushArrays._fields)))
    carry_specs = DeltaCarry(Pp, Pp, P(), P(), P(), P())

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(arr_specs, parr_specs, carry_specs, P()),
        out_specs=carry_specs,
    )
    def run(arr_blk, parr_blk, c_blk, max_iters):
        def cond(c):
            return (c.active > 0) & (c.it < max_iters)

        def body(c):
            return _spmd_delta_iteration(
                prog, pspec, spec, method, delta, arr_blk, parr_blk, c
            )

        return jax.lax.while_loop(cond, body, c_blk)

    return run


def run_push_delta_dist(
    prog,
    shards: PushShards,
    delta: int,
    mesh,
    max_iters: int = 100_000,
    method: str = "auto",
):
    """Distributed delta-stepping over a parts mesh (k resident parts
    per device supported): the same bucket discipline with ONE psum for
    the bucket-occupancy vote and ONE pmin for the threshold advance —
    both ride ICI, the loop stays on device end to end."""
    from lux_tpu.parallel.mesh import shard_stacked

    _validate(prog, delta)
    method = methods.resolve(method, prog.reduce)
    spec, pspec = shards.spec, shards.pspec
    assert spec.num_parts % mesh.devices.size == 0
    arrays_h = jax.tree.map(jnp.asarray, shards.arrays)
    c0 = _init_carry(prog, pspec, arrays_h, delta)
    arrays = shard_stacked(mesh, arrays_h)
    parrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.parrays))
    c0 = DeltaCarry(
        *shard_stacked(mesh, (c0.state, c0.pending)),
        c0.thr, c0.it, c0.active, c0.edges,
    )
    out = _compile_delta_dist(prog, mesh, pspec, spec, method, delta)(
        arrays, parrays, c0, jnp.int32(max_iters)
    )
    return out.state, out.it, out.edges


def run_push_delta(
    prog,
    shards: PushShards,
    delta: int,
    max_iters: int = 100_000,
    method: str = "auto",
    route=None,
):
    """Single-device delta-stepping driver (min-reduce programs).
    Returns (final stacked state, rounds run, edges [hi, lo]).  ``delta``
    is the bucket width in distance units; small Δ approaches Dijkstra
    (fewest edge relaxations, most rounds), large Δ approaches the
    chaotic engine (fewest rounds, most edges).  ``route`` (an expand
    plan on the pull layout) routes the dense rounds' gather —
    bitwise-identical."""
    _validate(prog, delta)
    method = methods.resolve(method, prog.reduce)
    spec, pspec = shards.spec, shards.pspec
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    parrays = jax.tree.map(jnp.asarray, shards.parrays)
    c0 = _init_carry(prog, pspec, arrays, delta)
    if route is None:
        loop = _compile_delta_loop(prog, pspec, spec, method, delta)
        out = loop(arrays, parrays, c0, jnp.int32(max_iters))
    else:
        from lux_tpu.engine.pull import _route_interpret

        rs, ra = route
        ra = jax.tree.map(jnp.asarray, ra)
        loop = _compile_delta_loop(prog, pspec, spec, method, delta,
                                   route_static=rs,
                                   interpret=_route_interpret())
        out = loop(arrays, parrays, c0, jnp.int32(max_iters),
                   route_arrays=ra)
    return out.state, out.it, out.edges
