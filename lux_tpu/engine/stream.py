"""Host-offload edge streaming: pull iterations for graphs whose edge
arrays exceed one chip's HBM.

The reference's capacity story is zero-copy host memory: whole-region
state lives in ZC and the mapper stages slices into framebuffer on
demand (core/lux_mapper.cc:146-165), so one GPU can process a partition
bigger than its FB.  The TPU analog here: the O(nv) vertex state stays
device-resident (it is small), the O(ne) edge arrays stay in HOST
memory, and each iteration streams them through the device in
fixed-size chunks:

    for chunk in part: device_put(next chunk)   # async, overlaps ...
                       partial = gather+reduce(current chunk)  # ... this
    acc = combine(partials); state = apply(acc)

Chunks are CSC edge ranges, so a chunk is a contiguous run of
destination segments (possibly splitting one segment at each border).
Per chunk the GLOBAL row_ptr is re-based and clipped to the chunk
(`np.clip(row_ptr - lo, 0, chunk_e)`), head flags are rebuilt from the
re-based pointers, and the standard segmented reduce
(ops/segment.reducers) runs unchanged; cross-chunk combination is the
reduce's own op (add / minimum / maximum), so min/max results are
BITWISE identical to the monolithic engine and sums differ only in
association order.  One (prog, method, shapes) compile serves every
chunk of every iteration — chunks share a static padded shape.

`jax.device_put` is dispatched asynchronously: the next chunk's
host->device transfer is issued BEFORE the current chunk's compute is
consumed, double-buffering the stream (2 chunks resident, the
`dist_lr[2]` ping-pong of core/graph.h:83 but across the host link).
Peak resident edge bytes are `streamed_hbm_bytes(...)` — the capacity
contract tests/biggraph assert against the configured budget.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import methods
from lux_tpu.graph.shards import (
    LANE, PullShards, ShardArrays, ShardSpec, alloc_arrays,
)
from lux_tpu.ops import segment

_REDUCERS = segment.reducers()
_COMBINE = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


class StreamChunk(NamedTuple):
    """Edge arrays for ONE chunk of one part — the unit that is
    device_put per step.  A jax pytree (device_put maps it).

    Host storage holds only the O(chunk_e) fields; the (V+1,) re-based
    row_ptr is DERIVED at device_put time from the part's single global
    row_ptr (`_rebased_row_ptr`) — storing it per chunk would cost
    O(P * n_chunks * V) host bytes, which at the RMAT27 target is GiBs
    of row_ptr copies on the very machine the capacity feature exists
    to relieve."""

    row_ptr: Any    # (V+1,) int32 re-based to the chunk, clipped
    src_pos: Any    # (chunk_e,) int32 gather positions
    dst_local: Any  # (chunk_e,) int32 (padding -> nv_pad sentinel)
    head_flag: Any  # (chunk_e,) bool rebuilt from the re-based row_ptr
    weights: Any    # (chunk_e,) float32


class _HostChunk(NamedTuple):
    """Stored form of a chunk: edge arrays + the chunk's base offset."""

    lo: int
    src_pos: Any
    dst_local: Any
    head_flag: Any
    weights: Any


@dataclasses.dataclass
class StreamedPullShards:
    """Host bundle: chunked edge arrays + device-residable vertex side."""

    spec: ShardSpec
    cuts: np.ndarray
    chunk_e: int
    #: chunks[p][c] — per part, _HostChunk for edge range [c*chunk_e, ...)
    chunks: list
    #: row_ptrs[p] — the part's ONE global (V+1,) int64 row_ptr; chunks
    #: re-base from it at device_put time
    row_ptrs: list
    #: vertex-side ShardArrays (P, V) with ZERO-width edge arrays — all
    #: any program's init_state/apply reads (degree/vtx_mask/global_vid)
    varrays: ShardArrays

    def scatter_to_global(self, stacked):
        from lux_tpu.graph.shards import stacked_to_global

        return stacked_to_global(self.cuts, stacked)


#: per-edge f32 compute-buffer passes of one ACTIVE chunk: the gathered
#: (chunk_e, W) src_state, the edge-value array, and ~2 scan
#: intermediates of the segmented reduce (ops/segment scan path)
_COMPUTE_PASSES = 4


def streamed_hbm_bytes(spec: ShardSpec, chunk_e: int,
                       state_bytes: int = 4, state_width: int = 1) -> int:
    """Peak device bytes of the streamed engine: full state + gathered
    copy + accumulator + TWO resident transfer chunks (double buffer) +
    the ACTIVE chunk's per-edge compute buffers — the latter scale with
    ``state_width`` (CF's (V, K) latent matrix makes them the dominant
    term at K=20; a width-blind budget would overshoot by ~an order of
    magnitude exactly when the flag matters)."""
    per_chunk = chunk_e * (4 + 4 + 1 + 4) + (spec.nv_pad + 1) * 4
    compute = chunk_e * 4 * state_width * _COMPUTE_PASSES
    state = spec.num_parts * spec.nv_pad * state_bytes * state_width
    return 2 * per_chunk + compute + 3 * state


def edge_bytes_total(spec: ShardSpec) -> int:
    """Monolithic-engine device edge bytes (what streaming avoids)."""
    return spec.num_parts * spec.e_pad * (4 + 4 + 1 + 1 + 4)


def chunk_edges_for_budget(spec: ShardSpec, budget_bytes: int,
                           state_bytes: int = 4,
                           state_width: int = 1) -> int:
    """Largest LANE-aligned chunk_e whose streamed footprint fits the
    budget (>= one LANE; raises if even that cannot fit)."""
    # state + 2 row_ptrs
    fixed = streamed_hbm_bytes(spec, 0, state_bytes, state_width)
    # double-buffered transfer arrays + the active chunk's compute bufs
    per_edge = 2 * (4 + 4 + 1 + 4) + 4 * state_width * _COMPUTE_PASSES
    chunk_e = max(0, budget_bytes - fixed) // per_edge // LANE * LANE
    if chunk_e <= 0:
        raise ValueError(
            f"HBM budget {budget_bytes} cannot hold even one {LANE}-edge "
            f"chunk plus the state ({fixed} fixed bytes)"
        )
    return min(chunk_e, spec.e_pad)


def build_streamed_pull(shards: PullShards, chunk_e: int
                        ) -> StreamedPullShards:
    """Chunk an in-memory pull layout for streaming.  ``chunk_e`` is the
    static per-chunk edge capacity (LANE-aligned; from
    chunk_edges_for_budget for a byte budget)."""
    if chunk_e % LANE:
        raise ValueError(f"chunk_e must be a multiple of {LANE}")
    spec, arrays = shards.spec, shards.arrays
    P, V, E = spec.num_parts, spec.nv_pad, spec.e_pad
    n_chunks = -(-E // chunk_e)
    chunks: list = []
    row_ptrs: list = []
    for p in range(P):
        rp = arrays.row_ptr[p].astype(np.int64)
        row_ptrs.append(rp)
        part_chunks = []
        for c in range(n_chunks):
            lo, hi = c * chunk_e, min((c + 1) * chunk_e, E)
            m = hi - lo
            rp_c = _rebased_row_ptr(rp, lo, chunk_e)
            head = np.zeros(chunk_e, bool)
            starts = rp_c[:V][rp_c[:V] < rp_c[1 : V + 1]]
            head[starts] = True
            dst = np.full(chunk_e, V, np.int32)
            dst[:m] = arrays.dst_local[p, lo:hi]
            src = np.zeros(chunk_e, np.int32)
            src[:m] = arrays.src_pos[p, lo:hi]
            w = np.zeros(chunk_e, np.float32)
            w[:m] = arrays.weights[p, lo:hi]
            part_chunks.append(_HostChunk(lo, src, dst, head, w))
        chunks.append(part_chunks)
    varrays = alloc_arrays(P, V, 0)._replace(
        vtx_mask=arrays.vtx_mask.copy(),
        degree=arrays.degree.copy(),
        global_vid=arrays.global_vid.copy(),
    )
    return StreamedPullShards(
        spec=spec, cuts=shards.cuts, chunk_e=chunk_e, chunks=chunks,
        row_ptrs=row_ptrs, varrays=varrays,
    )


def _rebased_row_ptr(rp: np.ndarray, lo: int, chunk_e: int) -> np.ndarray:
    """The chunk-local (V+1,) int32 row_ptr: a pure function of the
    part's global row_ptr and the chunk base (derived per transfer, not
    stored per chunk)."""
    return np.clip(rp - lo, 0, chunk_e).astype(np.int32)


def _put_chunk(sh: StreamedPullShards, p: int, c: int):
    """Assemble and (async) transfer one chunk's device pytree."""
    hc = sh.chunks[p][c]
    return jax.device_put(StreamChunk(
        _rebased_row_ptr(sh.row_ptrs[p], hc.lo, sh.chunk_e),
        hc.src_pos, hc.dst_local, hc.head_flag, hc.weights,
    ))


@lru_cache(maxsize=64)
def _compiled_chunk_partial(prog, method: str):
    @jax.jit
    def f(chunk: StreamChunk, full_state, local_state):
        src_state = full_state[chunk.src_pos]
        dst_state = local_state[
            jnp.clip(chunk.dst_local, 0, local_state.shape[0] - 1)
        ]
        vals = prog.edge_value(src_state, chunk.weights, dst_state)
        return _REDUCERS[prog.reduce](
            vals, chunk.row_ptr, chunk.head_flag, chunk.dst_local,
            method=method,
        )

    return f


@lru_cache(maxsize=64)
def _compiled_apply(prog):
    @jax.jit
    def f(local_state, acc, varr_p):
        return prog.apply(local_state, acc, varr_p)

    return f


def run_pull_fixed_streamed(
    prog,
    sh: StreamedPullShards,
    state0,
    num_iters: int,
    method: str = "auto",
    prefetch: bool = True,
):
    """Fixed-iteration pull with host-resident edges.  ``prefetch=False``
    disables the double buffer (serial transfer->compute; the A/B knob
    for measuring the overlap win).  Returns the final (P, V, ...)
    stacked state (device)."""
    method = methods.resolve(method, prog.reduce)
    step = _compiled_chunk_partial(prog, method)
    apply_f = _compiled_apply(prog)
    varr_p = _varr_parts(jax.tree.map(jnp.asarray, sh.varrays),
                         sh.spec.num_parts)
    state = jnp.asarray(state0)
    for _ in range(num_iters):
        state = _streamed_iteration(
            prog, sh, step, apply_f, varr_p, state, prefetch
        )
    return state


def _varr_parts(varr, num_parts: int) -> list:
    """Per-part vertex-array views, sliced ONCE per run (not per chunk
    per iteration — tree-mapping inside the hot loop re-dispatched P
    slice ops every pass)."""
    return [jax.tree.map(lambda a, p=p: a[p], varr)
            for p in range(num_parts)]


def _streamed_iteration(prog, sh: StreamedPullShards, step, apply_f,
                        varr_p: list, state, prefetch: bool):
    """One whole-graph pull iteration with host-resident edges: stream
    every part's chunks (double-buffered when ``prefetch``), combine the
    per-chunk partial reductions with the reduce's own op, apply."""
    spec = sh.spec
    full = state.reshape((spec.gathered_size,) + state.shape[2:])
    new_parts = []
    dev = _put_chunk(sh, 0, 0)
    for p in range(spec.num_parts):
        acc = None
        n_chunks = len(sh.chunks[p])
        for c in range(n_chunks):
            cur = dev
            nxt = (p, c + 1) if c + 1 < n_chunks else (
                (p + 1, 0) if p + 1 < spec.num_parts else None
            )
            if prefetch and nxt is not None:
                # issue the next transfer BEFORE consuming this chunk's
                # compute: XLA executes the enqueued step while the
                # host link moves the next chunk
                dev = _put_chunk(sh, *nxt)
            part = step(cur, full, state[p])
            acc = part if acc is None else _COMBINE[prog.reduce](acc, part)
            if not prefetch:
                jax.block_until_ready(acc)  # finish compute ...
                if nxt is not None:  # ... before the next transfer
                    dev = _put_chunk(sh, *nxt)
                    jax.block_until_ready(dev)
        new_parts.append(apply_f(state[p], acc, varr_p[p]))
    return jnp.stack(new_parts)


def run_pull_until_streamed(
    prog,
    sh: StreamedPullShards,
    state0,
    max_iters: int,
    active_fn,
    method: str = "auto",
    prefetch: bool = True,
):
    """Convergence-driven streamed pull (the CC contract: iterate until
    no vertex is active).  The convergence test costs one scalar fetch
    per iteration — next to the full edge-array host->device stream the
    iteration already pays, that is noise.  Returns (final state,
    iterations run)."""
    method = methods.resolve(method, prog.reduce)
    step = _compiled_chunk_partial(prog, method)
    apply_f = _compiled_apply(prog)
    varr_p = _varr_parts(jax.tree.map(jnp.asarray, sh.varrays),
                         sh.spec.num_parts)
    state = jnp.asarray(state0)
    it = 0
    while it < max_iters:
        new = _streamed_iteration(
            prog, sh, step, apply_f, varr_p, state, prefetch
        )
        active = int(jnp.sum(jax.vmap(active_fn)(state, new)))
        state = new
        it += 1
        if active == 0:
            break
    return state, it
