"""The push (frontier/scatter) execution engine with direction optimization.

TPU-native re-design of the reference push model (core/push_model.inl +
sssp_gpu.cu/components_gpu.cu):

  * State per part: the vertex values (dist/labels) + a sparse frontier
    QUEUE of (vertex id, value) pairs with static capacity ``f_cap``.
    Carrying the value in the queue means sparse iterations exchange only
    queues — NOT the whole state — so ICI traffic per sparse round is
    O(P * f_cap), the analog of the reference's sparse-queue frontier
    (FrontierHeader::SPARSE_QUEUE, core/graph.h:100-106).
  * Direction switch per iteration (sssp_gpu.cu:414): global frontier
    count > nv/16  =>  DENSE/pull mode (segmented reduce over all in-edges
    of the all-gathered state); otherwise SPARSE/push mode (compact the
    frontier's out-edges into a fixed ``e_sp`` buffer, scatter-min/max into
    the local slice).  Overflow of any queue or edge buffer forces dense —
    the graceful sparse->dense degradation of sssp_gpu.cu:485-490.
  * The mode predicate is made GLOBAL (psum'd) so collectives (the dense
    branch's all_gather) sit inside `lax.cond` without divergence.
  * Cross-part merge (sparse rounds): bulk concatenate-and-scatter, or
    the static asynchronous reduction TREE of ops/merge_tree.py
    (``merge="tree"`` / the banked ``tpu:merge_mode`` winner) — per-part
    partial frontiers combine pairwise, bitwise-identical for the
    min/max programs at any arity.
  * Convergence: psum'd changed-vertex count reaches zero — on-device,
    zero-lag (vs the 4-iteration SLIDING_WINDOW host pipeline,
    sssp/sssp.cc:115-129).

Determinism note: the reference's sparse queues tolerate duplicate entries
via atomicMin races (sssp_gpu.cu:74-81); here queue construction is an
exact compaction (`nonzero`) and scatters are XLA scatter-min/max —
deterministic, duplicates impossible.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, NamedTuple, Protocol

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine import methods
from lux_tpu.graph.push_shards import PushArrays, PushShards, PushSpec, SRC_SENTINEL
from lux_tpu.graph.shards import ShardArrays, ShardSpec
from lux_tpu.ops import merge_tree, segment
from lux_tpu.parallel.mesh import PARTS_AXIS, flatten_gather, shard_stacked


class PushProgram(Protocol):
    """Frontier vertex program (SSSP/CC app contract)."""

    #: "min" | "max" — combiner AND monotone direction of the state.
    reduce: str

    def init_state(self, global_vid, degree, vtx_mask) -> jnp.ndarray: ...

    def init_frontier(self, global_vid, state, vtx_mask) -> jnp.ndarray:
        """Initial active mask (e.g. the single source, or everyone)."""
        ...

    def relax(self, src_val, weight) -> jnp.ndarray:
        """Candidate value pushed along an edge from a source with value
        ``src_val`` (e.g. src_val + 1 for BFS-SSSP, sssp_gpu.cu:122)."""
        ...


def _op(prog):
    return jnp.minimum if prog.reduce == "min" else jnp.maximum


def _seg_reduce(prog):
    return segment.segment_min_csc if prog.reduce == "min" else segment.segment_max_csc


def dense_part_step(prog, arr: ShardArrays, full_state, local, method="scan",
                    route=None, interpret=False, del_val=None):
    """Pull-mode relaxation over ALL in-edges (sssp_pull_kernel semantics:
    new[v] = op(old[v], op over in-edges relax(state[src])).

    ``route`` = (ExpandStatic, this part's arrays): the routed-shuffle
    expand replaces the flat gather (ops/expand.py) — relax is
    elementwise on (src, weight), so results stay bitwise identical.
    A pass-fused plan (expand.to_pf / pf=True planners) replays through
    the fused kernel family transparently — apply_expand dispatches on
    the static's type, same bits, ~half the HBM sweeps per dense
    round.  ``del_val`` (lux_tpu.mutate.overlay tombstone mask)
    neutralizes deleted base edges' relax values — exactly absorbed by
    the min/max combiner, so a dense round equals the merged graph's
    bitwise; insert folding happens once per iteration in _push_relax."""
    if route is not None:
        from lux_tpu.ops import expand

        src = expand.apply_expand(full_state, route[0], route[1],
                                  interpret=interpret)
    elif arr.mirror_pos.shape[-1] > 0:
        # compact-gather mirror (engine/pull.pull_gather_part semantics)
        src = full_state[arr.mirror_pos][arr.mirror_rel]
    else:
        src = full_state[arr.src_pos]
    vals = prog.relax(src, arr.weights)
    if del_val is not None:
        from lux_tpu.mutate import overlay as _ovl

        vals = _ovl.mask_deleted(vals, del_val, prog.reduce)
    acc = _seg_reduce(prog)(
        vals, arr.row_ptr, arr.head_flag, arr.dst_local, method=method
    )
    new = _op(prog)(local, acc)
    return jnp.where(arr.vtx_mask, new, local)


def sparse_prep(parr: PushArrays, q_vids):
    """Per-part frontier -> (row index, out-edge count) via binary search
    over the part's unique sources.  Returns (rows, counts, total)."""
    u = parr.uniq_src.shape[0]
    idx = jnp.searchsorted(parr.uniq_src, q_vids)
    idx_c = jnp.clip(idx, 0, u - 1)
    found = parr.uniq_src[idx_c] == q_vids
    starts = parr.csr_row_ptr[idx_c]
    ends = parr.csr_row_ptr[jnp.clip(idx + 1, 0, u)]
    counts = jnp.where(found, ends - starts, 0)
    incl = jnp.cumsum(counts)
    total = incl[-1] if counts.shape[0] else jnp.int32(0)
    return idx_c, counts, incl, total


def _sparse_walk(prog, pspec: PushSpec, parr: PushArrays, nv_pad,
                 q_vids, q_vals, rows, incl, cap: int | None):
    """The compacted out-edge walk both merge modes share: map each slot
    of a ``cap``-sized buffer to a (queue entry, within-entry edge) pair
    and gather (dst, candidate).  Returns (dst, cand, entry_c); invalid
    slots carry ``dst == nv_pad`` (the drop sentinel)."""
    j = jnp.arange(cap or pspec.e_sp, dtype=jnp.int32)
    entry = jnp.searchsorted(incl, j, side="right")
    entry_c = jnp.clip(entry, 0, q_vids.shape[0] - 1)
    prev = jnp.where(entry_c > 0, incl[entry_c - 1], 0)
    within = j - prev
    e_max = parr.csr_dst_local.shape[0] - 1
    edge = jnp.clip(parr.csr_row_ptr[rows[entry_c]] + within, 0, e_max)
    total = incl[-1]
    valid = j < total
    dst = jnp.where(valid, parr.csr_dst_local[edge], nv_pad)
    cand = prog.relax(q_vals[entry_c], parr.csr_weight[edge])
    return dst, cand, entry_c


def sparse_part_step(prog, pspec: PushSpec, parr: PushArrays, nv_pad,
                     q_vids, q_vals, rows, counts, incl, local,
                     cap: int | None = None):
    """Push-mode BULK merge: compact the frontier's out-edges (restricted
    to this part's dsts) into a ``cap``-sized buffer (default the full
    e_sp tier), then scatter-combine the whole concatenated frontier
    into the local slice in one pass."""
    del counts
    dst, cand, _ = _sparse_walk(
        prog, pspec, parr, nv_pad, q_vids, q_vals, rows, incl, cap
    )
    if prog.reduce == "min":
        return local.at[dst].min(cand, mode="drop")
    return local.at[dst].max(cand, mode="drop")


def sparse_part_step_tree(prog, pspec: PushSpec, parr: PushArrays, nv_pad,
                          q_vids, q_vals, rows, counts, incl, local,
                          cap: int | None = None):
    """Push-mode TREE merge (Tascade-style, ops/merge_tree.py): the same
    compacted walk, but each SOURCE part's candidates scatter into their
    own neutral-initialized partial accumulator; the per-part partials
    then combine pairwise up the static reduction tree and the root
    combines with the local slice.  min/max scatters are
    order-independent and the tree reassociates only min/max, so the
    result is bitwise-identical to the bulk scatter at any arity —
    while giving the compiler P independent partial frontiers with no
    serializing all-to-one scatter dependence (the asynchronous-merge
    shape; ISSUE 17 / PERF.md "Asynchronous merge")."""
    del counts
    dst, cand, entry_c = _sparse_walk(
        prog, pspec, parr, nv_pad, q_vids, q_vals, rows, incl, cap
    )
    # queue layout is P consecutive f_cap runs (one per source part; the
    # dist exchange may rotate WHICH part owns a run, never run layout)
    blk = entry_c // pspec.f_cap
    num_blocks = q_vids.shape[0] // pspec.f_cap
    neu = merge_tree.neutral(prog.reduce, local.dtype)
    partials = jnp.full((num_blocks,) + local.shape, neu, local.dtype)
    if prog.reduce == "min":
        partials = partials.at[blk, dst].min(cand, mode="drop")
    else:
        partials = partials.at[blk, dst].max(cand, mode="drop")
    op = _op(prog)
    return op(local, merge_tree.tree_combine(partials, op))


def _resolve_merge(merge: str | None) -> str:
    """auto-resolution shim for the cross-part merge mode (OUTSIDE the
    compile caches, like methods.resolve_sum): None reads the banked
    ``tpu:merge_mode`` winner / LUX_MERGE_MODE override."""
    m = methods.merge_mode() if merge is None else merge
    if m not in methods.MERGE_MODES:
        raise ValueError(
            f"merge must be one of {methods.MERGE_MODES}, got {m!r}")
    return m


def build_queue(pspec: PushSpec, arr: ShardArrays, changed, values):
    """Exact compaction of changed vertices into a (vid, value) queue.
    Returns (q_vid, q_val, count); count may exceed f_cap (overflow — the
    queue is then truncated and the next iteration must go dense)."""
    count = jnp.sum(changed.astype(jnp.int32))
    loc = jnp.nonzero(changed, size=pspec.f_cap, fill_value=0)[0]
    slot = jnp.arange(pspec.f_cap, dtype=jnp.int32)
    in_q = slot < count
    q_vid = jnp.where(in_q, arr.global_vid[loc], SRC_SENTINEL)
    q_val = jnp.where(in_q, values[loc], jnp.zeros((), values.dtype))
    return q_vid, q_val, count


class VertexView(NamedTuple):
    """Slim (P, V) per-vertex arrays — everything the queue/carry logic
    reads from ShardArrays, without the O(E) edge arrays (the push-ring
    driver must never device-place those)."""

    global_vid: Any
    degree: Any
    vtx_mask: Any


def vertex_view(arrays) -> VertexView:
    return VertexView(arrays.global_vid, arrays.degree, arrays.vtx_mask)


class PushCarry(NamedTuple):
    state: Any
    q_vid: Any
    q_val: Any
    count: Any
    it: Any
    active: Any
    #: edges actually traversed so far, EXACT: a (2,) uint32 [hi, lo] pair
    #: (x64 is disabled under jit and float32 absorbs increments past 2^24;
    #: the reference's per-iteration traversal accounting, SURVEY.md §6)
    edges: Any
    #: per-part sparse-round walked out-edge totals since the last driver
    #: checkpoint, SATURATING uint32 (P,) — a load signal for the
    #: repartition policy (engine/repartition.py): exact to 2^32 edges
    #: per part per window, pinned at UINT32_MAX beyond (a saturated hot
    #: part still reads hot; the policy needs the imbalance ratio, not
    #: absolute totals — tests/test_repartition.py pins the saturation
    #: behavior).
    #: Dense-round work is `dense_rounds * static part edge count`, kept
    #: out of the carry (the host derives it from the cuts).
    sp_work: Any
    #: dense rounds since the last driver checkpoint, int32 scalar.
    dense_rounds: Any


def _acc_edges(edges, dense_ne: int, sparse_total, use_dense):
    """64-bit add into the [hi, lo] uint32 pair.  ``dense_ne`` is the static
    whole-graph edge count (may exceed 2^32: split at trace time);
    ``sparse_total`` is this round's traversed count, < 2^32 by construction
    (any part whose frontier out-edges exceed e_sp forces the dense mode)."""
    d_hi = jnp.where(use_dense, jnp.uint32(dense_ne >> 32), jnp.uint32(0))
    d_lo = jnp.where(
        use_dense,
        jnp.uint32(dense_ne & 0xFFFFFFFF),
        sparse_total.astype(jnp.uint32),
    )
    lo = edges[1] + d_lo  # wraps mod 2^32
    hi = edges[0] + d_hi + (lo < edges[1]).astype(jnp.uint32)
    return jnp.stack([hi, lo])


def _zero_edges():
    return jnp.zeros((2,), jnp.uint32)


def _acc_load(c: "PushCarry", total, use_dense):
    """Window load stats for the repartition policy: sparse rounds add the
    walked out-edge totals (per part, or this part's scalar in the SPMD
    bodies) into a SATURATING uint32; dense rounds bump the shared round
    counter.  Saturation (not wrap) on overflow: a wrapped counter would
    make the window's hottest part read cold and invert the recut."""
    inc = jnp.where(use_dense, 0, jnp.asarray(total)).astype(jnp.uint32)
    added = c.sp_work + inc  # wraps mod 2^32 ...
    sp_work = jnp.where(added < c.sp_work, jnp.uint32(0xFFFFFFFF), added)
    return sp_work, c.dense_rounds + use_dense.astype(jnp.int32)




def edges_total(edges) -> int:
    """Exact Python int from the device-side [hi, lo] accumulator."""
    import numpy as np

    hi, lo = np.asarray(edges).astype(np.uint64)
    return int((hi << np.uint64(32)) | lo)


def _init_carry(prog, pspec, arrays):
    """Initial state + frontier queues (stacked (P, ...) layout)."""
    state0 = jax.vmap(prog.init_state)(
        arrays.global_vid, arrays.degree, arrays.vtx_mask
    )
    mask0 = jax.vmap(prog.init_frontier)(
        arrays.global_vid, state0, arrays.vtx_mask
    ) & arrays.vtx_mask
    q_vid, q_val, cnt = jax.vmap(partial(build_queue, pspec))(
        arrays, mask0, state0
    )
    num_parts = arrays.global_vid.shape[0]
    return PushCarry(
        state0, q_vid, q_val, cnt, jnp.int32(0), jnp.int32(1),
        _zero_edges(), jnp.zeros((num_parts,), jnp.uint32), jnp.int32(0),
    )


def _push_prep(pspec: PushSpec, spec: ShardSpec, parrays, c: PushCarry):
    """LOAD phase: flatten the frontier queues and plan each part's sparse
    out-edge walk (vmap over parts); decide the global direction.  Returns
    (q_vids_all, q_vals_all, (rows, counts, incl, totals) stacked (P, ...),
    use_dense)."""
    P_ = spec.num_parts
    q_vids_all = c.q_vid.reshape(P_ * pspec.f_cap)
    q_vals_all = c.q_val.reshape(P_ * pspec.f_cap)
    preps = jax.vmap(lambda parr: sparse_prep(parr, q_vids_all))(parrays)
    totals = preps[3]
    overflow = jnp.any(c.count > pspec.f_cap)
    edge_overflow = totals.max() > pspec.e_sp
    use_dense = (
        (jnp.sum(c.count) > spec.nv // pspec.pull_threshold_den)
        | overflow
        | edge_overflow
    )
    return q_vids_all, q_vals_all, preps, use_dense


def _push_relax(prog, pspec: PushSpec, spec: ShardSpec, method, arrays,
                parrays, c: PushCarry, q_vids_all, q_vals_all, preps,
                use_dense, route_static=None, route_arrays=None,
                interpret=False, ostatic=None, oarrays=None,
                merge: str = "bulk"):
    """COMP phase: dense (pull over all in-edges) or sparse (scatter the
    frontier's out-edges) relaxation -> new stacked state.

    ``use_dense`` is GLOBAL (identical for every part), so the direction
    switch is ONE `lax.cond` whose branches vmap over parts — a genuine
    branch (only the taken mode executes) with compile size O(1) in P,
    not the P-fold Python unroll of round 1.

    ``ostatic``/``oarrays`` (lux_tpu.mutate.overlay): dense rounds
    neutralize tombstoned base edges in-place; sparse rounds already
    skip them (the patched CSR pads their destinations to the
    drop-sentinel, build_push_overlay).  The fixed-capacity INSERT
    buffer is folded in once per round AFTER the direction branch —
    always relaxing every delta edge from the round's input state is
    monotone-safe (min/max relaxation is idempotent) and keeps both
    branches' traces identical in shape."""
    V = spec.nv_pad
    full = c.state.reshape((spec.gathered_size,) + c.state.shape[2:])
    rows, counts, incl, _ = preps
    if (ostatic is None) != (oarrays is None):
        # a loop compiled without overlay_static would otherwise
        # silently IGNORE a passed oarrays (base-graph results under a
        # caller who believes churn applied); the reverse dies on None
        raise ValueError(
            "overlay_static and oarrays must be passed together: "
            "compile_push_chunk(..., overlay_static=ostatic) and "
            "loop(..., oarrays=oarr)")
    dv = oarrays.del_val if ostatic is not None else None

    def dense_all():
        if route_static is not None:
            return jax.vmap(
                lambda arr, loc, ra, *o: dense_part_step(
                    prog, arr, full, loc, method,
                    route=(route_static, ra), interpret=interpret,
                    del_val=o[0] if o else None)
            )(arrays, c.state, route_arrays,
              *((dv,) if dv is not None else ()))
        return jax.vmap(
            lambda arr, loc, *o: dense_part_step(
                prog, arr, full, loc, method,
                del_val=o[0] if o else None)
        )(arrays, c.state, *((dv,) if dv is not None else ()))

    def sparse_all():
        step = sparse_part_step if merge == "bulk" else sparse_part_step_tree

        def run(cap):
            def f(arr, parr, r, cn, inc, loc):
                return jnp.where(
                    arr.vtx_mask,
                    step(
                        prog, pspec, parr, V, q_vids_all, q_vals_all,
                        r, cn, inc, loc, cap,
                    ),
                    loc,
                )

            return jax.vmap(f)(arrays, parrays, rows, counts, incl, c.state)

        small = pspec.e_sp_small
        if not small:
            return run(pspec.e_sp)
        # two-tier walk: a round whose largest per-part out-edge total fits
        # the small buffer pays O(e_sp_small), not O(e_sp) — the SSSP/CC
        # late-round tail is many tiny frontiers
        fits = preps[3].max() <= small
        return jax.lax.cond(
            fits, lambda: run(small), lambda: run(pspec.e_sp)
        )

    new = jax.lax.cond(use_dense, dense_all, sparse_all)
    if ostatic is None:
        return new
    from lux_tpu.mutate import overlay as _ovl

    # insert fold: O(cap) gather + drop-scatter per round, relaxing
    # every live delta edge from the round's INPUT state (c.state, the
    # same state both branches read) — exact for the monotone min/max
    # programs, and the empty-slot sentinel drops everything else
    return jax.vmap(
        lambda oa, loc: _ovl.delta_scatter(loc, full, oa, prog.relax,
                                           prog.reduce)
    )(oarrays, new)


def _push_requeue(prog, pspec: PushSpec, spec: ShardSpec, arrays,
                  c: PushCarry, new, preps, use_dense) -> PushCarry:
    """UPDATE phase: rebuild the frontier queues from changed vertices and
    account traversed edges."""
    changed = (new != c.state) & arrays.vtx_mask
    q_vid, q_val, cnt = jax.vmap(partial(build_queue, pspec))(
        arrays, changed, new
    )
    active = jnp.sum(cnt)
    # traversal accounting (SURVEY.md §6): dense walks every real edge,
    # sparse walks the frontier's out-edges (the preps totals)
    edges = _acc_edges(c.edges, spec.ne, preps[3].sum(), use_dense)
    sp_work, dense_rounds = _acc_load(c, preps[3], use_dense)
    return PushCarry(
        new, q_vid, q_val, cnt, c.it + 1, active, edges, sp_work,
        dense_rounds,
    )


def _push_iteration(prog, pspec: PushSpec, spec: ShardSpec, method,
                    arrays, parrays, c: PushCarry, route_static=None,
                    route_arrays=None, interpret=False, ostatic=None,
                    oarrays=None, merge: str = "bulk") -> PushCarry:
    """One direction-optimized iteration over all parts (single device)."""
    q_vids_all, q_vals_all, preps, use_dense = _push_prep(pspec, spec, parrays, c)
    new = _push_relax(
        prog, pspec, spec, method, arrays, parrays, c,
        q_vids_all, q_vals_all, preps, use_dense,
        route_static, route_arrays, interpret, ostatic, oarrays, merge,
    )
    return _push_requeue(prog, pspec, spec, arrays, c, new, preps, use_dense)


def compile_push_chunk(prog, pspec: PushSpec, spec: ShardSpec,
                       method: str = "auto", donate: bool = False,
                       telemetry: bool = False, overlay_static=None,
                       merge: str | None = None):
    """Single-device push loop with a DYNAMIC iteration stop (one compile
    serves every run length and every adaptive-repartition window; the
    driver inspects the carry's load stats between windows).

    ``donate=True`` selects the donating twin (the carry — state + both
    queue buffers — is consumed, argnum 2), matching the pull engine's
    run_pull_fixed/run_pull_until ``donate=`` API: the loop's ping-pong
    reuses the input carry's HBM instead of holding a second full copy.
    The caller must not reuse the carry it passed in.  luxaudit LUX-J2
    asserts the aliases actually land in the lowered module.

    ``telemetry=True`` selects the flight-recorder twin:
    ``loop(arrays, parrays, carry, it_stop, ring)`` with an
    ``obs.ring.new_ring("push")`` riding the while carry, recording
    (iteration, frontier size, traversed edges, direction) per round —
    every column derived from the carry the engine already maintains, so
    the state math (and its bytes) is untouched.  Returns (carry, ring);
    ``donate`` consumes the ring with the carry.

    ``overlay_static`` (lux_tpu.mutate.overlay.OverlayStatic) compiles
    the mutation-overlay twin: the loop then takes the stacked
    OverlayArrays as a trailing ``oarrays`` argument — occupancy is
    data, so churn re-calls never recompile (LUX-J1).

    ``merge`` ("bulk" | "tree" | None) selects the cross-part merge of
    the sparse rounds (ops/merge_tree.py); None resolves the banked
    ``tpu:merge_mode`` winner.  Bitwise-identical either way for the
    min/max push programs.

    Resolution happens OUTSIDE the compile cache: caching on "auto" would
    pin the first platform resolution for the process and split the cache
    between "auto" and its concrete equivalent."""
    return _compile_push_chunk_cached(
        prog, pspec, spec, methods.resolve_sum(method, prog.reduce),
        donate=donate, telemetry=telemetry, ostatic=overlay_static,
        merge=_resolve_merge(merge),
    )


def compile_push_chunk_routed(prog, pspec: PushSpec, spec: ShardSpec,
                              route_static, method: str = "auto",
                              donate: bool = False,
                              telemetry: bool = False,
                              overlay_static=None,
                              merge: str | None = None):
    """compile_push_chunk with the dense rounds' gather routed
    (interpret mode resolved here, off-chip = CPU tests)."""
    from lux_tpu.engine.pull import _route_interpret

    return _compile_push_chunk_cached(
        prog, pspec, spec, methods.resolve_sum(method, prog.reduce),
        route_static=route_static, interpret=_route_interpret(),
        donate=donate, telemetry=telemetry, ostatic=overlay_static,
        merge=_resolve_merge(merge),
    )


@lru_cache(maxsize=64)
def _compile_push_chunk_cached(prog, pspec: PushSpec, spec: ShardSpec,
                               method: str, route_static=None,
                               interpret=False, donate=False,
                               telemetry=False, ostatic=None,
                               merge: str = "bulk"):
    if telemetry:
        return _compile_push_chunk_telemetry(
            prog, pspec, spec, method, route_static, interpret, donate,
            ostatic, merge)

    @partial(jax.jit, donate_argnums=(2,) if donate else ())
    def loop(arrays, parrays, carry: PushCarry, it_stop, route_arrays=None,
             oarrays=None):
        def cond(c):
            return (c.active > 0) & (c.it < it_stop)

        def body(c):
            return _push_iteration(prog, pspec, spec, method, arrays,
                                   parrays, c, route_static, route_arrays,
                                   interpret, ostatic, oarrays, merge)

        return jax.lax.while_loop(cond, body, carry)

    return loop


def _compile_push_chunk_telemetry(prog, pspec: PushSpec, spec: ShardSpec,
                                  method: str, route_static, interpret,
                                  donate, ostatic=None, merge: str = "bulk"):
    """The flight-recorder twin of the push chunk loop (see
    compile_push_chunk).  A separate compile, cached under the same
    lru key family: the ring rides the while carry, every recorded
    column is a pure DERIVATION of consecutive carries (frontier =
    queued count, traversed = edge-counter delta, direction =
    dense-round delta), so the engine body is byte-for-byte the
    non-telemetry one."""
    from lux_tpu.obs import ring as obs_ring

    @partial(jax.jit, donate_argnums=(2, 4) if donate else ())
    def loop(arrays, parrays, carry: PushCarry, it_stop, ring,
             route_arrays=None, oarrays=None):
        def cond(cr):
            c, _ = cr
            return (c.active > 0) & (c.it < it_stop)

        def body(cr):
            c, rg = cr
            c2 = _push_iteration(prog, pspec, spec, method, arrays,
                                 parrays, c, route_static, route_arrays,
                                 interpret, ostatic, oarrays, merge)
            # uint32 wrap-around subtraction gives the exact per-round
            # traversed count (< 2^32 per round by construction)
            rg = obs_ring.ring_push(
                rg, c.it, c.active, c2.edges[1] - c.edges[1],
                c2.dense_rounds - c.dense_rounds)
            return c2, rg

        return jax.lax.while_loop(cond, body, (carry, ring))

    return loop


def compile_push_phases(prog, pspec: PushSpec, spec: ShardSpec,
                        method: str = "auto", merge: str | None = None):
    """Uncached resolution shim — see compile_push_chunk."""
    return _compile_push_phases_cached(
        prog, pspec, spec, methods.resolve_sum(method, prog.reduce),
        _resolve_merge(merge),
    )


@lru_cache(maxsize=64)
def _compile_push_phases_cached(prog, pspec: PushSpec, spec: ShardSpec,
                                method: str, merge: str = "bulk"):
    """One push iteration as THREE separately-jitted sub-steps for the
    -verbose phase breakdown (the reference's per-iteration
    loadTime/compTime/updateTime, sssp_gpu.cu:513-518):

      load(parrays, carry)                 -> (qv, qw, preps, use_dense)
      comp(arrays, parrays, carry, plan)   -> new stacked state
      update(arrays, carry, new, plan)     -> next PushCarry

    Observability path (fences between phases); run_push is the perf path.
    """

    @jax.jit
    def load(parrays, carry: PushCarry):
        return _push_prep(pspec, spec, parrays, carry)

    @jax.jit
    def comp(arrays, parrays, carry: PushCarry, plan):
        q_vids_all, q_vals_all, preps, use_dense = plan
        return _push_relax(
            prog, pspec, spec, method, arrays, parrays, carry,
            q_vids_all, q_vals_all, preps, use_dense, merge=merge,
        )

    @jax.jit
    def update(arrays, carry: PushCarry, new, plan):
        _, _, preps, use_dense = plan
        return _push_requeue(prog, pspec, spec, arrays, carry, new, preps, use_dense)

    return load, comp, update


def compile_push_step(prog, pspec: PushSpec, spec: ShardSpec,
                      method: str = "auto", merge: str | None = None):
    """Jitted SINGLE iteration (verbose mode / step-wise drivers — the
    per-iteration observability the reference gets from -verbose kernel
    timers, sssp_gpu.cu:513-518).  The carry is donated (state/queue
    double buffers reuse HBM)."""
    return _compile_push_step_cached(
        prog, pspec, spec, methods.resolve_sum(method, prog.reduce),
        _resolve_merge(merge),
    )


@lru_cache(maxsize=64)
def _compile_push_step_cached(prog, pspec: PushSpec, spec: ShardSpec,
                              method: str, merge: str = "bulk"):

    @partial(jax.jit, donate_argnums=2)
    def step(arrays, parrays, carry: PushCarry):
        return _push_iteration(prog, pspec, spec, method, arrays, parrays,
                               carry, merge=merge)

    return step


def push_init(prog, shards: PushShards):
    """(arrays, parrays, carry0) device tuple for step-wise driving."""
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    parrays = jax.tree.map(jnp.asarray, shards.parrays)
    return arrays, parrays, _init_carry(prog, shards.pspec, arrays)


def run_push(
    prog: PushProgram,
    shards: PushShards,
    max_iters: int = 10_000,
    method: str = "auto",
    route=None,
    donate: bool = False,
    telemetry=None,
    merge: str | None = None,
):
    """Single-device driver.  The direction switch is one global `lax.cond`
    over vmapped per-part branches — a genuine branch (only the taken mode
    executes; the global predicate makes this legal) with compile size O(1)
    in the part count.  ``route`` (ops.expand.plan_expand_shards on the
    PULL layout, unfused or pass-fused — both bitwise-identical) runs
    the dense rounds' gather through the routed expand.  ``donate=True``
    runs the donating loop twin: the freshly-built initial carry is
    consumed, so the hot loop holds ONE state + queue copy in HBM
    instead of two (the pull engine's ``donate=`` contract on the push
    side; opt-in because benchmark drivers re-run from one carry).
    ``telemetry`` (``obs.ring.new_ring("push")``) records the
    per-iteration frontier/traversed/direction curve in the loop carry
    (bitwise no-op on the results; the return gains the fetched ring).
    ``merge`` ("bulk" | "tree", None = the banked ``tpu:merge_mode``)
    selects the sparse rounds' cross-part merge — bitwise-identical for
    the min/max push programs (ops/merge_tree.py).
    Returns (final stacked state, iters, edge counter[, ring]).
    """
    method = methods.resolve_sum(method, prog.reduce)
    spec, pspec = shards.spec, shards.pspec
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    parrays = jax.tree.map(jnp.asarray, shards.parrays)
    carry0 = _init_carry(prog, pspec, arrays)
    tel = telemetry
    if tel is not None:
        tel = jax.tree.map(jnp.asarray, tel)
    extra = () if tel is None else (tel,)
    if route is None:
        loop = compile_push_chunk(prog, pspec, spec, method, donate=donate,
                                  telemetry=tel is not None, merge=merge)
        out = loop(arrays, parrays, carry0, jnp.int32(max_iters), *extra)
    else:
        rs, ra = route
        ra = jax.tree.map(jnp.asarray, ra)
        loop = compile_push_chunk_routed(prog, pspec, spec, rs, method,
                                         donate=donate,
                                         telemetry=tel is not None,
                                         merge=merge)
        out = loop(arrays, parrays, carry0, jnp.int32(max_iters), *extra,
                   route_arrays=ra)
    if tel is not None:
        out, ring = out
        return out.state, out.it, out.edges, ring
    return out.state, out.it, out.edges


def _carry_specs():
    """shard_map PartitionSpecs for the stacked PushCarry: state/queues/
    count/sp_work live on the parts axis; it/active/edges/dense_rounds are
    replicated scalars (psum'd or identical on every device)."""
    return PushCarry(
        *([P(PARTS_AXIS)] * 4), P(), P(), P(), P(PARTS_AXIS), P()
    )


def _spmd_push_prep(pspec: PushSpec, spec: ShardSpec, parr_blk,
                    c: PushCarry, merge: str = "bulk", num_dev: int = 1):
    """LOAD phase from a device's perspective inside shard_map: exchange
    the frontier (vid, value) queues (they are small: O(P * f_cap)), plan
    each resident part's sparse out-edge walk, and psum the GLOBAL
    direction/tier votes.  Returns the plan
    (q_vids_all, q_vals_all, (rows, counts, incl, totals), use_dense,
    flags) — use_dense/flags are psum results (replicated); the preps are
    per-resident-lane.

    ``merge == "tree"`` swaps the bulk all_gather barrier for the staged
    ppermute concatenation (merge_tree.staged_concat_gather) — each
    device then holds the full queue in a per-device ROTATED part order,
    which every downstream consumer absorbs (walk totals are sums, the
    destination scatter is min/max: order-independent, bitwise)."""
    if merge == "tree" and num_dev > 1:
        # unconditional straight-line collectives with static offsets —
        # the LUX-J3 deadlock-freedom argument (ops/merge_tree.py)
        q_vids_all = merge_tree.staged_concat_gather(
            c.q_vid, PARTS_AXIS, num_dev
        ).reshape(-1)
        q_vals_all = merge_tree.staged_concat_gather(
            c.q_val, PARTS_AXIS, num_dev
        ).reshape(-1)
    else:
        # device order x resident order == global part order
        # (shard_stacked gives device d parts [d*k, (d+1)*k)), so the
        # tiled gather flattens straight into the (P * f_cap,) global
        # queue view
        q_vids_all = jax.lax.all_gather(
            c.q_vid, PARTS_AXIS, tiled=True
        ).reshape(-1)
        q_vals_all = jax.lax.all_gather(
            c.q_val, PARTS_AXIS, tiled=True
        ).reshape(-1)
    rows, counts, incl, totals = jax.vmap(
        lambda parr: sparse_prep(parr, q_vids_all)
    )(parr_blk)
    g_cnt = jax.lax.psum(jnp.sum(c.count), PARTS_AXIS)
    flags = jax.lax.psum(
        jnp.stack(
            [
                jnp.sum((c.count > pspec.f_cap).astype(jnp.int32)),
                jnp.sum((totals > pspec.e_sp).astype(jnp.int32)),
                # tier vote: any part too big for the small buffer?
                jnp.sum((totals > pspec.e_sp_small).astype(jnp.int32)),
            ]
        ),
        PARTS_AXIS,
    )
    use_dense = (
        (g_cnt > spec.nv // pspec.pull_threshold_den)
        | (flags[:2].max() > 0)
    )
    return q_vids_all, q_vals_all, (rows, counts, incl, totals), use_dense, flags


def _spmd_push_relax(prog, pspec: PushSpec, spec: ShardSpec, parr_blk,
                     qarr_blk, dense_fn, c: PushCarry, plan,
                     merge: str = "bulk"):
    """COMP phase from a device's perspective: one GLOBAL `lax.cond`
    between the engine-specific dense relaxation and the sparse frontier
    scatter over the resident lanes."""
    q_vids_all, q_vals_all, (rows, counts, incl, _), use_dense, flags = plan
    local = c.state  # (k, V)
    V = spec.nv_pad

    def sparse_branch():
        step = sparse_part_step if merge == "bulk" else sparse_part_step_tree

        def run(cap):
            def f(qarr, parr, r, cn, inc, loc):
                return jnp.where(
                    qarr.vtx_mask,
                    step(
                        prog, pspec, parr, V, q_vids_all, q_vals_all,
                        r, cn, inc, loc, cap,
                    ),
                    loc,
                )

            return jax.vmap(f)(qarr_blk, parr_blk, rows, counts, incl, local)

        if not pspec.e_sp_small:
            return run(pspec.e_sp)
        # globally-agreed tier (flags[2] is a psum) — identical branch on
        # every device, collective-free branches
        return jax.lax.cond(
            flags[2] == 0, lambda: run(pspec.e_sp_small),
            lambda: run(pspec.e_sp),
        )

    return jax.lax.cond(use_dense, lambda: dense_fn(local), sparse_branch)


def _spmd_push_requeue(prog, pspec: PushSpec, spec: ShardSpec, qarr_blk,
                       c: PushCarry, new, plan) -> PushCarry:
    """UPDATE phase from a device's perspective: rebuild the frontier
    queues from changed vertices, psum the global active count, and
    account traversed edges."""
    (_, _, (_, _, _, totals), use_dense, _) = plan
    changed = (new != c.state) & qarr_blk.vtx_mask
    q_vid, q_val, cnt = jax.vmap(partial(build_queue, pspec))(
        qarr_blk, changed, new
    )
    active = jax.lax.psum(jnp.sum(cnt), PARTS_AXIS)
    # uint32 psum is exact: a sparse round's global total is bounded by
    # sum_p e_sp_p ≈ ne/4 < 2^32 (bigger frontiers force dense)
    g_total = jax.lax.psum(jnp.sum(totals.astype(jnp.uint32)), PARTS_AXIS)
    edges = _acc_edges(c.edges, spec.ne, g_total, use_dense)
    sp_work, dense_rounds = _acc_load(c, totals, use_dense)
    return PushCarry(
        new, q_vid, q_val, cnt, c.it + 1, active, edges, sp_work,
        dense_rounds,
    )


def _spmd_push_iter(prog, pspec: PushSpec, spec: ShardSpec, parr_blk,
                    qarr_blk, dense_fn, c: PushCarry,
                    merge: str = "bulk", num_dev: int = 1) -> PushCarry:
    """ONE direction-optimized iteration from a device's perspective
    inside shard_map — the single source of truth for the dist, step-dist,
    ring, and pallas engines (their only difference is ``dense_fn``), and
    for the -verbose phase split (compile_push_phases_dist jits the three
    sub-phases separately).

    Each device holds k = P / mesh_size resident parts as the leading axis
    of every blocked field (k == 1 when parts == devices); per-part work
    vmaps over the resident lanes — the mapper-slicing analog
    (core/lux_mapper.cc:102-122).

    * frontier (vid, value) queues are exchanged unconditionally (bulk
      all_gather, or ``merge == "tree"``'s staged ppermute concatenation
      — same straight-line legality, see _spmd_push_prep);
    * the mode decision is GLOBAL (psum'd count + overflow/tier flags) so
      the dense branch's collectives sit inside `lax.cond` without
      divergence;
    * ``qarr_blk`` carries the per-vertex arrays (vtx_mask/global_vid) for
      the sparse mask and queue rebuild — ShardArrays on the all-gather
      engines, the slim VertexView on the ring engine;
    * ``dense_fn(block)`` is the engine-specific dense relaxation over the
      (k, V, ...) resident block: the all-gathered segmented reduce, or
      the ppermute ring fold.
    """
    plan = _spmd_push_prep(pspec, spec, parr_blk, c, merge, num_dev)
    new = _spmd_push_relax(
        prog, pspec, spec, parr_blk, qarr_blk, dense_fn, c, plan, merge
    )
    return _spmd_push_requeue(prog, pspec, spec, qarr_blk, c, new, plan)


def _allgather_dense_fn(prog, arr_blk, method, route_static=None,
                        route_blk=None, interpret=False):
    """Dense relaxation for the all-gather engines: whole state over ICI,
    then the segmented reduce over each resident part's in-edges
    (optionally through the routed-shuffle expand — bitwise)."""

    def dense_fn(block):
        full = flatten_gather(block)
        if route_static is not None:
            return jax.vmap(
                lambda arr, loc, ra: dense_part_step(
                    prog, arr, full, loc, method,
                    route=(route_static, ra), interpret=interpret)
            )(arr_blk, block, route_blk)
        return jax.vmap(
            lambda arr, loc: dense_part_step(prog, arr, full, loc, method)
        )(arr_blk, block)

    return dense_fn


@lru_cache(maxsize=64)
def _compile_push_dist(prog, mesh, pspec: PushSpec, spec: ShardSpec,
                       method: str, route_static=None,
                       interpret: bool = False, merge: str = "bulk"):
    arr_specs = ShardArrays(*([P(PARTS_AXIS)] * len(ShardArrays._fields)))
    parr_specs = PushArrays(*([P(PARTS_AXIS)] * len(PushArrays._fields)))
    carry_specs = _carry_specs()
    routed = route_static is not None
    in_specs = (arr_specs, parr_specs, carry_specs, P())
    kw = {}
    if routed:
        in_specs = in_specs + (P(PARTS_AXIS),)
        kw["check_vma"] = False  # pallas under shard_map (see dist.py)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=carry_specs,
        **kw,
    )
    def run(arr_blk, parr_blk, carry_blk, it_stop, *route_blk):

        def cond(c):
            return (c.active > 0) & (c.it < it_stop)

        def body(c):
            return _spmd_push_iter(
                prog, pspec, spec, parr_blk, arr_blk,
                _allgather_dense_fn(
                    prog, arr_blk, method, route_static,
                    route_blk[0] if routed else None, interpret),
                c, merge, mesh.devices.size,
            )

        return jax.lax.while_loop(cond, body, carry_blk)

    return run


def compile_push_phases_dist(prog, mesh, pspec: PushSpec, spec: ShardSpec,
                             method: str = "auto"):
    """One DISTRIBUTED push iteration as THREE separately-jitted,
    fence-able shard_map sub-steps — the multi-GPU `-verbose` breakdown
    of the reference (per-GPU loadTime/compTime/updateTime printed on
    multi-GPU runs, sssp_gpu.cu:513-518):

      load(parrays, carry)                -> plan (queue all_gather + walk
                                             planning + psum'd direction)
      comp(arrays, parrays, carry, plan)  -> new stacked state (the dense
                                             branch's state all_gather
                                             happens here, as it does in
                                             the single-device split)
      update(arrays, carry, new, plan)    -> next PushCarry (queue rebuild
                                             + active/edges psums)

    The phase bodies are the SAME _spmd_push_* the fused engines use.
    Observability path; _compile_push_dist is the perf path.  Always
    bulk-merge: the phase split's plan_specs model the gathered queue
    views as value-replicated lanes, which the tree exchange's rotated
    per-device order would not be (the perf loops take merge=)."""
    return _compile_push_phases_dist_cached(
        prog, mesh, pspec, spec, methods.resolve_sum(method, prog.reduce)
    )


@lru_cache(maxsize=64)
def _compile_push_phases_dist_cached(prog, mesh, pspec: PushSpec,
                                     spec: ShardSpec, method: str):
    arr_specs = ShardArrays(*([P(PARTS_AXIS)] * len(ShardArrays._fields)))
    parr_specs = PushArrays(*([P(PARTS_AXIS)] * len(PushArrays._fields)))
    carry_specs = _carry_specs()
    Pp = P(PARTS_AXIS)
    # The gathered queue views are value-replicated but shard_map cannot
    # statically infer all_gather outputs as such, so each device carries
    # its copy as a (1, P*f_cap) lane under the parts spec (global shape
    # (D, P*f_cap) — exactly the per-device replicated queue view the
    # fused engines hold internally); psum'd votes ARE inferred
    # replicated; walk plans are per-resident-lane.
    plan_specs = (Pp, Pp, (Pp, Pp, Pp, Pp), P(), P())

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(parr_specs, carry_specs),
        out_specs=plan_specs,
    )
    def load(parr_blk, c):
        qv, qw, preps, use_dense, flags = _spmd_push_prep(
            pspec, spec, parr_blk, c
        )
        return qv[None], qw[None], preps, use_dense, flags

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(arr_specs, parr_specs, carry_specs, plan_specs),
        out_specs=Pp,
    )
    def comp(arr_blk, parr_blk, c, plan):
        qv, qw, preps, use_dense, flags = plan
        return _spmd_push_relax(
            prog, pspec, spec, parr_blk, arr_blk,
            _allgather_dense_fn(prog, arr_blk, method), c,
            (qv[0], qw[0], preps, use_dense, flags),
        )

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(arr_specs, carry_specs, Pp, plan_specs),
        out_specs=carry_specs,
    )
    def update(arr_blk, c, new, plan):
        qv, qw, preps, use_dense, flags = plan
        return _spmd_push_requeue(
            prog, pspec, spec, arr_blk, c, new,
            (qv[0], qw[0], preps, use_dense, flags),
        )

    return load, comp, update


def push_init_dist(prog, shards: PushShards, mesh: Mesh):
    """(arrays, parrays, carry0) sharded over the mesh for step-wise
    distributed driving."""
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.arrays))
    parrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.parrays))
    carry0 = _init_carry(prog, shards.pspec, jax.tree.map(jnp.asarray, shards.arrays))
    return arrays, parrays, shard_carry(mesh, carry0)


def shard_carry(mesh: Mesh, c: PushCarry) -> PushCarry:
    """Place a host/stacked PushCarry onto the mesh (parts-axis fields
    sharded, scalars replicated)."""
    sharded = shard_stacked(
        mesh, (c.state, c.q_vid, c.q_val, c.count, c.sp_work)
    )
    return PushCarry(
        *sharded[:4], c.it, c.active, c.edges, sharded[4], c.dense_rounds
    )


def assemble_carry(c_local: PushCarry, assemble) -> PushCarry:
    """Multihost analog of shard_carry: stitch a per-host LOCAL-parts
    carry into the globally-sharded one.  ``assemble(host_stacked) ->
    global jax.Array`` (e.g. multihost.assemble_global bound to the mesh).
    Keeps the sharded-vs-replicated field split in ONE place with
    shard_carry/_carry_specs; the scalar fields are process-identical by
    construction of _init_carry."""
    import numpy as np

    def sh(a):
        return assemble(np.asarray(a))

    return PushCarry(
        sh(c_local.state), sh(c_local.q_vid), sh(c_local.q_val),
        sh(c_local.count), c_local.it, c_local.active, c_local.edges,
        sh(c_local.sp_work), c_local.dense_rounds,
    )


@lru_cache(maxsize=64)
def _compile_push_ring(prog, mesh, pspec: PushSpec, spec: ShardSpec,
                       e_bucket_pad: int, method: str,
                       route_static=None, interpret: bool = False,
                       merge: str = "bulk"):
    """Direction-optimizing push with the RING dense exchange: sparse
    rounds exchange (vid, value) queues exactly like _compile_push_dist;
    dense rounds fold ppermute-streamed state blocks through the ring
    buckets (min/max end-reductions) instead of all-gathering the whole
    state — per-chip resident state stays O(nv/P), so CC/SSSP scale past
    the replicated-state ceiling (SURVEY.md §7.3)."""
    from lux_tpu.parallel.ring import RingArrays, neutral_like, ring_sweep

    num_parts = spec.num_parts
    D = mesh.devices.size
    k = num_parts // D
    rarr_specs = RingArrays(*([P(PARTS_AXIS)] * len(RingArrays._fields)))
    parr_specs = PushArrays(*([P(PARTS_AXIS)] * len(PushArrays._fields)))
    view_specs = VertexView(*([P(PARTS_AXIS)] * len(VertexView._fields)))
    carry_specs = _carry_specs()
    routed = route_static is not None
    in_specs = (rarr_specs, parr_specs, view_specs, carry_specs, P())
    kw = {}
    if routed:
        in_specs = in_specs + (P(PARTS_AXIS),)
        kw["check_vma"] = False  # pallas under shard_map (see dist.py)

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=carry_specs,
        **kw,
    )
    def run(rarr_blk, parr_blk, view_blk, carry_blk, it_stop, *route_blk):
        V = spec.nv_pad
        my = jax.lax.axis_index(PARTS_AXIS)
        op = _op(prog)

        def cond(c):
            return (c.active > 0) & (c.it < it_stop)

        def ring_dense_fn(block):  # (k, V) resident parts
            def fold(s, acc, stream):
                # the in-flight stream holds the k parts resident on device
                # (my + s) % D; fold each streamed lane's bucket into every
                # resident lane (j is a static unroll: k is a compile-time
                # geometry constant, typically small)
                dev = (my + s) % D
                for j in range(k):
                    q = dev * k + j  # global part id of streamed lane j

                    def one(rarr_i, acc_i, ra_i=None, q=q):
                        if ra_i is not None:
                            from lux_tpu.ops import expand as _expand

                            src_vals = _expand.apply_expand(
                                stream[j], route_static,
                                jax.tree.map(lambda a: a[q], ra_i),
                                interpret=interpret)
                        else:
                            src_vals = stream[j][rarr_i.src_local[q]]
                        vals = prog.relax(src_vals, rarr_i.weights[q])
                        part = segment.segment_reduce_by_ends(
                            vals, rarr_i.head_flag[q], rarr_i.dst_local[q],
                            V, reduce=prog.reduce, method=method,
                        )
                        return op(acc_i, part)

                    if routed:
                        acc = jax.vmap(one)(rarr_blk, acc, route_blk[0])
                    else:
                        acc = jax.vmap(one)(rarr_blk, acc)
                return acc

            acc = ring_sweep(block, neutral_like(block, prog.reduce), fold, D)
            return jnp.where(view_blk.vtx_mask, op(block, acc), block)

        def body(c):
            return _spmd_push_iter(
                prog, pspec, spec, parr_blk, view_blk, ring_dense_fn, c,
                merge, D,
            )

        return jax.lax.while_loop(cond, body, carry_blk)

    return run


def place_ring_statics(shards, mesh: Mesh):
    """Device-place the ring push engine's static arrays: only O(part
    edges) buckets/CSR and the O(V) vertex view — never the pull layout's
    O(E) stacked arrays.  Returns (rarrays, parrays, view)."""
    rarrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.rarrays))
    parrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.parrays))
    view = shard_stacked(
        mesh, jax.tree.map(jnp.asarray, vertex_view(shards.arrays))
    )
    return rarrays, parrays, view


def ring_init_dist(prog, shards, mesh: Mesh):
    """(rarrays, parrays, view, carry0) sharded tuple for driving the
    ring push engine."""
    rarrays, parrays, view = place_ring_statics(shards, mesh)
    carry0 = shard_carry(
        mesh,
        _init_carry(
            prog, shards.pspec,
            jax.tree.map(jnp.asarray, vertex_view(shards.arrays)),
        ),
    )
    return rarrays, parrays, view, carry0


def run_push_ring(
    prog: PushProgram,
    shards,  # parallel.ring.PushRingShards
    mesh: Mesh,
    max_iters: int = 10_000,
    method: str = "auto",
    route=None,
    merge: str | None = None,
):
    """Distributed push driver with the ring dense exchange.  Only the
    O(part edges) CSR/bucket arrays and O(V) vertex arrays touch the
    devices — never the pull layout's O(E) stacked arrays.  ``route``
    (ops.expand.plan_ring_route_shards on the ring buckets) replays the
    dense rounds' streamed-block gathers as routed lane shuffles —
    bitwise-identical (note its plan-footprint SCALE NOTE: the routed
    mode trades the O(nv/P) memory story for hot-loop speed)."""
    method = methods.resolve_sum(method, prog.reduce)
    merge = _resolve_merge(merge)
    spec, pspec = shards.spec, shards.pspec
    assert spec.num_parts % mesh.devices.size == 0
    assert method in ("scan", "scatter"), (
        segment.BUCKETED_METHODS_NOTE
    )
    rarrays, parrays, view, carry0 = ring_init_dist(prog, shards, mesh)
    if route is None:
        run = _compile_push_ring(
            prog, mesh, pspec, spec, shards.e_bucket_pad, method,
            merge=merge,
        )
        out = run(rarrays, parrays, view, carry0, jnp.int32(max_iters))
    else:
        from lux_tpu.parallel.mesh import routed_run_args

        rs, ra, interp = routed_run_args(mesh, route)
        run = _compile_push_ring(
            prog, mesh, pspec, spec, shards.e_bucket_pad, method,
            route_static=rs, interpret=interp, merge=merge,
        )
        out = run(rarrays, parrays, view, carry0, jnp.int32(max_iters), ra)
    return out.state, out.it, out.edges


def run_push_dist(
    prog: PushProgram,
    shards: PushShards,
    mesh: Mesh,
    max_iters: int = 10_000,
    method: str = "auto",
    route=None,
    merge: str | None = None,
):
    """Distributed driver: queues (sparse rounds) or whole state (dense
    rounds) exchanged over ICI inside the on-device loop.  ``route``
    (an expand plan on the pull layout) replays the dense rounds'
    gather as routed shuffles — bitwise-identical.  ``merge`` ("bulk" |
    "tree", None = banked winner): tree mode exchanges the queues via
    staged ppermutes and merges through the static reduction tree —
    also bitwise (ops/merge_tree.py)."""
    method = methods.resolve_sum(method, prog.reduce)
    merge = _resolve_merge(merge)
    spec, pspec = shards.spec, shards.pspec
    assert spec.num_parts % mesh.devices.size == 0
    arrays, parrays, carry0 = push_init_dist(prog, shards, mesh)
    if route is None:
        run = _compile_push_dist(prog, mesh, pspec, spec, method,
                                 merge=merge)
        out = run(arrays, parrays, carry0, jnp.int32(max_iters))
    else:
        from lux_tpu.engine.pull import _route_interpret
        from lux_tpu.parallel.mesh import shard_stacked

        rs, ra = route
        ra = shard_stacked(mesh, jax.tree.map(jnp.asarray, ra))
        run = _compile_push_dist(prog, mesh, pspec, spec, method,
                                 route_static=rs,
                                 interpret=_route_interpret(),
                                 merge=merge)
        out = run(arrays, parrays, carry0, jnp.int32(max_iters), ra)
    return out.state, out.it, out.edges
