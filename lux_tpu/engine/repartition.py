"""Adaptive dynamic repartitioning for the push engine.

The Lux paper (PVLDB 11(3) 2017) describes monitoring per-partition
runtimes and moving the contiguous cut boundaries to rebalance load — a
feature the reference CODE never shipped: its partitioner is the static
edge-balanced sweep (core/pull_model.inl:105-131) computed once at graph
construction.  This module is the TPU-native version of that missing
capability:

  * The engine's carry accumulates a per-part load estimate on device
    (PushCarry.sp_work = sparse-round walked out-edges per part;
    PushCarry.dense_rounds counts dense rounds, whose per-part work is the
    static real edge count derivable from the cuts on the host).
  * The driver runs the engine in windows (compile_push_chunk /
    _compile_push_dist with a dynamic `it_stop`), inspects the window's
    load split between windows, and when the estimated imbalance exceeds a
    threshold recuts with partition.weighted_cuts, rebuilds the shards,
    remaps the in-flight state + frontier to the new layout, and resumes.
    sp_work accumulates in a SATURATING uint32 — exact to 2^32 walked
    edges per part per window, pinned at UINT32_MAX beyond, so a hot part
    can never read cold however long the window runs.

Correctness: min/max label relaxation is confluent — the fixpoint is
unique regardless of the iteration/mode schedule — so the adaptive run
converges to exactly the same final state as the static run (the tests
assert array equality).  The exact traversed-edge counter (carry.edges)
is carried across repartitions unchanged.

Frontier recoverability: the per-part queues are exact compactions ONLY
while count <= f_cap; an overflowed queue is truncated (the engine then
forces a dense round, which never reads it).  A repartition at such a
window boundary would rebuild an incomplete frontier, so the driver skips
rebalancing whenever any part's count exceeds its queue capacity and
simply waits for the frontier to shrink.
"""
from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import push
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.partition import part_of_vertex, weighted_cuts
from lux_tpu.graph.push_shards import SRC_SENTINEL, build_push_shards
from lux_tpu.parallel.mesh import PARTS_AXIS


class AdaptiveResult(NamedTuple):
    state: np.ndarray  # (nv,) global final state
    iters: int
    edges: Any  # exact traversed-edge accumulator (push.edges_total)
    reparts: int  # number of repartitions performed
    shards: Any  # final PushShards layout (cuts may differ from t=0)
    stacked: Any  # final stacked device state under that layout


def part_edge_counts(cuts: np.ndarray, row_ptr: np.ndarray) -> np.ndarray:
    """Real (unpadded) in-edge count per part under ``cuts``."""
    rp = np.asarray(row_ptr)
    return (rp[cuts[1:]] - rp[cuts[:-1]]).astype(np.float64)


def part_work(sp_work: np.ndarray, dense_rounds: int, cuts: np.ndarray,
              row_ptr: np.ndarray) -> np.ndarray:
    """Estimated edges processed per part over the window: each dense
    round walks every real in-edge of the part; sparse rounds walked the
    accumulated ``sp_work`` out-edge totals."""
    return (
        np.asarray(sp_work, np.float64)
        + float(dense_rounds) * part_edge_counts(cuts, row_ptr)
    )


def imbalance(work: np.ndarray) -> float:
    """max/mean load ratio (1.0 = perfectly balanced)."""
    total = float(work.sum())
    if total <= 0.0:
        return 1.0
    return float(work.max()) * len(work) / total


def vertex_weights(work: np.ndarray, cuts: np.ndarray,
                   row_ptr: np.ndarray) -> np.ndarray:
    """Per-vertex work estimate for the recut: the part's measured
    per-edge intensity (work / real edges) spread over its vertices
    proportionally to in-degree, plus a small floor so zero-degree
    stretches still consume boundary room."""
    nv = len(row_ptr) - 1
    deg = np.diff(np.asarray(row_ptr)).astype(np.float64)
    e_counts = part_edge_counts(cuts, row_ptr)
    intensity = work / np.maximum(e_counts, 1.0)
    owner = part_of_vertex(cuts, np.arange(nv, dtype=np.int64))
    w = deg * intensity[owner]
    floor = max(w.mean() * 1e-3, 1e-9)
    return w + floor


def _changed_mask_from_queues(q_vid: np.ndarray, counts: np.ndarray,
                              f_cap: int, nv: int) -> np.ndarray:
    """Global changed-vertex mask from the per-part (vid, value) queues.
    One vectorized gather over all parts (a per-part Python loop adds
    O(P) host latency to every recut)."""
    assert counts.max() <= f_cap, "truncated queue: frontier unrecoverable"
    q = np.asarray(q_vid)
    slot = np.arange(q.shape[1])
    vids = q[slot[None, :] < np.asarray(counts)[:, None]]
    vids = vids[vids != SRC_SENTINEL]
    mask = np.zeros(nv, dtype=bool)
    mask[vids] = True
    return mask


def _rebuild_carry(prog, shards_new, state_g: np.ndarray,
                   changed_g: np.ndarray, it, edges) -> push.PushCarry:
    """Remap in-flight state + frontier onto a fresh shard layout.  Only
    the slim O(V) VertexView touches the device here — the O(E) edge
    arrays are placed (sharded) by the caller's engine setup."""
    view = jax.tree.map(
        jnp.asarray, push.vertex_view(shards_new.arrays)
    )
    state_st = jnp.asarray(shards_new.pull.global_to_stacked(state_g))
    changed_st = (
        jnp.asarray(shards_new.pull.global_to_stacked(changed_g))
        & view.vtx_mask
    )
    q_vid, q_val, cnt = jax.vmap(partial(push.build_queue, shards_new.pspec))(
        view, changed_st, state_st
    )
    num_parts = shards_new.spec.num_parts
    return push.PushCarry(
        state_st, q_vid, q_val, cnt, jnp.int32(it), jnp.sum(cnt),
        jnp.asarray(edges), jnp.zeros((num_parts,), jnp.uint32),
        jnp.int32(0),
    )


def _reset_window(carry: push.PushCarry) -> push.PushCarry:
    """Zero the window load stats without touching state/frontier."""
    return carry._replace(
        sp_work=jax.device_put(
            np.zeros(carry.sp_work.shape, np.uint32), carry.sp_work.sharding
        ),
        dense_rounds=jax.device_put(
            np.int32(0), carry.dense_rounds.sharding
        ),
    )


def _place_statics(prog, shards, mesh, method, exchange):
    """Device-place a layout's static arrays and fetch the compiled window
    loop.  Returns (statics, loop) with loop(*statics, carry, it_stop)."""
    if mesh is None:
        arrays = jax.tree.map(jnp.asarray, shards.arrays)
        parrays = jax.tree.map(jnp.asarray, shards.parrays)
        loop = push.compile_push_chunk(
            prog, shards.pspec, shards.spec, method
        )
        return (arrays, parrays), loop
    from lux_tpu.parallel.mesh import shard_stacked

    if exchange == "ring":
        loop = push._compile_push_ring(
            prog, mesh, shards.pspec, shards.spec, shards.e_bucket_pad,
            method,
        )
        return push.place_ring_statics(shards, mesh), loop
    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.arrays))
    parrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, shards.parrays))
    loop = push._compile_push_dist(
        prog, mesh, shards.pspec, shards.spec, method
    )
    return (arrays, parrays), loop


def _preflight_recut(shards, exchange, k: int = 1):
    """A recut can concentrate edges and grow e_pad/e_sp/buckets past what
    the startup preflight validated — re-check before allocating.  ``k``
    is the resident-parts-per-device factor (parts on a single device, or
    num_parts / mesh size when parts exceed the mesh)."""
    from lux_tpu.utils import preflight

    if exchange == "ring":
        est = preflight.estimate_push_ring(
            shards.spec, shards.pspec, shards.e_bucket_pad
        )
    else:
        est = preflight.estimate_push(shards.spec, shards.pspec)
    preflight.check_fits(preflight.scale_residency(est, k))


def run_push_adaptive(
    prog,
    g: HostGraph,
    num_parts: int,
    chunk: int = 32,
    threshold: float = 1.25,
    max_iters: int = 10_000,
    method: str = "auto",
    mesh=None,
    on_repartition=None,
    shards=None,
    exchange: str = "allgather",
    sort_segments: bool = False,
    compact_gather: bool = False,
):
    """Direction-optimized push with window-based dynamic repartitioning.

    Runs ``chunk`` iterations at a time; between windows, if the measured
    per-part load imbalance (max/mean) exceeds ``threshold``, recuts with
    weighted_cuts and resumes on the rebuilt layout.  ``mesh`` selects the
    distributed engine; None runs single-device.  ``exchange`` picks the
    dense-round strategy: "allgather" (replicated state) or "ring"
    (ppermute-streamed O(nv/P) blocks — needs a mesh; the composition for
    graphs that are both big AND skewed).
    ``on_repartition(it, old_cuts, new_cuts, work)`` observes each recut;
    ``shards`` optionally supplies a pre-built initial layout.

    Returns an AdaptiveResult.  Each repartition recompiles the window
    loop for the new geometry — worth it only when windows are long
    enough to amortize (the policy exists for skewed long runs, not
    5-iteration BFS tails).
    """
    from lux_tpu.engine import methods

    method = methods.resolve(method, prog.reduce)
    if chunk <= 0:
        raise ValueError(f"chunk must be positive, got {chunk}")
    if exchange not in ("allgather", "ring"):
        raise ValueError(f"unsupported exchange {exchange!r}")
    if sort_segments and exchange != "allgather":
        raise ValueError(
            "sort_segments relays out the allgather dense-round layout; "
            "the ring bucket layout has its own edge order"
        )
    if compact_gather and exchange != "allgather":
        raise ValueError(
            "compact_gather mirrors the allgather dense-round layout; "
            "the ring bucket layout ships only owned slices"
        )
    if exchange == "ring" and mesh is None:
        raise ValueError("exchange='ring' needs a mesh")

    def build(cuts=None):
        if exchange == "ring":
            from lux_tpu.parallel.ring import build_push_ring_shards

            return build_push_ring_shards(g, num_parts, cuts=cuts)
        # recuts keep the caller's gather-layout choices
        return build_push_shards(
            g, num_parts, cuts=cuts, sort_segments=sort_segments,
            compact_gather=compact_gather,
        )

    if shards is None:
        shards = build()
    if mesh is not None:
        assert num_parts % mesh.devices.size == 0
    statics, loop = _place_statics(prog, shards, mesh, method, exchange)
    carry = push._init_carry(
        prog, shards.pspec,
        jax.tree.map(jnp.asarray, push.vertex_view(shards.arrays)),
    )
    if mesh is not None:
        carry = push.shard_carry(mesh, carry)
    reparts = 0
    while True:
        it_stop = jnp.int32(min(int(carry.it) + chunk, max_iters))
        carry = loop(*statics, carry, it_stop)
        it, active = int(carry.it), int(carry.active)
        if active == 0 or it >= max_iters:
            break
        counts = np.asarray(carry.count)
        if counts.max() > shards.pspec.f_cap:
            # truncated queues: the frontier is not recoverable from the
            # carry — defer rebalancing until it shrinks
            carry = _reset_window(carry)
            continue
        work = part_work(
            np.asarray(carry.sp_work), int(carry.dense_rounds),
            shards.cuts, g.row_ptr,
        )
        if imbalance(work) < threshold:
            carry = _reset_window(carry)
            continue
        new_cuts = weighted_cuts(
            vertex_weights(work, shards.cuts, g.row_ptr), num_parts
        )
        if np.array_equal(new_cuts, shards.cuts):
            carry = _reset_window(carry)
            continue
        state_g = shards.scatter_to_global(np.asarray(carry.state))
        changed_g = _changed_mask_from_queues(
            np.asarray(carry.q_vid), counts, shards.pspec.f_cap, g.nv
        )
        if on_repartition is not None:
            on_repartition(it, shards.cuts, new_cuts, work)
        shards = build(cuts=new_cuts)
        k_res = (num_parts // mesh.shape[PARTS_AXIS]) if mesh is not None \
            else num_parts
        _preflight_recut(shards, exchange, k_res)
        carry = _rebuild_carry(
            prog, shards, state_g, changed_g, it, np.asarray(carry.edges)
        )
        if mesh is not None:
            carry = push.shard_carry(mesh, carry)
        statics, loop = _place_statics(prog, shards, mesh, method, exchange)
        reparts += 1
    state_g = shards.scatter_to_global(np.asarray(carry.state))
    return AdaptiveResult(
        state_g, int(carry.it), carry.edges, reparts, shards, carry.state
    )
