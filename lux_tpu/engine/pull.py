"""The pull (gather) execution engine.

TPU-native equivalent of the reference pull model (core/pull_model.inl +
per-app `pull_app_task_impl` kernels): every iteration, each part reads the
WHOLE previous vertex state and writes only its own contiguous slice
(region contract at core/pull_model.inl:454-469).  Here that contract is:

    full_state  = all parts' padded states, concatenated -> (P*V, ...)
    local_state = this part's padded slice                -> (V, ...)

and one iteration per part is

    gather src states -> per-edge values -> segmented reduce by dst -> apply.

Apps plug in as `PullProgram`s (a gather-apply vertex program).  The engine
provides single-device execution (vmap over parts); the multi-chip driver
(lux_tpu.parallel.dist) reuses the same per-part step inside shard_map with
`all_gather` supplying full_state over ICI.

Iteration pipelining: the reference keeps 4 speculative iterations in flight
through Legion futures (SLIDING_WINDOW, sssp/app.h:20) to hide host latency.
On TPU the entire loop lives on-device in `lax.fori_loop` /
`lax.while_loop` (convergence via summed active counts), so there is no host
round-trip to hide at all.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp

from lux_tpu import obs
from lux_tpu.engine import methods
from lux_tpu.graph.shards import ShardArrays, ShardSpec
from lux_tpu.obs import ring as obs_ring
from lux_tpu.ops import segment


class PullProgram(Protocol):
    """A gather-apply vertex program (the app contract, analog of the
    compile-time app.h + kernel pair in the reference)."""

    #: "sum" | "min" | "max" — the per-destination combiner.
    reduce: str

    def init_state(self, global_vid: jnp.ndarray, degree: jnp.ndarray,
                   vtx_mask: jnp.ndarray) -> Any:
        """Per-vertex initial state for one part (padded slots included)."""
        ...

    def edge_value(self, src_state: jnp.ndarray, weight: jnp.ndarray,
                   dst_state: jnp.ndarray = None) -> jnp.ndarray:
        """Per-edge value from the gathered source state (and weight).
        ``dst_state`` is the destination's CURRENT state gathered per edge
        (needed by CF's error term; unused gathers are DCE'd by XLA)."""
        ...

    def apply(self, old_local: jnp.ndarray, acc: jnp.ndarray,
              arrays: ShardArrays) -> jnp.ndarray:
        """New local state from the old state and the reduced per-dst acc."""
        ...


_REDUCERS: dict[str, Callable] = segment.reducers()


def _route_interpret() -> bool:
    """Pallas interpret mode off-chip (CPU tests / virtual meshes)."""
    return jax.default_backend() not in ("tpu", "axon")


def _dst_gather(arrays: ShardArrays, local_state: jnp.ndarray):
    """Per-edge destination-state read (sentinel-clipped), shared by the
    direct and routed LOAD paths so the padding convention can't drift."""
    return local_state[jnp.clip(arrays.dst_local, 0, local_state.shape[0] - 1)]


def pull_gather_part_routed(arrays: ShardArrays, full_state: jnp.ndarray,
                            local_state: jnp.ndarray, route_static,
                            route_arrays, interpret: bool):
    """LOAD phase via the routed expand (ops/expand.py): the per-edge
    state read as Benes lane shuffles instead of a flat XLA gather —
    bitwise equal on real edge slots (padding junk is only ever read
    through row_ptr / the dst_local sentinel, same as the direct
    layout's state[0] reads there)."""
    from lux_tpu.ops import expand

    src_state = expand.apply_expand(full_state, route_static, route_arrays,
                                    interpret=interpret)
    return src_state, _dst_gather(arrays, local_state)


def pull_gather_part(arrays: ShardArrays, full_state: jnp.ndarray,
                     local_state: jnp.ndarray):
    """LOAD phase for ONE part: the per-edge (src, dst) state gather —
    the replicated-state read the reference's load_kernel does ZC→FB
    (pagerank_gpu.cu:34-47).  Shared by the fused step and the -verbose
    phase split (single-device AND distributed) so the phase boundary
    can never drift from the fused math.

    With the compact-gather layout (nonzero mirror width — a STATIC
    shape, so this branch resolves at trace time), the per-edge read is
    two-stage like the reference's load_kernel: one O(U) ascending
    gather fills the part's unique-in-source mirror, then the O(E)
    per-edge gather indexes the U-sized mirror instead of the (P*V,)
    state.  mirror_pos[mirror_rel] == src_pos exactly, so results are
    bitwise identical to the direct layout."""
    if arrays.mirror_pos.shape[-1] > 0:
        mirror = full_state[arrays.mirror_pos]  # (U, ...) compact stage
        src_state = mirror[arrays.mirror_rel]   # (E, ...) from U, not P*V
    else:
        src_state = full_state[arrays.src_pos]  # (E, ...) direct gather
    return src_state, _dst_gather(arrays, local_state)


def pull_reduce_part(prog: PullProgram, arrays: ShardArrays, gath,
                     method: str, del_val=None):
    """COMP phase for ONE part: per-edge values + segmented reduce by
    destination (the pr_kernel hot loop, pagerank_gpu.cu:49-102).
    ``del_val`` (the mutation overlay's tombstone mask,
    lux_tpu.mutate.overlay) neutralizes deleted base edges' VALUES —
    the base arrays and the reduce itself run unchanged, so the overlay
    never retraces (LUX-J1)."""
    src_state, dst_state = gath
    vals = prog.edge_value(src_state, arrays.weights, dst_state)
    if del_val is not None:
        from lux_tpu.mutate import overlay as _ovl

        vals = _ovl.mask_deleted(vals, del_val, prog.reduce)
    return _REDUCERS[prog.reduce](
        vals, arrays.row_ptr, arrays.head_flag, arrays.dst_local, method=method
    )


def local_pull_step(
    prog: PullProgram,
    arrays: ShardArrays,
    full_state: jnp.ndarray,
    local_state: jnp.ndarray,
    method: str = "scan",
    route=None,
    interpret: bool = False,
    overlay=None,
) -> jnp.ndarray:
    """One pull iteration for ONE part.  ``full_state`` is the (P*V, ...)
    concatenated padded state of all parts; ``local_state`` is (V, ...).
    ``route`` = (ExpandStatic, per-part arrays) switches the LOAD phase
    to the routed-shuffle expand; (FusedStatic, arrays) replaces BOTH
    the load and the segmented reduce with the fused routed pipeline
    (ops/expand.apply_fused — dst-state-independent programs only).
    ``overlay`` = (OverlayStatic, this part's OverlayArrays): the
    dynamic-graph mutation overlay (lux_tpu.mutate) — tombstoned base
    edges neutralize, then the fixed-capacity insert buffer gathers D
    extra source states and scatter-combines them into the accumulator
    BEFORE apply.  Static shapes throughout: churn never retraces.
    Overlays compose with the expand AND fused-pf/fused-mx routes (the
    fused families tombstone in group space through the plan's gslot
    route, apply_fused ``del_val=``); only the CF route remains
    overlay-free (mutate.overlay.FUSED_OVERLAY_NOTE)."""
    from lux_tpu.ops import expand

    if overlay is not None and route is not None and isinstance(
            route[0], expand.CFRouteStatic):
        from lux_tpu.mutate.overlay import FUSED_OVERLAY_NOTE

        raise ValueError(FUSED_OVERLAY_NOTE)
    if route is not None and isinstance(route[0], expand.CFRouteStatic):
        gath = expand.apply_cf_route(full_state, local_state, route[0],
                                     route[1], interpret=interpret)
        acc = pull_reduce_part(prog, arrays, gath, method)
        return prog.apply(local_state, acc, arrays)
    if route is not None and isinstance(route[0], expand.FusedStatic):
        assert route[0].reduce == prog.reduce, (
            f"fused plan was built for reduce={route[0].reduce!r} but the "
            f"program reduces with {prog.reduce!r}")
        acc = expand.apply_fused(
            full_state, route[0], route[1],
            edge_value=lambda s, w: prog.edge_value(s, w, None),
            interpret=interpret,
            del_val=overlay[1].del_val if overlay is not None else None)
        if overlay is not None:
            from lux_tpu.mutate import overlay as _ovl

            acc = _ovl.delta_scatter(
                acc, full_state, overlay[1],
                lambda s, w: prog.edge_value(s, w, None), prog.reduce)
        return prog.apply(local_state, acc, arrays)
    if route is not None:
        gath = pull_gather_part_routed(arrays, full_state, local_state,
                                       route[0], route[1], interpret)
    else:
        gath = pull_gather_part(arrays, full_state, local_state)
    acc = pull_reduce_part(
        prog, arrays, gath, method,
        del_val=overlay[1].del_val if overlay is not None else None)
    if overlay is not None:
        from lux_tpu.mutate import overlay as _ovl

        acc = _ovl.delta_scatter(
            acc, full_state, overlay[1],
            lambda s, w: prog.edge_value(s, w, None), prog.reduce)
    return prog.apply(local_state, acc, arrays)


def init_state(prog: PullProgram, arrays: ShardArrays) -> jnp.ndarray:
    """Stacked (P, V, ...) initial state via vmap over parts."""
    return jax.vmap(prog.init_state)(
        jnp.asarray(arrays.global_vid),
        jnp.asarray(arrays.degree),
        jnp.asarray(arrays.vtx_mask),
    )


def _pull_iteration(prog, spec: ShardSpec, method, arrays, state,
                    route_static=None, route_arrays=None,
                    interpret: bool = False, ostatic=None, oarrays=None):
    """One pull iteration over the whole (P, V, ...) shard stack.
    ``ostatic``/``oarrays`` carry the mutation overlay (static half as
    a jit static, arrays vmapped with the shards)."""
    full = state.reshape((spec.gathered_size,) + state.shape[2:])

    def step(arr, loc, ra=None, oa=None):
        return local_pull_step(
            prog, arr, full, loc, method,
            route=(route_static, ra) if route_static is not None else None,
            interpret=interpret,
            overlay=(ostatic, oa) if ostatic is not None else None)

    if route_static is None and ostatic is None:
        return jax.vmap(lambda arr, loc: step(arr, loc))(arrays, state)
    if route_static is None:
        return jax.vmap(
            lambda arr, loc, oa: step(arr, loc, oa=oa)
        )(arrays, state, oarrays)
    if ostatic is None:
        return jax.vmap(
            lambda arr, loc, ra: step(arr, loc, ra=ra)
        )(arrays, state, route_arrays)
    return jax.vmap(
        lambda arr, loc, ra, oa: step(arr, loc, ra=ra, oa=oa)
    )(arrays, state, route_arrays, oarrays)


def compile_pull_step(prog: PullProgram, spec: ShardSpec, method: str = "auto",
                      route=None):
    """Jitted SINGLE pull iteration over the whole shard stack (verbose
    mode / step-wise drivers).  The state buffer is donated — the ping-pong
    double buffer of the reference (dist_lr[2], core/graph.h:83) without
    holding both copies.  ``route``: a routed-pull plan; its device-
    placed arrays are bound as ordinary jit arguments (already-on-device
    operands cost nothing per call — baking them in as closure constants
    would bloat the lowered module instead)."""
    method = methods.resolve_sum(method, prog.reduce)
    rs, ra = route if route is not None else (None, None)
    interpret = _route_interpret()
    if ra is None:

        @partial(jax.jit, donate_argnums=1)
        def step(arrays, state):
            return _pull_iteration(prog, spec, method, arrays, state)

        return step
    ra = jax.tree.map(jnp.asarray, ra)

    @partial(jax.jit, donate_argnums=1)
    def routed_step(arrays, state, route_arrays):
        return _pull_iteration(prog, spec, method, arrays, state,
                               rs, route_arrays, interpret)

    return lambda arrays, state: routed_step(arrays, state, ra)


def compile_pull_phases(prog: PullProgram, spec: ShardSpec, method: str = "auto"):
    """One pull iteration as THREE separately-jitted, fence-able sub-steps
    — the per-phase observability of the reference's -verbose kernel timers
    (loadTime/compTime/updateTime, sssp_gpu.cu:513-518):

      load(arrays, state)          -> per-edge gathered (src, dst) states
                                      (the replicated-state HBM read phase)
      comp(arrays, gath)           -> per-destination reduced accumulators
                                      (edge_value + segmented reduction)
      update(arrays, state, acc)   -> new state (apply)

    Fencing between phases costs dispatch latency and blocks cross-phase
    fusion — this is the observability path; run_pull_fixed is the perf
    path.  Returns (load, comp, update).
    """
    method = methods.resolve_sum(method, prog.reduce)

    @jax.jit
    def load(arrays, state):
        full = state.reshape((spec.gathered_size,) + state.shape[2:])
        return jax.vmap(
            lambda arr, loc: pull_gather_part(arr, full, loc)
        )(arrays, state)

    @jax.jit
    def comp(arrays, gathered):
        return jax.vmap(
            lambda arr, gath: pull_reduce_part(prog, arr, gath, method)
        )(arrays, gathered)

    @partial(jax.jit, donate_argnums=1)
    def update(arrays, state, acc):
        return jax.vmap(lambda arr, local, a: prog.apply(local, a, arr))(
            arrays, state, acc
        )

    return load, comp, update


def _pull_fixed_fn(prog, spec, num_iters, method, arrays, state0,
                   ring=None, route_static=None, route_arrays=None,
                   interpret=False, ostatic=None, oarrays=None):
    def body(_, state):
        return _pull_iteration(prog, spec, method, arrays, state,
                               route_static, route_arrays, interpret,
                               ostatic, oarrays)

    if ring is None:
        return jax.lax.fori_loop(0, num_iters, body, state0)

    # telemetry twin: the ring rides the SAME fori carry (static shapes,
    # LUX-J1) and the state math is untouched — bitwise-identical
    # results, one extra O(P*V) residual reduction per iteration against
    # the O(E) gather work (obs/ring.py; the l1 residual is the
    # convergence curve for the fixed-iteration apps)
    def body_t(i, carry):
        state, rg = carry
        new = body(i, state)
        resid = jnp.sum(jnp.abs(new.astype(jnp.float32)
                                - state.astype(jnp.float32)))
        return new, obs_ring.ring_push(rg, i, resid)

    return jax.lax.fori_loop(0, num_iters, body_t, (state0, ring))


_PULL_FIXED_STATICS = ("prog", "spec", "num_iters", "method",
                       "route_static", "interpret", "ostatic")
_pull_fixed_jit = jax.jit(_pull_fixed_fn,
                          static_argnames=_PULL_FIXED_STATICS)
#: donating twin: state0 (positional 5) is consumed, so the loop's
#: ping-pong can reuse its HBM buffer instead of holding TWO full state
#: copies for the whole run (the reference's dist_lr[2] double buffer,
#: core/graph.h:83, without the second copy).  Opt-in via ``donate=``:
#: benchmark timing loops re-run from one s0 and must keep it alive.
#: The telemetry ring (positional 6) is donated WITH the state: it is
#: pure loop carry, so its input buffer is dead the moment the loop
#: starts (None when telemetry is off — an empty pytree donates
#: nothing; luxaudit LUX-J2 audits both aliases).
_pull_fixed_jit_donate = jax.jit(_pull_fixed_fn,
                                 static_argnames=_PULL_FIXED_STATICS,
                                 donate_argnums=(5, 6))


def run_pull_fixed(
    prog: PullProgram,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0: jnp.ndarray,
    num_iters: int,
    method: str = "auto",
    route=None,
    donate: bool = False,
    telemetry=None,
    overlay=None,
):
    """Single-device driver: fixed iteration count (PageRank/CF style,
    pagerank/pagerank.cc:109-114).  Whole loop stays on device; the
    compiled program is cached on (prog, spec, num_iters, method).
    ``method="auto"`` resolves to the platform's measured winner
    (engine.methods).  ``route`` (from ops.expand.plan_expand_shards)
    switches the LOAD phase to the routed-shuffle expand — bitwise-equal
    results, measured ~15 HBM-bandwidth passes instead of an E-sized
    scalar-issue-bound flat gather (a pass-fused ``pf=True`` plan cuts
    that to ~7, same bits).  ``donate=True`` donates ``state0`` to the
    loop (jit donate_argnums) so the hot loop holds ONE full state copy
    in HBM instead of two — the caller must not reuse ``state0`` after.
    ``telemetry`` (an ``obs.ring.new_ring("pull_fixed")``) carries the
    per-iteration residual curve in the loop carry — results stay
    bitwise-identical, the return becomes (state, ring), and a donating
    run consumes the ring with the state.
    ``overlay`` ((OverlayStatic, OverlayArrays) from
    lux_tpu.mutate.overlay) runs the step against the mutating graph:
    base gathers unchanged, tombstones neutralized, the fixed-capacity
    insert buffer folded in per iteration — occupancy is data, so
    churn never recompiles (luxaudit LUX-J1 pins it).
    Returns the final stacked (P, V, ...) state.
    """
    method = methods.resolve_sum(method, prog.reduce)
    arrays = jax.tree.map(jnp.asarray, arrays)
    rs, ra = route if route is not None else (None, None)
    if ra is not None:
        ra = jax.tree.map(jnp.asarray, ra)
    os_, oa = overlay if overlay is not None else (None, None)
    if oa is not None:
        oa = jax.tree.map(jnp.asarray, oa)
    tel = telemetry
    if tel is not None:
        tel = jax.tree.map(jnp.asarray, tel)
    fn = _pull_fixed_jit_donate if donate else _pull_fixed_jit
    return fn(prog, spec, num_iters, method, arrays, state0, tel,
              route_static=rs, route_arrays=ra,
              interpret=_route_interpret(), ostatic=os_, oarrays=oa)


def run_pull_fixed_overlapped(
    prog: PullProgram,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0: jnp.ndarray,
    num_iters: int,
    method: str = "auto",
    route_future=None,
    chunk: int = 1,
):
    """run_pull_fixed that PIPELINES routed-plan construction with the
    first iterations: while ``route_future`` (an ops.expand.PlanFuture)
    is still building on the host, iterations run in ``chunk``-sized
    direct-gather windows; the moment the plan resolves, the remaining
    iterations run routed.  The routed expand (and the CF route) is
    bitwise-equal to the direct gather, so the handover point cannot
    change the result.  Fused plans change the reduce ASSOCIATION, so a
    mid-run handover would mix two deterministic orders: a fused future
    that is already resolved at entry runs fused from iteration 0 (the
    normal fused semantics); one that resolves mid-run finishes the
    remaining iterations DIRECT instead — completed device work is
    never discarded, and the result is exactly the direct engine's.

    This is the time-to-first-iteration fix for cold plan caches
    (VERDICT r5 #6): an engine no longer stalls ~90 s/part at 2^24
    before its first dense round.  Returns (final_state, routed_iters)
    — routed_iters counts how many iterations actually ran routed, so
    drivers can report the overlap honestly.  Compile note: each
    distinct handover residual (num_iters - done) is a separate jit
    static; a driver calls this once per run, and repeat processes hit
    the persistent XLA compile cache, so the program-cache growth is
    bounded in practice.
    """
    from lux_tpu.ops import expand

    if route_future is None:
        return run_pull_fixed(prog, spec, arrays, state0, num_iters,
                              method), 0
    if route_future.ready():
        route = route_future.result()
        return run_pull_fixed(prog, spec, arrays, state0, num_iters,
                              method, route=route), num_iters
    state = state0
    done = 0
    while done < num_iters and not route_future.ready():
        k = min(chunk, num_iters - done)
        # per-chunk flight-recorder span (host-side, OUTSIDE the
        # compiled loop — the block_until_ready below is the handover
        # race's own fence, not a telemetry one)
        with obs.span("pull.chunk", k=k, done=done, routed=False):
            # chunks after the first own their input state (the previous
            # chunk's output) — donate it so the handover loop never
            # holds two full state copies; the caller's state0 itself
            # stays alive
            state = run_pull_fixed(prog, spec, arrays, state, k, method,
                                   donate=done > 0)
            # materialize before re-polling: dispatch is async, so
            # without a sync the loop would queue every chunk before the
            # future could ever win the race
            jax.block_until_ready(state)
        done += k
    if done >= num_iters:
        return state, 0
    route = route_future.result()
    if isinstance(route[0], expand.FusedStatic):
        # mixing associations mid-run is invalid; the direct result IS a
        # valid deterministic answer, so finish direct rather than throw
        # away the iterations already computed
        with obs.span("pull.chunk", k=num_iters - done, done=done,
                      routed=False, fused_skip=True):
            state = run_pull_fixed(prog, spec, arrays, state,
                                   num_iters - done, method,
                                   donate=done > 0)
        return state, 0
    with obs.span("pull.chunk", k=num_iters - done, done=done,
                  routed=True):
        state = run_pull_fixed(prog, spec, arrays, state, num_iters - done,
                               method, route=route, donate=done > 0)
    return state, num_iters - done


def run_pull_until(
    prog: PullProgram,
    spec: ShardSpec,
    arrays: ShardArrays,
    state0: jnp.ndarray,
    max_iters: int,
    active_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    method: str = "auto",
    route=None,
    donate: bool = False,
    telemetry=None,
    overlay=None,
):
    """Single-device driver: iterate until no vertex is active (the push-app
    convergence contract — total active count == 0, sssp/sssp.cc:115-129 —
    but with the test on-device instead of 4 iterations behind on the host).

    active_fn(old_stacked, new_stacked) -> per-part active counts (P,);
    pass a top-level function (hashable) so the compiled loop caches.
    ``donate=True`` consumes ``state0`` (see run_pull_fixed).
    ``telemetry`` (``obs.ring.new_ring("pull_until")``) records the
    per-iteration active count in the loop carry (bitwise no-op on the
    state; the return becomes (state, iters, ring)).  ``overlay`` runs
    against the mutating graph (see run_pull_fixed) — this is the
    incremental-refresh entry point (lux_tpu.mutate.refresh): warm
    state in, iterate the overlay step until quiescent.
    Returns (final_state, num_iters_run).
    """
    method = methods.resolve_sum(method, prog.reduce)
    arrays = jax.tree.map(jnp.asarray, arrays)
    rs, ra = route if route is not None else (None, None)
    if ra is not None:
        ra = jax.tree.map(jnp.asarray, ra)
    os_, oa = overlay if overlay is not None else (None, None)
    if oa is not None:
        oa = jax.tree.map(jnp.asarray, oa)
    tel = telemetry
    if tel is not None:
        tel = jax.tree.map(jnp.asarray, tel)
    fn = _pull_until_jit_donate if donate else _pull_until_jit
    return fn(prog, spec, max_iters, active_fn, method, arrays,
              state0, tel, route_static=rs, route_arrays=ra,
              interpret=_route_interpret(), ostatic=os_, oarrays=oa)


def _pull_until_fn(prog, spec, max_iters, active_fn, method, arrays, state0,
                   ring=None, route_static=None, route_arrays=None,
                   interpret=False, ostatic=None, oarrays=None):
    def cond(carry):
        return (carry[2] > 0) & (carry[1] < max_iters)

    def body(carry):
        state, it = carry[0], carry[1]
        new = _pull_iteration(prog, spec, method, arrays, state,
                              route_static, route_arrays, interpret,
                              ostatic, oarrays)
        active = jnp.sum(active_fn(state, new))
        if ring is None:
            return new, it + 1, active
        # telemetry rides the while carry (static shapes; the recorded
        # active count is the one the convergence test already computes)
        return new, it + 1, active, obs_ring.ring_push(carry[3], it, active)

    init = (state0, jnp.int32(0), jnp.int32(1))
    if ring is not None:
        init = init + (ring,)
    out = jax.lax.while_loop(cond, body, init)
    if ring is None:
        return out[0], out[1]
    return out[0], out[1], out[3]


_PULL_UNTIL_STATICS = ("prog", "spec", "max_iters", "active_fn", "method",
                       "route_static", "interpret", "ostatic")
_pull_until_jit = jax.jit(_pull_until_fn,
                          static_argnames=_PULL_UNTIL_STATICS)
#: donating twin of the convergence loop (state0 = positional 6); the
#: old state is folded into the while carry immediately, so donation
#: frees the input buffer for the loop's ping-pong.  The telemetry ring
#: (positional 7) is carry too and donates alongside (None = no-op).
_pull_until_jit_donate = jax.jit(_pull_until_fn,
                                 static_argnames=_PULL_UNTIL_STATICS,
                                 donate_argnums=(6, 7))
