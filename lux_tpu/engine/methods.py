"""Measured per-platform segment-reduction method defaults.

The reference hard-codes its reduction strategy (CUB block-scan + atomics,
pagerank_gpu.cu:59-95) because it targets exactly one architecture.  Here
four interchangeable strategies exist (lux_tpu.ops.segment) and the right
one depends on where the program runs, so the engine-wide default is
``"auto"``: resolved once at driver entry from the runtime platform and
the measured winners below.  Defaults follow measurements, not tradition:

  * **1-core CPU host** (BASELINE.md round-2 phase table): ``scatter``
    beats ``scan`` ~2x on the dominating comp phase — XLA:CPU lowers the
    sorted segment-sum to a tight sequential accumulation, while the
    log-depth associative scan makes multiple passes over the edge array.
  * **TPU** (PERF.md round-2 chip session): XLA ``scatter`` SERIALIZES
    on-chip — measured 264 ms/iter = 0.06 GTEPS at rmat20/ef16, 6x slower
    than the same code on the CPU fallback.  ``scan`` is the vectorized
    default until the Pallas sweep (tools/tpu_pallas_check.py --sweep)
    records a faster winner; update WINNERS when it does.

``resolve`` is pure/host-side: it runs before any trace, so the concrete
string participates in jit static arguments and compile caches as usual.
"""
from __future__ import annotations

import os

#: Concrete strategies a resolution may produce.  ("cumsum"/"mxsum" are
#: sum-only prefix-diff strategies and "pallas" needs the block-CSR
#: layout — none is safe as a blanket default, so winners stay within
#: the universally-valid {scan, scatter} set.)
CONCRETE = ("scan", "cumsum", "mxsum", "scatter")

#: (platform, reduce) -> measured winner.  The chip battery
#: (tools/chip_day.sh) is the only sanctioned way to change a tpu row.
WINNERS = {
    ("cpu", "sum"): "scatter",
    ("cpu", "min"): "scatter",
    ("cpu", "max"): "scatter",
    ("tpu", "sum"): "scan",
    ("tpu", "min"): "scan",
    ("tpu", "max"): "scan",
}

#: Unknown platform (gpu via XLA, interpreters): the portable choice.
FALLBACK = "scan"

#: Measured-winners overlay file: written by an (unattended) TPU bench
#: race (bench.py) so a chip window updates defaults WITHOUT a code
#: edit.  Format: {"tpu:sum": "mxsum", ...}; entries must be in
#: CONCRETE.  Overridable via LUX_METHOD_WINNERS; missing file = no-op.
WINNERS_FILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    ".lux_winners.json",
)

_file_winners_cache: dict | None = None
_platform_cache: str | None = None


def _file_winners() -> dict:
    """The overlay winners, loaded once per process.  Malformed files and
    non-CONCRETE entries are ignored (a half-written file must never
    break every driver)."""
    global _file_winners_cache
    if _file_winners_cache is None:
        path = os.environ.get("LUX_METHOD_WINNERS", WINNERS_FILE)
        winners = {}
        try:
            import json

            with open(path) as f:
                raw = json.load(f)
            if not isinstance(raw, dict):
                raw = {}
            for key, val in raw.items():
                plat, _, red = str(key).partition(":")
                # blanket defaults must hold on EVERY engine path: the
                # bucketed (row_ptr-free) ring/edge2d layouts only run
                # scan/scatter, and cumsum/mxsum are sum-only anyway —
                # so the overlay is restricted exactly like WINNERS
                if plat and red and val in ("scan", "scatter"):
                    winners[(plat, red)] = val
        except (OSError, ValueError):
            pass
        _file_winners_cache = winners
    return _file_winners_cache


def default_platform() -> str:
    """The jax default backend, overridable via LUX_METHOD_PLATFORM (so
    resolution never has to touch a possibly-wedged device tunnel just to
    pick a strategy string)."""
    global _platform_cache
    env = os.environ.get("LUX_METHOD_PLATFORM")
    if env:
        return env
    if _platform_cache is None:
        import jax

        _platform_cache = jax.default_backend()
    return _platform_cache


def _normalize(platform: str) -> str:
    """'axon' is this environment's tunneled-TPU PJRT plugin — the chip
    behind it IS a TPU, so it must take the tpu rows (not FALLBACK, which
    would silently diverge the moment a tpu row changes)."""
    return "tpu" if platform == "axon" else platform


def resolve(method: str, reduce: str = "sum",
            platform: str | None = None) -> str:
    """``"auto"`` -> the measured winner for (platform, reduce); concrete
    methods pass through unchanged (explicit user choice always wins)."""
    if method != "auto":
        return method
    plat = _normalize(platform if platform is not None else default_platform())
    chosen = _file_winners().get(
        (plat, reduce), WINNERS.get((plat, reduce), FALLBACK)
    )
    assert chosen in CONCRETE, (chosen, plat, reduce)
    return chosen
