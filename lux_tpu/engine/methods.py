"""Measured per-platform segment-reduction method defaults.

The reference hard-codes its reduction strategy (CUB block-scan + atomics,
pagerank_gpu.cu:59-95) because it targets exactly one architecture.  Here
four interchangeable strategies exist (lux_tpu.ops.segment) and the right
one depends on where the program runs, so the engine-wide default is
``"auto"``: resolved once at driver entry from the runtime platform and
the measured winners below.  Defaults follow measurements, not tradition:

  * **1-core CPU host** (BASELINE.md round-2 phase table): ``scatter``
    beats ``scan`` ~2x on the dominating comp phase — XLA:CPU lowers the
    sorted segment-sum to a tight sequential accumulation, while the
    log-depth associative scan makes multiple passes over the edge array.
  * **TPU** (PERF.md round-2 chip session): XLA ``scatter`` SERIALIZES
    on-chip — measured 264 ms/iter = 0.06 GTEPS at rmat20/ef16, 6x slower
    than the same code on the CPU fallback.  ``scan`` is the vectorized
    default until the Pallas sweep (tools/tpu_pallas_check.py --sweep)
    records a faster winner; update WINNERS when it does.

``resolve`` is pure/host-side: it runs before any trace, so the concrete
string participates in jit static arguments and compile caches as usual.
"""
from __future__ import annotations

import os
import threading

#: Concrete strategies a resolution may produce.  ("cumsum"/"mxsum" are
#: sum-only prefix-diff strategies and "pallas" needs the block-CSR
#: layout — none is safe as a blanket default, so winners stay within
#: the universally-valid {scan, scatter} set.  "mxscan" (ISSUE 11) is
#: the blocked MXU segmented scan: valid on every csc path and on 1-D
#: bucketed paths, reached through the scan-family refinement below.)
CONCRETE = ("scan", "cumsum", "mxsum", "mxscan", "scatter")

#: (platform, reduce) -> measured winner.  The chip battery
#: (tools/chip_day.sh) is the only sanctioned way to change a tpu row.
WINNERS = {
    ("cpu", "sum"): "scatter",
    ("cpu", "min"): "scatter",
    ("cpu", "max"): "scatter",
    ("tpu", "sum"): "scan",
    ("tpu", "min"): "scan",
    ("tpu", "max"): "scan",
}

#: Unknown platform (gpu via XLA, interpreters): the portable choice.
FALLBACK = "scan"

#: Measured-winners overlay file: written by an (unattended) TPU bench
#: race (bench.py) so a chip window updates defaults WITHOUT a code
#: edit.  Format: {"tpu:sum": "mxsum", ...}; entries must be in
#: CONCRETE.  Overridable via LUX_METHOD_WINNERS; missing file = no-op.
WINNERS_FILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    ".lux_winners.json",
)

_overlay_raw_cache: dict | None = None
_file_winners_cache: dict | None = None
_platform_cache: str | None = None
#: one lock for every lazy-init cache above (+ _tiles_cache): method
#: resolution runs inside engine setup, which PR 2's planner fan-out
#: calls from worker threads — an unlocked check-then-act would load the
#: overlay file N times and, worse, let a reset in record_overlay_entry
#: interleave with a half-done init (luxcheck LUX-C001).  RLock because
#: _file_winners/pallas_tiles re-enter _overlay_raw under the same lock.
_CACHE_LOCK = threading.RLock()


def overlay_path() -> str:
    """The measured-winners overlay path (LUX_METHOD_WINNERS override) —
    shared by every reader AND writer so a recorded measurement always
    lands where the readers look."""
    return os.environ.get("LUX_METHOD_WINNERS", WINNERS_FILE)


def _overlay_raw() -> dict:
    """The overlay file as a raw dict, loaded once per process; malformed
    or missing files read as empty (a half-written file must never break
    every driver)."""
    global _overlay_raw_cache
    with _CACHE_LOCK:
        if _overlay_raw_cache is None:
            raw: dict = {}
            try:
                import json

                with open(overlay_path()) as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    raw = loaded
            except (OSError, ValueError):
                pass
            _overlay_raw_cache = raw
        return _overlay_raw_cache


def _deep_merge(dst: dict, src: dict) -> dict:
    """Recursively merge ``src`` into a copy of ``dst``: dict-valued
    sub-keys merge, everything else overwrites.  This is what keeps one
    recorder from clobbering a SIBLING measurement — the round-5
    regression where a later ``tpu:micro_sum`` write dropped the banked
    mxsum/gather micro rows (VERDICT r5 weak #2)."""
    out = dict(dst)
    for k, v in src.items():
        if isinstance(out.get(k), dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def record_overlay_entry(key: str, value) -> None:
    """Atomic read-modify-write of ONE overlay entry — the single writer
    for unattended chip measurements (bench.py's method winner, the
    Pallas sweep's tile winner).  A corrupt existing file is replaced,
    not fatal: readers already treat it as empty, and losing a chip
    window's measurement to a bad old file would be strictly worse.

    Dict values MERGE with the existing entry (recursively) instead of
    replacing it: recording one method's micro row must never erase a
    previously-banked row for a different method — chip-window data is
    too scarce to lose.  Scalar values still overwrite (a winner string
    is a decision, not a table).

    The read-modify-write holds an ``fcntl`` lock on a sidecar lockfile:
    the re-arming tunnel_watch can overlap two recorders (micro race +
    bench race of consecutive windows), and an unlocked RMW would lose
    one window's entry.  On success the module's read caches reset so
    the recording process itself sees what it just wrote."""
    import json

    path = overlay_path()
    try:
        lock = open(path + ".lock", "a+")
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass  # no flock (non-POSIX): degraded to the old racy RMW
        try:
            prev = {}
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        prev = json.load(f)
                except ValueError:
                    prev = {}  # corrupt: start fresh, don't drop the win
            if not isinstance(prev, dict):
                prev = {}
            if isinstance(prev.get(key), dict) and isinstance(value, dict):
                prev[key] = _deep_merge(prev[key], value)
            else:
                prev[key] = value
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(prev, f, indent=1)
            os.replace(tmp, path)
        finally:
            lock.close()  # releases the flock
        global _overlay_raw_cache, _file_winners_cache, _tiles_cache
        with _CACHE_LOCK:
            _overlay_raw_cache = None
            _file_winners_cache = None
            _tiles_cache = None
        print(f"# recorded {key} -> {value!r} ({path})", flush=True)
    except OSError as e:
        print(f"# winners file not written: {e}", flush=True)


def _file_winners() -> dict:
    """The method-winner view of the overlay.  Non-CONCRETE entries are
    ignored; blanket defaults must hold on EVERY engine path (the
    bucketed row_ptr-free ring/edge2d layouts only run scan/scatter, and
    cumsum/mxsum are sum-only anyway), so the overlay is restricted
    exactly like WINNERS."""
    global _file_winners_cache
    with _CACHE_LOCK:
        if _file_winners_cache is None:
            winners = {}
            for key, val in _overlay_raw().items():
                plat, _, red = str(key).partition(":")
                if plat and red and val in ("scan", "scatter"):
                    winners[(plat, red)] = val
            _file_winners_cache = winners
        return _file_winners_cache


#: LOAD-phase route modes the bench A/Bs and the overlay may record:
#: "routed" = the unfused Benes expand (one kernel per pass), "routed-pf"
#: = the pass-fused replay (2-3 passes per kernel, VMEM-resident
#: intermediates, ops/expand.to_pf).  Both are bitwise-identical to the
#: direct gather, so either is always safe to follow.
ROUTE_MODES = ("routed", "routed-pf")

#: overlay key the TPU bench race records its routed-vs-routed-pf
#: winner under (bench.py _record_route_mode) — like "tpu:sum", an
#: unattended chip window updates the default without a code edit.
ROUTE_MODE_KEY = "tpu:route_mode"


def route_mode() -> str:
    """The preferred routed-plan flavor: LUX_ROUTE_MODE env override,
    else the chip-measured overlay entry, else "routed-pf" (the
    analytic winner — ~40% fewer HBM sweeps per iteration — until a
    window banks the A/B; both modes are bitwise-identical so the
    default is a perf bet, never a correctness one)."""
    env = os.environ.get("LUX_ROUTE_MODE")
    if env:
        if env not in ROUTE_MODES:
            raise ValueError(
                f"LUX_ROUTE_MODE must be one of {ROUTE_MODES}, got {env!r}")
        return env
    rec = _overlay_raw().get(ROUTE_MODE_KEY)
    return rec if rec in ROUTE_MODES else "routed-pf"


#: REDUCE-phase modes of the fused routed hot loop the bench/micro
#: races may record: "group" = the plain masked group reshape-reduce
#: (VPU sweep over the group space, PR 4's fused form), "mxreduce" =
#: the segmented reduction fused INTO the final routed Pallas kernel
#: as a one-hot x state contraction on the MXU (ops/expand plan_fused
#: mx=True; arXiv:1811.09736's construction).  Sum rides the MXU (bf16
#: operands where exact, f32 accumulate — ops/pallas_shuffle
#: StaticMXGroup documents the precision contract); min/max and
#: integer sums use the same in-kernel layout with a masked VPU reduce
#: (min has no matmul identity), dtype-preserving bitwise.  Unlike the
#: route modes the two flavors are NOT bitwise-identical for float
#: sums (each has its own deterministic association, like mxsum vs
#: scan), so the default stays "group" until a chip window BANKS the
#: measured winner — the three-way tpu:sum story (mxsum vs scan vs
#: mxreduce) is a race, not an assumption.
REDUCE_MODES = ("group", "mxreduce")

#: overlay key the mxu-vs-vpu micro race (tools/tpu_micro_race.py,
#: chip_day step 0) and the bench micro row record their winner under.
REDUCE_MODE_KEY = "tpu:reduce_mode"


def reduce_mode() -> str:
    """The preferred fused-reduce flavor: LUX_REDUCE_MODE env override,
    else the chip-measured overlay entry, else "group" (the shipped
    PR-4 behavior — mxreduce changes float-sum association, so it is
    followed only once measured).  Consumed by the fused planners'
    ``mx=None`` default (ops/expand.resolve_fused_mx) and the apps'
    ``--route-gather fused-pf`` path."""
    env = os.environ.get("LUX_REDUCE_MODE")
    if env:
        if env not in REDUCE_MODES:
            raise ValueError(
                f"LUX_REDUCE_MODE must be one of {REDUCE_MODES}, "
                f"got {env!r}")
        return env
    rec = _overlay_raw().get(REDUCE_MODE_KEY)
    return rec if rec in REDUCE_MODES else "group"


#: CF error-dot flavors (models/colfilter): "vpu" = the elementwise
#: multiply + lane-axis jnp.sum (the shipped form), "mxu" = the K-dim
#: contraction as a true (rows, K) @ (K, 1) dot_general matmul tile
#: (f32 operands, f32 accumulate — MXU association, so float results
#: may differ from "vpu" in the last ulps; the race is exactness-gated
#: against the NumPy oracle with the documented tolerance).
CF_DOT_MODES = ("vpu", "mxu")

#: overlay key the CF error-dot micro race (tools/tpu_micro_race.py
#: ``cfdot`` worker) banks its measured winner under.
CF_DOT_KEY = "tpu:cf_err_dot"


def cf_err_dot_mode() -> str:
    """The preferred CF error-dot flavor: LUX_CF_ERR_DOT env override,
    else the chip-measured overlay entry, else "vpu" (the shipped
    behavior — the MXU tile changes f32 association, so it is followed
    only once measured).  Resolved at driver entry (models/colfilter
    ``colfilter``/``make_pallas_runner`` with err_dot=None), never
    inside a trace."""
    env = os.environ.get("LUX_CF_ERR_DOT")
    if env:
        if env not in CF_DOT_MODES:
            raise ValueError(
                f"LUX_CF_ERR_DOT must be one of {CF_DOT_MODES}, "
                f"got {env!r}")
        return env
    rec = _overlay_raw().get(CF_DOT_KEY)
    return rec if rec in CF_DOT_MODES else "vpu"


#: SCAN-FAMILY float-sum strategies the three-way ``tpu:sum`` race
#: (tools/tpu_micro_race.py mxsum/mxscan/scan workers, bench.py's
#: standing ``scan_micro_mx_vs_vpu`` row) may bank: "scan" = the VPU
#: ``lax.associative_scan`` ladder (the shipped default), "mxsum" = the
#: prefix-diff blocked triangular matmul (arXiv:1811.09736; global-
#: prefix f32 caveat), "mxscan" = the segmented scan ITSELF as masked
#: triangular MXU contractions (ops/pallas_scan, arXiv:2505.15112's
#: blocked systolic scan; accumulation stays within a segment).  The
#: three differ only in float-sum association (min/max/integer paths
#: are bitwise), so like ``tpu:reduce_mode`` the VPU default is retired
#: only through a banked on-chip measurement — never assumed.
SUM_MODES = ("scan", "mxsum", "mxscan")

#: overlay key the scan-family race banks its winner under.  The SAME
#: key also carries the app-level bench race's blanket winner (which
#: may be "scatter"): the two readers consume disjoint value domains —
#: ``_file_winners`` follows {scan, scatter} as the blanket default,
#: ``sum_mode`` follows SUM_MODES as the csc-path refinement — so one
#: key stays coherent whichever race wrote last.
SUM_MODE_KEY = "tpu:sum"


def sum_mode(platform: str | None = None) -> str:
    """The preferred scan-family float-sum strategy: LUX_SUM_MODE env
    override (explicit choice, any platform), else the chip-measured
    ``tpu:sum`` overlay entry ON TPU ONLY, else "scan" — the shipped
    VPU default stays until a window measures, and CPU runs are
    bitwise-unchanged by a banked TPU winner (the acceptance contract
    of ISSUE 11)."""
    env = os.environ.get("LUX_SUM_MODE")
    if env:
        if env not in SUM_MODES:
            raise ValueError(
                f"LUX_SUM_MODE must be one of {SUM_MODES}, got {env!r}")
        return env
    plat = _normalize(platform if platform is not None
                      else default_platform())
    rec = _overlay_raw().get(SUM_MODE_KEY)
    if plat == "tpu" and rec in SUM_MODES:
        return rec
    return "scan"


def record_sum_family_winner(winner: str) -> bool:
    """Bank a scan-family race winner under ``tpu:sum`` — UNLESS the
    key currently holds a measured "scatter" blanket winner.  The
    scan-family races (micro race, bench's scan micro row) never time
    scatter, so overwriting a full-race scatter measurement with a
    family-internal winner would destroy the better datapoint (the
    same chip-data-is-scarce rule behind record_overlay_entry's
    deep-merge).  The full bench race's _record_winner times BOTH
    domains and may overwrite freely.  Returns True when recorded."""
    assert winner in SUM_MODES, winner
    prev = _overlay_raw().get(SUM_MODE_KEY)
    if prev == "scatter":
        print(f"# tpu:sum holds a measured blanket 'scatter' winner; "
              f"scan-family winner {winner!r} NOT banked over it "
              "(raw times live in the micro rows)", flush=True)
        return False
    record_overlay_entry(SUM_MODE_KEY, winner)
    return True


def resolve_sum(method: str, reduce: str = "sum",
                platform: str | None = None) -> str:
    """``resolve`` plus the scan-family refinement for the csc
    gather-apply engines (pull single-device + dist + the app CLIs;
    push also routes through here, though every shipped push program
    reduces with min/max, so the sum-only refinement is DORMANT there
    until a sum-reduce push program exists): when an AUTO resolution
    lands on the blanket "scan" default for a float SUM, the banked
    ``tpu:sum`` scan-family winner (mxsum/mxscan) is followed instead.
    Explicit concrete methods still pass through untouched, min/max
    rows are untouched, and the bucketed ring/edge2d/feat DRIVERS keep
    plain ``resolve`` (their scan/scatter asserts therefore never see
    a refined winner; apps/common downgrades an auto-refined winner
    before those exchanges, and ops/segment.segment_reduce_by_ends
    downgrades for library callers who pass one explicitly).

    A set LUX_SUM_MODE is an EXPLICIT user choice: under
    ``method="auto"`` it wins for float sums on every platform — even
    where the blanket resolution is "scatter" (the CPU default) — so
    'LUX_SUM_MODE forces a flavor anywhere' (docs/PERF.md) holds from
    every driver.  A BANKED winner (no env) still refines only the
    blanket "scan" default, on TPU."""
    if (method == "auto" and reduce == "sum"
            and os.environ.get("LUX_SUM_MODE")):
        return sum_mode(platform)  # validates + returns the env choice
    resolved = resolve(method, reduce, platform)
    if method == "auto" and reduce == "sum" and resolved == "scan":
        return sum_mode(platform)
    return resolved


#: CROSS-PART MERGE modes of the push engine's frontier aggregation
#: (ISSUE 17, luxmerge): "bulk" = the bulk-synchronous flatten — every
#: part's queue concatenated (single all_gather on the dist engines)
#: and scattered into one destination pass per superstep (the shipped
#: PR-3 behavior); "tree" = the asynchronous reduction tree
#: (ops/merge_tree, Tascade arXiv:2311.15810's atomic-free construction)
#: — per-source-block partial frontiers combine pairwise up a STATIC
#: schedule, and the dist queue exchange runs as staged
#: recursive-doubling ppermute rounds instead of one barrier
#: all_gather.  Both modes are bitwise-identical for min/max/integer
#: monoids (scatter-reduce into disjoint destination slots is
#: order-independent there — every shipped push program reduces with
#: min/max), but a float-SUM push program would see the tree's
#: association, so like ``tpu:reduce_mode`` the bulk default is retired
#: only through a banked on-chip measurement, never assumed.
MERGE_MODES = ("bulk", "tree")

#: overlay key the merge micro race (bench.py's standing
#: ``merge_micro_tree_vs_bulk`` row) banks its measured winner under.
MERGE_MODE_KEY = "tpu:merge_mode"


def merge_mode(platform: str | None = None) -> str:
    """The preferred cross-part merge flavor: LUX_MERGE_MODE env
    override (explicit choice, any platform), else the chip-measured
    ``tpu:merge_mode`` overlay entry ON TPU ONLY, else "bulk" — the
    shipped bulk-synchronous merge stays until a window measures, and
    CPU runs are bitwise-unchanged by a banked TPU winner (the same
    acceptance contract as ``tpu:sum``)."""
    env = os.environ.get("LUX_MERGE_MODE")
    if env:
        if env not in MERGE_MODES:
            raise ValueError(
                f"LUX_MERGE_MODE must be one of {MERGE_MODES}, got {env!r}")
        return env
    plat = _normalize(platform if platform is not None
                      else default_platform())
    rec = _overlay_raw().get(MERGE_MODE_KEY)
    if plat == "tpu" and rec in MERGE_MODES:
        return rec
    return "bulk"


_tiles_cache: tuple | None = None


def pallas_tiles() -> tuple | None:
    """Measured (v_blk, t_chunk) Pallas tile winner from the overlay
    (key ``"tpu:pallas_tiles"``, written by an unattended
    `tools/tpu_pallas_check --sweep`); None while unmeasured — the
    kernels then use their compiled-in defaults (ops/pallas_spmv
    V_BLK/T_CHUNK).  Malformed entries are ignored, and v_blk must keep
    the 128-lane alignment the kernel grid assumes."""
    global _tiles_cache
    with _CACHE_LOCK:
        if _tiles_cache is not None:
            return _tiles_cache or None
        tiles: tuple = ()
        t = _overlay_raw().get("tpu:pallas_tiles")
        if (
            isinstance(t, dict)
            and isinstance(t.get("v_blk"), int)
            and 0 < t["v_blk"] <= 4096 and t["v_blk"] % 128 == 0
            and isinstance(t.get("t_chunk"), int)
            # sublane-aligned: the 2-D CF kernel's (1, t, k) BlockSpec
            # requires t a multiple of 8 (ops/pallas_spmv.py)
            and 0 < t["t_chunk"] <= 8192 and t["t_chunk"] % 8 == 0
        ):
            tiles = (t["v_blk"], t["t_chunk"])
        _tiles_cache = tiles
    return _tiles_cache or None


def default_platform() -> str:
    """The jax default backend, overridable via LUX_METHOD_PLATFORM (so
    resolution never has to touch a possibly-wedged device tunnel just to
    pick a strategy string)."""
    global _platform_cache
    env = os.environ.get("LUX_METHOD_PLATFORM")
    if env:
        return env
    with _CACHE_LOCK:
        if _platform_cache is None:
            import jax

            _platform_cache = jax.default_backend()
        return _platform_cache


def _normalize(platform: str) -> str:
    """'axon' is this environment's tunneled-TPU PJRT plugin — the chip
    behind it IS a TPU, so it must take the tpu rows (not FALLBACK, which
    would silently diverge the moment a tpu row changes)."""
    return "tpu" if platform == "axon" else platform


def resolve(method: str, reduce: str = "sum",
            platform: str | None = None) -> str:
    """``"auto"`` -> the measured winner for (platform, reduce); concrete
    methods pass through unchanged (explicit user choice always wins)."""
    if method != "auto":
        return method
    plat = _normalize(platform if platform is not None else default_platform())
    chosen = _file_winners().get(
        (plat, reduce), WINNERS.get((plat, reduce), FALLBACK)
    )
    assert chosen in CONCRETE, (chosen, plat, reduce)
    return chosen
