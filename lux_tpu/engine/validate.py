"""On-device invariant validation (the `-check` task).

The reference validates AFTER convergence with a dedicated GPU task
(CHECK_TASK_ID, core/graph.h:46; check_kernel re-walks every edge and
counts violations — sssp_gpu.cu:773-798, components_gpu.cu:768-792).
Here the same edge-walk is a jitted pull pass with a sum reduction of a
per-edge violation indicator — it runs sharded, so graphs too large for
host memory validate in place on the mesh.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.graph.shards import PullShards


def count_violations(
    shards: PullShards,
    state_stacked,
    edge_violation: Callable,
) -> int:
    """Walk every edge on device; count violations exactly (int64).

    edge_violation(src_state, dst_state, weight) -> bool per edge.
    state_stacked: (P, V, ...) final vertex state.
    """
    spec = shards.spec
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    state = jnp.asarray(state_stacked)

    @jax.jit
    def run(arrays, state):
        full = state.reshape((spec.gathered_size,) + state.shape[2:])

        def per_part(arr, local):
            src_state = full[arr.src_pos]
            dst_state = local[jnp.clip(arr.dst_local, 0, local.shape[0] - 1)]
            bad = edge_violation(src_state, dst_state, arr.weights)
            # int32 is exact per part (part edge counts are < 2^31 by the
            # shards builder's guard); the cross-part total sums in Python
            return jnp.sum((bad & arr.edge_mask).astype(jnp.int32))

        return jax.vmap(per_part)(arrays, state)

    return int(np.sum(np.asarray(run(arrays, state), dtype=np.int64)))


def sssp_violation(inf: int, weighted: bool = False):
    """dist[dst] <= dist[src] + w for every edge with a reached source
    (triangle inequality, sssp check_kernel semantics; w == 1 for the
    BFS flavor, the edge weight for the Dijkstra-style extension)."""

    def fn(src_state, dst_state, weight):
        w = weight.astype(src_state.dtype) if weighted else 1
        return (dst_state > src_state + w) & (src_state < inf)

    return fn


def cc_violation():
    """label[dst] >= label[src] (cc check_kernel semantics)."""

    def fn(src_state, dst_state, weight):
        del weight
        return dst_state < src_state

    return fn
