"""Connected Components by max-label propagation.

Semantic parity with the reference app (components/):
  * labels initialize to the vertex's own id (components_gpu.cu:738-739);
  * each iteration a vertex takes the max of its label and its in-neighbors'
    labels (cc_pull_kernel atomicMax gather, components_gpu.cu:85-130);
  * convergence when no label changes anywhere — the reference tests the
    summed active counts from 4 iterations back (components.cc:113-127); we
    test on-device with zero lag;
  * the `-check` validator asserts label[dst] >= label[src] on every edge
    (check_kernel, components_gpu.cu:768-792).

The pull formulation here is the dense path; the frontier-driven
direction-optimizing path lives in the push engine (lux_tpu.engine.push).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import pull
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import PullShards, build_pull_shards
from lux_tpu.program import SpecBacked, library


@dataclasses.dataclass(frozen=True)
class MaxLabelProgram(SpecBacked):
    """Max-label propagation vertex program (the CC kernel), evaluated
    from the declarative spec (lux_tpu.program.library.COMPONENTS —
    ISSUE 13): labels init to the vertex id (-1 on padding so it never
    wins a max), everyone starts active (the reference's dense all-ones
    bitmap, components_gpu.cu:733-737).  The spec's edge/apply serve the
    pull engine and its edge/frontier the push engine — one declaration,
    both contracts."""

    @property
    def spec(self):
        return library.COMPONENTS


def active_count(old_local, new_local):
    """Per-part count of vertices whose label changed (the convergence
    quantity returned by push_app_task_impl, core/graph.h:205-207)."""
    return jnp.sum(old_local != new_local)


def active_count_stacked(old_stacked, new_stacked):
    """(P, V) stacked variant -> (P,) counts (top-level function so jitted
    convergence loops cache on it)."""
    return jnp.sum(old_stacked != new_stacked, axis=-1)


def connected_components(
    g: HostGraph | PullShards,
    max_iters: int = 10_000,
    num_parts: int = 1,
    method: str = "auto",
) -> np.ndarray:
    """Run CC to convergence; returns (nv,) int32 labels."""
    shards = g if isinstance(g, PullShards) else build_pull_shards(g, num_parts)
    prog = MaxLabelProgram()
    state0 = pull.init_state(prog, shards.arrays)
    final, _ = pull.run_pull_until(
        prog, shards.spec, shards.arrays, state0, max_iters,
        active_count_stacked, method=method,
    )
    return shards.scatter_to_global(np.asarray(final))


def connected_components_push(
    g,
    max_iters: int = 10_000,
    num_parts: int = 1,
    mesh=None,
    method: str = "auto",
    exchange: str = "allgather",
    repartition_every: int = 0,
    repartition_threshold: float = 1.25,
    route=None,
) -> np.ndarray:
    """CC on the frontier/push engine (direction-optimizing; what the
    reference app actually runs).  ``g``: HostGraph or pre-built shards;
    ``exchange="ring"`` (with a mesh) streams dense rounds;
    ``repartition_every > 0`` enables adaptive dynamic repartitioning."""
    from lux_tpu.graph.push_shards import PushShards, build_push_shards
    from lux_tpu.models.sssp import _push_run
    from lux_tpu.parallel.ring import PushRingShards

    shards = (
        g if isinstance(g, (PushShards, PushRingShards))
        else build_push_shards(g, num_parts)
    )
    prog = MaxLabelProgram()
    return _push_run(
        prog, g, shards, mesh, max_iters, method, exchange, num_parts,
        repartition_every, repartition_threshold, route=route,
    )


def check_labels(g: HostGraph, labels: np.ndarray) -> int:
    """Host oracle for the `-check` invariant: number of edges with
    label[dst] < label[src] (must be 0 after convergence)."""
    dst = g.dst_of_edges()
    return int(np.sum(labels[dst] < labels[g.col_idx]))
