"""PageRank on the pull engine.

Math parity with the reference app (pagerank/pagerank_gpu.cu):
  * ranks are stored PRE-DIVIDED by out-degree: the state holds r[v]/deg[v]
    so the gather needs no degree lookup (init at pagerank_gpu.cu:256-259:
    ``rank/degree`` with rank = 1/nv, undivided when degree == 0);
  * one iteration: new[v] = (initRank + ALPHA * sum_{u->v} state[u]),
    divided by deg[v] when deg[v] != 0 (pr_kernel tail,
    pagerank_gpu.cu:97-100), with initRank = (1 - ALPHA)/nv
    (pagerank/pagerank.cc:141-144) and ALPHA = 0.15 (pagerank/app.h:24);
  * fixed iteration count, no convergence test (pagerank.cc:109-114).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import pull
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import PullShards, ShardArrays, build_pull_shards

ALPHA = 0.15


@dataclasses.dataclass(frozen=True)
class PageRankProgram:
    nv: int
    alpha: float = ALPHA

    reduce: str = dataclasses.field(default="sum", init=False)

    def init_state(self, global_vid, degree, vtx_mask):
        rank = jnp.float32(1.0 / self.nv)
        deg = degree.astype(jnp.float32)
        state = jnp.where(degree > 0, rank / jnp.maximum(deg, 1.0), rank)
        return jnp.where(vtx_mask, state, 0.0)

    def edge_value(self, src_state, weight, dst_state=None):
        del weight, dst_state
        return src_state

    def apply(self, old_local, acc, arrays: ShardArrays):
        del old_local
        init_rank = jnp.float32((1.0 - self.alpha) / self.nv)
        pr = init_rank + jnp.float32(self.alpha) * acc
        deg = arrays.degree.astype(jnp.float32)
        pr = jnp.where(arrays.degree > 0, pr / jnp.maximum(deg, 1.0), pr)
        return jnp.where(arrays.vtx_mask, pr, 0.0)


def pagerank(
    g: HostGraph | PullShards,
    num_iters: int = 10,
    num_parts: int = 1,
    method: str = "scan",
) -> np.ndarray:
    """Run PageRank; returns the (nv,) pre-divided rank vector (same
    semantics as the reference's final vertex state)."""
    shards = g if isinstance(g, PullShards) else build_pull_shards(g, num_parts)
    prog = PageRankProgram(nv=shards.spec.nv)
    state0 = pull.init_state(prog, shards.arrays)
    final = pull.run_pull_fixed(
        prog, shards.spec, shards.arrays, state0, num_iters, method=method
    )
    return shards.scatter_to_global(np.asarray(final))


def pagerank_reference(g: HostGraph, num_iters: int) -> np.ndarray:
    """NumPy oracle implementing the identical recurrence (for tests)."""
    deg = g.out_degrees().astype(np.float64)
    nv = g.nv
    state = np.where(deg > 0, (1.0 / nv) / np.maximum(deg, 1.0), 1.0 / nv)
    dst = g.dst_of_edges()
    for _ in range(num_iters):
        acc = np.zeros(nv, np.float64)
        np.add.at(acc, dst, state[g.col_idx])
        pr = (1.0 - ALPHA) / nv + ALPHA * acc
        state = np.where(deg > 0, pr / np.maximum(deg, 1.0), pr)
    return state.astype(np.float32)
