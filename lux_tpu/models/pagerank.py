"""PageRank on the pull engine.

Math parity with the reference app (pagerank/pagerank_gpu.cu):
  * ranks are stored PRE-DIVIDED by out-degree: the state holds r[v]/deg[v]
    so the gather needs no degree lookup (init at pagerank_gpu.cu:256-259:
    ``rank/degree`` with rank = 1/nv, undivided when degree == 0);
  * one iteration: new[v] = (initRank + ALPHA * sum_{u->v} state[u]),
    divided by deg[v] when deg[v] != 0 (pr_kernel tail,
    pagerank_gpu.cu:97-100), with initRank = (1 - ALPHA)/nv
    (pagerank/pagerank.cc:141-144) and ALPHA = 0.15 (pagerank/app.h:24);
  * fixed iteration count, no convergence test (pagerank.cc:109-114).
"""
from __future__ import annotations

import dataclasses
import functools

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import pull
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.shards import PullShards, build_pull_shards
from lux_tpu.program import SpecBacked, library

#: reference ALPHA (pagerank/app.h:24) — defined with the spec it
#: parameterizes (lux_tpu.program.library), re-exported here
ALPHA = library.ALPHA


def apply_rank_update(acc, degree, nv, alpha=ALPHA):
    """The PageRank recurrence tail for the BLOCK-CSR Pallas runner
    below: (initRank + alpha*acc), pre-divided by out-degree when
    nonzero (pr_kernel, pagerank_gpu.cu:97-100).  The gather-apply
    engines evaluate the same math from the declarative spec
    (program.library.PAGERANK — ISSUE 13); the Pallas path keeps this
    explicit form because its padded block layout is not the spec's
    per-part environment."""
    init_rank = jnp.float32((1.0 - alpha) / nv)
    pr = init_rank + jnp.float32(alpha) * acc
    deg = degree.astype(jnp.float32)
    return jnp.where(degree > 0, pr / jnp.maximum(deg, 1.0), pr)


@dataclasses.dataclass(frozen=True)
class PageRankProgram(SpecBacked):
    """PageRank as a named parameter bundle over the declarative spec
    (lux_tpu.program.library.PAGERANK): init/edge/apply are EVALUATED
    from the spec — there is no hand-wired body left (ISSUE 13), and
    the personalized variant below is the same template with a one-hot
    teleport mass substituted."""

    nv: int
    alpha: float = ALPHA
    #: state storage dtype.  "bfloat16" halves HBM gather traffic and the
    #: per-iteration all_gather over ICI; accumulation stays float32.
    dtype: str = "float32"

    @property
    def spec(self):
        return library.PAGERANK

    def _env(self):
        return {"nv": self.nv, "alpha": self.alpha, "dtype": self.dtype}


@dataclasses.dataclass(frozen=True)
class PPRProgram(PageRankProgram):
    """Personalized PageRank: the same pre-divided recurrence with the
    uniform teleport mass (1-ALPHA)/nv replaced by a one-hot mass at
    ``seed`` — the single-query form of the serving path's batched
    multi-seed program (lux_tpu.serve.batched.MultiSourcePPR, the SAME
    spec Q-lifted); column q of a batched run equals this program's
    pull run bitwise."""

    seed: int = 0

    @property
    def spec(self):
        return library.PPR

    def _env(self):
        return {**super()._env(), "seed": self.seed}


def ppr_reference(g: HostGraph, seed: int, num_iters: int) -> np.ndarray:
    """NumPy float64 oracle of the personalized recurrence (tests)."""
    deg = g.out_degrees().astype(np.float64)
    mass = np.zeros(g.nv, np.float64)
    mass[seed] = 1.0
    state = np.where(deg > 0, mass / np.maximum(deg, 1.0), mass)
    dst = g.dst_of_edges()
    for _ in range(num_iters):
        acc = np.zeros(g.nv, np.float64)
        np.add.at(acc, dst, state[g.col_idx])
        pr = (1.0 - ALPHA) * mass + ALPHA * acc
        state = np.where(deg > 0, pr / np.maximum(deg, 1.0), pr)
    return state.astype(np.float32)


def pagerank(
    g: HostGraph | PullShards,
    num_iters: int = 10,
    num_parts: int = 1,
    method: str = "auto",
    dtype: str = "float32",
    route=None,
) -> np.ndarray:
    """Run PageRank; returns the (nv,) pre-divided rank vector (same
    semantics as the reference's final vertex state).  ``route``: a
    routed-pull plan (ops.expand.plan_expand_shards / plan_fused_shards)
    for the lane-shuffle hot loop."""
    shards = g if isinstance(g, PullShards) else build_pull_shards(g, num_parts)
    prog = PageRankProgram(nv=shards.spec.nv, dtype=dtype)
    state0 = pull.init_state(prog, shards.arrays)
    final = pull.run_pull_fixed(
        prog, shards.spec, shards.arrays, state0, num_iters, method=method,
        route=route,
    )
    return shards.scatter_to_global(np.asarray(final))


def make_pallas_runner(
    g: HostGraph,
    interpret: bool = False,
    v_blk: int | None = None,
    t_chunk: int | None = None,
    dtype: str = "float32",
    dynamic_iters: bool = False,
):
    """Build the block-CSR layout once; return (run, state0) where
    run(state, num_iters) executes the full on-device loop on the fused
    Pallas kernel (lux_tpu.ops.pallas_spmv) — the pr_kernel-equivalent
    hot path.

    ``dynamic_iters`` traces the iteration count instead of specializing
    on it: one compile serves every count — what the tunnel-side sweep
    harness needs, where each compile costs minutes."""
    import jax

    from lux_tpu.ops import pallas_spmv as ps

    kw = {}
    if v_blk:
        kw["v_blk"] = v_blk
    if t_chunk:
        kw["t_chunk"] = t_chunk
    bc = ps.build_blockcsr(g, **kw)
    nvp = bc.num_vblocks * bc.v_blk
    deg = g.out_degrees()
    degree = np.zeros(nvp, np.int32)
    degree[: g.nv] = deg
    state0 = np.zeros(nvp, np.float32)
    state0[: g.nv] = np.where(
        deg > 0, (1.0 / g.nv) / np.maximum(deg, 1), 1.0 / g.nv
    )
    degree_d = jnp.asarray(degree)
    e_src = jnp.asarray(bc.e_src_pos)
    e_dst = jnp.asarray(bc.e_dst_rel)
    cb = jnp.asarray(bc.chunk_block)
    cf = jnp.asarray(bc.chunk_first)

    def body(_, s):
        # state stored in `dtype`; bf16 state also feeds the MXU at
        # the bf16 rate (kernel accumulates f32 either way)
        vals = s[e_src]
        acc = ps.spmv_blockcsr(
            vals, e_dst, cb, cf, op="sum", v_blk=bc.v_blk,
            num_vblocks=bc.num_vblocks, interpret=interpret,
            compute_dtype=dtype,
        )
        return apply_rank_update(acc, degree_d, g.nv).astype(dtype)

    if dynamic_iters:
        @jax.jit
        def run(state, num_iters):
            return jax.lax.fori_loop(0, num_iters, body, state)
    else:
        @functools.partial(jax.jit, static_argnames="num_iters")
        def run(state, num_iters):
            return jax.lax.fori_loop(0, num_iters, body, state)

    return run, jnp.asarray(state0).astype(dtype)


def pagerank_pallas(
    g: HostGraph,
    num_iters: int = 10,
    interpret: bool = False,
    v_blk: int | None = None,
    t_chunk: int | None = None,
) -> np.ndarray:
    """Single-chip PageRank on the fused Pallas kernel; returns (nv,)."""
    run, state0 = make_pallas_runner(g, interpret, v_blk, t_chunk)
    return np.asarray(run(state0, num_iters))[: g.nv]


def _host_iteration(g: HostGraph, stored: np.ndarray,
                    deg: np.ndarray) -> np.ndarray:
    """One exact float64 host application of the recurrence
    (pagerank_gpu.cu:97-100 math) — the single source of truth shared by
    the test oracle and the -check validator."""
    acc = np.zeros(g.nv, np.float64)
    np.add.at(acc, g.dst_of_edges(), stored[g.col_idx])
    pr = (1.0 - ALPHA) / g.nv + ALPHA * acc
    return np.where(deg > 0, pr / np.maximum(deg, 1.0), pr)


def pagerank_reference(g: HostGraph, num_iters: int) -> np.ndarray:
    """NumPy oracle implementing the identical recurrence (for tests)."""
    deg = g.out_degrees().astype(np.float64)
    nv = g.nv
    state = np.where(deg > 0, (1.0 / nv) / np.maximum(deg, 1.0), 1.0 / nv)
    for _ in range(num_iters):
        state = _host_iteration(g, state, deg)
    return state.astype(np.float32)


def check_ranks(g: HostGraph, stored: np.ndarray,
                num_iters: int | None = None,
                dtype: str = "float32") -> int:
    """Fixed-point validation for `-check` — an EXTENSION: the reference
    ships no check task for its pull apps (only sssp/components have
    CHECK_TASK_ID, core/graph.h:46).  Re-applies one exact host
    iteration of the recurrence (_host_iteration — the same code the
    test oracle runs) and counts vertices whose stored pre-divided rank
    moved beyond tolerance.  The tolerance tracks what a CORRECT engine
    can deliver: the true residual contracts like ALPHA^num_iters (so
    few-iteration runs get a proportionally loose band) and a bfloat16
    state carries ~2^-8 relative quantization per rank; it is applied
    per vertex against max(|rank|, mean) so hub ranks are judged
    relative to themselves.  Non-finite ranks always count."""
    stored = np.asarray(stored, np.float64)
    deg = g.out_degrees().astype(np.float64)
    new = _host_iteration(g, stored, deg)
    base = 2e-2 if dtype == "bfloat16" else 1e-3
    tol = base if num_iters is None else max(base, 3.0 * ALPHA ** num_iters)
    scale = max(float(np.mean(np.abs(stored))), 1e-30)
    thresh = tol * np.maximum(np.abs(stored), scale)
    bad = ~np.isfinite(stored) | (np.abs(new - stored) > thresh)
    return int(bad.sum())
