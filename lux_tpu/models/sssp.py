"""Single-source shortest paths (BFS flavor) on the push engine.

Parity with the reference app (sssp/):
  * UNWEIGHTED relaxation ``dist[dst] = min(dist[dst], dist[src] + 1)``
    (sssp_gpu.cu:122,208,225) — the reference's "SSSP" is BFS with labels;
    its app.h is literally the CC header and no EDGE_WEIGHT path exists
    (SURVEY.md §2.2);
  * dist is int with INF encoded as nv (init at sssp_gpu.cu:733-734);
  * single-source sparse frontier at ``start`` (sssp_gpu.cu:735-744);
  * direction-optimizing iteration + convergence on zero active vertices
    (driver loop sssp/sssp.cc:110-137);
  * `-check` invariant: dist[dst] <= dist[src] + 1 for every edge
    (check_kernel, sssp_gpu.cu:773-798).

A weighted delta-relaxation variant (`WeightedSSSPProgram`) is provided as
an extension beyond the reference (BASELINE.json frames it as a target).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from lux_tpu.engine import push
from lux_tpu.graph.csc import HostGraph
from lux_tpu.graph.push_shards import PushShards, build_push_shards
from lux_tpu.parallel.mesh import Mesh
from lux_tpu.program import SpecBacked, library


@dataclasses.dataclass(frozen=True)
class SSSPProgram(SpecBacked):
    """BFS-SSSP vertex program: hop-count relaxation, evaluated from the
    declarative spec (lux_tpu.program.library.SSSP — ISSUE 13).  The
    weighted variant below is the same template with the relax
    expression substituted; the former copy-pasted bodies are gone."""

    nv: int
    start: int = 0

    @property
    def spec(self):
        return library.SSSP

    @property
    def inf(self) -> int:
        """Unreached sentinel: nv, reference parity (hop counts < nv)."""
        return self.nv

    def _env(self):
        return {"start": self.start, "inf": self.inf}


@dataclasses.dataclass(frozen=True)
class WeightedSSSPProgram(SSSPProgram):
    """True weighted SSSP (chaotic relaxation; extension, not in the
    reference code).  Weights are integer ratings/costs (WeightType =
    int in the reference, col_filter/app.h:24); sssp() validates
    integrality."""

    @property
    def spec(self):
        return library.SSSP_WEIGHTED

    @property
    def inf(self) -> int:
        # weighted distances can exceed nv; use a large sentinel that still
        # survives `inf + max_weight` in int32
        return 1 << 30


def _push_run(prog, g, shards, mesh, max_iters, method, exchange,
              num_parts, repartition_every=0, repartition_threshold=1.25,
              route=None):
    """Shared dispatch for the frontier-model wrappers: single-device,
    all_gather-distributed, or ring-dense distributed; a positive
    ``repartition_every`` selects the adaptive dynamic-repartitioning
    driver (allgather exchange, needs the HostGraph for rebuilds).
    ``route`` applies to the single-device non-adaptive path only —
    silently ignoring it elsewhere would misreport routed numbers."""
    if route is not None and (mesh is not None or repartition_every > 0):
        raise ValueError(
            "route= is a single-device non-adaptive driver option; the "
            "distributed/ring/repartition push paths run the direct "
            "gather")
    from lux_tpu.parallel.ring import PushRingShards, build_push_ring_shards

    if repartition_every > 0:
        if not isinstance(g, HostGraph):
            raise ValueError(
                "repartition_every needs the HostGraph (shard rebuilds)"
            )
        from lux_tpu.engine import repartition

        if exchange == "ring":
            if isinstance(shards, PushRingShards):
                init = shards
            else:
                # wrap the already-built push layout with ring buckets on
                # the SAME partition — no second O(E) push build
                from lux_tpu.parallel.ring import build_ring_shards

                rs = build_ring_shards(
                    g, shards.spec.num_parts, pull=shards.pull
                )
                init = PushRingShards(
                    push=shards, rarrays=rs.rarrays,
                    e_bucket_pad=rs.e_bucket_pad,
                )
        else:
            init = shards.push if isinstance(shards, PushRingShards) else shards
        res = repartition.run_push_adaptive(
            prog, g, shards.spec.num_parts, chunk=repartition_every,
            threshold=repartition_threshold, max_iters=max_iters,
            method=method, mesh=mesh, shards=init, exchange=exchange,
        )
        return res.state
    if mesh is None:
        if isinstance(shards, PushRingShards):
            shards = shards.push  # ring buckets are a distributed layout
        final, _, _ = push.run_push(prog, shards, max_iters, method=method,
                                    route=route)
    elif exchange == "ring":
        if isinstance(shards, PushRingShards):
            rshards = shards
        elif isinstance(g, HostGraph):
            rshards = build_push_ring_shards(g, num_parts)
        else:
            raise ValueError(
                "exchange='ring' needs a HostGraph or pre-built PushRingShards"
            )
        final, _, _ = push.run_push_ring(
            prog, rshards, mesh, max_iters, method=method
        )
    else:
        if isinstance(shards, PushRingShards):
            shards = shards.push
        final, _, _ = push.run_push_dist(
            prog, shards, mesh, max_iters, method=method
        )
    return shards.scatter_to_global(np.asarray(final))


def sssp(
    g: HostGraph | PushShards,
    start: int = 0,
    num_parts: int = 1,
    mesh: Mesh | None = None,
    max_iters: int = 10_000,
    weighted: bool = False,
    method: str = "auto",
    exchange: str = "allgather",
    repartition_every: int = 0,
    repartition_threshold: float = 1.25,
    delta: int = 0,
    route=None,
) -> np.ndarray:
    """Run SSSP from ``start``; returns (nv,) int32 distances, nv == INF.
    ``exchange="ring"`` (with a mesh) streams dense rounds instead of
    all-gathering the state.  ``repartition_every > 0`` rebalances the
    vertex cuts from measured per-part load every N iterations (the Lux
    paper's dynamic repartitioning; engine/repartition.py).
    ``delta > 0`` selects the delta-stepping bucketed-priority driver
    (weighted single-device runs; engine/delta.py) — same distances,
    far fewer relaxed edges than chaotic relaxation."""
    from lux_tpu.parallel.ring import PushRingShards

    shards = (
        g if isinstance(g, (PushShards, PushRingShards))
        else build_push_shards(g, num_parts)
    )
    if not 0 <= start < shards.spec.nv:
        raise ValueError(f"start vertex {start} out of range [0, {shards.spec.nv})")
    if weighted:
        if not shards.spec.weighted:
            raise ValueError("weighted=True requires an edge-weighted graph")
        if isinstance(g, HostGraph) and not np.issubdtype(
            g.weights.dtype, np.integer
        ):
            raise ValueError(
                "weighted SSSP uses integer edge costs (reference parity, "
                "WeightType=int); got dtype " + str(g.weights.dtype)
            )
    cls = WeightedSSSPProgram if weighted else SSSPProgram
    prog = cls(nv=shards.spec.nv, start=start)
    if delta > 0:
        if not weighted:
            raise ValueError("delta-stepping orders WEIGHTED distances; "
                             "unweighted BFS buckets are the iterations")
        if exchange != "allgather" or repartition_every:
            raise ValueError(
                "delta-stepping is an allgather-exchange driver"
            )

        # check the SHARDS' weights (covers pre-built PushShards too —
        # bucket order silently finalizes too early under negative
        # costs; padding slots are 0.0 so only real negatives trip)
        if float(np.asarray(shards.arrays.weights).min()) < 0:
            raise ValueError("delta-stepping needs non-negative weights")
        from lux_tpu.engine import delta as delta_mod

        if route is not None and mesh is not None:
            raise ValueError("route= delta-stepping is single-device")
        if mesh is not None:
            final, _, _ = delta_mod.run_push_delta_dist(
                prog, shards, delta, mesh, max_iters, method=method
            )
        else:
            final, _, _ = delta_mod.run_push_delta(
                prog, shards, delta, max_iters, method=method, route=route
            )
        return shards.scatter_to_global(np.asarray(final))
    return _push_run(
        prog, g, shards, mesh, max_iters, method, exchange, num_parts,
        repartition_every, repartition_threshold, route=route,
    )


def sssp_batched(
    g: HostGraph | PushShards,
    sources,
    num_parts: int = 1,
    method: str = "auto",
    max_iters: int = 10_000,
) -> np.ndarray:
    """Answer ``len(sources)`` BFS-SSSP queries in ONE batched engine run
    (lux_tpu.serve.batched — the serving hot path as a library call);
    returns (Q, nv) int32 distances, nv == INF.  Each row is bitwise
    equal to ``sssp(g, start=sources[q])``."""
    from lux_tpu.graph.shards import PullShards, build_pull_shards
    from lux_tpu.serve.batched import BatchedEngine

    if isinstance(g, PushShards):
        shards = g.pull
    elif isinstance(g, PullShards):
        shards = g
    else:
        shards = build_pull_shards(g, num_parts)
    sources = np.asarray(sources, np.int32)
    eng = BatchedEngine(shards, "sssp", len(sources), method=method,
                        max_iters=max_iters)
    return eng.run(sources).state


def inf_value(nv: int, weighted: bool = False) -> int:
    """The unreached-distance sentinel sssp() returns."""
    return (
        WeightedSSSPProgram(nv=nv).inf if weighted else SSSPProgram(nv=nv).inf
    )


def check_distances(g: HostGraph, dist: np.ndarray, weighted: bool = False) -> int:
    """Host `-check` oracle: count of edges violating the triangle
    inequality dist[dst] <= dist[src] + w (must be 0 at a fixpoint)."""
    w = g.weights if (weighted and g.weights is not None) else np.ones(g.ne, np.int64)
    dst = g.dst_of_edges()
    lhs = dist[dst].astype(np.int64)
    rhs = dist[g.col_idx].astype(np.int64) + w
    # relaxations from unreached (INF) sources don't count
    reached = dist[g.col_idx] < inf_value(g.nv, weighted)
    return int(np.sum((lhs > rhs) & reached))


def bfs_reference(g: HostGraph, start: int) -> np.ndarray:
    """Host BFS oracle over the out-adjacency (CSR) view."""
    from collections import deque

    csr_row_ptr, csr_dst, _ = g.to_csr()
    dist = np.full(g.nv, g.nv, np.int32)
    dist[start] = 0
    dq = deque([start])
    while dq:
        u = dq.popleft()
        for v in csr_dst[csr_row_ptr[u] : csr_row_ptr[u + 1]]:
            if dist[v] == g.nv:
                dist[v] = dist[u] + 1
                dq.append(v)
    return dist
